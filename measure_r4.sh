#!/bin/bash
# Round-4 measurement matrix: supersedes measure_r3.sh (still valid) with
# the fused-conv A/B stacked on remat, the peephole/masked LSTM kernels,
# and the 1F1B pipeline A/B. One command for a live-tunnel window; the
# tunnel is single-client — stop any pytest/python first. Every live
# record auto-persists into BENCH_TPU_MEASURED.json as it completes.
#
#   bash measure_r4.sh 2>&1 | tee /tmp/measure_r4.log
set -u
cd "$(dirname "$0")"

run() { echo "=== ${CFG} $* ==="; env "$@" python bench.py "${CFG}"; }

# 1. the north star: ResNet50 MFU — baseline / remat / remat+fused A/B/C
CFG=resnet50 run BENCH_REMAT=0
CFG=resnet50 run BENCH_REMAT=1
CFG=resnet50 run BENCH_REMAT=1 BENCH_FUSED_CONV=1
CFG=resnet50 run BENCH_REMAT=0 BENCH_FUSED_CONV=1
CFG=resnet50 run BENCH_REMAT=1 BENCH_BATCH=128
CFG=resnet50 run BENCH_REMAT=1 BENCH_FUSED_CONV=1 BENCH_BATCH=128
CFG=resnet50 run BENCH_REMAT=1 BENCH_BATCH=256
CFG=resnet50 run BENCH_REMAT=1 BENCH_FUSED_CONV=1 BENCH_BATCH=256
# 2. tiled-Wh LSTM past the old H=512 cap, with scan-path A/B
CFG=lstm run BENCH_LSTM_HIDDEN=1024
CFG=lstm run BENCH_LSTM_HIDDEN=1024 DL4J_TPU_FUSED_LSTM=0
CFG=lstm run BENCH_LSTM_HIDDEN=2048
CFG=lstm run BENCH_LSTM_HIDDEN=2048 DL4J_TPU_FUSED_LSTM=0
# 2b. masked-batch LSTM (state-freezing kernel path) A/B vs scan
CFG=lstm run BENCH_LSTM_MASKED=1
CFG=lstm run BENCH_LSTM_MASKED=1 DL4J_TPU_FUSED_LSTM=0
# 3. word2vec at production scale (V=100k, D=300, 10M words)
CFG=word2vec run BENCH_W2V_SCALE=production
# 4. refresh the standard sweep records
for c in lenet lstm word2vec parallel transformer longcontext; do
  CFG=$c run _=;
done
echo "=== matrix complete; records merged into BENCH_TPU_MEASURED.json ==="
