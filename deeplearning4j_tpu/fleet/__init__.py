"""Fleet serving tier: a multi-process engine pool behind one front.

The multi-process half of the serving story (ROADMAP "millions of users"
tier; the PS/worker deployment architecture of the TensorFlow system
papers, PAPERS.md arxiv 1603.04467 §deployment / 1605.08695) re-expressed
over this framework's serving seams:

* :class:`FleetWorker` (``fleet/worker.py``) — ONE process serving ONE
  :class:`~deeplearning4j_tpu.serving.ServingEngine` behind a local HTTP
  wire protocol (``/submit``, ``/health``, ``/stats``, ``/swap``).
  Started from a checkpoint/bundle + warm manifest, a worker warms up
  with ZERO compiles (PR 9's instant-restart tier) — which is what makes
  elastic replacement a seconds-long blip instead of an outage.
* :class:`FleetRouter` (``fleet/router.py``) — the single admission/
  routing front: load-aware dispatch (least outstanding rows, bounded
  per-worker in-flight window), deadline-aware shedding with the serving
  tier's shed semantics (``serving_shed_total`` + new ``fleet_*``
  counters), cross-worker ``/health`` aggregation, and idempotent
  retry-on-dead-worker — a request is answered, retried onto a live
  worker, or counted-shed; never silently dropped (inference is
  stateless, so a replay is safe by construction).
* :class:`FleetSupervisor` (``fleet/supervisor.py``) — spawns N workers
  as subprocesses, probes liveness, and elastically REPLACES a dead
  worker from the same bundle + manifest (replacement warm-start is
  counter-asserted: manifest hits only, zero compiles), fanning
  ``ModelRegistry``-style hot swaps out to every worker warm-then-atomic.

Quickstart (also: ``python -m deeplearning4j_tpu fleet --workers 3``)::

    from deeplearning4j_tpu import fleet
    sup = fleet.FleetSupervisor(3, model_path="ckpt.zip",
                                warm_manifest="wm.zip", buckets=[1, 8])
    router = fleet.FleetRouter(max_queue=256, default_deadline_s=0.25)
    sup.attach(router)      # endpoints follow respawns automatically
    sup.start()
    y = router.submit(example).get(timeout=1.0)

The process-default front (what the UIServer ``/fleet`` endpoint reads)
is registered by the ``fleet`` CLI verb via :func:`set_default_front`.
"""

from __future__ import annotations

import threading

from deeplearning4j_tpu.fleet.prober import (FleetProber,
                                             seq_sweep_canaries)
from deeplearning4j_tpu.fleet.router import FleetRouter
from deeplearning4j_tpu.fleet.supervisor import (FleetSupervisor,
                                                 default_worker_env)
from deeplearning4j_tpu.fleet.worker import FleetWorker

__all__ = ["FleetProber", "FleetRouter", "FleetSupervisor", "FleetWorker",
           "default_worker_env", "fleet_status", "get_default_front",
           "reset", "seq_sweep_canaries", "set_default_front"]

_front_lock = threading.Lock()
_front = {"router": None, "supervisor": None}


def set_default_front(router=None, supervisor=None):
    """Register the process-default fleet front — the router/supervisor
    pair the UIServer's ``/fleet`` endpoint reports on (the ``fleet``
    CLI verb calls this). Registering a router also plugs the fleet into
    the cluster observability plane: its workers become federated
    ``/metrics?federate=1`` targets and ``/traces?cluster=1`` timeline
    sources."""
    from deeplearning4j_tpu.telemetry import federate as _federate
    from deeplearning4j_tpu.telemetry import timeline as _timeline
    with _front_lock:
        if router is not None:
            _front["router"] = router
        if supervisor is not None:
            _front["supervisor"] = supervisor
    if router is not None:
        _federate.register_target_provider(_front_metric_targets)
        _timeline.register_source_provider(_front_timeline_sources)


def _front_metric_targets():
    """Federation targets of the default front's workers."""
    router, _sup = get_default_front()
    if router is None:
        return []
    return [(wid, addr + "/metrics") for wid, addr in router.endpoints()]


def _front_timeline_sources():
    """Cluster-timeline sources of the default front (the router's own
    ring is the UIServer process's 'local' source already)."""
    router, _sup = get_default_front()
    if router is None:
        return []
    return router.timeline_sources(include_local=False)


def get_default_front():
    """(router, supervisor) of the process-default front (either may be
    None when nothing registered them)."""
    with _front_lock:
        return _front["router"], _front["supervisor"]


def reset():
    """Drop the process-default front (tests). Does NOT stop the router
    or supervisor — ownership stays with whoever built them."""
    from deeplearning4j_tpu.telemetry import federate as _federate
    from deeplearning4j_tpu.telemetry import timeline as _timeline
    with _front_lock:
        _front["router"] = None
        _front["supervisor"] = None
    _federate.unregister_target_provider(_front_metric_targets)
    _timeline.unregister_source_provider(_front_timeline_sources)


def fleet_status(probe=False):
    """The ``/fleet`` payload: router counters + per-worker dispatch
    state, the supervisor's worker table + respawn ledger and its CACHED
    last health probe per worker (the cross-worker aggregation, served
    without re-probing). ``probe=True`` (``/fleet?probe=1``) re-probes
    every worker's ``/health`` live through the router instead."""
    router, supervisor = get_default_front()
    if router is None and supervisor is None:
        return {"active": False,
                "note": "no fleet front registered in this process "
                        "(start one with the `fleet` CLI verb)"}
    out = {"active": True}
    if router is not None:
        out["router"] = router.stats()
        if probe:
            out["health"] = router.health()
    if supervisor is not None:
        out["workers"] = supervisor.status()
    from deeplearning4j_tpu.fleet import prober as _prober
    probe_status = _prober.status()
    if probe_status is not None:
        # the synthetic-monitoring verdicts ride /fleet so one read
        # answers "is the fleet up AND answering correctly"
        out["prober"] = probe_status
    return out
