"""Fleet supervisor: spawn N serving workers, probe them, replace the dead.

The elastic half of the fleet tier: every worker is a subprocess running
``python -m deeplearning4j_tpu.fleet.worker`` from the SAME checkpoint +
warm manifest, so a replacement process warms up by DESERIALIZING its
executables (PR 9's instant-restart tier) — the supervisor counter-asserts
this from the replacement's ready line (``aot.manifest_hits == warmed``,
zero lazy compiles) and records the verdict in its respawn ledger, making
"worker death is a seconds-long blip, zero recompiles" a measured claim,
not a hope.

Liveness is HTTP ``/health`` probes on an interval; a worker is declared
dead after ``max_missed_probes`` consecutive failures (or the moment its
process exits). On death the supervisor respawns from the same spec,
pushes the fresh endpoint to the attached :class:`FleetRouter` (stable
worker id, new address — metric labels stay bounded), and the router's
in-flight retries land on the survivors meanwhile.

Hot swap fans out ``ModelRegistry``-style: :meth:`update_model` POSTs
``/swap`` to every worker SEQUENTIALLY — each worker's swap is
warm-then-atomic internally, and the sequential fan-out keeps N-1 workers
serving at full capacity while each replacement forward warms.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque

from deeplearning4j_tpu import telemetry as _tm
from deeplearning4j_tpu.fleet.router import _http_json
from deeplearning4j_tpu.fleet.worker import ORIGIN_HEADER as _ORIGIN_HEADER


def default_worker_env():
    """Subprocess env for a CPU fleet worker: the tunnel/device-count
    vars scrubbed (``PALLAS_AXON_POOL_IPS`` would dial the axon TPU
    tunnel at import; an inherited ``XLA_FLAGS`` host-device-count would
    give every worker a virtual 8-device mesh), the backend pinned to
    CPU, and the repo root on ``PYTHONPATH`` so ``-m`` resolves the
    package from any cwd. Accelerator fleets pass their own ``env=``."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pp = env.get("PYTHONPATH")
    env["PYTHONPATH"] = repo if not pp else repo + os.pathsep + pp
    return env


class _WorkerProc:
    """One spawned worker: process handle + the state the monitor loop
    tracks. stdout/stderr are drained by daemon reader threads into
    bounded rings (a full pipe would wedge the worker)."""

    def __init__(self, wid, generation, proc):
        self.wid = wid
        self.generation = generation
        self.proc = proc
        self.port = None
        self.ready = threading.Event()
        self.ready_doc = None
        self.ready_at = None  # monotonic time the ready line landed
        #: the worker's monotonic+epoch clock pair off its ready line and
        #: the offset (its clock minus ours) estimated at receipt — the
        #: clock-alignment seed the cluster timeline re-anchors with
        self.clock = None
        self.clock_offset_s = 0.0
        self.missed = 0
        self.last_health = None
        self.out_ring = deque(maxlen=50)
        self.err_ring = deque(maxlen=50)

    @property
    def address(self):
        return None if self.port is None else f"http://127.0.0.1:{self.port}"

    def snapshot(self):
        return {"worker_id": self.wid, "generation": self.generation,
                "pid": self.proc.pid, "port": self.port,
                "alive": self.proc.poll() is None,
                "missed_probes": self.missed,
                "clock": self.clock,
                "clock_offset_s": self.clock_offset_s,
                "last_health": self.last_health}


class FleetSupervisor:
    """Spawn, probe, and elastically replace N fleet worker processes."""

    def __init__(self, n_workers, *, model_path=None, zoo=None,
                 name="default", buckets=None, seq_buckets=None,
                 input_shape=None,
                 warm_manifest=None, compile_cache=None, max_queue=256,
                 max_batch=32, deadline_ms=None, batch_window_ms=1.0,
                 env=None, worker_command=None, python=None,
                 spawn_timeout_s=180.0, probe_interval_s=0.5,
                 probe_timeout_s=2.0, max_missed_probes=3,
                 respawn_backoff_base_s=0.5, respawn_backoff_cap_s=30.0,
                 crashloop_window_s=5.0):
        if model_path is None and zoo is None and worker_command is None:
            raise ValueError("FleetSupervisor needs model_path=, zoo=, "
                             "or a custom worker_command=")
        self.n_workers = int(n_workers)
        self.model_path = model_path
        self.zoo = zoo
        self.name = name
        self.buckets = buckets
        self.seq_buckets = seq_buckets
        self.input_shape = input_shape
        self.warm_manifest = warm_manifest
        self.compile_cache = compile_cache
        self.max_queue = max_queue
        self.max_batch = max_batch
        self.deadline_ms = deadline_ms
        self.batch_window_ms = batch_window_ms
        self._env = env
        self._worker_command = worker_command
        self._python = python or sys.executable
        self.spawn_timeout_s = spawn_timeout_s
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.max_missed_probes = max_missed_probes
        self.respawn_backoff_base_s = float(respawn_backoff_base_s)
        self.respawn_backoff_cap_s = float(respawn_backoff_cap_s)
        self.crashloop_window_s = float(crashloop_window_s)
        self._lock = threading.Lock()
        self._workers = {}        # wid -> _WorkerProc
        self._respawns = []       # ledger: one dict per replacement
        self._backoff = {}        # wid -> {level, not_before, gen}
        self._router = None
        self._stop = threading.Event()
        self._monitor = None
        reg = self._reg = _tm.get_registry()
        self._m_respawn = reg.counter(
            "fleet_respawn_total",
            "dead workers elastically replaced by the supervisor, "
            "labeled by worker and whether the replacement warm-started "
            "(warm=true means manifest hits only, zero compiles)")
        self._m_probe = reg.counter(
            "fleet_probe_total",
            "supervisor liveness probes by result (ok/missed/dead)")
        self._m_backoff = reg.counter(
            "fleet_respawn_backoff_total",
            "respawns deferred by the crash-loop backoff (a worker that "
            "died within crashloop_window_s of becoming ready, or whose "
            "respawn itself failed, waits min(cap, base*2^level) before "
            "the next attempt), labeled by worker")

    # ---- spawning ----

    def _command(self, wid):
        """argv for one worker process. ``worker_command`` (tests, exotic
        deployments) overrides; it must print the same ready line."""
        if self._worker_command is not None:
            return list(self._worker_command(wid))
        cmd = [self._python, "-m", "deeplearning4j_tpu.fleet.worker",
               "--worker-id", wid, "--port", "0", "--name", self.name,
               "--max-queue", str(self.max_queue),
               "--max-batch", str(self.max_batch),
               "--batch-window-ms", str(self.batch_window_ms)]
        if self.model_path:
            cmd += ["--model-path", self.model_path]
        else:
            cmd += ["--zoo", self.zoo]
        if self.buckets:
            cmd += ["--buckets",
                    ",".join(str(int(b)) for b in self.buckets)]
        if self.seq_buckets:
            cmd += ["--seq-buckets",
                    ",".join(str(int(b)) for b in self.seq_buckets)]
        if self.input_shape:
            cmd += ["--input-shape",
                    ",".join(str(int(d)) for d in self.input_shape)]
        if self.deadline_ms is not None:
            cmd += ["--deadline-ms", str(self.deadline_ms)]
        if self.warm_manifest:
            cmd += ["--warm-manifest", self.warm_manifest]
        if self.compile_cache:
            cmd += ["--compile-cache", self.compile_cache]
        return cmd

    def _spawn(self, wid, generation):
        env = self._env if self._env is not None else default_worker_env()
        proc = subprocess.Popen(self._command(wid), env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        w = _WorkerProc(wid, generation, proc)

        def read_out():
            for line in proc.stdout:
                line = line.rstrip("\n")
                w.out_ring.append(line)
                if not w.ready.is_set() and line.lstrip().startswith("{"):
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        continue
                    if doc.get("fleet_worker_ready"):
                        w.ready_doc = doc
                        w.port = int(doc["port"])
                        w.clock = doc.get("clock")
                        if w.clock:
                            # the stamp happened within the pipe latency
                            # of now: offset clamps to 0 on a shared
                            # clock (same host), keeps a real skew
                            from deeplearning4j_tpu.telemetry import (
                                timeline as _timeline)
                            recv = time.time()
                            w.clock_offset_s, _ = \
                                _timeline.estimate_offset(
                                    w.clock.get("unix"), recv - 0.25,
                                    recv)
                        w.ready.set()
            proc.stdout.close()

        def read_err():
            for line in proc.stderr:
                w.err_ring.append(line.rstrip("\n"))
            proc.stderr.close()

        threading.Thread(target=read_out, daemon=True,
                         name=f"fleet-out-{wid}").start()
        threading.Thread(target=read_err, daemon=True,
                         name=f"fleet-err-{wid}").start()
        return w

    def _await_ready(self, w):
        """Block until the worker's ready line (bound port + warmup
        counters) or raise with its stderr tail."""
        deadline = time.monotonic() + self.spawn_timeout_s
        while not w.ready.wait(timeout=0.2):
            if w.proc.poll() is not None:
                tail = "\n".join(list(w.err_ring)[-10:]) or "<no stderr>"
                raise RuntimeError(
                    f"fleet worker {w.wid} (gen {w.generation}) exited "
                    f"rc={w.proc.returncode} before ready:\n{tail}")
            if time.monotonic() > deadline:
                w.proc.kill()
                raise RuntimeError(
                    f"fleet worker {w.wid} (gen {w.generation}) not "
                    f"ready after {self.spawn_timeout_s:.0f}s")
        w.ready_at = time.monotonic()  # crash-loop window anchor
        return w

    @staticmethod
    def replacement_is_warm(ready_doc):
        """Counter-assert a worker warm-started: every warmed bucket came
        from the manifest, and nothing compiled lazily. The zero-recompile
        replacement contract, read off the ready line."""
        aot = (ready_doc or {}).get("aot") or {}
        return bool(aot.get("warmed")) \
            and aot.get("manifest_hits") == aot.get("warmed") \
            and not aot.get("lazy_compiles") \
            and not aot.get("manifest_misses")

    def start(self):
        """Spawn all workers CONCURRENTLY (their warmups overlap), wait
        for every ready line, push endpoints to the attached router, and
        start the monitor loop."""
        with self._lock:
            spawned = {f"w{i}": self._spawn(f"w{i}", 0)
                       for i in range(self.n_workers)}
            self._workers = spawned
        try:
            for w in spawned.values():
                self._await_ready(w)
        except Exception:
            self.stop()
            raise
        self._push_endpoints()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="fleet-supervisor",
                                         daemon=True)
        self._monitor.start()
        return self

    # ---- routing integration ----

    def attach(self, router):
        """Bind a :class:`FleetRouter`: it receives the live endpoint set
        now and after every respawn."""
        self._router = router
        if self.addresses():
            self._push_endpoints()
        return router

    def addresses(self):
        with self._lock:
            return [(w.wid, w.address) for w in self._workers.values()
                    if w.port is not None]

    def _push_endpoints(self):
        if self._router is not None:
            self._router.set_endpoints(self.addresses())

    # ---- monitoring / elastic replacement ----

    def _probe(self, w):
        """One liveness probe. True when the worker answered /health."""
        if w.address is None:
            return False
        try:
            # stamped synthetic: the worker counts this GET into its
            # origin=probe series, never the organic ones
            _code, doc = _http_json(w.address + "/health",
                                    timeout=self.probe_timeout_s,
                                    headers={_ORIGIN_HEADER: "probe"})
            w.last_health = doc
            return bool(doc.get("ok"))
        except Exception:  # noqa: BLE001 — probe failure IS the signal
            return False

    def _monitor_loop(self):
        while not self._stop.wait(timeout=self.probe_interval_s):
            with self._lock:
                workers = list(self._workers.values())
            for w in workers:
                if self._stop.is_set():
                    return
                exited = w.proc.poll() is not None
                if not exited and self._probe(w):
                    w.missed = 0
                    if self._reg.enabled:
                        self._m_probe.inc(result="ok")
                    if self._router is not None:
                        # a healthy probe REVIVES a worker the router
                        # wrote off on a transient stall — a
                        # false-positive mark_dead must not shrink the
                        # pool until the process actually dies
                        self._router.mark_alive(w.wid)
                    continue
                w.missed += 1
                if self._reg.enabled:
                    self._m_probe.inc(result="missed")
                if not exited and w.missed < self.max_missed_probes:
                    continue
                if self._in_backoff(w):
                    continue  # crash-loop: defer the respawn this tick
                self._replace(w, reason=("exited rc="
                                         f"{w.proc.returncode}" if exited
                                         else f"{w.missed} missed probes"))

    def _in_backoff(self, w):
        """Capped exponential backoff between respawns of a crash-looping
        worker, so a worker that dies the moment it comes up (bad model
        path after a botched hot-swap, OOM on load) cannot spin the
        supervisor — and the node — hot. A worker that lived at least
        ``crashloop_window_s`` after its ready line respawns immediately
        and resets the level; one that died inside the window (or whose
        respawn attempt itself failed: no ready line at all) waits
        ``min(cap, base * 2^level)`` first, each deferral scheduled once
        per death and counted ``fleet_respawn_backoff_total``."""
        now = time.monotonic()
        deferred = False
        with self._lock:  # status() snapshots this map concurrently
            bo = self._backoff.setdefault(w.wid,
                                          {"level": 0, "not_before": 0.0,
                                           "gen": None})
            if bo["gen"] != w.generation:  # first tick observing THIS death
                bo["gen"] = w.generation
                lived = None if w.ready_at is None else now - w.ready_at
                if lived is not None and lived >= self.crashloop_window_s:
                    bo["level"] = 0
                    bo["not_before"] = 0.0
                else:
                    bo["level"] = min(bo["level"] + 1, 16)
                    delay = min(self.respawn_backoff_base_s
                                * (2 ** (bo["level"] - 1)),
                                self.respawn_backoff_cap_s)
                    bo["not_before"] = now + delay
                    deferred = True
            backing_off = now < bo["not_before"]
        if deferred and self._reg.enabled:
            self._m_backoff.inc(worker=w.wid)
        return backing_off

    def _replace(self, dead, reason):
        """Elastic replacement: same spec (bundle + warm manifest), fresh
        process, counter-asserted warm start, endpoints re-pushed."""
        if self._reg.enabled:
            self._m_probe.inc(result="dead")
        if self._router is not None:
            # survivors take the traffic while the replacement warms
            self._router.mark_dead(dead.wid, error=reason)
        try:
            dead.proc.kill()
        except OSError:
            pass
        t0 = time.monotonic()
        event = {"worker_id": dead.wid, "generation": dead.generation + 1,
                 "reason": reason, "warm": None, "spawn_s": None}
        try:
            fresh = self._spawn(dead.wid, dead.generation + 1)
            with self._lock:
                self._workers[dead.wid] = fresh
            self._await_ready(fresh)
            event["spawn_s"] = round(time.monotonic() - t0, 3)
            event["warm"] = self.replacement_is_warm(fresh.ready_doc)
            event["aot"] = (fresh.ready_doc or {}).get("aot")
            self._push_endpoints()
        except Exception as e:  # noqa: BLE001 — keep supervising
            # the respawn itself failed: record it and let the next
            # monitor tick try again (the worker slot stays dead)
            event["error"] = str(e)[:300]
            with self._lock:
                # when _spawn itself raised (bad command, Popen failure),
                # the dead generation is still installed — _in_backoff's
                # per-death gen marker would never re-arm and the monitor
                # would retry every probe tick forever. Escalate the
                # backoff HERE for that case.
                spawn_failed = self._workers.get(dead.wid) is dead
                if spawn_failed:
                    bo = self._backoff.setdefault(
                        dead.wid, {"level": 0, "not_before": 0.0,
                                   "gen": None})
                    bo["gen"] = dead.generation
                    bo["level"] = min(bo["level"] + 1, 16)
                    bo["not_before"] = time.monotonic() + min(
                        self.respawn_backoff_base_s
                        * (2 ** (bo["level"] - 1)),
                        self.respawn_backoff_cap_s)
            if spawn_failed and self._reg.enabled:
                self._m_backoff.inc(worker=dead.wid)
        with self._lock:
            self._respawns.append(event)
        if self._reg.enabled:
            self._m_respawn.inc(worker=dead.wid,
                                warm=str(bool(event["warm"])).lower())

    # ---- operations ----

    def kill_worker(self, wid, sig=signal.SIGKILL):
        """Chaos hook: deliver ``sig`` to one worker process (tests and
        the bench's kill-a-worker leg). The monitor loop notices and
        replaces it like any other death."""
        with self._lock:
            w = self._workers[wid]
        os.kill(w.proc.pid, sig)
        return w.proc.pid

    def update_model(self, model_path, warm=None):
        """Hot-swap every worker from ``model_path``, warm-then-atomic
        per worker, sequentially (N-1 workers keep serving at full
        capacity during each warmup). Returns {wid: swap response}."""
        out = {}
        for wid, addr in self.addresses():
            try:
                _code, doc = _http_json(
                    addr + "/swap",
                    {"model_path": model_path, "warm": warm},
                    timeout=max(self.spawn_timeout_s, 30.0))
                out[wid] = doc
            except Exception as e:  # noqa: BLE001 — per-worker verdict
                out[wid] = {"ok": False, "error": str(e)[:300]}
        return out

    def status(self):
        """The supervisor's /fleet payload: worker table (with each
        worker's CACHED last /health probe — cross-worker aggregation
        without re-probing) + the respawn ledger."""
        with self._lock:
            workers = [w.snapshot() for w in self._workers.values()]
        return {"n_workers": self.n_workers, "workers": workers,
                "respawns": list(self._respawns),
                "probe_interval_s": self.probe_interval_s,
                "max_missed_probes": self.max_missed_probes,
                "backoff": {wid: dict(bo)
                            for wid, bo in self._backoff.items()}}

    def stop(self):
        """Graceful stop: /shutdown every worker, then make sure the
        processes are gone (terminate -> kill)."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            if w.proc.poll() is not None:
                continue
            if w.address is not None:
                try:
                    _http_json(w.address + "/shutdown", {}, timeout=2.0)
                except Exception:  # noqa: BLE001 — force-kill below
                    pass
        for w in workers:
            try:
                w.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                w.proc.terminate()
                try:
                    w.proc.wait(timeout=2)
                except subprocess.TimeoutExpired:
                    w.proc.kill()
                    w.proc.wait(timeout=5)
