"""Synthetic monitoring: the fleet judged from OUTSIDE, even at zero load.

Every SLI the metrics plane holds is self-reported by the process being
judged; a fleet serving nothing reports nothing. :class:`FleetProber`
closes that gap: a loop submits known-answer canary requests through the
full submit path (a :class:`~deeplearning4j_tpu.fleet.FleetRouter`'s
wire hop included), checks the answers against pinned references within
a tolerance, and publishes verdict-labeled counters plus a probe latency
series — so "the fleet is up AND answering correctly" is measured
continuously, and a wrong model swap or a dead pool fires the
``probe_failure_ratio`` SLO gate rule within one window even when no
organic request would have noticed.

Isolation discipline: every canary is submitted ``origin="probe"`` and
rides that label end-to-end (router → wire → worker → engine), so its
request/latency/shed series are DISTINCT from the organic ones and every
default SLO rule excludes them — a prober storm cannot move an organic
SLI, and an idle fleet's organic series stay exactly zero while
``probe_total`` advances.

Verdicts (the ``probe_total{model,verdict}`` label):

* ``ok`` — answered within tolerance;
* ``wrong_answer`` — answered, but off the pinned reference;
* ``shed`` — admission control shed the canary (queue_full/deadline);
* ``unreachable`` — no live worker / shutdown / timeout: counted, NEVER
  a hang (every wait is bounded by ``timeout_s``);
* ``error`` — the submit path raised something else.

``extra_probes`` extends the loop beyond inference: ``(name, fn)``
pairs where ``fn()`` returning truthy is ok — e.g. a canary train-step
probe against the continuous loop's registry handoff.

For a 2-D (batch × seq) serving grid, :func:`seq_sweep_canaries` builds
the canary set at varied sequence lengths (shortest bucket, just under
the median bucket, the max bucket) so the outside-in correctness floor
exercises seq-bucket selection AND the pad-then-slice round trip — a
wrong 2-D bucket or a bad seq slice is a ``wrong_answer`` verdict, not
a silent waste regression.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from deeplearning4j_tpu import telemetry as _tm
from deeplearning4j_tpu.serving.engine import (ServingOverloaded,
                                               ServingShutdown,
                                               shed_reason)

#: verdicts a canary probe can land on (the probe_total label values)
VERDICTS = ("ok", "wrong_answer", "shed", "unreachable", "error")


class FleetProber:
    """Known-answer canary loop over one submit target.

    ``target`` is anything with the engine-shaped ``submit(x, batched=,
    tenant=, origin=)`` -> future contract (ServingEngine, FleetRouter).
    ``canaries``: dicts with ``x`` (one example or, with ``batched``,
    an ``[n, ...]`` batch), ``expect`` (the pinned reference output),
    optional ``name`` and ``model`` (metric label; defaults to the
    target's model name)."""

    def __init__(self, target, canaries, *, interval_s=15.0, tol=1e-6,
                 timeout_s=10.0, deadline_s=None, extra_probes=(),
                 registry=None):
        self.target = target
        self.canaries = [dict(c) for c in canaries]
        for i, c in enumerate(self.canaries):
            c.setdefault("name", f"canary{i}")
            c.setdefault("model", getattr(target, "name", "default"))
        self.interval_s = float(interval_s)
        self.tol = float(tol)
        self.timeout_s = float(timeout_s)
        self.deadline_s = deadline_s
        self.extra_probes = list(extra_probes)
        self._reg = registry or _tm.get_registry()
        self._lock = threading.Lock()
        self._last = {}     # probe name -> last verdict doc
        self._rounds = 0
        self._thread = None
        self._stop = threading.Event()
        self._m_total = self._reg.counter(
            "probe_total",
            "synthetic canary probes by model and verdict (ok/"
            "wrong_answer/shed/unreachable/error)")
        self._m_bad = self._reg.counter(
            "probe_bad_total",
            "synthetic canary probes with any non-ok verdict, per model "
            "(the probe_failure_ratio SLO rule's numerator)")
        self._m_latency = self._reg.histogram(
            "probe_latency_seconds",
            "submit-to-answer latency of synthetic canaries, per model "
            "(the externally-measured serving latency floor)")
        if self._reg.enabled:
            # pre-register every verdict series at zero: the SLO delta
            # discipline ignores a series' FIRST appearance, so a
            # failure series born mid-storm would contribute nothing
            # that interval and delay the probe_failure_ratio gate by a
            # full window
            for model in {c["model"] for c in self.canaries}:
                self._m_bad.inc(0, model=model)
                for verdict in VERDICTS:
                    self._m_total.inc(0, model=model, verdict=verdict)

    # ---- one probe round ----

    def _verdict_of(self, canary):
        """Run one canary through the full submit path. Returns
        (verdict, latency_s_or_None, detail)."""
        t0 = time.perf_counter()
        try:
            fut = self.target.submit(canary["x"],
                                     deadline_s=self.deadline_s,
                                     batched=bool(canary.get("batched")),
                                     tenant=canary.get("tenant"),
                                     origin="probe")
            y = fut.get(timeout=self.timeout_s)
        except ServingOverloaded as e:
            reason = shed_reason(e) or "queue_full"
            if reason == "no_worker":
                # the whole pool is down — that is unreachability, not
                # load shedding (an idle dead fleet has no load to shed)
                return "unreachable", None, reason
            return "shed", None, reason
        except ServingShutdown as e:
            return "unreachable", None, str(e)[:200]
        except TimeoutError as e:
            # a bounded wait that expired: counted, never a hang
            return "unreachable", None, str(e)[:200] or "timeout"
        except Exception as e:  # noqa: BLE001 — verdict, not crash
            return "error", None, f"{type(e).__name__}: {e}"[:200]
        dt = time.perf_counter() - t0
        try:
            got = np.asarray(y, dtype=np.float64)
            want = np.asarray(canary["expect"], dtype=np.float64)
            if got.shape != want.shape:
                return ("wrong_answer", dt,
                        f"shape {got.shape} != {want.shape}")
            err = float(np.max(np.abs(got - want))) if got.size else 0.0
        except Exception as e:  # noqa: BLE001 — uncomparable answer
            return "wrong_answer", dt, f"uncomparable: {e}"[:200]
        if err > self.tol:
            return "wrong_answer", dt, f"max|err|={err:.3e}>{self.tol:g}"
        return "ok", dt, f"max|err|={err:.3e}"

    def probe_once(self):
        """One full round over every canary + extra probe; returns the
        verdict docs (also retained for ``status()``)."""
        results = []
        for canary in self.canaries:
            verdict, dt, detail = self._verdict_of(canary)
            results.append({"probe": canary["name"],
                            "model": canary["model"],
                            "verdict": verdict, "detail": detail,
                            "latency_ms": (None if dt is None
                                           else round(1e3 * dt, 3))})
            if self._reg.enabled:
                self._m_total.inc(model=canary["model"], verdict=verdict)
                if verdict != "ok":
                    self._m_bad.inc(model=canary["model"])
                if dt is not None:
                    self._m_latency.observe(dt, model=canary["model"])
        for name, fn in self.extra_probes:
            try:
                verdict = "ok" if fn() else "wrong_answer"
                detail = None
            except Exception as e:  # noqa: BLE001 — verdict, not crash
                verdict, detail = "error", f"{type(e).__name__}: {e}"[:200]
            results.append({"probe": name, "model": name,
                            "verdict": verdict, "detail": detail,
                            "latency_ms": None})
            if self._reg.enabled:
                self._m_total.inc(model=name, verdict=verdict)
                if verdict != "ok":
                    self._m_bad.inc(model=name)
        with self._lock:
            self._rounds += 1
            for r in results:
                self._last[r["probe"]] = r
        return results

    # ---- lifecycle / status ----

    def start(self):
        """Probe every ``interval_s`` on a daemon thread (first round
        fires immediately — a fresh fleet gets its verdict now, not one
        interval late)."""
        if self._thread is not None:
            return self

        def loop():
            while not self._stop.is_set():
                try:
                    self.probe_once()
                except Exception:  # the prober must never kill the host
                    pass
                if self._stop.wait(self.interval_s):
                    return

        self._stop.clear()  # graftlint: disable=R6 -- threading.Event is internally synchronized; self._lock guards probe state, not lifecycle
        self._thread = threading.Thread(target=loop, name="fleet-prober",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=max(5.0, self.timeout_s + 1.0))

    @property
    def running(self):
        return self._thread is not None and self._thread.is_alive()

    def status(self):
        """The prober's slice of /fleet: per-probe last verdicts + loop
        bookkeeping."""
        with self._lock:
            last = dict(self._last)
            rounds = self._rounds
        return {"running": self.running, "interval_s": self.interval_s,
                "tol": self.tol, "rounds": rounds,
                "probes": last,
                "ok": all(r["verdict"] == "ok" for r in last.values())
                if last else None}


def seq_sweep_canaries(reference, feature_shape, seq_buckets, *,
                       model="default", seed=0):
    """Known-answer canaries at varied sequence lengths for a 2-D grid.

    Picks three lengths from ``seq_buckets``: the shortest bucket
    (exact fit), one just UNDER the median bucket (forces a seq-axis pad
    and the slice back to real steps), and the max bucket (the old
    max_seq path). Each canary's ``expect`` is pinned NOW through
    ``reference`` — a callable taking one ``[n, T, ...]`` batch (e.g.
    ``net.output``) — so the prober later judges the serving path
    against the unbucketed forward at probe-build time.

    ``feature_shape``: per-step trailing shape (e.g. ``(n_features,)``);
    inputs are deterministic ``float32`` draws seeded per length, so a
    respawned prober pins identical canaries.
    """
    bs = sorted({int(b) for b in seq_buckets})
    if not bs:
        raise ValueError("seq_sweep_canaries needs a non-empty seq grid")
    lengths = sorted({bs[0], max(1, bs[len(bs) // 2] - 1), bs[-1]})
    canaries = []
    for length in lengths:
        rng = np.random.default_rng(seed + length)
        x = rng.standard_normal(
            (length,) + tuple(feature_shape)).astype(np.float32)
        expect = np.asarray(reference(x[None]))[0]
        canaries.append({"x": x, "expect": expect,
                         "name": f"seq{length}", "model": model})
    return canaries


# ---- process-default prober ----

_default = None
_default_lock = threading.Lock()


def set_default(prober):
    """Install (or clear, with None) the process-default prober — what
    ``fleet_status()`` folds into /fleet. Stops any previous one."""
    global _default
    with _default_lock:
        old, _default = _default, prober
    if old is not None and old is not prober:
        old.stop()
    return prober


def get_default():
    with _default_lock:
        return _default


def status():
    """The default prober's status, or None when none is installed (the
    inert-seam contract: /fleet embeds this without starting anything)."""
    with _default_lock:
        prober = _default
    return None if prober is None else prober.status()


def reset():
    """Drop the process-default prober (telemetry.reset())."""
    set_default(None)
