"""Fleet serving worker: one process, one ServingEngine, one HTTP wire.

A :class:`FleetWorker` wraps a started
:class:`~deeplearning4j_tpu.serving.ServingEngine` behind a local HTTP
protocol on ``127.0.0.1`` (the supervisor/router never leave the host in
this tier; cross-host fronts terminate here too):

    POST /submit    {"rows": [...], "deadline_ms": f} -> {"outputs": [...]}
    GET  /health    liveness + engine stats + compile-cache counters
    GET  /stats     the engine's /serving stats payload
    GET  /usage     per-model/per-tenant usage ledger (metering)
    POST /swap      {"model_path": p} -> warm-then-atomic hot swap
    POST /shutdown  clean stop (engine drained, waiters failed promptly)

``/submit`` carries MULTI-example batches (``rows`` leading axis =
examples; a dict body is the ComputationGraph multi-input form) so the
router's fleet-level continuous batching pays one HTTP round trip per
device batch, not per request. Sheds surface as HTTP 429 with the reason
(``queue_full`` / ``deadline``) so the front can count them into the same
``serving_shed_total`` semantics; a stopped engine answers 503.

Run as a subprocess (what :class:`FleetSupervisor` spawns)::

    python -m deeplearning4j_tpu.fleet.worker --model-path ckpt.zip \
        --warm-manifest wm.zip --buckets 1,8 --port 0 --worker-id w0

The process prints ONE machine-readable ready line after warmup —
``{"fleet_worker_ready": true, "port": <bound>, "aot": {...}, ...}`` —
carrying the actually-bound port (``--port 0`` never collides) and the
warmup counters, so the spawner can assert a replacement warm-started
with zero compiles without a single extra round trip.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from deeplearning4j_tpu.serving.engine import (ServingOverloaded,
                                               ServingShutdown,
                                               shed_reason)
from deeplearning4j_tpu.telemetry import timeline as _timeline
from deeplearning4j_tpu.telemetry import tracectx as _tracectx

#: the trace-propagation headers the router stamps on /submit (Dapper
#: style: the worker ADOPTS the router's trace id and parents its root
#: under the router's attempt span)
TRACE_ID_HEADER = "X-DL4J-Trace-Id"
PARENT_SPAN_HEADER = "X-DL4J-Parent-Span"
#: synthetic-traffic marker: router/supervisor health probes and the
#: prober's canaries stamp this so every wire hop counts them into
#: origin-labeled series (which the default SLO rules exclude) instead
#: of the organic ones
ORIGIN_HEADER = "X-DL4J-Origin"

#: the GET routes the wire counter buckets path labels into — an unknown
#: or mistyped path charts as "/other" instead of minting a new metric
#: series per distinct request string (label-cardinality hygiene, R13)
GET_ROUTES = ("/health", "/stats", "/usage", "/metrics", "/traces")


def _tree_to_jsonable(y):
    """Outputs as JSON-ready nested lists (dict heads for multi-output
    graphs). float32 -> Python float is exact (every float32 is a
    double), so the wire costs no precision: fleet answers can hold the
    ≤1e-6 parity gate against a single in-process engine."""
    import jax
    return jax.tree_util.tree_map(lambda a: np.asarray(a).tolist(), y)


def _rows_from_json(rows):
    """The submit payload's ``rows`` back into engine inputs: a dict is
    the multi-input pytree (per-key [n, ...] arrays), anything else one
    [n, ...] array."""
    if isinstance(rows, dict):
        return {k: np.asarray(v, dtype=np.float32) for k, v in rows.items()}
    return np.asarray(rows, dtype=np.float32)


class FleetWorker:
    """HTTP front for ONE serving engine (usable in-process for tests;
    the supervisor runs it via this module's ``main()`` in a fresh
    process). ``port=0`` binds an ephemeral port; ``self.port`` is the
    actually-bound one."""

    def __init__(self, engine, *, worker_id="w0", port=0):
        self.engine = engine
        self.worker_id = worker_id
        self._t0 = time.time()
        self._swap_lock = threading.Lock()
        self._swaps = 0
        from deeplearning4j_tpu.telemetry import get_registry
        self._reg = get_registry()
        self._m_http = self._reg.counter(
            "fleet_worker_http_total",
            "worker HTTP GETs by path and origin (health-check probes "
            "carry origin=probe, so wire-level SLIs can exclude them)")
        worker = self

        class Handler(BaseHTTPRequestHandler):
            # one request = one short-lived handler thread
            # (ThreadingHTTPServer); all shared state lives on the worker
            daemon_threads = True

            def log_message(self, *args):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b"{}"
                doc = json.loads(raw)
                if not isinstance(doc, dict):
                    raise ValueError("request body must be a JSON object")
                return doc

            def do_GET(self):
                worker._count_get(self.path,
                                  self.headers.get(ORIGIN_HEADER))
                if self.path.startswith("/health"):
                    self._json(worker.health())
                elif self.path.startswith("/stats"):
                    self._json(worker.engine.stats())
                elif self.path.startswith("/usage"):
                    # the per-model/per-tenant usage ledger (metering)
                    self._json(worker.usage())
                elif self.path.startswith("/metrics"):
                    # the federation scrape: full registry snapshot (kind
                    # + help + series) so the aggregator can re-render
                    # OpenMetrics with an added instance label, plus the
                    # clock pair for per-scrape offset estimation
                    self._json(worker.metrics())
                elif self.path.startswith("/traces"):
                    # the timeline scrape: this process's slow-trace ring
                    # in the flight-dump 'traces' shape timeline.load_file
                    # and the cluster merge both accept
                    self._json({"worker_id": worker.worker_id,
                                "pid": os.getpid(),
                                "clock": _timeline.clock_pair(),
                                "traces":
                                    _tracectx.get_ring().snapshot()})
                else:
                    self._json({"error": f"unknown path {self.path!r}"},
                               code=404)

            def do_POST(self):
                try:
                    doc = self._body()
                except (ValueError, UnicodeDecodeError) as e:
                    self._json({"error": f"bad request body: {e}"},
                               code=400)
                    return
                if self.path.startswith("/submit"):
                    self._submit(doc)
                elif self.path.startswith("/swap"):
                    self._swap(doc)
                elif self.path.startswith("/shutdown"):
                    self._json({"ok": True, "worker_id": worker.worker_id})
                    # stop AFTER the response is on the wire, off this
                    # handler thread (stop() joins the serve loop)
                    threading.Thread(target=worker.stop,
                                     daemon=True).start()
                else:
                    self._json({"error": f"unknown path {self.path!r}"},
                               code=404)

            def _submit(self, doc):
                # wire-propagated tracing: adopt the router's trace id so
                # the device-side spans (queue_wait, device_exec, ...)
                # land on ONE trace spanning both processes; the doc rides
                # the response for the router to graft into its ring
                rctx = _tracectx.maybe_start_remote(
                    "fleet.worker_submit",
                    self.headers.get(TRACE_ID_HEADER),
                    self.headers.get(PARENT_SPAN_HEADER),
                    worker=worker.worker_id)
                try:
                    rows = _rows_from_json(doc["rows"])
                    seq_len = doc.get("seq_len")
                    if seq_len is not None:
                        # a seq-aware router declares the length it
                        # batched on; cross-check against the decoded
                        # rows so routing and engine can never silently
                        # disagree about which 2-D bucket this batch is
                        lead = (next(iter(rows.values()))
                                if isinstance(rows, dict) else rows)
                        got = (int(lead.shape[1]) if lead.ndim >= 2
                               else None)
                        if got != int(seq_len):
                            raise ValueError(
                                f"payload seq_len={seq_len} disagrees "
                                f"with the rows' sequence axis ({got})")
                    deadline_ms = doc.get("deadline_ms")
                    fut = worker.engine.submit(
                        rows, batched=True,
                        deadline_s=(None if deadline_ms is None
                                    else deadline_ms / 1e3),
                        tctx=rctx,
                        # demand attribution rides the payload (header as
                        # origin fallback): tenant feeds the usage ledger,
                        # origin=probe keeps canaries out of organic SLIs
                        tenant=doc.get("tenant"),
                        origin=(doc.get("origin")
                                or self.headers.get(ORIGIN_HEADER)))
                    y = fut.get(timeout=doc.get("timeout_s", 60))
                    resp = {"outputs": _tree_to_jsonable(y),
                            "worker_id": worker.worker_id,
                            "latency_ms": (
                                None if fut.latency_s is None
                                else round(1e3 * fut.latency_s, 3))}
                    if rctx is not None:
                        # the engine finished the trace BEFORE resolving
                        # the future, so the doc here is complete; the
                        # clock pair lets the router align our timestamps
                        resp["trace"] = rctx.trace.to_doc()
                        resp["clock"] = _timeline.clock_pair()
                    self._json(resp)
                except ServingOverloaded as e:
                    # shed, not error: the front retries or counts it
                    # (structured reason — never sniffed from message
                    # text, which embeds the free-form model name)
                    if rctx is not None:
                        rctx.finish(status="shed")  # idempotent: the
                        #   engine already closed admission/deadline sheds
                    self._json({"error": "shed",
                                "reason": shed_reason(e) or "queue_full",
                                "worker_id": worker.worker_id}, code=429)
                except ServingShutdown as e:
                    if rctx is not None:
                        rctx.abandon()
                    self._json({"error": "shutdown", "detail": str(e),
                                "worker_id": worker.worker_id}, code=503)
                except (KeyError, ValueError, TypeError) as e:
                    if rctx is not None:
                        rctx.finish(status="error")
                    self._json({"error": f"bad submit: {e}",
                                "worker_id": worker.worker_id}, code=400)
                except Exception as e:  # noqa: BLE001 — wire boundary
                    if rctx is not None:
                        rctx.finish(status="error")
                    self._json({"error": f"{type(e).__name__}: {e}",
                                "worker_id": worker.worker_id}, code=500)

            def _swap(self, doc):
                try:
                    result = worker.swap(doc["model_path"],
                                         warm=doc.get("warm"))
                    self._json(result)
                except (KeyError, ValueError, OSError) as e:
                    self._json({"error": f"bad swap: {e}",
                                "worker_id": worker.worker_id}, code=400)
                except Exception as e:  # noqa: BLE001 — wire boundary
                    self._json({"error": f"{type(e).__name__}: {e}",
                                "worker_id": worker.worker_id}, code=500)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        #: the ACTUALLY-BOUND port (`port=0` requests an ephemeral one, so
        #: N workers on one host never collide)
        self.port = self._httpd.server_address[1]
        self._thread = None

    @property
    def address(self):
        return f"http://127.0.0.1:{self.port}"

    def start(self):
        if not self.engine.running:
            self.engine.start()
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()  # release the listening socket too
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.engine.stop()

    def swap(self, model_path, warm=None):
        """ModelRegistry-style hot swap from a checkpoint/bundle path:
        the replacement forward is built and warmed OFF the serving path,
        then atomically rebound (no queued request dropped). Serialized
        under a lock so two concurrent /swap posts can't interleave their
        warm/rebind windows."""
        from deeplearning4j_tpu.models.zoo import restore_checkpoint
        with self._swap_lock:
            net = restore_checkpoint(model_path)
            self.engine.update_model(net, warm=warm)
            self._swaps += 1
            return {"ok": True, "worker_id": self.worker_id,
                    "swaps": self._swaps,
                    "aot": self.engine.stats()["aot"]}

    def _count_get(self, path, origin):
        """Wire-level GET accounting: probes carry their origin label,
        organic GETs keep the unlabeled series."""
        if self._reg.enabled:
            root = "/" + (path.lstrip("/").split("?")[0].split("/")[0]
                          or "")
            root = root if root in GET_ROUTES else "/other"
            self._m_http.inc(path=root,
                             **({"origin": str(origin)} if origin else {}))

    def usage(self):
        """The /usage payload: this process's per-model/per-tenant usage
        ledger (serving/metering.py) — what fleet /health aggregation
        folds up into the offered-load-per-model signal."""
        from deeplearning4j_tpu.serving import metering as _metering
        return {"worker_id": self.worker_id, "pid": os.getpid(),
                "usage": _metering.get_meter().usage()}

    def metrics(self):
        """The /metrics payload the ``federate()`` aggregator scrapes:
        the full registry snapshot (kind/help/series — a superset of the
        ``series_map`` wire form) plus this process's clock pair."""
        from deeplearning4j_tpu.telemetry import get_registry
        return {"worker_id": self.worker_id, "pid": os.getpid(),
                "clock": _timeline.clock_pair(),
                "metrics": get_registry().snapshot()}

    def health(self):
        """The /health payload: liveness + the engine's export hook
        (stats, compile-cache events, recompile counters) — what the
        supervisor probes and the router aggregates."""
        doc = self.engine.health()
        doc.update(ok=True, worker_id=self.worker_id, pid=os.getpid(),
                   uptime_s=round(time.time() - self._t0, 3),
                   port=self.port, swaps=self._swaps)
        return doc

    def describe(self):
        """The machine-readable ready line ``main()`` prints: bound port
        + warmup counters, so a spawner can counter-assert a warm start
        (manifest hits only, zero compiles) from the line alone."""
        stats = self.engine.stats()
        from deeplearning4j_tpu.utils import compile_cache as _cc
        return {"fleet_worker_ready": True, "worker_id": self.worker_id,
                "pid": os.getpid(), "port": self.port,
                "model": self.engine.name, "buckets": stats["buckets"],
                "seq_buckets": stats.get("seq_buckets"),
                "warmup_s": stats["warmup_s"], "aot": stats["aot"],
                "compile_cache_events": _cc.event_counts(),
                # clock-alignment seed: the spawner pairs this with its
                # receipt time to place this process on the cluster
                # timeline (ISSUE 16)
                "clock": _timeline.clock_pair()}


def _build_parser():
    p = argparse.ArgumentParser(
        prog="deeplearning4j_tpu.fleet.worker",
        description="one fleet serving worker process (spawned by "
                    "FleetSupervisor; see deeplearning4j_tpu/fleet/)")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-path", help="checkpoint/bundle zip to serve")
    src.add_argument("--zoo", help="zoo model name (fresh init)")
    p.add_argument("--worker-id", default="w0")
    p.add_argument("--name", default="default", help="served model name")
    p.add_argument("--port", type=int, default=0,
                   help="HTTP port (default 0 = ephemeral; the bound "
                        "port is printed in the ready line)")
    p.add_argument("--buckets",
                   help="comma-separated batch buckets to AOT-warm")
    p.add_argument("--seq-buckets",
                   help="comma-separated sequence-length buckets: the "
                        "engine warms the full (batch x seq) grid and "
                        "pads each request to its seq bucket instead of "
                        "max_seq")
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--input-shape",
                   help="per-example feature shape, e.g. 28,28,1 "
                        "(default: derived from the model conf)")
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--deadline-ms", type=float)
    p.add_argument("--batch-window-ms", type=float, default=1.0)
    p.add_argument("--warm-manifest", metavar="PATH",
                   help="serving warm manifest: warmup deserializes "
                        "every covered bucket instead of compiling "
                        "(the zero-compile replacement contract)")
    p.add_argument("--compile-cache", metavar="DIR",
                   help="persistent XLA compilation cache directory")
    return p


def main(argv=None):
    args = _build_parser().parse_args(argv)
    from deeplearning4j_tpu import telemetry
    # one model loader and one input-spec derivation, shared with the
    # serve/fleet CLI verbs — drift between processes of one fleet would
    # be a fingerprint mismatch
    from deeplearning4j_tpu.cli import _load_model, _serve_input_spec
    from deeplearning4j_tpu.serving import ServingEngine
    from deeplearning4j_tpu.utils import compile_cache as _cc

    telemetry.enable()  # the supervisor/router read this worker's counters
    _cc.enable_persistent_cache(args.compile_cache)
    net = _load_model(args)
    buckets = ([int(b) for b in args.buckets.split(",") if b.strip()]
               if args.buckets else None)
    seq_buckets = ([int(b) for b in args.seq_buckets.split(",")
                    if b.strip()] if args.seq_buckets else None)
    engine = ServingEngine(
        net, name=args.name, input_spec=_serve_input_spec(args, net),
        buckets=buckets, seq_buckets=seq_buckets,
        max_batch_size=args.max_batch,
        max_queue=args.max_queue,
        default_deadline_s=(None if args.deadline_ms is None
                            else args.deadline_ms / 1e3),
        batch_window_s=args.batch_window_ms / 1e3,
        warm_manifest=args.warm_manifest or None)
    worker = FleetWorker(engine, worker_id=args.worker_id, port=args.port)
    worker.start()
    # ONE ready line AFTER warmup: the spawner learns the bound port and
    # can assert zero-compile warm start from the aot counters in it
    print(json.dumps(worker.describe(), default=str), flush=True)
    serve_thread = worker._thread
    try:
        while serve_thread.is_alive():  # /shutdown ends the serve loop
            serve_thread.join(timeout=1.0)
    except KeyboardInterrupt:
        worker.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
