"""Fleet admission/routing front: one door in front of N worker processes.

The router owns the fleet's admission contract (the PR 6 shed semantics,
now one level up): a bounded queue of pending EXAMPLES sheds at submit
when full (``ServingOverloaded``, ``serving_shed_total{reason=queue_full}``),
requests stale past their deadline are shed before wasting a dispatch,
and every terminal outcome is COUNTED — a request is answered, retried
onto a live worker, or counted-shed; never silently dropped.

Dispatch is load-aware continuous batching at fleet level: dispatcher
threads drain whatever is queued (one shared straggler window, like the
engine's drain), pick the live worker with the LEAST outstanding rows
whose bounded in-flight window has room, and ship the whole batch as ONE
``/submit`` round trip. A connection failure marks the worker dead
(``fleet_failover_total``) and the batch retries onto the next-best live
worker (``fleet_retry_total``) — inference is stateless, so the replay is
idempotent by construction. A worker-side 429 ``queue_full`` also
retries (another worker may have room); a worker-side ``deadline`` shed
is terminal (the request is stale everywhere).

Liveness: the router marks workers dead on dispatch failures and
:meth:`FleetRouter.health` aggregates every worker's ``/health`` (the
cross-worker aggregation the UIServer ``/fleet?probe=1`` endpoint
serves). The supervisor pushes topology changes — respawned workers
arrive via :meth:`set_endpoints` with fresh addresses under stable
worker ids, so per-worker metric labels stay bounded across respawns.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from deeplearning4j_tpu import telemetry as _tm
from deeplearning4j_tpu.fleet.worker import (ORIGIN_HEADER,
                                             PARENT_SPAN_HEADER,
                                             TRACE_ID_HEADER)
from deeplearning4j_tpu.serving.engine import (InferenceFuture,
                                               ServingOverloaded,
                                               ServingShutdown, _as_input,
                                               _origin_labels, _overloaded)
from deeplearning4j_tpu.telemetry import timeline as _timeline
from deeplearning4j_tpu.telemetry import tracectx as _tracectx


class _Worker:
    """Router-side state for one worker endpoint. ``outstanding`` (rows
    in flight to it) is the load signal; mutated only under the router
    lock."""

    __slots__ = ("wid", "address", "alive", "outstanding", "dispatched",
                 "failures", "last_error")

    def __init__(self, wid, address):
        self.wid = wid
        self.address = address
        self.alive = True
        self.outstanding = 0
        self.dispatched = 0
        self.failures = 0
        self.last_error = None

    def snapshot(self):
        return {"worker_id": self.wid, "address": self.address,
                "alive": self.alive, "outstanding_rows": self.outstanding,
                "dispatched": self.dispatched, "failures": self.failures,
                "last_error": self.last_error}


def _http_json(url, payload=None, timeout=10.0, headers=None):
    """One JSON round trip. Returns (status_code, doc); raises OSError
    family (URLError / ConnectionError / timeout) when the worker is
    unreachable — the caller's failover signal. ``headers``: extra
    request headers (the trace-propagation pair rides here)."""
    if payload is None:
        req = urllib.request.Request(url, headers=dict(headers or {}))
    else:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        # the worker is ALIVE and answered (shed/error codes carry JSON)
        try:
            doc = json.loads(e.read().decode())
        except Exception:
            doc = {"error": str(e)}
        return e.code, doc


class FleetRouter:
    """Single admission/routing front over a pool of fleet workers.

    ``submit()`` mirrors :meth:`ServingEngine.submit` (same future type,
    same shed exceptions, same batched-rows contract) so a client moves
    from one engine to a fleet without changing shape.
    """

    def __init__(self, endpoints=(), *, name="fleet", max_queue=256,
                 max_inflight_rows=64, max_dispatch_rows=32,
                 default_deadline_s=None, batch_window_s=0.0,
                 concurrency=4, retries=2, request_timeout_s=30.0,
                 probe_timeout_s=2.0, no_worker_grace_s=15.0,
                 seq_aware=False):
        self.name = name
        #: seq-aware fronts read each request's sequence length (leaf
        #: axis 1) into the entry meta, so the meta-uniform chunking seam
        #: below also makes wire chunks SEQ-uniform — a short prompt is
        #: never concatenated into (and padded up to) a long batch before
        #: it even reaches a worker's 2-D bucket grid
        self.seq_aware = bool(seq_aware)
        self.max_queue = max_queue
        self.max_inflight_rows = max_inflight_rows
        self.max_dispatch_rows = max_dispatch_rows
        self.default_deadline_s = default_deadline_s
        self.batch_window_s = batch_window_s
        self.retries = retries
        self.request_timeout_s = request_timeout_s
        self.probe_timeout_s = probe_timeout_s
        #: how long a deadline-less request may wait for ANY live worker
        #: (e.g. mid-respawn) before it is counted-shed as no_worker —
        #: the backstop that keeps "never silently dropped" true even
        #: when the whole pool is down
        self.no_worker_grace_s = no_worker_grace_s
        self._queue: queue.Queue = queue.Queue()
        self._pending_rows = 0
        self._lock = threading.Lock()
        self._workers = {}  # wid -> _Worker
        self._stop = threading.Event()
        self._threads = []
        self._counts = {"submitted": 0, "served": 0, "served_rows": 0,
                        "shed_queue_full": 0, "shed_deadline": 0,
                        "shed_no_worker": 0, "shed_worker": 0,
                        "errors": 0, "retries": 0, "failovers": 0}
        self._recent_latencies = []
        reg = self._reg = _tm.get_registry()
        self._m_requests = reg.counter(
            "fleet_requests_total",
            "fleet front requests by outcome (submitted/served/"
            "shed_queue_full/shed_deadline/shed_no_worker/shed_worker/"
            "error)")
        self._m_shed = reg.counter(
            "serving_shed_total",
            "load-shed requests per model and reason "
            "(queue_full / deadline / shutdown)")
        self._m_dispatch = reg.counter(
            "fleet_dispatch_total",
            "fleet batches shipped per worker and result (ok/shed/error)")
        self._m_retry = reg.counter(
            "fleet_retry_total",
            "fleet batches retried onto another worker, labeled by the "
            "worker that failed")
        self._m_failover = reg.counter(
            "fleet_failover_total",
            "workers marked dead by the router (dispatch/probe failures)")
        self._m_alive = reg.gauge(
            "fleet_worker_alive",
            "1 when the router considers this worker live, else 0")
        self._m_outstanding = reg.gauge(
            "fleet_outstanding_rows",
            "rows currently in flight to this worker (the load signal "
            "least-outstanding dispatch balances on)")
        self._m_depth = reg.gauge(
            "fleet_admission_queue_depth",
            "pending examples in the fleet front's bounded queue")
        self._m_p50 = reg.gauge(
            "fleet_latency_p50_seconds",
            "rolling p50 fleet request latency (submit to resolve)")
        self._m_p99 = reg.gauge(
            "fleet_latency_p99_seconds",
            "rolling p99 fleet request latency (submit to resolve)")
        self._m_latency = reg.histogram(
            "fleet_request_latency_seconds",
            "fleet submit-to-resolve request latency")
        if reg.enabled:
            # pre-register every outcome series at zero (the prober
            # idiom): a shed/error series born mid-storm contributes
            # nothing to the SLO delta window it first appears in
            for outcome in ("submitted", "served", "shed_queue_full",
                            "shed_deadline", "shed_no_worker",
                            "shed_worker", "error"):
                self._m_requests.inc(0, outcome=outcome)
        self.set_endpoints(endpoints)
        for i in range(concurrency):
            t = threading.Thread(target=self._dispatch_loop,
                                 name=f"fleet-dispatch-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    # ---- topology ----

    def set_endpoints(self, endpoints):
        """Replace the worker set. ``endpoints``: iterable of addresses
        or ``(worker_id, address)`` pairs (the supervisor pushes pairs so
        metric labels stay stable across respawns — a respawned worker
        keeps its id under a fresh address, and arrives alive again)."""
        pairs = []
        for i, e in enumerate(endpoints):
            if isinstance(e, str):
                pairs.append((f"w{i}", e))
            else:
                pairs.append((str(e[0]), str(e[1])))
        with self._lock:
            fresh = {}
            for wid, addr in pairs:
                prev = self._workers.get(wid)
                if prev is not None and prev.address == addr:
                    fresh[wid] = prev  # same process: keep its state
                else:
                    fresh[wid] = _Worker(wid, addr)
            self._workers = fresh
            snapshot = list(fresh.values())
        if self._reg.enabled:
            for w in snapshot:
                self._m_alive.set(1.0 if w.alive else 0.0, worker=w.wid)
                self._m_outstanding.set(w.outstanding, worker=w.wid)

    def endpoints(self):
        with self._lock:
            return [(w.wid, w.address) for w in self._workers.values()]

    def mark_dead(self, wid, error=None):
        """Mark one worker dead (router-observed failure or an external
        liveness verdict, e.g. the supervisor's probe loop)."""
        with self._lock:
            w = self._workers.get(wid)
            if w is None or not w.alive:
                return
            w.alive = False
            w.failures += 1
            w.last_error = None if error is None else str(error)[:300]
            self._counts["failovers"] += 1
        if self._reg.enabled:
            self._m_failover.inc(worker=wid)
            self._m_alive.set(0.0, worker=wid)

    def mark_alive(self, wid):
        """Revive one worker — the recovery path for a false-positive
        ``mark_dead`` (a transient stall/timeout must not shrink the
        pool forever). Called by a successful ``health()`` probe and by
        the supervisor's probe loop on every healthy answer."""
        with self._lock:
            w = self._workers.get(wid)
            if w is None or w.alive:
                return
            w.alive = True
            w.last_error = None
        if self._reg.enabled:
            self._m_alive.set(1.0, worker=wid)

    # ---- request path ----

    def submit(self, x, deadline_s=None, *, batched=False, tenant=None,
               origin=None):
        """Queue one example (or one multi-example batch with
        ``batched=True``); returns an :class:`InferenceFuture`. Admission
        bounds queued EXAMPLES exactly like the engine's submit: a full
        front sheds here rather than queueing without bound.

        ``tenant``/``origin`` ride the wire to the worker engine
        (demand attribution / synthetic-traffic marking): a probe-origin
        request counts into origin-labeled series (excluded by every
        default SLO rule) and never enters the front's rolling p50/p99
        ring; a tenant feeds the per-tenant usage ledger worker-side."""
        if self._stop.is_set():
            raise ServingShutdown(
                f"fleet router {self.name!r} is stopped")
        meta = None
        if tenant is not None or origin is not None:
            meta = {"tenant": tenant, "origin": origin}
        olab = {"origin": str(origin)} if origin else {}
        item = _as_input(x)
        if batched:
            dims = {(int(np.shape(l)[0]) if np.ndim(l) else -1)
                    for l in _leaves(item)}
            if len(dims) != 1 or -1 in dims:
                raise ValueError(
                    "batched submit requires every input leaf to carry "
                    "the examples on axis 0 with one shared length; got "
                    f"leading dims {sorted(dims)}")
            nrows = dims.pop()
            if nrows == 0:
                raise ValueError("batched submit requires at least one "
                                 "example (got a 0-row batch)")
            if nrows > self.max_queue:
                raise ValueError(
                    f"batched submit of {nrows} rows exceeds the "
                    f"admission bound (max_queue={self.max_queue})")
        else:
            nrows = None
            item = _tree_map(lambda a: a[None], item)
        rows = 1 if nrows is None else nrows
        if self.seq_aware:
            lead = _leaves(item)[0]
            if np.ndim(lead) < 2:
                raise ValueError(
                    f"fleet {self.name!r} is seq-aware but the input "
                    f"carries no sequence axis (leaf shape "
                    f"{tuple(np.shape(lead))})")
            # seq rides the entry meta: chunk assembly compares meta for
            # uniformity, so co-drained entries with different lengths
            # ship as separate wire payloads (each rectangular as-is)
            meta = dict(meta or {}, seq=int(np.shape(lead)[1]))
        fut = InferenceFuture()
        # the fleet-level causal trace roots HERE: dispatch attempts and
        # the worker-side device spans (grafted from the /submit response)
        # all hang under this one trace id. Tracing off: None, a branch.
        tctx = _tracectx.maybe_start("fleet.request", model=self.name)
        if tctx is not None:
            fut.trace_id = tctx.trace_id
        now = time.perf_counter()
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        deadline = None if deadline_s is None else now + deadline_s
        self._count("submitted")
        if self._reg.enabled:
            self._m_requests.inc(outcome="submitted", **olab)
        with self._lock:
            if self._pending_rows + rows > self.max_queue:
                full = True
            else:
                full = False
                self._pending_rows += rows
        if full:
            self._count("shed_queue_full")
            if self._reg.enabled:
                self._m_shed.inc(model=self.name, reason="queue_full",
                                 **olab)
                self._m_requests.inc(outcome="shed_queue_full", **olab)
            if tctx is not None:
                tctx.add_span("fleet.shed", now, time.perf_counter(),
                              reason="queue_full")
                tctx.finish(status="shed")
            raise _overloaded(
                f"fleet {self.name!r}: admission queue full "
                f"({self.max_queue} pending)", "queue_full")
        self._queue.put((item, fut, now, deadline,
                         None if tctx is None else tctx.handoff(), nrows,
                         meta))
        if self._stop.is_set():
            # raced stop(): its drain may already be done — fail
            # stragglers rather than hang their waiters
            self._fail_pending()
        if self._reg.enabled:
            self._m_depth.set(self._pending_rows)
        return fut

    def output(self, x, deadline_s=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(x, deadline_s=deadline_s).get(
            timeout=self.request_timeout_s)

    # ---- dispatch ----

    def _take(self, block=True, timeout=None):
        item = self._queue.get(block=block, timeout=timeout)
        with self._lock:
            self._pending_rows -= item[5] or 1
        return item

    def _drain(self):
        """Fleet-level continuous batching: block briefly for the first
        entry, then take everything queued (no per-slot waits), bounded
        by ``max_dispatch_rows`` per shipped batch — and never assembled
        past the per-worker in-flight window, or the batch could fit on
        no worker and spin forever."""
        cap = min(self.max_dispatch_rows, self.max_inflight_rows)

        def rows(b):
            return sum(it[5] or 1 for it in b)
        try:
            batch = [self._take(timeout=0.05)]
        except queue.Empty:
            return []
        try:
            while rows(batch) < cap:
                batch.append(self._take(block=False))
        except queue.Empty:
            if self.batch_window_s > 0:
                deadline = time.perf_counter() + self.batch_window_s
                while rows(batch) < cap:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(self._take(timeout=remaining))
                    except queue.Empty:
                        break
        return batch

    def _shed(self, entries, reason, exc_msg):
        """Terminal counted shed for a batch of entries — the 'never
        silently dropped' contract's third leg."""
        err = _overloaded(exc_msg, reason)
        now = time.perf_counter()
        for _x, fut, _t, _dl, tctx, _n, _meta in entries:
            if tctx is not None:
                # close the trace BEFORE waking the waiter: a shed is a
                # terminal outcome worth ringing (the overload p99 story)
                tctx.add_span("fleet.shed", now, now, reason=reason)
                tctx.finish(status="shed")
            if not fut.done():
                fut._set_error(err)
        n = len(entries)
        count_key = (f"shed_{reason}" if reason in
                     ("queue_full", "deadline", "no_worker") else
                     "shed_worker")
        self._count(count_key, n)
        if self._reg.enabled:
            metric_reason = {"no_worker": "no_worker",
                             "deadline": "deadline",
                             "queue_full": "queue_full"}.get(reason,
                                                            "worker_shed")
            # per entry, not bulk: synthetic entries shed into their own
            # origin-labeled series (organic shed SLIs stay untouched)
            for e in entries:
                olab = _origin_labels(e[6])
                self._m_shed.inc(model=self.name, reason=metric_reason,
                                 **olab)
                self._m_requests.inc(outcome=count_key, **olab)

    def _pick_worker(self, rows, exclude):
        """Least-outstanding live worker whose in-flight window has room
        for ``rows`` more; reserves the rows before returning (released
        by ``_release``). None when no such worker exists right now."""
        with self._lock:
            best = None
            for w in self._workers.values():
                if not w.alive or w.wid in exclude:
                    continue
                if w.outstanding + rows > self.max_inflight_rows \
                        and not (w.outstanding == 0
                                 and rows > self.max_inflight_rows):
                    # window full — except a single batched submit wider
                    # than the window itself, which ships alone to an
                    # IDLE worker (it could never fit otherwise)
                    continue
                if best is None or w.outstanding < best.outstanding:
                    best = w
            if best is not None:
                best.outstanding += rows
                out = best.outstanding
        if best is not None and self._reg.enabled:
            self._m_outstanding.set(out, worker=best.wid)
        return best

    def _release(self, w, rows):
        with self._lock:
            w.outstanding -= rows
            out = w.outstanding
        if self._reg.enabled:
            self._m_outstanding.set(out, worker=w.wid)

    def _any_alive(self):
        with self._lock:
            return any(w.alive for w in self._workers.values())

    def _dispatch_loop(self):
        while not self._stop.is_set():
            batch = self._drain()
            if not batch:
                continue
            now = time.perf_counter()
            live = []
            for entry in batch:
                _x, fut, t_sub, deadline, _tc, _n, _meta = entry
                if deadline is not None and now > deadline:
                    self._shed([entry], "deadline",
                               f"fleet {self.name!r}: deadline exceeded "
                               f"while queued "
                               f"({1e3 * (now - t_sub):.1f} ms)")
                    continue
                live.append(entry)
            if self._reg.enabled:
                self._m_depth.set(self._pending_rows)
            # ship in window-sized chunks: a drained multi-row (batched)
            # entry can push the assembly past max_inflight_rows, and an
            # over-window batch only ever fits an IDLE worker — chunking
            # keeps the co-drained single-row entries from becoming its
            # hostages (an indivisible over-window entry still ships
            # alone via _pick_worker's idle exception)
            chunk, chunk_rows, chunk_meta = [], 0, None
            for entry in live:
                r = entry[5] or 1
                # one wire payload carries ONE (tenant, origin) pair, so
                # a chunk must be meta-uniform: co-drained entries with a
                # different attribution start a fresh chunk rather than
                # inherit the lead entry's identity
                if chunk and (chunk_rows + r > self.max_inflight_rows
                              or entry[6] != chunk_meta):
                    self._dispatch(chunk)
                    chunk, chunk_rows = [], 0
                if not chunk:
                    chunk_meta = entry[6]
                chunk.append(entry)
                chunk_rows += r
            if chunk:
                self._dispatch(chunk)

    def _note_attempt(self, entries, wid, attempt, outcome, t0,
                      graft_doc=None, offset_s=0.0, **args):
        """Stamp one dispatch attempt as a child span on EVERY member
        trace — retries/failovers ride the SAME trace as numbered
        attempt spans, and a 200's worker-side trace doc grafts in under
        its attempt, giving the ring one admission→dispatch→worker-device
        →resolve story per request."""
        t1 = time.perf_counter()
        for _x, _f, _t, _dl, tctx, _n, _meta in entries:
            if tctx is None:
                continue
            span = tctx.add_span("fleet.attempt", t0, t1, worker=wid,
                                 attempt=attempt, outcome=outcome, **args)
            if graft_doc is not None:
                tctx.trace.graft(graft_doc, span["span_id"],
                                 offset_s=offset_s, instance=wid)

    def _dispatch(self, entries):
        """Ship one assembled batch, retrying across workers. Exits with
        every entry's future resolved (answer / shed / error)."""
        rows = sum(e[5] or 1 for e in entries)
        xs = _tree_map(lambda *leaves: np.concatenate(leaves),
                       *[e[0] for e in entries])
        # the batch's effective deadline is its EARLIEST member's
        deadlines = [e[3] for e in entries if e[3] is not None]
        deadline = min(deadlines) if deadlines else None
        t_disp = time.perf_counter()
        for _x, _f, t_sub, _dl, tctx, _n, _meta in entries:
            if tctx is not None:
                # fleet-level queue wait, distinct from the worker-side
                # serving.queue_wait that grafts in after dispatch
                tctx.add_span("fleet.queue_wait", t_sub, t_disp)
        attempt = 0
        tried = set()
        t_wait0 = time.perf_counter()
        # chunks are meta-uniform, so the lead entry speaks for the batch
        meta = entries[0][6] or {}
        span_args = ({} if meta.get("seq") is None
                     else {"seq_len": meta["seq"]})
        while True:
            if self._stop.is_set():
                self._fail_entries(entries, ServingShutdown(
                    f"fleet router {self.name!r} stopped before "
                    f"dispatching this request"))
                return
            remaining = (None if deadline is None
                         else deadline - time.perf_counter())
            if remaining is not None and remaining <= 0:
                self._shed(entries, "deadline",
                           f"fleet {self.name!r}: deadline exceeded "
                           f"before a worker could serve the request")
                return
            w = self._pick_worker(rows, tried)
            if w is None:
                if tried and not self._untried_alive(tried):
                    # every live worker already failed or shed THIS
                    # batch: terminal counted shed (a retry loop over
                    # the same pool would spin, not help)
                    self._shed(entries, "no_worker" if not
                               self._any_alive() else "worker",
                               f"fleet {self.name!r}: every live worker "
                               f"failed or shed this request")
                    return
                if (not self._any_alive()
                        and time.perf_counter() - t_wait0
                        > self.no_worker_grace_s):
                    # whole pool down past the grace window (respawns
                    # take seconds, not this long): counted shed
                    self._shed(entries, "no_worker",
                               f"fleet {self.name!r}: no live worker "
                               f"within {self.no_worker_grace_s:.1f}s")
                    return
                # window full / mid-respawn: wait briefly for capacity
                time.sleep(0.005)
                continue
            attempt += 1
            t_att = time.perf_counter()
            sent_unix = time.time()
            try:
                payload = {"rows": _tree_map(lambda a: a.tolist(), xs)}
                if remaining is not None:
                    payload["deadline_ms"] = max(1e3 * remaining, 1.0)
                # demand attribution rides the payload
                if meta.get("tenant") is not None:
                    payload["tenant"] = meta["tenant"]
                if meta.get("origin") is not None:
                    payload["origin"] = meta["origin"]
                if meta.get("seq") is not None:
                    # the seq length the router batched on, declared so
                    # the worker can cross-check it against the rows it
                    # decodes (routing/metering/trace all see ONE bucket)
                    payload["seq_len"] = meta["seq"]
                timeout = self.request_timeout_s
                if remaining is not None:
                    timeout = min(timeout, remaining + 5.0)
                # ONE trace carrier per wire hop: the worker roots a
                # single remote-parented trace under the first entry's
                # identity, and the returned doc grafts into EVERY
                # member's trace (the batch is one device-side event)
                lead = entries[0][4]
                headers = (None if lead is None else
                           {TRACE_ID_HEADER: lead.trace_id,
                            PARENT_SPAN_HEADER: str(lead.span_id)})
                code, doc = _http_json(w.address + "/submit", payload,
                                       timeout=timeout, headers=headers)
            except Exception as e:  # noqa: BLE001 — connection failure
                # the failover leg: worker unreachable mid-request
                self._release(w, rows)
                self.mark_dead(w.wid, error=e)
                tried.add(w.wid)
                self._count("retries")
                if self._reg.enabled:
                    self._m_retry.inc(worker=w.wid)
                    self._m_dispatch.inc(worker=w.wid, result="error")
                self._note_attempt(entries, w.wid, attempt, "error",
                                   t_att, error=str(e)[:120])
                continue  # idempotent replay onto the next-best worker
            recv_unix = time.time()
            self._release(w, rows)
            with self._lock:
                w.dispatched += 1
            if code == 200:
                if self._reg.enabled:
                    self._m_dispatch.inc(worker=w.wid, result="ok")
                # clock offset from THIS round trip (NTP single sample,
                # clamped to 0 inside the RTT uncertainty) re-anchors the
                # worker's span timestamps onto our timeline
                offset_s, _unc = _timeline.estimate_offset(
                    (doc.get("clock") or {}).get("unix"),
                    sent_unix, recv_unix)
                self._note_attempt(entries, w.wid, attempt, "ok", t_att,
                                   graft_doc=doc.get("trace"),
                                   offset_s=offset_s, **span_args)
                self._resolve(entries, doc)
                return
            if code == 429:
                if self._reg.enabled:
                    self._m_dispatch.inc(worker=w.wid, result="shed")
                self._note_attempt(entries, w.wid, attempt, "shed",
                                   t_att, reason=doc.get("reason"))
                if doc.get("reason") == "deadline":
                    # stale everywhere — retrying cannot help
                    self._shed(entries, "deadline",
                               f"fleet {self.name!r}: worker "
                               f"{w.wid} shed the request (deadline)")
                    return
                # that worker's queue is full; another may have room
                tried.add(w.wid)
                self._count("retries")
                if self._reg.enabled:
                    self._m_retry.inc(worker=w.wid)
                if not self._untried_alive(tried):
                    self._shed(entries, "worker",
                               f"fleet {self.name!r}: every live worker "
                               f"shed the request (queue_full)")
                    return
                continue
            if code == 503:
                # stopping worker: treat like a dead one and fail over
                self.mark_dead(w.wid, error="worker shutting down")
                tried.add(w.wid)
                self._count("retries")
                if self._reg.enabled:
                    self._m_retry.inc(worker=w.wid)
                    self._m_dispatch.inc(worker=w.wid, result="error")
                self._note_attempt(entries, w.wid, attempt, "shutdown",
                                   t_att)
                continue
            # 4xx/5xx: a real error answer — the request itself is bad
            # or the model failed; replaying identical bytes would fail
            # identically, so propagate (counted, never silent)
            if self._reg.enabled:
                self._m_dispatch.inc(worker=w.wid, result="error")
            self._note_attempt(entries, w.wid, attempt, "error", t_att,
                               code=code)
            self._fail_entries(entries, RuntimeError(
                f"fleet worker {w.wid} answered {code}: "
                f"{doc.get('error', doc)}"))
            return

    def _untried_alive(self, tried):
        with self._lock:
            return any(w.alive and w.wid not in tried
                       for w in self._workers.values())

    def _resolve(self, entries, doc):
        # arrays FIRST: raw JSON nested lists would explode into
        # per-scalar leaves under tree_map (a dict stays the multi-output
        # pytree, each head one [n, ...] array)
        outputs = doc.get("outputs")
        if isinstance(outputs, dict):
            outputs = {k: np.asarray(v) for k, v in outputs.items()}
        else:
            outputs = np.asarray(outputs)
        done = time.perf_counter()
        off = 0
        lats, origins = [], []
        for _x, fut, t_sub, _dl, tctx, n, meta in entries:
            width = n or 1
            y = _tree_map(
                lambda a: (a[off:off + width] if n is not None
                           else a[off]), outputs)
            off += width
            lats.append(done - t_sub)
            origins.append((meta or {}).get("origin"))
            if tctx is not None:
                tctx.add_span("fleet.resolve", done, time.perf_counter())
                tctx.finish()
            fut.latency_s = done - t_sub
            # resolve LAST: a waiter that wakes here must see a COMPLETE
            # trace in the ring (same discipline as the engine's worker)
            fut._set(y)
        # accounting is in REQUESTS (submit calls) everywhere, so
        # submitted == served + shed_* + errors balances for batched
        # submits too; rows ride separately as served_rows
        self._count("served", len(entries))
        self._count("served_rows", sum(e[5] or 1 for e in entries))
        self._note_latencies(lats, origins=origins)
        if self._reg.enabled:
            for e in entries:
                self._m_requests.inc(outcome="served",
                                     **_origin_labels(e[6]))

    def _fail_entries(self, entries, err, count_key="errors"):
        for _x, fut, _t, _dl, tctx, _n, meta in entries:
            if tctx is not None:
                tctx.finish(status="error")
            if not fut.done():
                fut._set_error(err)
            if self._reg.enabled:
                self._m_requests.inc(outcome="error",
                                     **_origin_labels(meta))
        self._count(count_key, len(entries))

    def _fail_pending(self):
        err = ServingShutdown(
            f"fleet router {self.name!r} stopped before serving this "
            f"request")
        while True:
            try:
                _x, fut, _t, _dl, tctx, _n, _meta = self._take(block=False)
            except queue.Empty:
                break
            if tctx is not None:
                # never completed its causal story — don't ring it
                tctx.abandon()
            if not fut.done():
                fut._set_error(err)
                self._count("errors")
                if self._reg.enabled:
                    self._m_shed.inc(model=self.name, reason="shutdown")

    def _count(self, key, n=1):
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def _note_latencies(self, lats, origins=None):
        """Synthetic requests (``origins`` aligned with ``lats``) observe
        into origin-labeled histogram series but never enter the rolling
        p50/p99 ring — same isolation discipline as the engine's."""
        organic = [dt for i, dt in enumerate(lats)
                   if not (origins and origins[i])]
        with self._lock:
            self._recent_latencies.extend(organic)
            del self._recent_latencies[:-512]
            recent = list(self._recent_latencies)
        if self._reg.enabled:
            for i, dt in enumerate(lats):
                self._m_latency.observe(
                    dt, **({"origin": str(origins[i])}
                           if origins and origins[i] else {}))
            if recent:
                self._m_p50.set(float(np.percentile(recent, 50)))
                self._m_p99.set(float(np.percentile(recent, 99)))

    # ---- lifecycle / status ----

    def stop(self):
        """Stop dispatching and FAIL every pending request promptly —
        a stopped front must not leave waiters blocked."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []
        self._fail_pending()

    def health(self):
        """Cross-worker /health aggregation: every worker probed live,
        CONCURRENTLY (a dead worker costs one probe timeout total, not
        one per worker — this runs inside the UIServer's single-threaded
        /fleet?probe=1 handler). A healthy answer revives a worker the
        router had written off; an unreachable one is marked dead and
        appears with ``ok: false``. Probes are stamped ``origin=probe``
        on the wire, so worker-side accounting never mistakes them for
        organic traffic; each worker's usage-ledger slice is folded into
        a per-model ``usage`` aggregate (the fleet-wide demand signal)."""
        eps = self.endpoints()
        slots = [None] * len(eps)

        def probe(i, wid, addr):
            try:
                _code, doc = _http_json(addr + "/health",
                                        timeout=self.probe_timeout_s,
                                        headers={ORIGIN_HEADER: "probe"})
                slots[i] = doc  # each thread owns exactly slot i
                self.mark_alive(wid)
            except Exception as e:  # noqa: BLE001 — probe failure
                self.mark_dead(wid, error=e)
                slots[i] = {"ok": False, "error": str(e)[:300]}

        threads = [threading.Thread(target=probe, args=(i, wid, addr),
                                    daemon=True)
                   for i, (wid, addr) in enumerate(eps)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.probe_timeout_s + 1.0)
        out = {wid: (slots[i] if slots[i] is not None
                     else {"ok": False, "error": "probe hung"})
               for i, (wid, _addr) in enumerate(eps)}
        alive = sum(1 for doc in out.values() if doc.get("ok"))
        usage = {}
        for doc in out.values():
            model = (doc.get("stats") or {}).get("model")
            if model and isinstance(doc.get("usage"), dict):
                _merge_usage(usage.setdefault(model, {}), doc["usage"])
        return {"workers": out, "alive": alive, "total": len(out),
                "usage": usage}

    def federated_metrics(self, timeout_s=None):
        """One scrape for the whole fleet: every worker's ``/metrics``
        merged under stable ``instance`` labels (the worker ids the
        supervisor keeps across respawns). Dead members are counted
        (``federate_scrape_total{outcome="error"}``), never a hang —
        the aggregation semantics of telemetry.federate."""
        from deeplearning4j_tpu.telemetry import federate as _fed
        targets = [(wid, addr + "/metrics")
                   for wid, addr in self.endpoints()]
        return _fed.federate(
            targets, timeout_s=timeout_s or self.probe_timeout_s)

    def timeline_sources(self, timeout_s=None, include_local=True):
        """Per-process timeline sources for the cluster merge: this
        router's own ring plus every worker's ``/traces`` scrape, each
        worker's clock offset estimated from ITS scrape round trip. A
        dead worker simply contributes no source (the merge proceeds —
        its last traces still arrive via flight dumps postmortem)."""
        timeout = timeout_s or self.probe_timeout_s
        eps = self.endpoints()
        slots = [None] * len(eps)

        def scrape(i, wid, addr):
            sent = time.time()
            try:
                _code, doc = _http_json(addr + "/traces", timeout=timeout)
            except Exception:  # noqa: BLE001 — dead member, no source
                return
            off, _unc = _timeline.estimate_offset(
                (doc.get("clock") or {}).get("unix"), sent, time.time())
            slots[i] = _timeline.source(wid, doc.get("traces") or {},
                                        clock_offset_s=off)

        threads = [threading.Thread(target=scrape, args=(i, wid, addr),
                                    daemon=True)
                   for i, (wid, addr) in enumerate(eps)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout + 1.0)
        sources = []
        if include_local:
            sources.append(_timeline.source(
                f"router:pid{os.getpid()}",
                _tracectx.get_ring().snapshot()))
        sources.extend(s for s in slots if s is not None)
        return sources

    def latency_percentiles(self):
        with self._lock:
            recent = list(self._recent_latencies)
        if not recent:
            return None, None
        return (float(np.percentile(recent, 50)),
                float(np.percentile(recent, 99)))

    def slo_snapshot(self):
        """The hedging-policy seam: per-worker burn signals (outstanding
        rows = the queue_wait pressure a request would join, liveness,
        dispatch/failure history) + the front's live shed ratio + the
        SLO engine's serving-tagged verdicts, one read-only doc. Inert
        today — a future hedge policy decides 'queued behind a slow
        member' vs 'the model is just slow' from exactly these signals
        (the per-attempt queue_wait/device_exec spans PR 16 grafts give
        the per-request version; this is the steady-state one)."""
        from deeplearning4j_tpu.telemetry import slo as _slo
        with self._lock:
            counts = dict(self._counts)
            workers = {w.wid: {"alive": w.alive,
                               "outstanding": w.outstanding,
                               "dispatched": w.dispatched,
                               "failures": w.failures}
                       for w in self._workers.values()}
            pending = self._pending_rows
        submitted = counts.get("submitted", 0)
        shed = sum(v for k, v in counts.items() if k.startswith("shed_"))
        p50, p99 = self.latency_percentiles()
        return {"model": self.name,
                "queue_depth": pending,
                "submitted": submitted,
                "shed": shed,
                "shed_ratio": (shed / submitted) if submitted else 0.0,
                "latency_s": {"p50": p50, "p99": p99},
                "workers": workers,
                "alerts": _slo.alerts(tag="serving")}

    def stats(self):
        """The fleet front's status payload (rides /fleet)."""
        with self._lock:
            counts = dict(self._counts)
            workers = [w.snapshot() for w in self._workers.values()]
            pending = self._pending_rows
        p50, p99 = self.latency_percentiles()
        return {
            "name": self.name,
            "max_queue": self.max_queue,
            "max_inflight_rows": self.max_inflight_rows,
            "queue_depth": pending,
            "requests": counts,
            "workers": workers,
            "latency_ms": {
                "p50": None if p50 is None else round(1e3 * p50, 3),
                "p99": None if p99 is None else round(1e3 * p99, 3)},
        }


def _merge_usage(dst, src):
    """Fold one worker's usage-ledger slice (numeric fields + a
    ``tenants`` breakdown) into the fleet aggregate, in place."""
    for k, v in src.items():
        if isinstance(v, (int, float)):
            dst[k] = dst.get(k, 0) + v
        elif k == "tenants" and isinstance(v, dict):
            tenants = dst.setdefault("tenants", {})
            for tenant, fields in v.items():
                _merge_usage(tenants.setdefault(tenant, {}), fields)
    return dst


def _leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)


def _tree_map(fn, *trees):
    import jax
    return jax.tree_util.tree_map(fn, *trees)
