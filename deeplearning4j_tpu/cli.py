"""Command-line entry points: data-parallel training + UI server + bench.

Reference analog: parallelism/main/ParallelWrapperMain.java (JCommander
flags --modelPath/--workers/--averagingFrequency/--modelOutputPath/--uiUrl)
and PlayUIServer's CLI. Invoke as::

    python -m deeplearning4j_tpu train --model-path ckpt.zip \\
        --data features.npy --labels labels.npy --epochs 2 \\
        --averaging-frequency 5 --model-output-path out.zip
    python -m deeplearning4j_tpu train --zoo lenet --data x.npy --labels y.npy
    python -m deeplearning4j_tpu ui --port 9000
    python -m deeplearning4j_tpu serve --model-path ckpt.zip --max-batch 32
    python -m deeplearning4j_tpu bench lenet

"workers" in the reference = replica threads on N GPUs; here the worker
count IS the mesh data axis (defaults to every local device), and
averaging-frequency selects between the per-step gradient-sharing master
(frequency 1, exact psum) and the local-SGD parameter-averaging master
(frequency k > 1) — the same semantics ParallelWrapper exposes.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def _build_parser():
    p = argparse.ArgumentParser(
        prog="deeplearning4j_tpu",
        description="TPU-native dl4j: train / serve UI / bench")
    sub = p.add_subparsers(dest="command", required=True)

    def add_compile_cache(sp):
        sp.add_argument(
            "--compile-cache", metavar="DIR",
            help="persistent XLA compilation cache directory "
                 "(utils/compile_cache): every jit in the process reuses "
                 "on-disk compilations across restarts; defaults to "
                 "$DL4J_TPU_COMPILE_CACHE when set")

    t = sub.add_parser("train", help="data-parallel training over the mesh")
    add_compile_cache(t)
    src = t.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-path", help="checkpoint zip to resume")
    src.add_argument("--zoo", help="zoo model name (e.g. lenet)")
    t.add_argument("--data", required=True,
                   help=".npy features, or a labelled .csv/.dat file")
    t.add_argument("--labels", help=".npy labels (one-hot); unused for CSV")
    t.add_argument("--label-column", type=int, default=-1,
                   help="CSV label column (default: last)")
    t.add_argument("--n-classes", type=int,
                   help="one-hot CSV labels to this many classes")
    t.add_argument("--skip-lines", type=int, default=0,
                   help="CSV header lines to skip")
    t.add_argument("--epochs", type=int, default=1)
    t.add_argument("--workers", type=int, default=0,
                   help="mesh data-axis size (0 = all local devices)")
    t.add_argument("--batch-size-per-worker", type=int, default=32)
    t.add_argument("--averaging-frequency", type=int, default=1,
                   help="1 = per-step gradient psum; k>1 = local SGD with "
                        "parameter averaging every k steps")
    t.add_argument("--no-average-updaters", action="store_true")
    t.add_argument("--model-output-path", help="save checkpoint here")
    t.add_argument("--ui-port", type=int,
                   help="start the training dashboard on this port")
    t.add_argument("--report-score", action="store_true")

    u = sub.add_parser("ui", help="standalone training dashboard server")
    u.add_argument("--port", type=int, default=9000)

    sv = sub.add_parser(
        "serve",
        help="production inference server: continuous batching over "
             "AOT-warmed shape buckets, bounded admission queue with "
             "load shedding, /serving status on the dashboard port")
    add_compile_cache(sv)
    sv.add_argument("--warm-manifest", metavar="PATH",
                    help="warm AOT manifest (utils/compile_cache "
                         "WarmManifest zip): when PATH exists, warmup "
                         "DESERIALIZES each bucket's executable instead "
                         "of compiling — zero compiles on a warm restart; "
                         "the manifest is (re)saved to PATH after warmup "
                         "so the next restart covers every bucket")
    svsrc = sv.add_mutually_exclusive_group(required=True)
    svsrc.add_argument("--model-path", help="checkpoint zip to serve")
    svsrc.add_argument("--zoo", help="zoo model name (fresh init)")
    sv.add_argument("--name", default="default",
                    help="model name in the registry (default: 'default')")
    sv.add_argument("--max-batch", type=int, default=32,
                    help="largest serving batch (= largest bucket)")
    sv.add_argument("--buckets",
                    help="comma-separated batch buckets to AOT-warm "
                         "(default: powers of two up to --max-batch)")
    sv.add_argument("--input-shape",
                    help="per-example feature shape, e.g. 28,28,1 "
                         "(default: derived from the model's input type)")
    sv.add_argument("--max-queue", type=int, default=256,
                    help="admission queue bound; a full queue sheds "
                         "requests with ServingOverloaded")
    sv.add_argument("--deadline-ms", type=float,
                    help="default request deadline; requests stale in the "
                         "queue past this are shed, not served")
    sv.add_argument("--batch-window-ms", type=float, default=2.0,
                    help="max extra wait to fill a batch once at least "
                         "one request is in hand (ONE shared deadline)")
    sv.add_argument("--port", type=int, default=9000,
                    help="dashboard/status port (/serving, /metrics)")
    sv.add_argument("--smoke", type=int, metavar="N",
                    help="serve N synthetic requests, print the stats, "
                         "and exit (CI smoke mode)")

    fl = sub.add_parser(
        "fleet",
        help="multi-process serving fleet (fleet/): N worker processes "
             "from one checkpoint + warm manifest behind one admission/"
             "routing front with elastic worker replacement; /fleet "
             "status on the dashboard port")
    add_compile_cache(fl)
    flsrc = fl.add_mutually_exclusive_group(required=True)
    flsrc.add_argument("--model-path", help="checkpoint zip every worker "
                                            "serves")
    flsrc.add_argument("--zoo", help="zoo model name (fresh init per "
                                     "worker)")
    fl.add_argument("--workers", type=int, default=2,
                    help="worker processes to spawn (default 2)")
    fl.add_argument("--name", default="default",
                    help="served model name (default: 'default')")
    fl.add_argument("--max-batch", type=int, default=32)
    fl.add_argument("--buckets",
                    help="comma-separated batch buckets each worker "
                         "AOT-warms (default: powers of two up to "
                         "--max-batch)")
    fl.add_argument("--input-shape",
                    help="per-example feature shape, e.g. 28,28,1 "
                         "(default: derived from the model conf)")
    fl.add_argument("--warm-manifest", metavar="PATH",
                    help="serving warm manifest every worker (and every "
                         "elastic REPLACEMENT) restores executables "
                         "from — the zero-compile respawn contract")
    fl.add_argument("--max-queue", type=int, default=256,
                    help="front admission bound (queued examples); a "
                         "full front sheds with ServingOverloaded")
    fl.add_argument("--max-inflight", type=int, default=64,
                    help="per-worker bounded in-flight window (rows)")
    fl.add_argument("--deadline-ms", type=float,
                    help="default request deadline (front AND workers "
                         "shed stale requests)")
    fl.add_argument("--port", type=int, default=9000,
                    help="dashboard/status port (/fleet, /metrics)")
    fl.add_argument("--smoke", type=int, metavar="N",
                    help="serve N synthetic requests through the fleet, "
                         "print the front + worker status, and exit")

    e = sub.add_parser("eval", help="evaluate a checkpoint on a dataset")
    add_compile_cache(e)
    esrc = e.add_mutually_exclusive_group(required=True)
    esrc.add_argument("--model-path", help="checkpoint zip")
    esrc.add_argument("--zoo", help="zoo model name (fresh init)")
    e.add_argument("--data", required=True,
                   help=".npy features, or a labelled .csv/.dat file")
    e.add_argument("--label-column", type=int, default=-1)
    e.add_argument("--n-classes", type=int)
    e.add_argument("--skip-lines", type=int, default=0)
    e.add_argument("--labels",
                   help=".npy labels (one-hot or class indices); "
                        "unused for CSV")
    e.add_argument("--batch-size", type=int, default=128)
    e.add_argument("--regression", action="store_true",
                   help="report regression metrics instead of classification")

    b = sub.add_parser("bench", help="run a BASELINE.md bench config")
    b.add_argument("config", nargs="?", default="all")

    cn = sub.add_parser(
        "continuous",
        help="continuous-learning loop (continuous/): streaming ingest "
             "with bounded staleness -> watchdog-policed StepDriver "
             "rounds with rollback-to-last-good-bundle -> periodic "
             "snapshot + serving hot-swap handoff; all arguments forward "
             "to continuous.runner (use `continuous --help-runner` or "
             "`python -m deeplearning4j_tpu.continuous.runner --help`)")
    cn.add_argument("--help-runner", action="store_true",
                    help="print the runner's own argument reference")
    cn.add_argument("runner_args", nargs=argparse.REMAINDER)

    tn = sub.add_parser(
        "tune",
        help="kernel autotuner (tuning/): search Pallas configs "
             "(attention blocks + crossover, conv tiles, lstm column "
             "tiles), parity-gate every candidate against the reference "
             "path, and persist winners into the tuning DB the ops "
             "dispatch seams consult at trace time")
    tn.add_argument("--db", metavar="PATH",
                    help="tuning DB JSON to update (default: "
                         "$DL4J_TPU_TUNING_DB); existing entries merge — "
                         "a re-tune IS the refresh")
    tn.add_argument("--kernels",
                    help="comma-separated kernel subset "
                         "(attention,conv_matmul,conv3x3,lstm; default "
                         "all)")
    tn.add_argument("--interpret", action="store_true",
                    help="run candidates in Pallas interpret mode "
                         "(forced automatically off-TPU: the mechanics "
                         "run anywhere, the timings only transfer from "
                         "real hardware)")
    tn.add_argument("--smoke", action="store_true",
                    help="tiny shapes + trimmed candidate sets (CI "
                         "mechanics check)")
    tn.add_argument("--grad", action="store_true",
                    help="time fwd+bwd instead of forward only (opens "
                         "the attention remat dimension)")
    tn.add_argument("--iters", type=int,
                    help="chained in-jit iterations per timing window")
    tn.add_argument("--reps", type=int,
                    help="timing windows per candidate (best-of)")
    tn.add_argument("--tol", type=float, default=1e-6,
                    help="parity gate vs the reference path (default "
                         "1e-6; raise explicitly for bf16 tuning)")

    tl = sub.add_parser(
        "telemetry",
        help="dump a metrics snapshot (local registry, or scrape a "
             "running server's /metrics)")
    tl.add_argument("--url",
                    help="scrape this /metrics endpoint (e.g. "
                         "http://127.0.0.1:9000/metrics) instead of the "
                         "local registry")
    tl.add_argument("--format", choices=("prom", "json", "jsonl"),
                    default="prom",
                    help="local-registry output format (scrapes are always "
                         "the server's Prometheus text)")
    tl.add_argument("--chrome-trace",
                    help="also export the host-span Chrome trace JSON here")

    ln = sub.add_parser(
        "lint",
        help="graftlint: JAX-aware static analysis (hidden host syncs, "
             "jit purity, recompile hazards) — see analysis/")
    ln.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                         "deeplearning4j_tpu package)")
    ln.add_argument("--rules",
                    help="comma-separated rule subset (e.g. R1,R4); "
                         "default all")
    ln.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ln.add_argument("--format", choices=("human", "json"), default="human")
    ln.add_argument("--baseline",
                    help="baseline file (default: "
                         "<repo>/graftlint.baseline.json)")
    ln.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding")
    ln.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "and exit 0")
    ln.add_argument("--strict-baseline", action="store_true",
                    help="CI mode: stale baseline entries (fixed debt "
                         "still in the ledger) also fail")
    ln.add_argument("--verbose", action="store_true",
                    help="also print baselined findings")
    ln.add_argument("--diff", metavar="REF",
                    help="pre-commit mode: analyse everything (project "
                         "rules need the whole tree) but only REPORT "
                         "findings whose statement touches a line changed "
                         "vs this git ref (e.g. HEAD, origin/main)")
    ln.add_argument("--emit-schema", action="store_true",
                    help="instead of linting, write the harvested wire+"
                         "metric contract (routes, headers, response "
                         "keys, metric series with label sets) to "
                         "SCHEMA.json and METRICS.md — the same registry "
                         "rules R10/R11/R13 enforce")
    ln.add_argument("--schema-dir", metavar="DIR",
                    help="where --emit-schema writes (default: repo root)")
    ln.add_argument("--san-report", metavar="JSON",
                    help="merge a graftsan runtime report (Sanitizer.dump "
                         "/ GRAFTSAN_REPORT) with the static R9 lock "
                         "graph: maps observed acquisition orders onto "
                         "static lock identities and fails on cycles in "
                         "the MERGED graph — orders only runtime saw "
                         "compose with orders only the code declares")

    sl = sub.add_parser(
        "slo",
        help="SLO engine verdicts (telemetry/slo.py): evaluate the "
             "default ruleset over the local registry, or read a "
             "running server's /slo endpoint, and print every rule's "
             "ok|warning|firing state")
    sl.add_argument("--url",
                    help="read this /slo endpoint (e.g. "
                         "http://127.0.0.1:9000/slo — append ?federate=1 "
                         "for the cluster-wide evaluation) instead of "
                         "evaluating the local registry")
    sl.add_argument("--history", metavar="PATH",
                    help="replay a metrics-history dir (or one segment "
                         "file) through the engine before evaluating — "
                         "judge the minutes BEFORE a dump/restart, not "
                         "just the instant of death (the flightrec "
                         "'history' section names the dir)")
    sl.add_argument("--samples", type=int, default=2,
                    help="local mode: evaluation passes (rates need >=2 "
                         "samples spanning time; default 2)")
    sl.add_argument("--interval", type=float, default=2.0,
                    help="local mode: seconds between passes (default 2)")
    sl.add_argument("--gate", action="store_true",
                    help="exit nonzero when any rule is firing "
                         "(scriptable health check)")
    sl.add_argument("--json", action="store_true",
                    help="raw status JSON instead of the table")

    tc = sub.add_parser(
        "traces",
        help="inspect the slow-trace flight ring (telemetry/tracectx.py): "
             "list the slowest complete causal traces per root span and "
             "pretty-print one as an indented timeline")
    tc.add_argument("--url",
                    help="scrape a running server's /traces endpoint "
                         "(e.g. http://127.0.0.1:9000/traces) instead of "
                         "the local ring")
    tc.add_argument("--file", action="append", metavar="PATH",
                    help="read traces from JSON file(s) — a /traces "
                         "payload, a raw ring snapshot, a flight-recorder "
                         "dump (its 'traces' key) — or a DIRECTORY of "
                         "dumps (a dead generation's postmortem). "
                         "Repeatable; every source merges into one view")
    tc.add_argument("--name",
                    help="only this root-span name (e.g. serving.request)")
    tc.add_argument("--trace-id",
                    help="print the timeline of this trace id (the id a "
                         "/metrics exemplar or BENCH worst_trace_id "
                         "points at)")
    tc.add_argument("--cluster", action="store_true",
                    help="merge every source (--file/--url, or the live "
                         "cluster providers when neither is given) into "
                         "ONE time-aligned timeline: per-instance trace "
                         "rows, per-host round clocks, and the stalled "
                         "host of a dead hostfleet generation")
    tc.add_argument("--chrome", metavar="PATH",
                    help="with --cluster: also write the merged timeline "
                         "as a Chrome trace-event file (chrome://tracing "
                         "/ Perfetto)")
    tc.add_argument("--json", action="store_true",
                    help="raw JSON passthrough instead of the timeline")

    fr = sub.add_parser(
        "flightrec",
        help="pretty-print a crash flight-recorder dump "
             "(telemetry/flight.py JSON)")
    fr.add_argument("path", help="dump file written on anomaly/crash/SIGTERM")
    fr.add_argument("--last", type=int, default=10,
                    help="show only the last N step records (default 10; "
                         "0 = all)")
    fr.add_argument("--json", action="store_true",
                    help="raw JSON passthrough instead of the table")
    return p


def _load_model(args):
    if args.model_path:
        # sniffs the zip layout: this framework's format OR a reference
        # ModelSerializer zip (MLN or ComputationGraph) both load — the
        # CLI is the migration path's front door
        from deeplearning4j_tpu.models.zoo import restore_checkpoint
        return restore_checkpoint(args.model_path)
    from deeplearning4j_tpu.models import zoo
    try:
        builder = zoo.get_model(args.zoo).builder
    except KeyError:
        raise SystemExit(
            f"unknown zoo model {args.zoo!r}; known: {zoo.model_names()}")
    conf = builder()
    from deeplearning4j_tpu.nn.graph import ComputationGraph, GraphConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    net = (ComputationGraph(conf) if isinstance(conf, GraphConfiguration)
           else MultiLayerNetwork(conf))
    net.init()
    return net




def _load_xy(args):
    """Features+labels from .npy pairs or a single labelled CSV.

    --data model.csv with --label-column/--n-classes routes through
    datasets.records.csv_dataset (the RecordReaderDataSetIterator CLI
    shape); .npy keeps the original contract."""
    if args.data.endswith(".csv") or args.data.endswith(".dat"):
        if getattr(args, "labels", None):
            raise SystemExit(
                "--labels cannot be combined with a labelled CSV --data "
                "file: the CSV's --label-column is the label source. "
                "Drop --labels, or pass .npy features instead.")
        from deeplearning4j_tpu.datasets.records import csv_dataset
        x, y = csv_dataset(args.data, label_column=args.label_column,
                           n_classes=args.n_classes,
                           skip_lines=args.skip_lines)
        if y.ndim == 1:
            # no --n-classes: raw label column — make it an explicit
            # [N, 1] regression target (a 1-D y would silently broadcast
            # into a wrong loss downstream)
            y = y[:, None]
        return x, y
    if not getattr(args, "labels", None):
        raise SystemExit("--labels is required with .npy features")
    x = np.load(args.data)
    y = np.load(args.labels)
    return x, y

def _enable_compile_cache(args):
    """Point jax's persistent compile cache at --compile-cache (or
    $DL4J_TPU_COMPILE_CACHE) BEFORE any jax work compiles — the
    instant-restart tier every CLI verb shares."""
    from deeplearning4j_tpu.utils import compile_cache as _cc
    cache_dir = _cc.enable_persistent_cache(
        getattr(args, "compile_cache", None))
    if cache_dir:
        print(f"persistent compile cache: {cache_dir}")
    return cache_dir


def _cmd_train(args):
    import jax
    from jax.sharding import Mesh
    from deeplearning4j_tpu.parallel.distributed import (
        DistributedMultiLayer, ParameterAveragingTrainingMaster,
        SharedTrainingMaster)

    _enable_compile_cache(args)

    # CLI training is the preemptable long-running entry point: a SIGTERM
    # (scheduler eviction) leaves a flight-recorder dump behind
    from deeplearning4j_tpu.telemetry import flight as _flight
    _flight.install_signal_handler()

    x, y = _load_xy(args)
    n_devices = len(jax.devices())
    n_workers = args.workers or n_devices
    if n_workers > n_devices:
        raise SystemExit(f"--workers {n_workers} exceeds the {n_devices} "
                         f"available device(s)")
    mesh = Mesh(np.array(jax.devices()[:n_workers]), ("data",))
    net = _load_model(args)

    ui_server = None
    if args.ui_port:
        from deeplearning4j_tpu.ui import (InMemoryStatsStorage,
                                           StatsListener, UIServer)
        storage = InMemoryStatsStorage()
        if hasattr(net, "add_listener"):
            net.add_listener(StatsListener(storage, session_id="cli"))
        ui_server = UIServer(port=args.ui_port).attach(storage).start()
        print(f"dashboard: http://127.0.0.1:{ui_server.port}/")

    if args.averaging_frequency <= 1:
        master = SharedTrainingMaster(
            mesh, batch_size_per_worker=args.batch_size_per_worker,
            threshold=None)
    else:
        master = ParameterAveragingTrainingMaster(
            mesh, batch_size_per_worker=args.batch_size_per_worker,
            averaging_frequency=args.averaging_frequency,
            average_updaters=not args.no_average_updaters)
    dist = DistributedMultiLayer(net, master)
    loss = dist.fit(x, y, epochs=args.epochs)
    if args.report_score and loss is not None:
        print(f"final loss: {loss}")
    print(f"training stats: {master.training_stats()}")

    if args.model_output_path:
        from deeplearning4j_tpu.utils.serialization import save_model
        save_model(net, args.model_output_path)
        print(f"saved: {args.model_output_path}")
    if ui_server is not None:
        ui_server.stop()
    return 0


def _serve_input_spec(args, net):
    """Per-example input shape for AOT warmup: --input-shape wins, else the
    model conf's input type (FeedForwardType(6) -> (6,))."""
    if args.input_shape:
        return tuple(int(d) for d in args.input_shape.split(",") if d.strip())
    input_type = getattr(net.conf, "input_type", None)
    if input_type is None:
        raise SystemExit(
            "--input-shape is required: the model conf carries no input "
            "type to derive the warmup shape from")
    return tuple(input_type.shape(1)[1:])


def _cmd_serve(args):
    """The production serving entry point (ROADMAP 'serving heavy
    traffic'): AOT-warm every registered bucket so no request pays a
    compile, then serve with continuous batching + admission control."""
    import time

    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.serving import get_model_registry
    from deeplearning4j_tpu.ui import UIServer

    telemetry.enable()  # SLO gauges/counters are the point of a server
    _enable_compile_cache(args)
    net = _load_model(args)
    input_spec = _serve_input_spec(args, net)
    buckets = None
    if args.buckets:
        buckets = [int(b) for b in args.buckets.split(",") if b.strip()]
    # a not-yet-created path is the normal first cold start: the engine
    # loads it leniently (missing -> None, no warning)
    warm_manifest = args.warm_manifest or None
    registry = get_model_registry()
    engine = registry.register(
        args.name, net, input_spec=input_spec,
        max_batch_size=args.max_batch, buckets=buckets,
        max_queue=args.max_queue,
        default_deadline_s=(None if args.deadline_ms is None
                            else args.deadline_ms / 1e3),
        batch_window_s=args.batch_window_ms / 1e3,
        warm_manifest=warm_manifest)
    st = engine.stats()
    aot = st["aot"]
    src = (f"{aot['manifest_hits']} from warm manifest, "
           f"{aot['warmed'] - aot['manifest_hits']} compiled"
           if warm_manifest else "compiled")
    print(f"model {args.name!r}: AOT-warmed buckets {st['buckets']} "
          f"in {st['warmup_s']:.2f}s ({src}; input {input_spec})")
    if args.warm_manifest:
        # (re)save AFTER warmup so a cold start's live compiles make the
        # NEXT restart warm — the instant-restart loop closes here.
        # Export ONCE: each export serializes (and verify-deserializes)
        # every executable not already in the manifest
        manifest = engine.export_warm_manifest()
        if manifest is not None:
            manifest.save(args.warm_manifest)
            print(f"warm manifest: {args.warm_manifest} "
                  f"({len(manifest)} executable(s))")
        else:
            print("warm manifest: backend cannot serialize executables "
                  "(persistent compile cache still applies)")
    ui_server = UIServer(port=args.port).start()
    print(f"serving status: http://127.0.0.1:{ui_server.port}/serving "
          f"(metrics on /metrics)")

    try:
        if args.smoke:
            import json

            import numpy as np
            from deeplearning4j_tpu.serving import ServingOverloaded
            rs = np.random.RandomState(0)
            xs = rs.rand(args.smoke, *input_spec).astype(np.float32)
            futs, shed = [], 0
            for i in range(args.smoke):
                # a smoke burst bigger than --max-queue legitimately sheds
                # (that's the admission control working): back off briefly
                # and keep going rather than crash the smoke
                for _ in range(1000):
                    try:
                        futs.append(engine.submit(xs[i]))
                        break
                    except ServingOverloaded:
                        time.sleep(0.001)
                else:
                    raise SystemExit("smoke: admission queue never drained")
            for f in futs:
                try:
                    f.get(timeout=30)
                except ServingOverloaded:
                    shed += 1  # stale-in-queue deadline shed (--deadline-ms)
            if shed:
                print(f"smoke: {shed} request(s) shed by deadline")
            print(json.dumps(registry.status()["models"][args.name],
                             indent=1))
            return 0
        # SIGTERM (docker stop / systemd) must route through the same
        # clean-stop path as Ctrl-C: killing the interpreter with the
        # serving worker mid-XLA-call aborts the process hard
        import signal

        def _term(signum, frame):
            raise KeyboardInterrupt
        signal.signal(signal.SIGTERM, _term)
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        registry.stop()
        ui_server.stop()
    return 0


def _cmd_fleet(args):
    """The multi-process serving entry point (ROADMAP's "millions of
    users" tier): spawn N workers from one checkpoint + warm manifest,
    put the admission/routing front before them, and keep the pool
    elastic — a worker death is a respawn, not an outage."""
    import time

    from deeplearning4j_tpu import fleet, telemetry
    from deeplearning4j_tpu.ui import UIServer

    telemetry.enable()
    _enable_compile_cache(args)
    if args.model_path is None:
        # zoo mode: workers init the model themselves (same seed = same
        # params); a checkpoint is the production path
        print("note: --zoo workers each init fresh (same seed); use "
              "--model-path for a real deployment")
    input_shape = (tuple(int(d) for d in args.input_shape.split(",")
                         if d.strip()) if args.input_shape else None)
    buckets = ([int(b) for b in args.buckets.split(",") if b.strip()]
               if args.buckets else None)
    supervisor = fleet.FleetSupervisor(
        args.workers, model_path=args.model_path, zoo=args.zoo,
        name=args.name, buckets=buckets, input_shape=input_shape,
        warm_manifest=args.warm_manifest or None,
        compile_cache=getattr(args, "compile_cache", None),
        max_queue=args.max_queue, max_batch=args.max_batch,
        deadline_ms=args.deadline_ms)
    router = fleet.FleetRouter(
        name=args.name, max_queue=args.max_queue,
        max_inflight_rows=args.max_inflight,
        default_deadline_s=(None if args.deadline_ms is None
                            else args.deadline_ms / 1e3))
    supervisor.attach(router)
    print(f"fleet: spawning {args.workers} worker(s)...")
    t0 = time.perf_counter()
    supervisor.start()
    fleet.set_default_front(router=router, supervisor=supervisor)
    starts = ", ".join(
        f"{w.wid}:" + ("warm" if fleet.FleetSupervisor
                       .replacement_is_warm(w.ready_doc) else "cold")
        for w in supervisor._workers.values())
    print(f"fleet: {args.workers} worker(s) ready in "
          f"{time.perf_counter() - t0:.1f}s ({starts})")
    ui_server = UIServer(port=args.port).start()
    print(f"fleet status: http://127.0.0.1:{ui_server.port}/fleet "
          f"(metrics on /metrics)")
    try:
        if args.smoke:
            import json

            import numpy as np
            from deeplearning4j_tpu.serving import ServingOverloaded
            spec = input_shape
            if spec is None:
                # read one worker's bucket spec indirectly: derive from
                # the model conf like the workers do
                net = _load_model(args)
                spec = _serve_input_spec(args, net)
            rs = np.random.RandomState(0)
            xs = rs.rand(args.smoke, *spec).astype(np.float32)
            futs, shed = [], 0
            for i in range(args.smoke):
                for _ in range(1000):
                    try:
                        futs.append(router.submit(xs[i]))
                        break
                    except ServingOverloaded:
                        time.sleep(0.001)
                else:
                    raise SystemExit("fleet smoke: admission queue "
                                     "never drained")
            for f in futs:
                try:
                    f.get(timeout=60)
                except ServingOverloaded:
                    shed += 1
            if shed:
                print(f"fleet smoke: {shed} request(s) shed")
            print(json.dumps({"router": router.stats(),
                              "workers": supervisor.status()},
                             indent=1, default=str))
            return 0
        import signal

        def _term(signum, frame):
            raise KeyboardInterrupt
        signal.signal(signal.SIGTERM, _term)
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
        supervisor.stop()
        fleet.reset()
        ui_server.stop()
    return 0


def _cmd_ui(args):
    from deeplearning4j_tpu.ui import UIServer
    server = UIServer(port=args.port).start()
    print(f"UI server on http://127.0.0.1:{server.port}/ (Ctrl-C to stop)")
    try:
        import time
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


def _cmd_bench(args):
    import os
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, os.path.join(repo, "bench.py")]
    if args.config != "all":
        cmd.append(args.config)
    return subprocess.call(cmd)


def _cmd_eval(args):
    """(reference role: Evaluation printed from MultiLayerNetwork.evaluate /
    the examples' eval.stats() tail — here as a CLI verb)."""
    _enable_compile_cache(args)
    net = _load_model(args)
    x, y = _load_xy(args)
    preds = []
    for i in range(0, x.shape[0], args.batch_size):
        out = net.output(x[i:i + args.batch_size])
        if isinstance(out, dict):  # multi-output graph: first output head
            out = next(iter(out.values()))
        preds.append(np.asarray(out))
    preds = np.concatenate(preds)
    if args.regression:
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        if y.ndim == 1:  # single-target vector -> column
            y = y[:, None]
        ev = RegressionEvaluation()
        ev.eval(y, preds)
        print(ev.stats())
        return 0
    from deeplearning4j_tpu.eval.classification import Evaluation
    n_classes = preds.shape[-1]
    if n_classes == 1:
        # single sigmoid output: Evaluation handles 1-column labels natively
        if y.ndim == 1:
            y = y[:, None]
    elif y.ndim == 1 or (y.ndim == 2 and y.shape[-1] == 1):
        y = np.eye(n_classes, dtype=np.float32)[y.astype(int).ravel()]
    ev = Evaluation()
    ev.eval(y, preds)
    print(ev.stats())
    return 0


def _cmd_tune(args):
    """Populate the kernel-tuning DB (ROADMAP's TVM-mold autotuner): the
    live-TPU workflow is one `tune --db tuned.json` per window — every
    later process with DL4J_TPU_TUNING_DB pointed at it traces tuned
    kernels, and warm manifests built under it serve TUNED executables
    with zero compiles."""
    import json
    import os

    from deeplearning4j_tpu import telemetry, tuning
    from deeplearning4j_tpu.ops.attention_pallas import backend_is_tpu

    telemetry.enable()  # the event counters are part of the output
    path = args.db or os.environ.get(tuning.ENV_DB)
    if not path:
        raise SystemExit("tune: no DB path (--db PATH or "
                         f"${tuning.ENV_DB})")
    interpret = args.interpret
    if not backend_is_tpu() and not interpret:
        print("tune: no TPU backend — running candidates in interpret "
              "mode (mechanics only; timings do not transfer)")
        interpret = True
    db = tuning.TuningDB.load_lenient(path) or tuning.TuningDB(path)
    tuning.set_db(db)  # this process's later traces see the fresh winners
    kernels = ([k.strip() for k in args.kernels.split(",") if k.strip()]
               if args.kernels else None)
    overrides = {"tol": args.tol}
    if args.iters:
        overrides["iters"] = args.iters
    if args.reps:
        overrides["reps"] = args.reps
    try:
        summaries = tuning.tune_kernels(
            db, kernels, smoke=args.smoke, interpret=interpret,
            grad=args.grad, log=print, **overrides)
    except ValueError as e:
        raise SystemExit(f"tune: {e}")
    finally:
        tuning.set_db(None)
    db.save(path)
    for name, s in summaries.items():
        print(f"{name}: winner {s['winner']} "
              f"({s['winner_ms']} ms/iter; {s['candidates']} measured, "
              f"{s['pruned_static']} pruned, {s['rejected_parity']} "
              f"parity-rejected)")
    print(f"tuning DB: {path} ({len(db)} entr"
          f"{'y' if len(db) == 1 else 'ies'}); events "
          f"{json.dumps(tuning.event_counts())}")
    print("note: warm manifests key on the DB content — executables "
          "compiled under the old DB refresh themselves on next start")
    return 0


def _cmd_telemetry(args):
    """Dump the unified telemetry snapshot — the 'what is this process (or
    that server) doing right now' CLI verb."""
    import json

    from deeplearning4j_tpu import telemetry

    if args.url:
        if args.chrome_trace:
            raise SystemExit(
                "--chrome-trace cannot be combined with --url: the host-span "
                "tracer lives in the traced process, and this fresh CLI "
                "process has recorded nothing — export the trace from the "
                "instrumented process instead "
                "(telemetry.get_tracer().export(path)).")
        import urllib.request
        with urllib.request.urlopen(args.url, timeout=10) as r:
            sys.stdout.write(r.read().decode())
    else:
        reg = telemetry.get_registry()
        if not any(m["series"] for m in reg.snapshot().values()):
            # a fresh CLI process has recorded nothing — say so instead of
            # letting an empty dump read as "telemetry is broken"
            print("note: local registry is empty (each process has its "
                  "own); run instrumented work in THIS process, or scrape "
                  "a live server with --url http://host:port/metrics",
                  file=sys.stderr)
        if args.format == "json":
            print(json.dumps(reg.snapshot(), indent=1, default=str))
        elif args.format == "jsonl":
            reg.to_jsonl(sys.stdout)
        else:
            sys.stdout.write(reg.to_prometheus())
    if args.chrome_trace:
        path = telemetry.get_tracer().export(args.chrome_trace)
        print(f"chrome trace: {path}", file=sys.stderr)
    return 0


def _cmd_lint(args):
    """graftlint CLI: exit 0 when every finding is fixed/suppressed/
    baselined, non-zero otherwise — the tier-1 gating contract."""
    import os

    from deeplearning4j_tpu import analysis
    from deeplearning4j_tpu.analysis import reporters

    if args.list_rules:
        for name, rule in analysis.all_rules().items():
            print(f"{name} [{rule.slug}]\n    {rule.description}")
        return 0

    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    root = os.path.dirname(pkg_dir)
    paths = args.paths or [pkg_dir]
    rules = args.rules.split(",") if args.rules else None

    if args.emit_schema:
        mods, errors = analysis.parse_paths(paths, root=root)
        if errors:
            for f in errors:
                print(f.human(), file=sys.stderr)
            raise SystemExit("graftlint: cannot emit a schema over "
                             "unparseable sources")
        schema = analysis.build_schema(mods)
        out_dir = args.schema_dir or root
        jp, mp = reporters.write_schema(schema, out_dir)
        print(f"graftlint: schema written: {jp}, {mp}", file=sys.stderr)
        return 0
    if args.san_report:
        return _lint_san_report(args, paths, root)
    if args.diff and args.update_baseline:
        raise SystemExit("graftlint: --diff filters findings to changed "
                         "lines; rewriting the baseline from that subset "
                         "would drop real debt — run --update-baseline "
                         "without --diff")

    try:
        findings = analysis.lint_paths(paths, rules=rules, root=root)
    except analysis.LintError as e:
        raise SystemExit(f"graftlint: {e}")

    if args.diff:
        changed = _git_changed_lines(args.diff, root)
        # a finding's statement spans sup_start (decorators included —
        # editing only a decorator line must still surface the finding
        # it causes on the def) through end_line
        findings = [f for f in findings
                    if any(ln in changed.get(f.path, ())
                           for ln in range(min(f.sup_start or f.line,
                                               f.line),
                                           max(f.end_line, f.line) + 1))]

    if args.no_baseline:
        baseline = {}
    else:
        bpath = args.baseline or analysis.default_baseline_path()
        if args.update_baseline:
            analysis.save_baseline(bpath, findings)
            print(f"graftlint: baseline rewritten with {len(findings)} "
                  f"finding(s): {bpath}", file=sys.stderr)
            return 0
        baseline = analysis.load_baseline(bpath)
    new, known, stale = analysis.apply_baseline(findings, baseline)
    if args.diff:
        # off-diff baselined debt is invisible here, so "stale" is
        # meaningless — the full (non-diff) CI run owns that check
        stale = []

    if args.format == "json":
        reporters.report_json(new, known, stale)
    else:
        reporters.report_human(new, known, stale, verbose=args.verbose)
    if new:
        return 1
    if stale and args.strict_baseline:
        return 1
    return 0


def _git_changed_lines(ref, root):
    """{repo-relative posix path: set of NEW-side line numbers} changed vs
    ``ref`` (committed AND working-tree changes — pre-commit wants both).
    Hunk headers only (-U0): pure deletions contribute no lines."""
    import re
    import subprocess
    from pathlib import Path

    try:
        out = subprocess.run(
            ["git", "-C", root, "diff", "--unified=0", ref, "--", "*.py"],
            capture_output=True, text=True, check=True).stdout
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        raise SystemExit(f"graftlint: git diff {ref} failed: "
                         f"{detail.strip()}")
    changed, cur = {}, None
    hunk = re.compile(r"^@@ -\d+(?:,\d+)? \+(\d+)(?:,(\d+))? @@")
    for line in out.splitlines():
        if line.startswith("+++ b/"):
            cur = line[6:]
        elif line.startswith("+++"):
            cur = None                      # /dev/null: file deleted
        elif cur is not None and line.startswith("@@"):
            m = hunk.match(line)
            if m:
                start = int(m.group(1))
                count = int(m.group(2)) if m.group(2) is not None else 1
                if count:
                    changed.setdefault(cur, set()).update(
                        range(start, start + count))
    # untracked files never appear in `git diff` hunks but ARE pending
    # changes — every line of them counts
    untracked = subprocess.run(
        ["git", "-C", root, "ls-files", "--others", "--exclude-standard",
         "--", "*.py"],
        capture_output=True, text=True).stdout
    for path in untracked.splitlines():
        if not path:
            continue
        try:
            with open(Path(root) / path, encoding="utf-8",
                      errors="replace") as fh:
                n = sum(1 for _ in fh)
        except OSError:
            continue
        changed.setdefault(path, set()).update(range(1, n + 1))
    return changed


def _lint_san_report(args, paths, root):
    """lint --san-report: one lock graph from both prongs. Static R9
    edges come in lock-id space; observed graftsan edges come keyed by
    allocation site (file:line) and map onto the SAME identity via the
    lock registry — so an order only runtime saw composes with an order
    only the code declares, and the merged cycle is reported even though
    neither prong alone had it."""
    import json
    from pathlib import Path, PurePosixPath

    from deeplearning4j_tpu import analysis
    from deeplearning4j_tpu.analysis.dataflow import project_facts

    with open(args.san_report, encoding="utf-8") as fh:
        report = json.load(fh)
    mods, parse_errors = analysis.parse_paths(paths, root=root)
    static = analysis.lint_modules(mods, rules=["R9"])
    facts = project_facts(mods)

    site_to_id = {f"{info['path']}:{info['line']}": lid
                  for lid, info in facts.locks.items()}

    def norm(site):
        fname, _, line = site.rpartition(":")
        try:
            rel = Path(fname).resolve().relative_to(Path(root).resolve())
        except ValueError:
            rel = Path(fname)
        return f"{PurePosixPath(rel)}:{line}"

    def ident(site):
        n = norm(site)
        return site_to_id.get(n, n)        # unmapped sites keep file:line

    merged = {}
    for src, dst, _mod, _node, _via in facts.lock_edges:
        if src != dst:          # self-edges are static R9's own call
            merged.setdefault(src, set()).add(dst)  # (RLock re-entry legal)
    observed = []
    for e in report.get("lock_order_edges", ()):
        a, b = ident(e["from"]), ident(e["to"])
        observed.append((a, b, e.get("count", 1)))
        if a != b:
            merged.setdefault(a, set()).add(b)

    from deeplearning4j_tpu.analysis.dataflow import reaches
    cycles = set()
    for a in sorted(merged):
        for b in sorted(merged[a]):
            if reaches(merged, b, a):
                cycles.add(tuple(sorted((a, b))))

    runtime_findings = report.get("findings", ())
    print(f"graftsan report: {len(observed)} observed lock-order edge(s), "
          f"{len(runtime_findings)} runtime finding(s)")
    for a, b, count in observed:
        print(f"  observed {a} -> {b} (x{count})")
    for f in runtime_findings:
        tail = f" [{f['site']}]" if f.get("site") else ""
        print(f"RUNTIME {f['kind']}: {f['message']}{tail}")
    for f in static:
        print(f"STATIC {f.human()}")
    for f in parse_errors:
        print(f"STATIC {f.human()}")
    for cyc in sorted(cycles):
        print("MERGED lock-order cycle: "
              + " -> ".join(cyc + (cyc[0],)))
    bad = bool(runtime_findings or static or parse_errors or cycles)
    if not bad:
        print("graftsan: static + observed lock graphs merge clean")
    return 1 if bad else 0


def _cmd_slo(args):
    """The metrics plane's verdict, on the command line: which rules
    are burning, and by how much (`slo --gate` scripts it)."""
    import json
    import time

    if args.url:
        import urllib.request
        with urllib.request.urlopen(args.url, timeout=10) as r:
            status = json.loads(r.read().decode())
    else:
        from deeplearning4j_tpu import telemetry
        reg = telemetry.get_registry()
        if not any(m["series"] for m in reg.snapshot().values()):
            print("note: local registry is empty (each process has its "
                  "own); run instrumented work in THIS process, or read "
                  "a live server with --url http://host:port/slo",
                  file=sys.stderr)
        engine = telemetry.slo.get_engine()
        if getattr(args, "history", None):
            # postmortem replay: judge the persisted minutes, not this
            # (possibly freshly-restarted, empty) process's instant. The
            # samples carry their own unix clocks, so mixing in live
            # monotonic-clock passes would corrupt the delta windows —
            # with --history the replay IS the evaluation.
            from deeplearning4j_tpu.telemetry import history as _history
            samples, corrupt = _history.load_dir(args.history)
            if not samples:
                print(f"slo --history: no samples under {args.history} "
                      f"({corrupt} corrupt segment(s))", file=sys.stderr)
                return 1
            status = None
            for s in samples:
                status = engine.evaluate(metrics=s["metrics"], now=s["t"])
            span_s = samples[-1]["t"] - samples[0]["t"]
            print(f"slo --history: replayed {len(samples)} sample(s) "
                  f"spanning {span_s:.0f}s ({corrupt} corrupt segment(s) "
                  f"skipped)", file=sys.stderr)
        else:
            status = engine.evaluate()
            for _ in range(max(args.samples - 1, 0)):
                time.sleep(max(args.interval, 0.0))
                status = engine.evaluate()
    if args.json:
        print(json.dumps(status, indent=1, default=str))
    else:
        rules = status.get("rules", [])
        w_name = max([len(r["name"]) for r in rules] + [4])
        print(f"{'rule'.ljust(w_name)}  state    value        bound  "
              f"kind        metric")
        for r in rules:
            v = r.get("value")
            if isinstance(v, dict):  # burn_rate: short/long pair
                vtxt = "/".join(f"{x:.3g}" for x in v.values())
            else:
                vtxt = "-" if v is None else f"{v:.4g}"
            bound = f"{'<=' if r.get('op') == 'lt' else '>='}" \
                    f"{r.get('fire'):g}"
            print(f"{r['name'].ljust(w_name)}  {r['state']:<7}  "
                  f"{vtxt:<11}  {bound:<5}  {r['kind']:<10}  "
                  f"{r['metric']}")
        firing = status.get("firing", [])
        warning = status.get("warning", [])
        print(f"firing: {firing or 'none'}  warning: {warning or 'none'} "
              f" ({status.get('evaluations')} evaluation(s))")
    if args.gate and status.get("firing"):
        return 1
    return 0


def _load_trace_rings(args):
    """{root name: [trace docs]} from --file / --url / the local ring.
    Accepts the three shapes traces travel in: a /traces payload
    ({"traces": {...}}), a raw ring snapshot ({name: [...]}), or a
    flight-recorder dump carrying a "traces" key. ``--file`` repeats and
    accepts directories of dumps; every source's rings merge."""
    import json

    if args.file:
        from deeplearning4j_tpu.telemetry import timeline as _tl
        rings = {}
        for src in _tl.load_paths(args.file):
            for name, docs in src["rings"].items():
                rings.setdefault(name, []).extend(docs)
        return rings
    if args.url:
        import urllib.request
        with urllib.request.urlopen(args.url, timeout=10) as r:
            doc = json.loads(r.read().decode())
        return doc.get("traces", doc)
    from deeplearning4j_tpu import telemetry
    rings = telemetry.tracectx.get_ring().snapshot()
    if not rings:
        print("note: local slow-trace ring is empty (each process has its "
              "own); run traced work in THIS process, scrape a live "
              "server with --url http://host:port/traces, or read a "
              "flight dump with --file", file=sys.stderr)
    return rings


def _print_trace_timeline(doc):
    """One trace as an indented timeline: spans sorted by start time,
    indented by causal depth — the 'where did the p99 request spend its
    time' view, readable without a trace viewer."""
    dur = doc.get("duration_s")
    head = f"trace {doc.get('trace_id')} {doc.get('name')}"
    if dur is not None:
        head += f" {1e3 * dur:.3f} ms"
    if doc.get("status") not in (None, "ok"):
        head += f" [{doc['status']}]"
    print(head)
    spans = [s for s in doc.get("spans", []) if isinstance(s, dict)]
    depth = {}
    by_id = {s.get("span_id"): s for s in spans}

    def depth_of(s):
        d, seen = 0, set()
        while s is not None and s.get("parent_id") is not None \
                and s.get("span_id") not in seen:
            seen.add(s.get("span_id"))
            s = by_id.get(s.get("parent_id"))
            d += 1
        return d

    for s in spans:
        depth[s.get("span_id")] = depth_of(s)
    for s in sorted(spans, key=lambda s: (s.get("t0_s", 0.0),
                                          depth[s.get("span_id")])):
        pad = "  " * depth[s.get("span_id")]
        d = s.get("dur_s")
        dtxt = "?" if d is None else f"{1e3 * d:.3f} ms"
        line = (f"  {1e3 * s.get('t0_s', 0.0):>10.3f}  {pad}"
                f"{s.get('name')}  {dtxt}  [{s.get('thread', '?')}]")
        if s.get("args"):
            line += "  " + " ".join(f"{k}={v}"
                                    for k, v in sorted(s["args"].items()))
        print(line)


def _cmd_traces_cluster(args):
    """``traces --cluster``: one time-aligned timeline over every source
    — a directory of a dead generation's dumps, multiple --file scrapes,
    or the live cluster providers — ending with the per-host round
    clocks and the stalled host (the postmortem's first question)."""
    import json

    from deeplearning4j_tpu.telemetry import timeline as _tl

    if args.file:
        merged = _tl.merge(_tl.load_paths(args.file))
    elif args.url:
        import urllib.request
        with urllib.request.urlopen(args.url, timeout=10) as r:
            doc = json.loads(r.read().decode())
        src = _tl._source_from_doc(doc, args.url)
        merged = _tl.merge([src] if src is not None else [])
    else:
        merged = _tl.cluster_snapshot()
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(_tl.to_chrome(merged), f)
        print(f"chrome trace written to {args.chrome}", file=sys.stderr)
    if args.json:
        print(json.dumps(merged, indent=1, default=str))
        return 0
    print(f"cluster timeline: {merged['n_traces']} trace(s) across "
          f"{len(merged['instances'])} instance(s)")
    base = merged.get("t0_unix")
    for t in merged["traces"]:
        if args.name and t["name"] != args.name:
            continue
        rel = ("?" if (t["t0_unix"] is None or base is None)
               else f"{t['t0_unix'] - base:+.3f}s")
        dur = t.get("duration_s")
        dtxt = "?" if dur is None else f"{1e3 * dur:.3f} ms"
        line = f"  {rel:>10}  {t['instance']}  {t['name']}  {dtxt}"
        if t.get("status") not in (None, "ok"):
            line += f" [{t['status']}]"
        print(line)
    if merged["hosts"]:
        print()
        for inst in sorted(merged["hosts"]):
            h = merged["hosts"][inst]
            print(f"host {inst}: last round {h['last_round']}")
        if merged.get("stalled") is not None:
            h = merged["hosts"][merged["stalled"]]
            print(f"stalled: {merged['stalled']} — round clock stopped "
                  f"at round {h['last_round']} while peers advanced")
    return 0


def _cmd_traces(args):
    """The gauge->exemplar->timeline landing: `traces --trace-id <id>`
    renders the causal story a p99 exemplar points at."""
    import json

    if args.cluster:
        return _cmd_traces_cluster(args)
    rings = _load_trace_rings(args)
    if args.name:
        rings = {args.name: rings.get(args.name, [])}
    if args.trace_id:
        for docs in rings.values():
            for doc in docs:
                if doc.get("trace_id") == args.trace_id:
                    if args.json:
                        print(json.dumps(doc, indent=1, default=str))
                    else:
                        _print_trace_timeline(doc)
                    return 0
        print(f"traces: no trace {args.trace_id!r} in the ring",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(rings, indent=1, default=str))
        return 0
    slowest = None
    for name in sorted(rings):
        docs = rings[name]
        if not docs:
            continue
        durs = [d.get("duration_s") or 0.0 for d in docs]
        print(f"{name}: {len(docs)} trace(s), slowest "
              f"{1e3 * max(durs):.3f} ms, fastest kept "
              f"{1e3 * min(durs):.3f} ms")
        for d in docs:
            if slowest is None or (d.get("duration_s") or 0.0) > \
                    (slowest.get("duration_s") or 0.0):
                slowest = d
    if slowest is not None:
        print()
        _print_trace_timeline(slowest)
    return 0


#: flight-record columns in display order; only those present in the dump
#: are rendered (health fields appear when the watchdog annotated the ring)
_FLIGHT_COLS = ("step", "score", "loss", "step_time_s", "etl_time_s",
                "grad_norm", "loss_nonfinite", "grad_nonfinite",
                "trace_id", "device_bytes_in_use", "live_array_bytes")


def _cmd_flightrec(args):
    """Postmortem reader: the last-N-steps table a human scans for 'where
    did it go wrong' without hand-parsing the dump JSON."""
    import json

    with open(args.path) as f:
        doc = json.load(f)
    if args.json:
        print(json.dumps(doc, indent=1, default=str))
        return 0
    recs = doc.get("records", [])
    print(f"flight dump: reason={doc.get('reason')} "
          f"dumped_at={doc.get('dumped_at')} pid={doc.get('pid')} "
          f"records={len(recs)}")
    if doc.get("error"):
        print(f"error: {doc['error']}")
    if doc.get("anomaly"):
        print(f"anomaly: {doc['anomaly']}")
    hist = doc.get("history")
    if hist:
        # where to find the minutes BEFORE this dump: the persisted
        # metrics-history segments replay with `slo --history <dir>`
        print(f"history: {hist.get('samples', 0)} sample(s) in ring, "
              f"{hist.get('segments', 0)} segment(s) persisted"
              + (f" under {hist['dir']} (replay: slo --history "
                 f"{hist['dir']})" if hist.get("dir") else
                 " (persistence off: no history dir configured)"))
    show = recs[-args.last:] if args.last else recs

    def _fmt(v):
        if isinstance(v, bool):
            return "YES" if v else "-"
        if isinstance(v, float):
            return f"{v:.4g}"
        return "-" if v is None else str(v)

    cols = [c for c in _FLIGHT_COLS if any(c in r for r in show)]
    if cols:
        rows = [[_fmt(r.get(c)) for c in cols] for r in show]
        widths = [max(len(c), *(len(row[i]) for row in rows))
                  for i, c in enumerate(cols)]
        print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
        for row in rows:
            print("  ".join(v.ljust(w) for v, w in zip(row, widths)))
    flagged = [r for r in recs
               if r.get("loss_nonfinite") or r.get("grad_nonfinite")]
    if flagged:
        print(f"{len(flagged)} record(s) flagged nonfinite; first at step "
              f"{flagged[0].get('step')}")
    return 0


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "continuous":
        # forwarded verbatim BEFORE argparse: REMAINDER cannot capture
        # leading option-style args, so `continuous --snapshot ...`
        # would otherwise die with "unrecognized arguments"
        rest = list(argv[1:])
        if rest and rest[0] == "--":
            rest = rest[1:]
        if "--help-runner" in rest:
            rest = ["--help"]
        from deeplearning4j_tpu.continuous import runner
        return runner.main(rest)
    args = _build_parser().parse_args(argv)
    if args.command == "train":
        return _cmd_train(args)
    if args.command == "ui":
        return _cmd_ui(args)
    if args.command == "serve":
        return _cmd_serve(args)
    if args.command == "fleet":
        return _cmd_fleet(args)
    if args.command == "bench":
        return _cmd_bench(args)
    if args.command == "eval":
        return _cmd_eval(args)
    if args.command == "tune":
        return _cmd_tune(args)
    if args.command == "telemetry":
        return _cmd_telemetry(args)
    if args.command == "flightrec":
        return _cmd_flightrec(args)
    if args.command == "traces":
        return _cmd_traces(args)
    if args.command == "slo":
        return _cmd_slo(args)
    if args.command == "lint":
        return _cmd_lint(args)
    return 1


if __name__ == "__main__":
    sys.exit(main())
