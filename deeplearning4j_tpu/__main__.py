from deeplearning4j_tpu.cli import main

raise SystemExit(main())
