"""Production serving tier: continuous batching + AOT-warmed inference.

Grown from ``parallel/inference.py``'s ParallelInference into a real
serving path for heavy traffic (ROADMAP north star; the serving half of the
TensorFlow system paper, PAPERS.md arxiv 1605.08695):

* :class:`ServingEngine` — continuous (dynamic) batching over registered
  shape buckets with AOT warmup (``jax.jit(...).lower().compile()`` per
  bucket at startup), a bounded admission queue with deadline-aware
  load shedding (:class:`ServingOverloaded`), and per-model p50/p99 SLO
  gauges.
* :class:`ModelRegistry` — several named models served side by side with
  atomic ``update_model`` hot swaps; the process-default instance backs
  the UIServer's ``/serving`` endpoint and the ``serve`` CLI verb.
* :class:`BucketedForward` / :class:`InferenceFuture` — the compiled-
  forward core and the request future, shared with ParallelInference
  (which is rebased on them).

Quickstart::

    from deeplearning4j_tpu.serving import get_model_registry
    reg = get_model_registry()
    engine = reg.register("lenet", net, input_spec=(28, 28, 1),
                          max_batch_size=32, max_queue=256)
    fut = engine.submit(example)          # continuous batching
    y = fut.get(timeout=1.0)
    reg.update_model("lenet", retrained)  # atomic hot swap
"""

from deeplearning4j_tpu.serving.engine import (BucketedForward,
                                               InferenceFuture,
                                               ServingEngine,
                                               ServingOverloaded,
                                               ServingShutdown)
from deeplearning4j_tpu.serving.registry import (ModelRegistry,
                                                 get_model_registry, reset)

__all__ = ["BucketedForward", "InferenceFuture", "ModelRegistry",
           "ServingEngine", "ServingOverloaded", "ServingShutdown",
           "get_model_registry", "reset"]
