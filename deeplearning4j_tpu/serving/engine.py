"""Production inference engine: continuous batching over AOT-warmed buckets.

The serving half of the TensorFlow system paper (PAPERS.md, arxiv
1605.08695) as this framework's request path, grown from
``parallel/inference.py``'s ParallelInference:

* **Continuous (dynamic) batching** — a single worker drains whatever is
  queued the moment the accelerator frees (no per-slot waits), pads the
  ragged request batch to the nearest registered bucket
  (datasets/iterator.py ``BucketRegistry`` + ``pad_batch`` row padding) and
  runs ONE compiled forward, so arbitrary traffic shapes keep
  ``recompiles_total`` flat.
* **AOT warmup** — at startup every registered bucket (and its per-mesh
  shardings) is lowered and compiled via ``jax.jit(...).lower().compile()``
  (the whole-program AOT stance of the Julia-to-TPU paper, arxiv
  1810.09868), so time-to-first-request is the same histogram bucket as
  steady state: no user request ever pays a compile.
* **SLO + admission control** — per-model p50/p99 latency gauges, a bounded
  admission queue, deadline-aware shedding: a full queue rejects at
  ``submit()`` with :class:`ServingOverloaded`, and requests whose deadline
  passed while queued are shed before wasting a forward on them — the
  "load shedding beats queueing collapse" discipline of serving heavy
  traffic.

Hot swap: the compiled state lives in ONE immutable :class:`BucketedForward`
(params + apply_fn + executables); ``update_model`` builds and warms a fresh
one off to the side, then atomically rebinds — a batch can never mix one
model's params with another's apply_fn, and no queued request is dropped.
"""

from __future__ import annotations

import collections
import json
import os
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import telemetry as _tm
from deeplearning4j_tpu.telemetry import tracectx as _tracectx
from deeplearning4j_tpu.datasets.iterator import BucketRegistry, ShapeBuckets
from deeplearning4j_tpu.serving import metering as _metering
from deeplearning4j_tpu.utils import compile_cache as _cc

#: fill-ratio buckets: eighths of the padded bucket (shared with
#: ParallelInference — "how much of each compiled forward was real work")
FILL_BUCKETS = tuple(i / 8.0 for i in range(1, 9))


class ServingOverloaded(RuntimeError):
    """Request shed by admission control: the bounded queue is full, or the
    request's deadline passed before a worker picked it up. ``reason``
    (``"queue_full"`` / ``"deadline"`` / ...) is machine-readable — the
    fleet wire protocol must not sniff it out of the message text (which
    embeds the free-form model name). A future re-raised fresh chains
    ``from`` the original, so the reason survives on ``__cause__``."""

    reason = None


def _overloaded(msg, reason):
    e = ServingOverloaded(msg)
    e.reason = reason
    return e


def shed_reason(exc):
    """The structured shed reason off a ServingOverloaded — directly, or
    from the original it was re-raised ``from`` (InferenceFuture.get
    raises a fresh copy chained to the one that carries the attr)."""
    for e in (exc, getattr(exc, "__cause__", None)):
        r = getattr(e, "reason", None)
        if r is not None:
            return r
    return None


def _origin_labels(meta):
    """Metric labels for a queue entry's request meta: synthetic traffic
    gets ``origin=...`` series (which every default SLO rule excludes);
    organic traffic keeps the unlabeled series it always had."""
    origin = (meta or {}).get("origin")
    return {"origin": str(origin)} if origin else {}


class ServingShutdown(RuntimeError):
    """Request failed because the engine stopped before serving it."""


class InferenceFuture:
    """Future-like holder for one submitted request (the reference's
    observable-completion contract, hardened): ``done()`` polls, ``get()``
    blocks, and a failed request raises a FRESH exception chained from the
    original (``raise ... from e``) — re-raising one shared instance across
    waiter threads would mutate its traceback concurrently."""

    # __weakref__ so graftsan (analysis/sanitizer.py) can track instances
    # without keeping them alive
    __slots__ = ("_event", "_value", "_error", "latency_s", "trace_id",
                 "__weakref__")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None
        #: submit-to-result seconds, stamped by the serving worker when the
        #: request completes (None until then / on the direct path)
        self.latency_s = None
        #: causal trace id for this request (telemetry.tracectx), stamped
        #: at submit when tracing is on — `latency_s` decomposes into the
        #: queue-wait/pad/exec/fetch child spans of that trace
        self.trace_id = None

    def done(self):
        """True once a result or error is set (never blocks)."""
        return self._event.is_set()

    def _set(self, v):
        self._value = v
        self._event.set()

    def _set_error(self, e):
        self._error = e
        self._event.set()

    def get(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("inference result not ready")
        err = self._error
        if err is not None:
            try:
                fresh = type(err)(*err.args)
            except Exception:
                fresh = RuntimeError(f"{type(err).__name__}: {err}")
            raise fresh from err
        return self._value


def _example_structs(input_spec, batch, dtype, seq=None):
    """Pytree of ``jax.ShapeDtypeStruct`` for a ``batch``-sized input.

    ``input_spec`` is a per-example shape tuple, or a dict of them (the
    ComputationGraph multi-input form). With ``seq`` (2-D shape buckets),
    the per-example leading axis — the sequence axis of a ``[T, ...]``
    spec — is replaced by the bucketed length.
    """
    def struct(shape):
        shape = tuple(int(d) for d in shape)
        if seq is not None:
            if not shape:
                raise ValueError(
                    "seq-bucketed serving needs a per-example input spec "
                    "with a leading sequence axis (got a scalar spec)")
            shape = (int(seq),) + shape[1:]
        return jax.ShapeDtypeStruct((batch,) + shape, dtype)
    if isinstance(input_spec, dict):
        return {k: struct(v) for k, v in input_spec.items()}
    return struct(input_spec)


def _as_input(x):
    """Host-normalize one request input: a dict is the ComputationGraph
    multi-input pytree (each value coerced per key); anything else —
    ndarray, list, tuple, scalar row — is ONE array. Feeding lists through
    tree_map directly would explode them into per-scalar leaves."""
    if isinstance(x, dict):
        return {k: np.asarray(v) for k, v in x.items()}
    return np.asarray(x)


def _pad_rows_np(tree, target, seq_target=None):
    """Zero-pad every leaf to ``target`` rows along axis 0 (host-side).
    With ``seq_target`` (2-D shape bucket) leaves carrying a sequence
    axis (``ndim >= 2``) are zero-padded along axis 1 as well — the
    exact pad whose real-row/real-step slice is bit-identical to the
    unpadded forward."""
    def pad(a):
        a = np.asarray(a)
        n = a.shape[0]
        if n != target:
            a = np.concatenate(
                [a, np.zeros((target - n,) + a.shape[1:], a.dtype)])
        if seq_target is not None and a.ndim >= 2 \
                and a.shape[1] != seq_target:
            width = [(0, 0)] * a.ndim
            width[1] = (0, seq_target - a.shape[1])
            a = np.pad(a, width)
        return a
    return jax.tree_util.tree_map(pad, tree)


def _slice_seq(tree, padded_seq, real_seq):
    """Undo the seq-axis pad on a forward's outputs: slice axis 1 back to
    ``real_seq`` on every leaf whose axis 1 is the padded length. A
    pooled ``[B, C]`` head (no time axis) passes through untouched unless
    C collides with the padded length — callers that pool to exactly the
    bucket width should size buckets away from their class count."""
    if real_seq == padded_seq:
        return tree
    def cut(a):
        if a.ndim >= 2 and a.shape[1] == padded_seq:
            return a[:, :real_seq]
        return a
    return jax.tree_util.tree_map(cut, tree)


class BucketedForward:
    """One model's compiled, bucketed forward — IMMUTABLE once built, so a
    hot swap is a single reference rebind and a running batch keeps a
    consistent (params, state, apply_fn, executables) snapshot.

    ``warmup(input_spec)`` AOT-compiles every registered bucket; a request
    size with no compiled bucket falls back to a lazy compile, counted into
    ``recompiles_total{site=}`` and the engine's ``aot`` stats — a rising
    ``lazy_compiles`` means the registered buckets don't cover live traffic.

    With a warm ``manifest`` (utils/compile_cache.WarmManifest) the warmup
    DESERIALIZES each bucket's executable instead of compiling it — a warm
    restart performs zero compiles for manifest-covered signatures; any
    key mismatch falls back to a live compile, counted separately
    (``compile_cache_total{event=miss}`` + the ``manifest_misses`` stat).
    A manifest built for a different architecture or backend is dropped at
    construction (``manifest: "mismatch"`` in the aot stats) rather than
    trusted.
    """

    def __init__(self, net, buckets: BucketRegistry, mesh=None,
                 site="serving", dtype=np.float32, manifest=None):
        self.net = net
        self.mesh = mesh
        self.site = site
        self._manifest_state = "none"
        if manifest is not None:
            if manifest.matches(net):
                self._manifest_state = "attached"
            else:
                # counted, surfaced, and refused — executables for another
                # architecture/backend fail at call time with opaque XLA
                # errors, not a clean fallback
                self._manifest_state = "mismatch"
                _cc.count_event("mismatch_drop")
                manifest = None
        self.manifest = manifest
        # dtype=None: serve requests in whatever dtype they arrive
        # (ParallelInference back-compat); a FIXED dtype is what lets the
        # serving engine promise one jit signature per bucket
        self.dtype = None if dtype is None else np.dtype(dtype)
        if mesh is not None:
            # imported here, not at module top: parallel/__init__ pulls in
            # ParallelInference, which is itself rebased on this module
            from deeplearning4j_tpu.parallel import mesh as _mesh
            nd = mesh.shape["data"]
            buckets = buckets.round_up_to_multiple(nd)
            self._repl = _mesh.replicated(mesh)
            data_sh = _mesh.data_sharded(mesh)
            self._place = lambda x: jax.tree_util.tree_map(
                lambda a: jax.device_put(a, data_sh), x)

            def raw(p, s, x):
                return net.apply_fn(p, s, x, train=False)[0]
            self._jit = jax.jit(raw, in_shardings=(self._repl, self._repl,
                                                   data_sh),
                                out_shardings=data_sh)
        else:
            self._repl = None
            self._place = lambda x: jax.tree_util.tree_map(jnp.asarray, x)

            def raw(p, s, x):
                return net.apply_fn(p, s, x, train=False)[0]
            self._jit = jax.jit(raw)
        # params/state are read LIVE from the net on every call (a net
        # trained in place between requests serves its current weights —
        # and never a donated stale buffer); the mesh replication below is
        # cached by tree identity so steady-state serving pays zero
        # placement dispatches
        self._placed = None       # (params_repl, state_repl)
        self._placed_src = None   # (net.params, net.state) they came from
        self.buckets = buckets
        #: 2-D (batch, seq) grid vs the 1-D batch-only registry — decides
        #: the pad/slice path and the warmup iteration space
        self.seq_aware = isinstance(buckets, ShapeBuckets)
        # mesh executables bake in shardings over a concrete device set:
        # scope the manifest key by mesh shape + device count so a pod
        # topology change can never resurrect a stale executable. The 2-D
        # seq grid folds in too (AFTER any mesh rounding): a grid change
        # must invalidate stale executables, not resurrect shapes the new
        # grid never declares
        kind = ("serving" if mesh is None else
                f"serving:mesh={sorted(mesh.shape.items())}"
                f":ndev={len(jax.devices())}")
        if self.seq_aware:
            kind += f":grid={buckets.signature()}"
        self._manifest_kind = kind
        self._compiled = {}  # input signature -> AOT executable (False=jit)
        # manifest signature (incl. the tuning-DB fingerprint) captured
        # WHEN each executable compiled — export must ship that stamp,
        # not the fingerprint active at save time (a mid-process DB
        # refresh would otherwise relabel stale executables as tuned)
        self._compiled_sigs = {}
        self._warmed = False  # has an AOT warmup declared coverage?
        self._lock = threading.Lock()
        self._aot = {"warmed": 0, "lazy_compiles": 0, "hits": 0,
                     "jit_serves": 0, "manifest_hits": 0,
                     "manifest_misses": 0}
        reg = self._reg = _tm.get_registry()
        self._m_fill = reg.histogram(
            "serving_batch_fill_ratio",
            "fraction of each padded device batch holding real examples",
            buckets=FILL_BUCKETS)
        self._m_token_fill = reg.histogram(
            "serving_batch_token_fill_ratio",
            "fraction of each padded (batch, seq) device shape holding "
            "real tokens — the padded-FLOPs waste signal; equals the row "
            "fill on batch-only (1-D) buckets",
            buckets=FILL_BUCKETS)
        self._m_aot = reg.counter(
            "serving_aot_cache_total",
            "compiled-bucket lookups (site=, result=hit/miss); misses pay "
            "a lazy compile and also count into recompiles_total")
        self._c_comp = reg.counter(
            "compiles_total",
            "jit cache entries created, labeled by site "
            "(first-fill warm-up included)")
        self._c_rec = reg.counter(
            "recompiles_total",
            "jit cache misses beyond the first fill, labeled "
            "by site — a rising series is a recompile storm")

    def warmup(self, input_spec):
        """Lower + compile the forward for every registered bucket (and the
        mesh shardings baked into the jit). Returns the wall seconds spent —
        the startup cost that buys a compile-free request path."""
        t0 = time.perf_counter()
        dtype = self.dtype if self.dtype is not None else np.dtype("float32")
        if self.seq_aware:
            # the full (batch, seq) grid: len(batch) * len(seq) executables
            for b, s in self.buckets:
                self._ensure_compiled(
                    _example_structs(input_spec, b, dtype, seq=s),
                    warm=True)
        else:
            for b in self.buckets:
                self._ensure_compiled(_example_structs(input_spec, b, dtype),
                                      warm=True)
        self._warmed = True
        return time.perf_counter() - t0

    @staticmethod
    def _signature(x_struct):
        """Cache key: the full (shape, dtype) signature — two dtypes (or a
        malformed request shape) must not collide on one executable."""
        return tuple((tuple(l.shape), str(l.dtype))
                     for l in jax.tree_util.tree_leaves(x_struct))

    def _ensure_compiled(self, x_struct, warm=False):
        """The AOT executable for this input signature (compiling on miss)."""
        key = self._signature(x_struct)
        with self._lock:
            ex = self._compiled.get(key)
            if ex is not None:
                if not warm:
                    if ex is False:
                        # a jit-fallback entry is NOT an AOT hit: counting
                        # it as one would let "lazy_compiles: 0" read as a
                        # healthy AOT path on a server with no working
                        # executables at all
                        self._aot["jit_serves"] += 1
                    else:
                        self._aot["hits"] += 1
                        self._m_aot.inc(result="hit", site=self.site)
                return ex
            # compile under the lock: two threads racing the same bucket
            # would otherwise both pay (and double-count) the compile.
            # Manifest-first: a warm restart deserializes the executable
            # (src == "manifest", ZERO compiles) and only a key miss pays
            # a live lower+compile. Serialize-back is warmup-only: a LAZY
            # compile runs under this lock on the request path, and the
            # put() verify-deserialize would stall every in-flight
            # request — export_manifest's save-time walk covers lazy
            # executables instead.
            sig_now = _cc.full_signature(json.dumps(key))
            try:
                ex, src = _cc.aot_compile(
                    self._jit, self.net.params, self.net.state, x_struct,
                    manifest=self.manifest, kind=self._manifest_kind,
                    signature=json.dumps(key), serialize_back=warm)
            except Exception:
                if warm:
                    # startup/update_model warmup must fail FAST: a spec
                    # the model rejects, reported as "warmed", would serve
                    # nothing but errors (or silent lazy compiles)
                    raise
                ex, src = False, "compile"
                # odd request signature: serve via the jit path, which
                # surfaces any real shape error
            self._compiled[key] = ex
            if ex is not False:
                self._compiled_sigs[key] = sig_now
            if src == "manifest":
                self._aot["manifest_hits"] += 1
            elif self.manifest is not None:
                self._aot["manifest_misses"] += 1
            if warm:
                self._aot["warmed"] += 1
            else:
                if src != "manifest":
                    # a lazy manifest hit compiles nothing — neither the
                    # lazy counter nor the aot result="miss" series may
                    # move for it, or hit-ratio alerts fire on requests
                    # that never paid a compile
                    self._aot["lazy_compiles"] += 1
                    self._m_aot.inc(result="miss", site=self.site)
                if self._warmed and src != "manifest":
                    # a compile the warmup sweep claimed to cover but
                    # didn't IS a recompile (a shape outside the
                    # registered buckets); cold lazy compiles on an
                    # unwarmed forward are just first-fill
                    self._c_rec.inc(site=self.site)
            if src != "manifest":
                # a manifest-served executable performed no compile —
                # counting it would make a warm restart's "zero compiles"
                # claim unfalsifiable
                self._c_comp.inc(site=self.site)
            return ex

    def aot_stats(self):
        with self._lock:
            return dict(self._aot, manifest=self._manifest_state)

    def export_manifest(self):
        """The warm manifest covering every executable this forward has
        compiled (or restored): the attached manifest — autofilled by
        ``aot_compile`` as live compiles happen — or a fresh one built
        from the compiled buckets. Save it beside the checkpoint and the
        next restart's warmup performs zero compiles."""
        m = self.manifest
        if m is None:
            m = _cc.WarmManifest.for_net(self.net)
        with self._lock:
            compiled = dict(self._compiled)
            sigs = dict(self._compiled_sigs)
        for key, ex in compiled.items():
            # same key discipline as aot_compile's lookups: the tuning-DB
            # fingerprint ACTIVE WHEN THIS EXECUTABLE COMPILED folds into
            # the signature, so a restart under a re-tuned DB misses
            # these entries instead of serving executables baked with
            # stale kernel configs
            sig = sigs.get(key, _cc.full_signature(json.dumps(key)))
            if ex is False or m.has(self._manifest_kind, sig):
                continue  # jit fallback entries have no executable to ship
            m.put(self._manifest_kind, sig, ex)
        return m

    def _resolve(self):
        """The (params, state) to serve THIS call: always the net's live
        trees. With a mesh they are replicated on first use and the
        placement is reused until the net rebinds them (post-fit trees are
        new objects, so the identity check catches every update)."""
        net = self.net
        params, state = net.params, net.state
        if self._repl is None:
            return params, state
        with self._lock:
            if self._placed_src is not None \
                    and self._placed_src[0] is params \
                    and self._placed_src[1] is state:
                return self._placed
            placed = (jax.device_put(params, self._repl),
                      jax.device_put(state, self._repl))
            self._placed_src = (params, state)
            self._placed = placed
            return placed

    def _run(self, x_padded, _phases=None):
        """One compiled forward at the padded signature; jit fallback when
        AOT lowering was unavailable or rejects the call convention.
        ``_phases`` (when given) collects measured ``(name, t0, t1, args)``
        windows — AOT-cache lookup, device exec — that the serving worker
        copies into every request trace of the batch."""
        x_struct = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), x_padded)
        t0 = time.perf_counter() if _phases is not None else 0.0
        ex = self._ensure_compiled(x_struct)
        if _phases is not None:
            _phases.append(("serving.aot_lookup", t0, time.perf_counter(),
                            {"aot": ex is not False}))
        params, state = self._resolve()
        x_dev = self._place(x_padded)
        t0 = time.perf_counter() if _phases is not None else 0.0
        try:
            if ex is not False:
                try:
                    return ex(params, state, x_dev)
                except TypeError:
                    pass  # AOT arg-passing quirk on this jax version
            return self._jit(params, state, x_dev)
        finally:
            if _phases is not None:
                _phases.append(("serving.device_exec", t0,
                                time.perf_counter(), {}))

    def __call__(self, x, _phases=None, _usage=None):
        """Padded, bucketed forward of a host batch (any leading size):
        chunks by the largest batch bucket, pads each chunk up to its
        nearest registered bucket — BOTH axes under a 2-D grid: rows to
        the batch bucket, the sequence axis to the seq bucket — and
        slices real rows (and real timesteps) back out. ``_phases``
        collects per-phase timing windows for causal tracing (serving
        worker); ``_usage`` (a list) collects one
        ``{rows, seq, batch_bucket, seq_bucket}`` record per device chunk
        so the caller can meter padded vs real tokens exactly."""
        x = _as_input(x)
        first = jax.tree_util.tree_leaves(x)[0]
        n = first.shape[0]
        seq_in = (first.shape[1]
                  if self.seq_aware and first.ndim >= 2 else None)
        if self.seq_aware and seq_in is None:
            raise ValueError(
                f"{self.site}: seq-bucketed serving requires inputs with "
                f"a sequence axis ([rows, steps, ...]); got shape "
                f"{tuple(first.shape)}")
        outs = []
        step = self.buckets.max
        for i in range(0, n, step):
            t0 = time.perf_counter() if _phases is not None else 0.0
            chunk = jax.tree_util.tree_map(
                lambda a: np.asarray(a[i:i + step], dtype=self.dtype), x)
            real = jax.tree_util.tree_leaves(chunk)[0].shape[0]
            if self.seq_aware:
                shape = self.buckets.bucket_for(real, seq_in)
                if shape is None:
                    raise ValueError(
                        f"{self.site}: sequence of {seq_in} steps exceeds "
                        f"the largest registered seq bucket "
                        f"({self.buckets.max_seq}) — sequences cannot be "
                        "chunked")
                bucket, seq_bucket = shape
                fill = real / bucket
                token_fill = (real * seq_in) / (bucket * seq_bucket)
            else:
                bucket, seq_bucket = self.buckets.bucket_for(real), None
                fill = token_fill = real / bucket
            padded = _pad_rows_np(chunk, bucket, seq_target=seq_bucket)
            if _usage is not None:
                _usage.append({"rows": real, "seq": seq_in or 1,
                               "batch_bucket": bucket,
                               "seq_bucket": seq_bucket or 1})
            if _phases is not None:
                _phases.append(("serving.pad", t0, time.perf_counter(),
                                {"bucket": bucket,
                                 "seq_bucket": seq_bucket,
                                 "fill": round(fill, 4),
                                 "token_fill": round(token_fill, 4)}))
            with _tm.span("serving.forward", fill=fill, bucket=bucket,
                          seq_bucket=seq_bucket):
                y = self._run(padded, _phases)
                t0 = time.perf_counter() if _phases is not None else 0.0
                y = jax.tree_util.tree_map(
                    lambda a: np.asarray(a)[:real], y)
                if seq_bucket is not None:
                    y = _slice_seq(y, seq_bucket, seq_in)
                if _phases is not None:
                    _phases.append(("serving.fetch", t0,
                                    time.perf_counter(), {}))
            if self._reg.enabled:
                self._m_fill.observe(fill, site=self.site)
                # token fill rides beside row fill: a full batch of short
                # prompts padded to a long seq bucket reads 1.0 rows but
                # near-zero tokens — the waste row fill can't see
                self._m_token_fill.observe(token_fill, site=self.site)
            outs.append(y)
        if len(outs) == 1:
            return outs[0]
        return jax.tree_util.tree_map(
            lambda *parts: np.concatenate(parts), *outs)


class ServingEngine:
    """Continuous-batching inference server for ONE named model.

    ``submit()`` is the async request path (bounded admission queue,
    deadline-aware shedding); ``output()`` is the synchronous direct path
    (same compiled buckets, no queue). ``update_model()`` hot-swaps the
    served model atomically. ``stats()`` is the /serving status payload.
    """

    def __init__(self, net, *, name="default", input_spec=None,
                 buckets=None, seq_buckets=None, max_batch_size=32,
                 mesh=None, max_queue=256,
                 default_deadline_s=None, batch_window_s=0.0,
                 dtype=np.float32, warmup=None, warm_manifest=None):
        self.name = name
        self.mesh = mesh
        self.batch_window_s = batch_window_s
        self.default_deadline_s = default_deadline_s
        self._input_spec = input_spec
        self._dtype = np.dtype(dtype)
        if isinstance(warm_manifest, (str, os.PathLike)):
            # a path: the instant-restart artifact saved beside the
            # checkpoint (save_warm_manifest / utils.serialization bundle).
            # A truncated/non-zip file degrades to a cold warmup — the
            # manifest tier never turns a working server into a crash
            warm_manifest = _cc.WarmManifest.load_lenient(
                warm_manifest, context=f"warm manifest {warm_manifest!r}")
        self._warm_manifest = warm_manifest
        if not isinstance(buckets, ShapeBuckets):
            if buckets is None:
                buckets = BucketRegistry.powers_of_two(max_batch_size)
            elif not isinstance(buckets, BucketRegistry):
                buckets = BucketRegistry(buckets)
            if seq_buckets is not None:
                # the 2-D grid: batch sizes x declared seq edges
                buckets = ShapeBuckets(buckets, seq_buckets)
        self._fwd = BucketedForward(net, buckets, mesh,
                                    site=f"serving:{name}", dtype=dtype,
                                    manifest=warm_manifest)
        self.max_queue = max_queue
        self._pending_rows = 0  # queued EXAMPLES (a batched entry is n)
        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()
        # seq-aware continuous batching: one deque PER SEQ BUCKET (a
        # single None key on 1-D registries, which keeps the historical
        # one-global-queue behavior bit-for-bit), so requests coalesce
        # within a seq bucket and a short prompt is never padded into a
        # long batch. The condition shares the admission lock: enqueue,
        # drain and the pending-rows bound stay one atomic story.
        self._queues = {}
        self._not_empty = threading.Condition(self._lock)
        self._counts = {"submitted": 0, "served": 0, "shed_queue_full": 0,
                        "shed_deadline": 0, "errors": 0, "swaps": 0}
        self._recent_latencies = []   # bounded ring; /serving works even
        self._warmup_s = None         # with telemetry disabled
        reg = self._reg = _tm.get_registry()
        self._m_depth = reg.gauge(
            "serving_admission_queue_depth",
            "pending requests in the bounded admission queue, per model")
        self._m_latency = reg.histogram(
            "serving_model_latency_seconds",
            "submit-to-result request latency, per model")
        self._m_p50 = reg.gauge(
            "serving_latency_p50_seconds",
            "rolling p50 request latency per model (SLO gauge)")
        self._m_p99 = reg.gauge(
            "serving_latency_p99_seconds",
            "rolling p99 request latency per model (SLO gauge)")
        self._m_requests = reg.counter(
            "serving_model_requests_total",
            "requests by model and outcome "
            "(submitted/served/shed_queue_full/shed_deadline/error)")
        self._m_shed = reg.counter(
            "serving_shed_total",
            "load-shed requests per model and reason "
            "(queue_full / deadline / shutdown)")
        self._m_warm = reg.gauge(
            "serving_warmup_seconds",
            "wall seconds the AOT bucket warmup took at startup, per model")
        self._m_seq_len = reg.histogram(
            "serving_request_seq_len",
            "requested sequence lengths (steps) per model — the demand "
            "distribution seq grid edges derive from "
            "(datasets.iterator.seq_edges_from_demand)",
            buckets=(16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192))
        if reg.enabled:
            # pre-register every outcome series at zero (the prober
            # idiom): a shed/error series born mid-storm contributes
            # nothing to the SLO delta window it first appears in
            for outcome in ("submitted", "served", "served_direct",
                            "shed_queue_full", "shed_deadline", "error"):
                self._m_requests.inc(0, model=self.name, outcome=outcome)
        if warmup is None:
            warmup = input_spec is not None
        if warmup:
            self.warmup()

    # ---- lifecycle ----

    def warmup(self):
        """AOT-compile every registered bucket now, so no request pays a
        compile. Requires ``input_spec`` (per-example shape, or a dict of
        them for multi-input graphs)."""
        if self._input_spec is None:
            raise ValueError(
                "warmup needs input_spec (per-example feature shape)")
        self._warmup_s = self._fwd.warmup(self._input_spec)
        self._m_warm.set(self._warmup_s, model=self.name)
        return self._warmup_s

    def start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Stop the worker and FAIL every request it never picked up with
        :class:`ServingShutdown` — a stopped engine must not leave waiters
        blocked until their own get() timeout. ``submit()`` after stop
        raises immediately."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._fail_pending()

    def _pop_locked(self, dq):
        """Pop one entry off ``dq`` (holding the lock), releasing its
        admission rows (the submit side charged them)."""
        entry = dq.popleft()
        self._pending_rows -= entry[5] or 1  # graftlint: disable=R6 -- every caller holds self._not_empty (the _locked contract)
        return entry

    def _fail_pending(self):
        """Drain every seq-bucket queue, failing every pending request
        with :class:`ServingShutdown` (stop(), and submit()'s race
        guard)."""
        err = ServingShutdown(
            f"serving engine {self.name!r} stopped before serving this "
            f"request")
        with self._not_empty:
            drained = []
            for dq in self._queues.values():
                while dq:
                    drained.append(self._pop_locked(dq))
        for _, fut, _t, _dl, tctx, _n, _meta in drained:
            if not fut.done():
                fut._set_error(err)
                self._count("errors")
                if self._reg.enabled:
                    self._m_shed.inc(model=self.name, reason="shutdown")
            if tctx is not None:
                # a drained request's trace never completed its causal
                # story — close it without ringing
                tctx.abandon()

    @property
    def running(self):
        return self._thread is not None and self._thread.is_alive()

    @property
    def net(self):
        return self._fwd.net

    @property
    def buckets(self):
        return self._fwd.buckets

    def update_model(self, net, warm=None, *, manifest=None):
        """Hot-swap the served model. The replacement BucketedForward is
        built and (by default, when the engine knows its input spec) AOT-
        warmed OFF the serving path, then atomically rebound — in-flight
        batches finish on the old snapshot, later batches use the new one,
        and no queued request is dropped or errored by the swap. The
        engine's shape grid (1-D or 2-D) is reused as-is: a swap changes
        weights, never shapes. ``manifest``: warm manifest shipped WITH
        the replacement (a bundle's instant-restart artifact); it
        replaces the construction-time one for this and later swaps.
        Callers gating grids should validate it first
        (serving.registry.ModelRegistry.update_model does)."""
        if manifest is not None:
            if isinstance(manifest, (str, os.PathLike)):
                manifest = _cc.WarmManifest.load_lenient(
                    manifest, context=f"warm manifest {manifest!r}")
            if manifest is not None:
                self._warm_manifest = manifest
        fresh = BucketedForward(net, self._fwd.buckets, self.mesh,
                                site=f"serving:{self.name}",
                                dtype=self._dtype,
                                manifest=self._warm_manifest)
        if warm is None:
            warm = self._input_spec is not None
        if warm:
            if self._input_spec is None:
                raise ValueError(
                    "update_model(warm=True) needs input_spec")
            fresh.warmup(self._input_spec)
        self._fwd = fresh
        self._count("swaps")

    def export_warm_manifest(self):
        """The warm manifest covering every executable the served forward
        holds (utils/compile_cache.WarmManifest) — the instant-restart
        artifact. Returns None when nothing is serializable."""
        m = self._fwd.export_manifest()
        return m if len(m) else None

    def save_warm_manifest(self, path):
        """Serialize the served executables to ``path`` (zip). A restart
        that passes ``warm_manifest=path`` then warms up with ZERO
        compiles for every covered bucket. Returns the path, or None when
        no executable was serializable (the backend cannot export — the
        persistent compile cache tier still applies)."""
        m = self.export_warm_manifest()
        if m is None:
            return None
        return m.save(path)

    # ---- request paths ----

    def output(self, x):
        """Synchronous direct inference (no queue): pads/buckets internally,
        same compiled executables as the batched path. Counted into
        ``stats()``/the SLO ring like any served traffic — a server driven
        synchronously must not read as idle on /serving."""
        enabled = self._reg.enabled
        # direct-path trace: same root name as the queued path would be
        # misleading (no queue-wait exists), so it rings separately
        tctx = _tracectx.maybe_start("serving.request_direct",
                                     model=self.name)
        t0 = time.perf_counter()
        try:
            with _tracectx.attach(tctx):
                with _tm.span("serving.output", model=self.name):
                    out = self._fwd(x)  # asarray/bucketing per chunk
        except BaseException:
            if tctx is not None:
                # a failed direct call still completes its causal story
                # (and must not leave the trace open forever)
                tctx.finish(status="error")
            raise
        dt = time.perf_counter() - t0
        _cc.note_first_request()
        if tctx is not None:
            tctx.finish()
        n = jax.tree_util.tree_leaves(out)[0].shape[0]
        self._count("served", n)
        # ctxs: the direct request's trace stamps its latency bucket's
        # exemplar exactly like the queued path's does
        self._note_latencies([dt], ctxs=[tctx])
        if enabled:
            self._m_requests.inc(n, model=self.name, outcome="served_direct")
        return out

    def submit(self, x, deadline_s=None, *, batched=False, tctx=None,
               tenant=None, origin=None):
        """Queue ONE example (or, with ``batched=True``, one MULTI-example
        batch — leading axis = examples); returns ONE
        :class:`InferenceFuture`. A batched future resolves to the stacked
        ``[n, ...]`` outputs of its rows; the rows ride the same
        assemble/pad path as single-example requests, so a client holding
        a natural batch pays one submit and one wait instead of n.

        Admission control bounds queued EXAMPLES: a batched submit of n
        rows spends n of the ``max_queue`` slots, so batching cannot
        smuggle unbounded work past the bound. A full queue sheds the
        request here
        (``ServingOverloaded``, counted per model) rather than letting the
        backlog grow without bound; ``deadline_s`` (or the engine default)
        sheds it later if it goes stale while queued.

        ``tctx``: an already-rooted TraceContext to adopt instead of
        starting a fresh ``serving.request`` — the fleet worker passes
        its remote-parented context here so the device-side spans land
        on the ROUTER's trace (wire-propagated tracing).

        ``tenant`` attributes the request in the usage ledger
        (serving/metering.py); ``origin="probe"`` marks synthetic
        traffic — its counter series carry an ``origin`` label (which
        every default SLO rule excludes) and it never enters the rolling
        p50/p99 latency ring, so organic SLIs stay untouched by canaries
        and health checks. Probe traffic IS still metered: device time
        is device time, and the usage ledger must balance against router
        row accounting exactly.
        """
        if self._stop.is_set():
            raise ServingShutdown(
                f"serving engine {self.name!r} is stopped")
        meta = None
        if tenant is not None or origin is not None:
            meta = {"tenant": tenant, "origin": origin}
        olab = {"origin": str(origin)} if origin else {}
        fut = InferenceFuture()
        # the request's causal trace starts HERE: the root span is the
        # submit->resolve window, and the drain thread attaches via the
        # handoff carried in the queue tuple. Tracing off: None, a branch.
        if tctx is None:
            tctx = _tracectx.maybe_start("serving.request", model=self.name)
        if tctx is not None:
            fut.trace_id = tctx.trace_id
        now = time.perf_counter()
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        deadline = None if deadline_s is None else now + deadline_s
        self._count("submitted")
        if self._reg.enabled:
            self._m_requests.inc(model=self.name, outcome="submitted",
                                 **olab)
        try:
            # _as_input, not plain asarray: x may be the dict multi-input
            # form (ComputationGraph) the warmup spec and output() support.
            # The queue carries [n, ...] ROWS for every entry — a single
            # example is wrapped to n=1 and unwrapped at resolve, so the
            # worker has ONE assemble path (concatenate) for both forms.
            item = _as_input(x)
            if batched:
                # every leaf must carry the examples on a SHARED axis 0:
                # a multi-input dict with disagreeing leading dims would
                # be admitted on leaf one's count and detonate inside the
                # drain batch, failing innocent co-batched requests
                dims = {(int(np.shape(l)[0]) if np.ndim(l) else -1)
                        for l in jax.tree_util.tree_leaves(item)}
                if len(dims) != 1 or -1 in dims:
                    raise ValueError(
                        "batched submit requires every input leaf to "
                        "carry the examples on axis 0 with one shared "
                        f"length; got leading dims {sorted(dims)}")
                nrows = dims.pop()
                if nrows == 0:
                    # a 0-row entry would still count as one drain slot
                    # and shift every other request's resolve slice —
                    # refuse it here, where the caller can see why
                    raise ValueError(
                        "batched submit requires at least one example "
                        "(got a 0-row batch)")
                if nrows > self.max_queue:
                    # can NEVER be admitted: shedding it would read as
                    # transient load and send a well-behaved client into
                    # a retry-forever loop — fail it as a sizing error
                    raise ValueError(
                        f"batched submit of {nrows} rows exceeds the "
                        f"admission bound (max_queue={self.max_queue}) "
                        "and could never be admitted — split the batch "
                        "or raise max_queue")
            else:
                nrows = None
                item = jax.tree_util.tree_map(lambda a: a[None], item)
            skey = None
            if self._fwd.seq_aware:
                lead = jax.tree_util.tree_leaves(item)[0]
                if lead.ndim < 2:
                    raise ValueError(
                        f"model {self.name!r} serves 2-D (batch, seq) "
                        "buckets: requests need a sequence axis "
                        "([steps, ...] per example)")
                seq = int(lead.shape[1])
                skey = self._fwd.buckets.seq.bucket_for(seq)
                if skey is None:
                    # a sizing error, not load: shedding it would read as
                    # transient and retry forever (same stance as an
                    # inadmissibly large batched submit)
                    raise ValueError(
                        f"model {self.name!r}: sequence of {seq} steps "
                        f"exceeds the largest registered seq bucket "
                        f"({self._fwd.buckets.max_seq})")
                # the demand distribution grid edges derive from; and the
                # wire/meter view of the seq the engine bucketed
                meta = dict(meta or {}, seq=seq)
                if self._reg.enabled:
                    self._m_seq_len.observe(seq, model=self.name, **olab)
        except BaseException:
            if tctx is not None:
                # malformed input (asarray raised): the request never
                # entered the queue — close its trace, don't leak it
                tctx.abandon()
            raise
        rows = 1 if nrows is None else nrows
        try:
            with self._not_empty:
                # admission bounds queued EXAMPLES, not queue entries: a
                # batched entry spends one slot per row, so batching
                # cannot smuggle unbounded work past the load-shedding
                # contract max_queue documents
                if self._pending_rows + rows > self.max_queue:
                    raise queue.Full
                self._pending_rows += rows
                self._queues.setdefault(
                    skey, collections.deque()).append(
                        (item, fut, now, deadline,
                         None if tctx is None else tctx.handoff(),
                         nrows, meta))
                self._not_empty.notify()
        except queue.Full:
            self._count("shed_queue_full")
            if self._reg.enabled:
                self._m_shed.inc(model=self.name, reason="queue_full",
                                 **olab)
                self._m_requests.inc(model=self.name,
                                     outcome="shed_queue_full", **olab)
            if tctx is not None:
                # shed decision as a child span, then the trace completes
                # (a shed IS an end-to-end outcome worth ringing: the p99
                # story under overload is "we shed you")
                tctx.add_span("serving.shed", now, time.perf_counter(),
                              reason="queue_full")
                tctx.finish(status="shed")
            raise _overloaded(
                f"model {self.name!r}: admission queue full "
                f"({self.max_queue} pending)", "queue_full") from None
        if self._stop.is_set():
            # raced stop(): its drain may already have run, leaving this
            # request in a queue nobody reads — fail it (and any other
            # stragglers) rather than hang the waiter forever
            self._fail_pending()
        if self._reg.enabled:
            self._m_depth.set(self._pending_rows, model=self.name)
        return fut

    # ---- worker ----

    def _drain(self):
        """Continuous-batching drain: block briefly for the FIRST request,
        then take everything already queued in ITS seq bucket (no
        per-slot waits), then — only if the batch still has room and a
        batch window is configured — wait under ONE shared deadline for
        same-bucket stragglers. The worst-case added latency is
        ``batch_window_s`` total, not per empty slot.

        Seq-awareness: a drain batch is drawn from exactly ONE seq-bucket
        queue — the one whose head request has waited longest (arrival
        order across buckets, so no bucket starves) — because co-batching
        requests across seq buckets would pad every short prompt in the
        batch to the longest one's bucket, which is precisely the waste
        the 2-D grid exists to cut. On a 1-D registry there is a single
        ``None`` bucket and this is the historical global-queue drain."""
        cap = self._fwd.buckets.max

        def entry_rows(e):
            # entries carry [n, ...] rows (batched submits n > 1); the cap
            # bounds device-batch ROWS, not queue entries
            return e[5] or 1

        def oldest_key():
            # (found, key): the 1-D path queues under key None, so None
            # itself can't double as the "nothing queued" signal
            live = [k for k, dq in self._queues.items() if dq]
            if not live:
                return False, None
            return True, min(live, key=lambda k: self._queues[k][0][2])

        batch, rows = [], 0
        with self._not_empty:
            found, skey = oldest_key()
            if not found:
                self._not_empty.wait(timeout=0.05)
                found, skey = oldest_key()
                if not found:
                    return []
            dq = self._queues[skey]
            while dq and rows < cap:
                e = self._pop_locked(dq)
                batch.append(e)
                rows += entry_rows(e)
            if rows < cap and self.batch_window_s > 0:
                deadline = time.perf_counter() + self.batch_window_s
                while rows < cap:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0 or \
                            not self._not_empty.wait(timeout=remaining):
                        break
                    # woken: stragglers may have landed in OUR bucket (a
                    # notify for another bucket's arrival just loops)
                    dq = self._queues.get(skey)
                    while dq and rows < cap:
                        e = self._pop_locked(dq)
                        batch.append(e)
                        rows += entry_rows(e)
        return batch

    def _worker(self):
        while not self._stop.is_set():
            batch = self._drain()
            if not batch:
                continue
            now = time.perf_counter()
            live = []
            for item in batch:
                _x, fut, t_sub, deadline, tctx, _n, meta = item
                olab = _origin_labels(meta)
                if deadline is not None and now > deadline:
                    # stale request: shed it instead of spending a forward
                    # on an answer nobody is waiting for (deadline-aware
                    # load shedding)
                    self._count("shed_deadline")
                    if self._reg.enabled:
                        self._m_shed.inc(model=self.name, reason="deadline",
                                         **olab)
                        self._m_requests.inc(model=self.name,
                                             outcome="shed_deadline",
                                             **olab)
                    if tctx is not None:
                        tctx.add_span("serving.queue_wait", t_sub, now)
                        tctx.add_span("serving.shed", now, now,
                                      reason="deadline")
                        tctx.finish(status="shed")
                    # error LAST: a waiter that wakes on the future must
                    # see a COMPLETE trace (the fleet worker ships the
                    # doc back on the wire right after fut.get())
                    fut._set_error(_overloaded(
                        f"model {self.name!r}: deadline exceeded while "
                        f"queued ({1e3 * (now - t_sub):.1f} ms)",
                        "deadline"))
                    continue
                live.append(item)
            if self._reg.enabled:
                self._m_depth.set(self._pending_rows, model=self.name)
            if not live:
                continue
            # a failing forward (bad input shape, mid-swap architecture
            # mismatch) must fail THESE requests, not kill the serving loop
            try:
                # phase windows (assemble/pad/aot/exec/fetch) are measured
                # once per device batch and copied into EVERY member
                # request's trace — the batch is one device-side event
                # shared by N causal stories
                phases = ([] if any(it[4] is not None for it in live)
                          else None)
                n_rows = sum(it[5] or 1 for it in live)
                seq_aware = self._fwd.seq_aware
                with _tm.span("serving.batch", model=self.name,
                              size=n_rows):
                    t_asm = time.perf_counter()
                    # every entry is [n, ...] rows (single submits n=1, so
                    # this is the old stack): concatenate dict inputs too.
                    # A seq-aware drain batch is seq-bucket-uniform, but
                    # real lengths inside the bucket still vary — pad each
                    # entry's seq axis to the batch max (still <= the
                    # bucket BucketedForward pads to) so the concat is
                    # rectangular
                    parts = [b[0] for b in live]
                    batch_seq = None
                    if seq_aware:
                        batch_seq = max((b[6] or {}).get("seq", 1)
                                        for b in live)
                        parts = [
                            _pad_rows_np(p, b[5] or 1, seq_target=batch_seq)
                            for p, b in zip(parts, live)]
                    xs = jax.tree_util.tree_map(
                        lambda *leaves: np.concatenate(leaves), *parts)
                    if phases is not None:
                        phases.append(("serving.assemble", t_asm,
                                       time.perf_counter(),
                                       {"size": n_rows}))
                    t_fwd = time.perf_counter()
                    usage = []
                    ys = self._fwd(xs, _phases=phases,  # one atomic
                                   _usage=usage)        # model snapshot
                done = time.perf_counter()
                device_s = done - t_fwd
                # FLOPs priced at the padded (batch, seq) device shapes
                # the forward ACTUALLY ran — the 2-D grid makes this fall
                # for short prompts; the 1-D path degenerates to the old
                # padded-rows charge (seq bucket 1)
                padded_rows = sum(u["batch_bucket"] for u in usage)
                padded_tokens = sum(u["batch_bucket"] * u["seq_bucket"]
                                    for u in usage)
                flops = _metering.estimate_flops(
                    self._param_count(), padded_rows,
                    padded_tokens=padded_tokens)
                meter = _metering.get_meter()
                _cc.note_first_request()
                lats, ctxs, origins, off = [], [], [], 0
                for x_in, fut, t_sub, _dl, tctx, n, meta in live:
                    width = n or 1
                    real_seq = (meta or {}).get("seq", 1) if seq_aware \
                        else 1
                    # the usage ledger: every served row is attributed
                    # (probe traffic included — device time is device
                    # time), device wall, FLOPs and padded tokens
                    # prorated by rows; seq_tokens are the entry's REAL
                    # tokens, so padded - seq is the waste column
                    meter.record(
                        self.name, rows=width,
                        tokens=sum(int(np.size(l)) for l in
                                   jax.tree_util.tree_leaves(x_in)),
                        seq_tokens=width * real_seq,
                        padded_tokens=padded_tokens * width / n_rows,
                        queue_s=now - t_sub,
                        device_s=device_s * width / n_rows,
                        flops=flops * width / n_rows,
                        tenant=(meta or {}).get("tenant"))
                    y = jax.tree_util.tree_map(
                        lambda a: a[off:off + width], ys)
                    if batch_seq is not None:
                        # back to the entry's REAL length before the row
                        # axis is dropped (axis 1 is still the seq axis)
                        y = _slice_seq(y, batch_seq, real_seq)
                    if n is None:
                        y = jax.tree_util.tree_map(lambda a: a[0], y)
                    off += width
                    lats.append(done - t_sub)
                    ctxs.append(tctx)
                    origins.append((meta or {}).get("origin"))
                    if tctx is not None:
                        tctx.add_span("serving.queue_wait", t_sub, now)
                        for nm, a, b, kw in phases:
                            tctx.add_span(nm, a, b, **kw)
                        tctx.add_span("serving.resolve", done,
                                      time.perf_counter())
                        tctx.finish()
                    fut.latency_s = done - t_sub
                    # resolve LAST: a waiter that wakes here must see a
                    # COMPLETE trace (the fleet worker reads the doc and
                    # ships it back on the wire right after fut.get())
                    fut._set(y)
                self._count("served", n_rows)
                self._note_latencies(lats, outcome="served", ctxs=ctxs,
                                     origins=origins)
            except Exception as e:  # noqa: BLE001 — propagate to waiters
                for _, fut, _t, _dl, tctx, _n, meta in live:
                    if tctx is not None:
                        tctx.finish(status="error")
                    if not fut.done():
                        fut._set_error(e)
                    if self._reg.enabled:
                        self._m_requests.inc(model=self.name,
                                             outcome="error",
                                             **_origin_labels(meta))
                self._count("errors", len(live))

    def _count(self, key, n=1):
        with self._lock:
            self._counts[key] += n

    def _note_latencies(self, lats, outcome=None, ctxs=None, origins=None):
        """Record request latencies into the rolling SLO ring and refresh
        the p50/p99 gauges; with ``outcome`` each also counts into the
        per-model requests counter (the direct path counts its examples
        separately, so it passes None). ``ctxs`` (aligned with ``lats``)
        attaches each request's trace context around its observation, so
        the latency histogram's tail bucket carries that request's
        exemplar — the p99 gauge links to a concrete trace. ``origins``
        (aligned) marks synthetic requests: they observe into origin-
        labeled histogram series but NEVER enter the rolling ring or the
        p50/p99 gauges — a canary storm cannot move an organic SLI."""
        organic = [dt for i, dt in enumerate(lats)
                   if not (origins and origins[i])]
        with self._lock:
            self._recent_latencies.extend(organic)
            del self._recent_latencies[:-512]
            recent = list(self._recent_latencies)
        if self._reg.enabled:
            for i, dt in enumerate(lats):
                olab = ({"origin": str(origins[i])}
                        if origins and origins[i] else {})
                with _tracectx.attach(ctxs[i] if ctxs else None):
                    self._m_latency.observe(dt, model=self.name, **olab)
                if outcome is not None:
                    self._m_requests.inc(model=self.name, outcome=outcome,
                                         **olab)
            if recent:
                self._m_p50.set(float(np.percentile(recent, 50)),
                                model=self.name)
                self._m_p99.set(float(np.percentile(recent, 99)),
                                model=self.name)

    def _param_count(self):
        """Parameter count of the CURRENTLY served forward (recomputed
        cheaply per batch so a hot swap re-prices FLOPs); 0 when the net
        doesn't expose params — metering degrades to zero-FLOPs rows,
        never an error on the serving path."""
        try:
            return sum(int(np.size(l)) for l in
                       jax.tree_util.tree_leaves(self._fwd.net.params))
        except Exception:
            return 0

    # ---- status ----

    def health(self):
        """The per-process health export the fleet wire protocol ships
        (fleet/worker.py ``/health``): the engine's serving stats plus
        the compile-cache events and recompile counters a supervisor
        needs to counter-assert "this worker warm-started and is not
        compiling on the request path" without reaching into the
        process, plus this model's slice of the usage ledger (the
        per-model demand signal fleet /health aggregation folds up)."""
        from deeplearning4j_tpu.telemetry import devices as _devices
        usage = _metering.get_meter().usage()["models"].get(self.name)
        return {"stats": self.stats(),
                "compile_cache_events": _cc.event_counts(),
                "recompiles": _devices.recompile_counts(),
                "usage": usage}

    def latency_percentiles(self):
        """(p50_s, p99_s) over the recent-latency ring, or (None, None)."""
        with self._lock:
            recent = list(self._recent_latencies)
        if not recent:
            return None, None
        return (float(np.percentile(recent, 50)),
                float(np.percentile(recent, 99)))

    def stats(self):
        """The /serving status payload for this model."""
        with self._lock:
            counts = dict(self._counts)
        p50, p99 = self.latency_percentiles()
        fwd = self._fwd
        return {
            "model": self.name,
            "running": self.running,
            # 1-D: flat batch sizes (the historical payload); 2-D: the
            # batch axis, with the seq axis beside it — wire consumers
            # (fleet describe/health) keep reading ints either way
            "buckets": (fwd.buckets.batch.sizes() if fwd.seq_aware
                        else fwd.buckets.sizes()),
            "seq_buckets": (fwd.buckets.seq.sizes() if fwd.seq_aware
                            else None),
            "mesh": None if self.mesh is None else dict(self.mesh.shape),
            "max_queue": self.max_queue,
            "queue_depth": self._pending_rows,  # EXAMPLES, matching
            #                                  the admission bound
            "requests": counts,
            "aot": self._fwd.aot_stats(),
            "warmup_s": self._warmup_s,
            "latency_ms": {
                "p50": None if p50 is None else round(1e3 * p50, 3),
                "p99": None if p99 is None else round(1e3 * p99, 3)},
        }


