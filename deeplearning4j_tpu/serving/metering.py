"""Per-model / per-tenant usage metering: who is consuming the device.

The serving tier already counts *outcomes* (submitted/served/shed); what
elasticity needs is attributed *consumption*: how many rows, input
tokens, queue-seconds, device-exec seconds, and estimated FLOPs each
model (and each tenant, when the optional ``tenant`` field rides the
submit path router→worker→engine) actually burned. :class:`UsageMeter`
is that ledger.

Two views of the same numbers, recorded atomically per forward:

* an in-process ledger dict (always on, survives ``telemetry.disable``)
  whose per-model row totals balance EXACTLY against the router's
  ``served_rows`` accounting — the invariant ``scripts/check_demand.py``
  gates on. Synthetic ``origin=probe`` traffic IS metered (device time
  is device time; exclusion from SLIs happens at the metric-label layer,
  not here) so the two sides of the ledger see the same rows;
* ``usage_*_total{model,tenant}`` counters in the MetricsRegistry, so
  the federation/history/SLO planes can rate and window attribution
  like any other series.

The ledger serves on the worker/UI ``/usage`` endpoint and is folded
into fleet ``/health`` aggregation — the offered-load-per-model signal
the ROADMAP's elasticity item keys on.
"""

from __future__ import annotations

import threading

from deeplearning4j_tpu.telemetry import registry as _registry

#: ledger label for unattributed traffic (no tenant field on submit)
NO_TENANT = "-"

_FIELDS = ("rows", "tokens", "seq_tokens", "padded_tokens",
           "queue_seconds", "device_seconds", "flops")


class UsageMeter:
    """Accumulate per-(model, tenant) usage; export ledger + counters."""

    def __init__(self, registry=None):
        self._reg = registry or _registry.get_registry()
        self._lock = threading.Lock()
        self._ledger = {}  # (model, tenant) -> {field: total}
        self._m = {
            "rows": self._reg.counter(
                "usage_rows_total",
                "rows served per model and tenant (balances exactly "
                "against router served_rows)"),
            "tokens": self._reg.counter(
                "usage_tokens_total",
                "input elements consumed per model and tenant"),
            "seq_tokens": self._reg.counter(
                "usage_seq_tokens_total",
                "REAL sequence tokens served per model and tenant "
                "(rows x real steps; rows on batch-only models)"),
            "padded_tokens": self._reg.counter(
                "usage_padded_tokens_total",
                "PADDED sequence tokens the device ran per model and "
                "tenant (batch_bucket x seq_bucket per chunk, prorated "
                "by rows) — minus usage_seq_tokens_total this is the "
                "padded-waste column the 2-D shape grid exists to cut"),
            "queue_seconds": self._reg.counter(
                "usage_queue_seconds_total",
                "seconds requests spent queued per model and tenant"),
            "device_seconds": self._reg.counter(
                "usage_device_seconds_total",
                "device-exec seconds attributed per model and tenant "
                "(forward wall prorated by rows)"),
            "flops": self._reg.counter(
                "usage_flops_total",
                "estimated forward FLOPs per model and tenant "
                "(2 * params * padded rows, prorated)"),
        }

    def record(self, model, *, rows=0, tokens=0, seq_tokens=0,
               padded_tokens=0, queue_s=0.0, device_s=0.0, flops=0.0,
               tenant=None):
        """One request's consumption. Negative clock skew is clamped —
        the ledger is monotone by construction. ``seq_tokens`` /
        ``padded_tokens`` are the real-vs-padded sides of the seq-axis
        waste column (engine worker; zero on paths that predate it)."""
        model = str(model)
        tenant = NO_TENANT if tenant is None else str(tenant)
        vals = {"rows": max(int(rows), 0),
                "tokens": max(int(tokens), 0),
                "seq_tokens": max(float(seq_tokens), 0.0),
                "padded_tokens": max(float(padded_tokens), 0.0),
                "queue_seconds": max(float(queue_s), 0.0),
                "device_seconds": max(float(device_s), 0.0),
                "flops": max(float(flops), 0.0)}
        with self._lock:
            row = self._ledger.setdefault(  # graftlint: disable=R6 -- setdefault runs under self._lock
                (model, tenant), {f: 0.0 for f in _FIELDS})
            for f in _FIELDS:
                row[f] += vals[f]
        if self._reg.enabled:
            for f in _FIELDS:
                if vals[f]:
                    self._m[f].inc(vals[f], model=model, tenant=tenant)

    def usage(self):
        """The /usage payload: per-model totals with a per-tenant
        breakdown, plus the grand totals."""
        with self._lock:
            items = [(k, dict(v)) for k, v in self._ledger.items()]
        models = {}
        totals = {f: 0.0 for f in _FIELDS}
        for (model, tenant), vals in sorted(items):
            m = models.setdefault(model, {f: 0.0 for f in _FIELDS})
            m.setdefault("tenants", {})
            m["tenants"][tenant] = {f: _num(vals[f]) for f in _FIELDS}
            for f in _FIELDS:
                m[f] += vals[f]
                totals[f] += vals[f]
        for m in models.values():
            for f in _FIELDS:
                m[f] = _num(m[f])
        return {"models": models,
                "totals": {f: _num(totals[f]) for f in _FIELDS}}

    def rows_for(self, model):
        """Total metered rows for one model (the ledger-balance probe)."""
        with self._lock:
            return int(sum(v["rows"] for (m, _t), v in self._ledger.items()
                           if m == str(model)))

    def clear(self):
        with self._lock:
            self._ledger.clear()


def _num(v):
    """Integral floats print as ints in JSON (rows/tokens are counts)."""
    return int(v) if float(v).is_integer() else float(v)


def estimate_flops(param_count, padded_rows, *, padded_tokens=None):
    """Dense-forward estimate from the registered shapes: 2 FLOPs per
    parameter per padded row (multiply + add). Deliberately crude — a
    ranking signal for attribution, not a performance model; padding is
    charged because padding burns the device all the same. With
    ``padded_tokens`` (2-D shape buckets) the charge is per padded
    ``batch_bucket x seq_bucket`` TOKEN instead — on a batch-only engine
    the two are the same number (seq bucket 1), so the ledger's FLOPs
    column falls exactly when the seq grid stops padding to max_seq."""
    units = padded_rows if padded_tokens is None else padded_tokens
    return 2.0 * float(param_count) * float(units)


# ---- process-default meter ----

_default = None
_default_lock = threading.Lock()


def get_meter():
    """Process-default meter, created on first use (every ServingEngine
    records into it, so one process = one ledger)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = UsageMeter()
        return _default


def reset():
    """Drop the process-default meter (telemetry.reset())."""
    global _default
    with _default_lock:
        _default = None
