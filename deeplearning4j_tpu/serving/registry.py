"""Multi-model serving registry: several named models behind one process.

Reference analog: TensorFlow Serving's model manager (the serving half of
the system paper, PAPERS.md arxiv 1605.08695) — named models, each with its
own continuous-batching engine, atomic ``update_model`` hot swaps, and one
status surface (`/serving` on the UIServer, the ``serve`` CLI verb).

The registry PERSISTS each model's engine kwargs (the 2-D ``buckets``/
``seq_buckets`` shape grid included): :meth:`ModelRegistry.register_like`
registers an A/B challenger under the incumbent's exact serving config,
and a hot swap keeps the engine's grid by construction. A swap bundle
that ships a warm manifest is gated first — a manifest whose executables
were baked for a DIFFERENT shape grid is rejected with a counted
``serving_bundle_rejected_total`` increment (never silently attached,
which would degrade every request to a lazy compile).
"""

from __future__ import annotations

import os
import threading

from deeplearning4j_tpu import telemetry as _tm
from deeplearning4j_tpu.serving.engine import ServingEngine
from deeplearning4j_tpu.utils import compile_cache as _cc


def manifest_grid_signatures(manifest):
    """The set of 2-D grid signatures a warm manifest's SERVING
    executables were compiled for — ``None`` in the set stands for
    batch-only (1-D) entries whose kind carries no ``:grid=`` tag.
    Empty when the manifest holds no serving executables at all."""
    grids = set()
    for kind, _sig in manifest.keys():
        if not str(kind).startswith("serving"):
            continue
        grids.add(kind.split(":grid=", 1)[1] if ":grid=" in kind
                  else None)
    return grids


class ModelRegistry:
    """Named :class:`ServingEngine` instances with atomic hot swap."""

    def __init__(self):
        self._lock = threading.RLock()
        self._engines = {}
        self._engine_kw = {}  # name -> kwargs register() built with
        self._m_rejected = _tm.get_registry().counter(
            "serving_bundle_rejected_total",
            "hot-swap bundles refused per model and reason "
            "(grid_mismatch: the bundle's warm manifest was baked for a "
            "different shape grid than the registered engine serves)")

    def register(self, name, net, *, start=True, **engine_kw):
        """Build (and by default start) a serving engine for ``net`` under
        ``name``. Engine kwargs (``input_spec``, ``buckets``,
        ``seq_buckets``, ``mesh``, ``max_queue``, ``default_deadline_s``,
        ...) pass through; with an ``input_spec`` the engine AOT-warms
        every bucket before this returns, so the model is compile-free
        from its first request. The kwargs are retained per model —
        the A/B (:meth:`register_like`) and hot-swap paths carry the
        same serving config, the 2-D shape grid included."""
        def duplicate():
            return ValueError(f"model {name!r} already registered; use "
                              f"update_model for a hot swap")
        with self._lock:
            # check BEFORE building: the constructor AOT-warms every bucket
            # (seconds of compile) and registers per-model gauges — work
            # that must not run, let alone clobber the live engine's
            # metrics, for a name that will be rejected
            if name in self._engines:
                raise duplicate()
        engine = ServingEngine(net, name=name, **engine_kw)
        with self._lock:
            if name in self._engines:  # raced a concurrent register
                raise duplicate()
            self._engines[name] = engine
            self._engine_kw[name] = dict(engine_kw)
        if start:
            engine.start()
        return engine

    def engine_kwargs(self, name):
        """The engine kwargs ``name`` was registered with (a copy)."""
        self.engine(name)  # raise the helpful KeyError on unknown names
        with self._lock:
            return dict(self._engine_kw.get(name, {}))

    def register_like(self, src_name, name, net, *, start=True,
                      **overrides):
        """A/B helper: register ``net`` under ``name`` with the SAME
        engine kwargs as the incumbent ``src_name`` (input spec, shape
        grid, deadlines — the whole serving config), ``overrides``
        applied on top. The challenger then pads/buckets identically to
        the champion, so latency and waste comparisons are
        apples-to-apples."""
        kw = self.engine_kwargs(src_name)
        kw.update(overrides)
        return self.register(name, net, start=start, **kw)

    def engine(self, name) -> ServingEngine:
        with self._lock:
            try:
                return self._engines[name]
            except KeyError:
                raise KeyError(
                    f"no model {name!r} registered; known: "
                    f"{sorted(self._engines)}") from None

    def update_model(self, name, net, warm=None, *, manifest=None):
        """Atomic hot swap of one named model (in-flight batches finish on
        the old snapshot; no queued request is dropped). The engine keeps
        its registered shape grid — a swap changes weights, never shapes.

        ``manifest``: the replacement bundle's warm manifest (a
        :class:`~deeplearning4j_tpu.utils.compile_cache.WarmManifest` or
        a path to one). It is gated BEFORE the swap: executables baked
        for a different (batch, seq) grid than this engine serves are a
        config error, not a warm start — the swap is rejected with a
        ``ValueError`` and a ``serving_bundle_rejected_total`` count,
        never silently attached (every request would otherwise pay a
        lazy compile while the stale executables sit unused)."""
        engine = self.engine(name)
        if manifest is not None:
            self._gate_bundle_grid(engine, manifest)
        engine.update_model(net, warm=warm)

    def _gate_bundle_grid(self, engine, manifest):
        if isinstance(manifest, (str, os.PathLike)):
            manifest = _cc.WarmManifest.load_lenient(
                manifest, context=f"swap bundle manifest {manifest!r}")
            if manifest is None:  # unreadable file: cold swap, not a gate
                return
        declared = manifest_grid_signatures(manifest)
        if not declared:
            return  # no serving executables to disagree with
        fwd = engine._fwd
        registered = (fwd.buckets.signature() if fwd.seq_aware else None)
        if declared != {registered}:
            def show(g):
                return sorted("batch-only" if s is None else s
                              for s in g)
            if _tm.get_registry().enabled:
                self._m_rejected.inc(model=engine.name,
                                     reason="grid_mismatch")
            raise ValueError(
                f"model {engine.name!r}: swap bundle's warm manifest "
                f"was baked for shape grid(s) {show(declared)} but the "
                f"registered engine serves "
                f"{show({registered})} — re-export the manifest on the "
                f"registered grid (counted in "
                f"serving_bundle_rejected_total)")

    def unregister(self, name):
        with self._lock:
            engine = self._engines.pop(name)
        engine.stop()

    def names(self):
        with self._lock:
            return sorted(self._engines)

    def submit(self, name, x, deadline_s=None, *, batched=False,
               tenant=None, origin=None):
        return self.engine(name).submit(x, deadline_s=deadline_s,
                                        batched=batched, tenant=tenant,
                                        origin=origin)

    def output(self, name, x):
        return self.engine(name).output(x)

    def status(self):
        """The /serving payload: per-model engine stats."""
        with self._lock:
            engines = list(self._engines.values())
        return {"models": {e.name: e.stats() for e in engines}}

    def health(self):
        """Per-model engine health exports (the fleet worker wire
        payload, aggregated over every registered model)."""
        with self._lock:
            engines = list(self._engines.values())
        return {"models": {e.name: e.health() for e in engines}}

    def stop(self):
        with self._lock:
            engines = list(self._engines.values())
            self._engines.clear()
        for e in engines:
            e.stop()


_default = None
_default_lock = threading.Lock()


def get_model_registry() -> ModelRegistry:
    """The process-wide default registry — what the UIServer's /serving
    endpoint and the ``serve`` CLI verb read."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = ModelRegistry()
    return _default


def reset():
    """Stop every engine in the default registry and drop it (tests)."""
    global _default
    with _default_lock:
        reg, _default = _default, None
    if reg is not None:
        reg.stop()
