"""Multi-model serving registry: several named models behind one process.

Reference analog: TensorFlow Serving's model manager (the serving half of
the system paper, PAPERS.md arxiv 1605.08695) — named models, each with its
own continuous-batching engine, atomic ``update_model`` hot swaps, and one
status surface (`/serving` on the UIServer, the ``serve`` CLI verb).
"""

from __future__ import annotations

import threading

from deeplearning4j_tpu.serving.engine import ServingEngine


class ModelRegistry:
    """Named :class:`ServingEngine` instances with atomic hot swap."""

    def __init__(self):
        self._lock = threading.RLock()
        self._engines = {}

    def register(self, name, net, *, start=True, **engine_kw):
        """Build (and by default start) a serving engine for ``net`` under
        ``name``. Engine kwargs (``input_spec``, ``buckets``, ``mesh``,
        ``max_queue``, ``default_deadline_s``, ...) pass through; with an
        ``input_spec`` the engine AOT-warms every bucket before this
        returns, so the model is compile-free from its first request."""
        def duplicate():
            return ValueError(f"model {name!r} already registered; use "
                              f"update_model for a hot swap")
        with self._lock:
            # check BEFORE building: the constructor AOT-warms every bucket
            # (seconds of compile) and registers per-model gauges — work
            # that must not run, let alone clobber the live engine's
            # metrics, for a name that will be rejected
            if name in self._engines:
                raise duplicate()
        engine = ServingEngine(net, name=name, **engine_kw)
        with self._lock:
            if name in self._engines:  # raced a concurrent register
                raise duplicate()
            self._engines[name] = engine
        if start:
            engine.start()
        return engine

    def engine(self, name) -> ServingEngine:
        with self._lock:
            try:
                return self._engines[name]
            except KeyError:
                raise KeyError(
                    f"no model {name!r} registered; known: "
                    f"{sorted(self._engines)}") from None

    def update_model(self, name, net, warm=None):
        """Atomic hot swap of one named model (in-flight batches finish on
        the old snapshot; no queued request is dropped)."""
        self.engine(name).update_model(net, warm=warm)

    def unregister(self, name):
        with self._lock:
            engine = self._engines.pop(name)
        engine.stop()

    def names(self):
        with self._lock:
            return sorted(self._engines)

    def submit(self, name, x, deadline_s=None, *, batched=False,
               tenant=None, origin=None):
        return self.engine(name).submit(x, deadline_s=deadline_s,
                                        batched=batched, tenant=tenant,
                                        origin=origin)

    def output(self, name, x):
        return self.engine(name).output(x)

    def status(self):
        """The /serving payload: per-model engine stats."""
        with self._lock:
            engines = list(self._engines.values())
        return {"models": {e.name: e.stats() for e in engines}}

    def health(self):
        """Per-model engine health exports (the fleet worker wire
        payload, aggregated over every registered model)."""
        with self._lock:
            engines = list(self._engines.values())
        return {"models": {e.name: e.health() for e in engines}}

    def stop(self):
        with self._lock:
            engines = list(self._engines.values())
            self._engines.clear()
        for e in engines:
            e.stop()


_default = None
_default_lock = threading.Lock()


def get_model_registry() -> ModelRegistry:
    """The process-wide default registry — what the UIServer's /serving
    endpoint and the ``serve`` CLI verb read."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = ModelRegistry()
    return _default


def reset():
    """Stop every engine in the default registry and drop it (tests)."""
    global _default
    with _default_lock:
        reg, _default = _default, None
    if reg is not None:
        reg.stop()
