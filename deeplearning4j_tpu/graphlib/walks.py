"""Random-walk generators.

Reference analog: graph/iterator/RandomWalkIterator.java /
WeightedWalkIterator.java in /root/reference/deeplearning4j-graph.
"""

from __future__ import annotations

import numpy as np


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex."""

    def __init__(self, graph, walk_length, *, seed=0, no_edge_handling="self_loop"):
        self.graph = graph
        self.walk_length = walk_length
        self.rs = np.random.RandomState(seed)
        self.no_edge_handling = no_edge_handling

    def __iter__(self):
        for start in range(self.graph.n_vertices):
            yield self.walk_from(start)

    def walk_from(self, start):
        walk = [start]
        cur = start
        for _ in range(self.walk_length - 1):
            nbrs = self.graph.neighbors(cur)
            if not nbrs:
                if self.no_edge_handling == "self_loop":
                    walk.append(cur)
                    continue
                break
            cur = nbrs[self.rs.randint(len(nbrs))]
            walk.append(cur)
        return walk


class WeightedWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional walks."""

    def walk_from(self, start):
        walk = [start]
        cur = start
        for _ in range(self.walk_length - 1):
            nbrs = self.graph.neighbors_weighted(cur)
            if not nbrs:
                walk.append(cur)
                continue
            weights = np.array([w for _, w in nbrs])
            probs = weights / weights.sum()
            cur = nbrs[self.rs.choice(len(nbrs), p=probs)][0]
            walk.append(cur)
        return walk
