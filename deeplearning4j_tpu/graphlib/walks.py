"""Random-walk generators.

Reference analog: graph/iterator/RandomWalkIterator.java /
WeightedWalkIterator.java in /root/reference/deeplearning4j-graph.
"""

from __future__ import annotations

import numpy as np


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex."""

    def __init__(self, graph, walk_length, *, seed=0, no_edge_handling="self_loop"):
        self.graph = graph
        self.walk_length = walk_length
        self.rs = np.random.RandomState(seed)
        self.no_edge_handling = no_edge_handling

    def __iter__(self):
        for start in range(self.graph.n_vertices):
            yield self.walk_from(start)

    def walk_from(self, start):
        walk = [start]
        cur = start
        for _ in range(self.walk_length - 1):
            nbrs = self.graph.neighbors(cur)
            if not nbrs:
                if self.no_edge_handling == "self_loop":
                    walk.append(cur)
                    continue
                break
            cur = nbrs[self.rs.randint(len(nbrs))]
            walk.append(cur)
        return walk


class WeightedWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional walks."""

    def walk_from(self, start):
        walk = [start]
        cur = start
        for _ in range(self.walk_length - 1):
            nbrs = self.graph.neighbors_weighted(cur)
            if not nbrs:
                walk.append(cur)
                continue
            weights = np.array([w for _, w in nbrs])
            probs = weights / weights.sum()
            cur = nbrs[self.rs.choice(len(nbrs), p=probs)][0]
            walk.append(cur)
        return walk


class Node2VecWalkIterator(RandomWalkIterator):
    """Second-order biased walks (reference: models/node2vec/Node2Vec.java,
    which layers the Grover-Leskovec p/q sampling over SequenceVectors).

    Transition weight from walk step (t -> v) to candidate x:
      1/p if x == t (return), 1 if x is a neighbor of t (BFS-like),
      1/q otherwise (DFS-like).
    """

    def __init__(self, graph, walk_length, *, p=1.0, q=1.0, seed=0,
                 no_edge_handling="self_loop"):
        super().__init__(graph, walk_length, seed=seed,
                         no_edge_handling=no_edge_handling)
        self.p = float(p)
        self.q = float(q)

    def walk_from(self, start):
        walk = [start]
        prev = None
        cur = start
        for _ in range(self.walk_length - 1):
            nbrs = self.graph.neighbors(cur)
            if not nbrs:
                if self.no_edge_handling == "self_loop":
                    walk.append(cur)
                    continue
                break
            if prev is None:
                nxt = nbrs[self.rs.randint(len(nbrs))]
            else:
                prev_nbrs = set(self.graph.neighbors(prev))
                w = np.array([1.0 / self.p if x == prev
                              else (1.0 if x in prev_nbrs else 1.0 / self.q)
                              for x in nbrs])
                nxt = nbrs[self.rs.choice(len(nbrs), p=w / w.sum())]
            walk.append(nxt)
            prev, cur = cur, nxt
        return walk
