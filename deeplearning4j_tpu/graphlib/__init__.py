from deeplearning4j_tpu.graphlib.graph import Graph  # noqa: F401
from deeplearning4j_tpu.graphlib.walks import (  # noqa: F401
    Node2VecWalkIterator, RandomWalkIterator, WeightedWalkIterator,
)
from deeplearning4j_tpu.graphlib.deepwalk import DeepWalk, Node2Vec  # noqa: F401
