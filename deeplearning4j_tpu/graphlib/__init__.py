from deeplearning4j_tpu.graphlib.graph import Graph  # noqa: F401
from deeplearning4j_tpu.graphlib.walks import RandomWalkIterator, WeightedWalkIterator  # noqa: F401
from deeplearning4j_tpu.graphlib.deepwalk import DeepWalk  # noqa: F401
