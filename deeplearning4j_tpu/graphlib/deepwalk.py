"""DeepWalk graph embeddings.

Reference analog: graph/models/deepwalk/DeepWalk.java + GraphHuffman.java in
/root/reference/deeplearning4j-graph — random walks fed to skip-gram with
hierarchical softmax over a degree-based Huffman tree. Here the walks feed
SequenceVectors (the same reuse the reference makes of its word2vec core).
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.graphlib.walks import (Node2VecWalkIterator,
                                                RandomWalkIterator)
from deeplearning4j_tpu.text.word2vec import SequenceVectors


class DeepWalk:
    def __init__(self, *, vector_size=64, window=5, walk_length=40,
                 walks_per_vertex=10, learning_rate=0.05, epochs=3,
                 use_hierarchic_softmax=True, negative=5, seed=0):
        self.vector_size = vector_size
        self.window = window
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.use_hs = use_hierarchic_softmax
        self.negative = negative
        self.seed = seed
        self.vectors = None

    def _walks(self, graph):
        walks = []
        for rep in range(self.walks_per_vertex):
            it = RandomWalkIterator(graph, self.walk_length, seed=self.seed + rep)
            for walk in it:
                walks.append([str(v) for v in walk])
        return walks

    def fit(self, graph):
        walks = self._walks(graph)
        self._sv = SequenceVectors(
            vector_size=self.vector_size, window=self.window, min_count=1,
            negative=0 if self.use_hs else self.negative,
            learning_rate=self.learning_rate, epochs=self.epochs,
            batch_size=1024, subsample=0,
            use_hierarchic_softmax=self.use_hs, seed=self.seed)
        self._sv.fit(walks)
        self.vectors = np.stack([
            self._sv.get_word_vector(str(v)) if self._sv.has_word(str(v))
            else np.zeros(self.vector_size, np.float32)
            for v in range(graph.n_vertices)])
        return self

    def get_vertex_vector(self, v):
        return self.vectors[v]

    def similarity(self, a, b):
        va, vb = self.vectors[a], self.vectors[b]
        return float(np.dot(va, vb) /
                     (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12))


class Node2Vec(DeepWalk):
    """node2vec graph embeddings (reference: models/node2vec/Node2Vec.java —
    SequenceVectors over biased p/q walks). p controls return likelihood,
    q interpolates BFS (<1: outward/DFS-like) vs local (>1) exploration."""

    def __init__(self, *, p=1.0, q=1.0, **kw):
        super().__init__(**kw)
        self.p = float(p)
        self.q = float(q)

    def _walks(self, graph):
        walks = []
        for rep in range(self.walks_per_vertex):
            it = Node2VecWalkIterator(graph, self.walk_length, p=self.p,
                                      q=self.q, seed=self.seed + rep)
            for walk in it:
                walks.append([str(v) for v in walk])
        return walks
