"""Graph file loading: the GraphLoader role.

Reference analog: org.deeplearning4j.graph.data.GraphLoader
(loadUndirectedGraphEdgeListFile, loadWeightedEdgeListFile, the
vertex+edge two-file form) — the reference's own TestGraphLoading /
TestGraphLoadingWeighted drive it against
deeplearning4j-graph/src/test/resources/{simplegraph,WeightedGraph,
test_graph_vertices,test_graph_edges}.txt; the same genuine files
validate this module. Comment lines start ``//`` in those fixtures;
``ignore_prefix`` mirrors the reference's ignoreLinesStartingWith.
"""

from __future__ import annotations

from deeplearning4j_tpu.graphlib.graph import Graph


def _data_lines(path, ignore_prefix="//"):
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if line and not (ignore_prefix and
                             line.startswith(ignore_prefix)):
                yield lineno, line


def _vertex_id(raw, n_vertices, path, lineno):
    """int id, range-checked: a negative id would silently alias to a
    high-index vertex through Python list indexing."""
    v = int(raw)
    if not 0 <= v < n_vertices:
        raise ValueError(f"{path}:{lineno}: vertex id {v} outside "
                         f"[0, {n_vertices})")
    return v


def load_undirected_edge_list(path, n_vertices, *, delimiter=",",
                              ignore_prefix="//"):
    """``from,to`` lines -> undirected unweighted Graph
    (GraphLoader.loadUndirectedGraphEdgeListFile)."""
    g = Graph(n_vertices, directed=False)
    for lineno, line in _data_lines(path, ignore_prefix):
        a, b = line.split(delimiter)
        g.add_edge(_vertex_id(a, n_vertices, path, lineno),
                   _vertex_id(b, n_vertices, path, lineno))
    return g


def load_weighted_edge_list(path, n_vertices, *, delimiter=",",
                            directed=False, ignore_prefix="//"):
    """``from,to,weight`` lines -> weighted Graph
    (GraphLoader.loadWeightedEdgeListFile)."""
    g = Graph(n_vertices, directed=directed)
    for lineno, line in _data_lines(path, ignore_prefix):
        a, b, w = line.split(delimiter)
        g.add_edge(_vertex_id(a, n_vertices, path, lineno),
                   _vertex_id(b, n_vertices, path, lineno),
                   weight=float(w))
    return g


def load_graph(vertex_path, edge_path, *, delimiter=",",
               vertex_delimiter=":", directed=False, ignore_prefix="//"):
    """Two-file form (GraphLoader.loadGraph): a vertex file of
    ``index:label`` lines and an edge file of ``from,to`` lines.
    Returns (Graph, [label, ...]) with labels indexed by vertex id."""
    labels = {}
    for _, line in _data_lines(vertex_path, ignore_prefix):
        idx, label = line.split(vertex_delimiter, 1)
        labels[int(idx)] = label
    n = max(labels) + 1 if labels else 0
    if set(labels) != set(range(n)):
        missing = sorted(set(range(n)) - set(labels))
        raise ValueError(f"{vertex_path}: vertex ids not contiguous "
                         f"(missing {missing[:5]})")
    g = Graph(n, directed=directed)
    for lineno, line in _data_lines(edge_path, ignore_prefix):
        a, b = line.split(delimiter)
        g.add_edge(_vertex_id(a, n, edge_path, lineno),
                   _vertex_id(b, n, edge_path, lineno))
    return g, [labels[i] for i in range(n)]
