"""Graph API.

Reference analog: deeplearning4j-graph (/root/reference/deeplearning4j-graph/
src/main/java/org/deeplearning4j/graph/) — IGraph/Graph adjacency-list API
used by DeepWalk.
"""

from __future__ import annotations



class Graph:
    """Adjacency-list graph with optional edge weights."""

    def __init__(self, n_vertices, directed=False):
        self.n_vertices = n_vertices
        self.directed = directed
        self._adj = [[] for _ in range(n_vertices)]      # list of (dst, weight)

    def add_edge(self, a, b, weight=1.0):
        self._adj[a].append((b, float(weight)))
        if not self.directed:
            self._adj[b].append((a, float(weight)))

    def neighbors(self, v):
        return [d for d, _ in self._adj[v]]

    def neighbors_weighted(self, v):
        return list(self._adj[v])

    def degree(self, v):
        return len(self._adj[v])

    def num_edges(self):
        total = sum(len(a) for a in self._adj)
        return total if self.directed else total // 2
