"""Image directory loading: the DataVec ImageRecordReader role.

Reference analog: org.datavec.image ImageRecordReader(height, width,
channels, ParentPathLabelGenerator) — directory-per-class image trees,
as the reference's Spark data tests drive against
dl4j-spark/src/test/resources/imagetest/{0,1}/*.bmp
(TestDataVecDataSetFunctions.java, the image path). Decoding via PIL;
output is NHWC float32 (the TPU-native conv layout) with one-hot labels
from the parent directory name, sorted for a stable class index.
"""

from __future__ import annotations

import glob
import os

import numpy as np


def load_image(path, *, height=None, width=None, channels=3):
    """[H, W, C] float32 in [0, 255] (use datasets.normalizers.
    ImagePreProcessingScaler for 0-1 scaling, like the reference)."""
    from PIL import Image

    if channels not in (1, 3, 4):
        raise ValueError(f"channels must be 1, 3 or 4, got {channels}")
    if (height is None) != (width is None):
        raise ValueError("pass BOTH height and width to resize (got "
                         f"height={height}, width={width})")
    img = Image.open(path)
    img = img.convert({1: "L", 3: "RGB", 4: "RGBA"}[channels])
    if height is not None:
        img = img.resize((width, height))
    arr = np.asarray(img, np.float32)
    if arr.ndim == 2:
        arr = arr[..., None]
    return arr


def image_dataset(root, *, height, width, channels=3, extensions=None):
    """(images [N, H, W, C], labels [N, n_classes], class_names) from a
    directory-per-class tree — the ImageRecordReader +
    ParentPathLabelGenerator contract. Classes are the sorted child
    directory names; every readable image under each contributes one
    example."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    if not classes:
        raise ValueError(f"{root}: no class subdirectories")
    exts = tuple(e.lower() for e in (extensions
                                     or ("bmp", "png", "jpg", "jpeg",
                                         "gif")))
    xs, ys = [], []
    for ci, cname in enumerate(classes):
        # extension match is case-insensitive (.BMP/.JPG from cameras)
        files = sorted(
            os.path.join(root, cname, f) for f in
            os.listdir(os.path.join(root, cname))
            if "." in f and f.rsplit(".", 1)[1].lower() in exts)
        if not files:
            raise ValueError(f"{root}/{cname}: no images matching {exts}")
        for p in files:
            xs.append(load_image(p, height=height, width=width,
                                 channels=channels))
            ys.append(ci)
    x = np.stack(xs)
    y = np.eye(len(classes), dtype=np.float32)[np.asarray(ys)]
    return x, y, classes
