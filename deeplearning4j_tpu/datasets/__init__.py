from deeplearning4j_tpu.datasets.iterator import (  # noqa: F401
    DataSet, ArrayDataSetIterator, AsyncDataSetIterator, BenchmarkDataSetIterator,
    EarlyTerminationIterator, MultipleEpochsIterator, ShardedDataSetIterator,
)
from deeplearning4j_tpu.datasets.fetchers import (  # noqa: F401
    Cifar10DataFetcher, EmnistDataFetcher, IrisDataFetcher, LfwDataFetcher,
    MnistDataFetcher, SvhnDataFetcher, SyntheticDataFetcher,
    TinyImageNetFetcher, UciSequenceDataFetcher,
    cifar10_iterator, emnist_iterator, iris_iterator, mnist_iterator,
    svhn_iterator, synthetic_iterator, tiny_imagenet_iterator,
    uci_sequence_iterator,
)
from deeplearning4j_tpu.datasets.cacheable import (  # noqa: F401
    ChecksumError, ensure_extracted, ensure_file,
)
