from deeplearning4j_tpu.datasets.iterator import (  # noqa: F401
    DataSet, ArrayDataSetIterator, AsyncDataSetIterator, BenchmarkDataSetIterator,
    EarlyTerminationIterator, MultipleEpochsIterator,
)
from deeplearning4j_tpu.datasets.fetchers import (  # noqa: F401
    IrisDataFetcher, MnistDataFetcher, SyntheticDataFetcher,
    iris_iterator, mnist_iterator, synthetic_iterator,
)
