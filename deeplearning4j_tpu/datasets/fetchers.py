"""Dataset fetchers.

Reference analog: datasets/fetchers/ in /root/reference/deeplearning4j-core —
MnistDataFetcher (binary idx parsing in datasets/mnist/),
CacheableExtractableDataSetFetcher (download+cache+checksum), IrisDataFetcher,
and the iterator impls datasets/iterator/impl/ (MnistDataSetIterator,
IrisDataSetIterator, ...).

Offline-first: fetchers read from a local data directory
(``DL4J_TPU_DATA_DIR``, default ~/.deeplearning4j_tpu/data). Downloading is
gated — this build environment has zero egress, so missing data raises a
clear error pointing at the expected file layout; SyntheticDataFetcher covers
tests/benchmarks.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator


def data_dir():
    return os.environ.get("DL4J_TPU_DATA_DIR",
                          os.path.expanduser("~/.deeplearning4j_tpu/data"))


def _read_idx(path):
    """Parse an IDX (MNIST) file, gzipped or raw."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">HBB", f.read(4))
        _, dtype_code, ndim = magic
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dtype = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
                 0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}[dtype_code]
        data = np.frombuffer(f.read(), dtype=dtype.newbyteorder(">"))
        return data.reshape(dims)


class MnistDataFetcher:
    """Reads idx files from <data_dir>/mnist/ (train-images-idx3-ubyte[.gz],
    train-labels-idx1-ubyte[.gz], t10k-*)."""

    NUM_TRAIN = 60000
    NUM_TEST = 10000

    def __init__(self, train=True, root=None):
        root = root or os.path.join(data_dir(), "mnist")
        prefix = "train" if train else "t10k"
        img = self._find(root, f"{prefix}-images-idx3-ubyte")
        lab = self._find(root, f"{prefix}-labels-idx1-ubyte")
        self.images = _read_idx(img).astype(np.float32) / 255.0
        self.labels = np.eye(10, dtype=np.float32)[_read_idx(lab).astype(np.int64)]

    @staticmethod
    def _find(root, base):
        for cand in (os.path.join(root, base), os.path.join(root, base + ".gz")):
            if os.path.exists(cand):
                return cand
        raise FileNotFoundError(
            f"MNIST file {base}[.gz] not found under {root}. This environment "
            f"has no network egress; place the standard MNIST idx files there "
            f"or use SyntheticDataFetcher for benchmarks.")

    def arrays(self, flatten=False):
        x = self.images.reshape(-1, 784) if flatten else self.images[..., None]
        return x, self.labels


# Fisher's Iris measurements (public-domain data, embedded like the
# reference embeds it via IrisUtils; 150 rows of sepal/petal cm + class).
_IRIS_BASE = np.array([
    [5.0, 3.4, 1.5, 0.2], [4.9, 3.0, 1.4, 0.2], [4.7, 3.2, 1.3, 0.2],
    [4.6, 3.1, 1.5, 0.2], [5.0, 3.6, 1.4, 0.2], [5.4, 3.9, 1.7, 0.4],
    [6.4, 3.2, 4.5, 1.5], [6.9, 3.1, 4.9, 1.5], [5.5, 2.3, 4.0, 1.3],
    [6.5, 2.8, 4.6, 1.5], [5.7, 2.8, 4.5, 1.3], [6.3, 3.3, 4.7, 1.6],
    [6.3, 3.3, 6.0, 2.5], [5.8, 2.7, 5.1, 1.9], [7.1, 3.0, 5.9, 2.1],
    [6.3, 2.9, 5.6, 1.8], [6.5, 3.0, 5.8, 2.2], [7.6, 3.0, 6.6, 2.1],
], np.float32)
_IRIS_CLS = np.array([0, 0, 0, 0, 0, 0, 1, 1, 1, 1, 1, 1, 2, 2, 2, 2, 2, 2])


class IrisDataFetcher:
    """Iris (reference: IrisDataFetcher.java). A representative embedded
    subset expanded with class-conditional jitter to 150 examples — used for
    smoke tests exactly as the reference uses Iris."""

    def __init__(self, n=150, seed=6):
        rs = np.random.RandomState(seed)
        reps = int(np.ceil(n / len(_IRIS_BASE)))
        x = np.tile(_IRIS_BASE, (reps, 1))[:n]
        y = np.tile(_IRIS_CLS, reps)[:n]
        x = x + 0.05 * rs.randn(*x.shape).astype(np.float32)
        self.features = x
        self.labels = np.eye(3, dtype=np.float32)[y]


class SyntheticDataFetcher:
    """Deterministic random data for benchmarks/tests (reference role:
    BenchmarkDataSetIterator)."""

    def __init__(self, n, feature_shape, n_classes, seed=0, one_hot=True):
        rs = np.random.RandomState(seed)
        self.features = rs.rand(n, *feature_shape).astype(np.float32)
        idx = rs.randint(0, n_classes, n)
        self.labels = np.eye(n_classes, dtype=np.float32)[idx] if one_hot \
            else idx.astype(np.int32)


def mnist_iterator(batch_size=128, train=True, flatten=False, shuffle=True, seed=123):
    f = MnistDataFetcher(train=train)
    x, y = f.arrays(flatten=flatten)
    return ArrayDataSetIterator(x, y, batch_size, shuffle=shuffle, seed=seed)


def iris_iterator(batch_size=150, shuffle=True, seed=123):
    f = IrisDataFetcher()
    return ArrayDataSetIterator(f.features, f.labels, batch_size, shuffle=shuffle, seed=seed)


def synthetic_iterator(n=1024, feature_shape=(28, 28, 1), n_classes=10,
                       batch_size=128, seed=0):
    f = SyntheticDataFetcher(n, feature_shape, n_classes, seed=seed)
    return ArrayDataSetIterator(f.features, f.labels, batch_size)
