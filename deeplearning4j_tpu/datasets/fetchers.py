"""Dataset fetchers.

Reference analog: datasets/fetchers/ in /root/reference/deeplearning4j-core —
MnistDataFetcher (binary idx parsing in datasets/mnist/),
CacheableExtractableDataSetFetcher (download+cache+checksum), IrisDataFetcher,
and the iterator impls datasets/iterator/impl/ (MnistDataSetIterator,
IrisDataSetIterator, ...).

Offline-first: fetchers read from a local data directory
(``DL4J_TPU_DATA_DIR``, default ~/.deeplearning4j_tpu/data). Downloading is
gated — this build environment has zero egress, so missing data raises a
clear error pointing at the expected file layout; SyntheticDataFetcher covers
tests/benchmarks.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator


def data_dir():
    return os.environ.get("DL4J_TPU_DATA_DIR",
                          os.path.expanduser("~/.deeplearning4j_tpu/data"))


def _read_idx(path):
    """Parse an IDX (MNIST) file, gzipped or raw."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">HBB", f.read(4))
        _, dtype_code, ndim = magic
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dtype = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
                 0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}[dtype_code]
        data = np.frombuffer(f.read(), dtype=np.dtype(dtype).newbyteorder(">"))
        return data.reshape(dims)


class MnistDataFetcher:
    """Reads idx files from <data_dir>/mnist/ (train-images-idx3-ubyte[.gz],
    train-labels-idx1-ubyte[.gz], t10k-*)."""

    NUM_TRAIN = 60000
    NUM_TEST = 10000

    def __init__(self, train=True, root=None):
        root = root or os.path.join(data_dir(), "mnist")
        prefix = "train" if train else "t10k"
        img = self._find(root, f"{prefix}-images-idx3-ubyte")
        lab = self._find(root, f"{prefix}-labels-idx1-ubyte")
        self.images = _read_idx(img).astype(np.float32) / 255.0
        self.labels = np.eye(10, dtype=np.float32)[_read_idx(lab).astype(np.int64)]

    @staticmethod
    def _find(root, base):
        for cand in (os.path.join(root, base), os.path.join(root, base + ".gz")):
            if os.path.exists(cand):
                return cand
        raise FileNotFoundError(
            f"MNIST file {base}[.gz] not found under {root}. This environment "
            f"has no network egress; place the standard MNIST idx files there "
            f"or use SyntheticDataFetcher for benchmarks.")

    def arrays(self, flatten=False):
        x = self.images.reshape(-1, 784) if flatten else self.images[..., None]
        return x, self.labels


# Fisher's Iris measurements (public-domain data, embedded like the
# reference embeds it via IrisUtils; 150 rows of sepal/petal cm + class).
_IRIS_BASE = np.array([
    [5.0, 3.4, 1.5, 0.2], [4.9, 3.0, 1.4, 0.2], [4.7, 3.2, 1.3, 0.2],
    [4.6, 3.1, 1.5, 0.2], [5.0, 3.6, 1.4, 0.2], [5.4, 3.9, 1.7, 0.4],
    [6.4, 3.2, 4.5, 1.5], [6.9, 3.1, 4.9, 1.5], [5.5, 2.3, 4.0, 1.3],
    [6.5, 2.8, 4.6, 1.5], [5.7, 2.8, 4.5, 1.3], [6.3, 3.3, 4.7, 1.6],
    [6.3, 3.3, 6.0, 2.5], [5.8, 2.7, 5.1, 1.9], [7.1, 3.0, 5.9, 2.1],
    [6.3, 2.9, 5.6, 1.8], [6.5, 3.0, 5.8, 2.2], [7.6, 3.0, 6.6, 2.1],
], np.float32)
_IRIS_CLS = np.array([0, 0, 0, 0, 0, 0, 1, 1, 1, 1, 1, 1, 2, 2, 2, 2, 2, 2])


class IrisDataFetcher:
    """Iris (reference: IrisDataFetcher.java). A representative embedded
    subset expanded with class-conditional jitter to 150 examples — used for
    smoke tests exactly as the reference uses Iris."""

    def __init__(self, n=150, seed=6):
        rs = np.random.RandomState(seed)
        reps = int(np.ceil(n / len(_IRIS_BASE)))
        x = np.tile(_IRIS_BASE, (reps, 1))[:n]
        y = np.tile(_IRIS_CLS, reps)[:n]
        x = x + 0.05 * rs.randn(*x.shape).astype(np.float32)
        self.features = x
        self.labels = np.eye(3, dtype=np.float32)[y]


class SyntheticDataFetcher:
    """Deterministic random data for benchmarks/tests (reference role:
    BenchmarkDataSetIterator)."""

    def __init__(self, n, feature_shape, n_classes, seed=0, one_hot=True):
        rs = np.random.RandomState(seed)
        self.features = rs.rand(n, *feature_shape).astype(np.float32)
        idx = rs.randint(0, n_classes, n)
        self.labels = np.eye(n_classes, dtype=np.float32)[idx] if one_hot \
            else idx.astype(np.int32)


def mnist_iterator(batch_size=128, train=True, flatten=False, shuffle=True, seed=123):
    f = MnistDataFetcher(train=train)
    x, y = f.arrays(flatten=flatten)
    return ArrayDataSetIterator(x, y, batch_size, shuffle=shuffle, seed=seed)


def iris_iterator(batch_size=150, shuffle=True, seed=123):
    f = IrisDataFetcher()
    return ArrayDataSetIterator(f.features, f.labels, batch_size, shuffle=shuffle, seed=seed)


def synthetic_iterator(n=1024, feature_shape=(28, 28, 1), n_classes=10,
                       batch_size=128, seed=0):
    f = SyntheticDataFetcher(n, feature_shape, n_classes, seed=seed)
    return ArrayDataSetIterator(f.features, f.labels, batch_size)


# ---------------------------------------------------------------------------
# EMNIST (reference: EmnistDataFetcher.java / EmnistDataSetIterator.Set)
# ---------------------------------------------------------------------------

EMNIST_SPLITS = {
    # split -> (file tag, n_classes)  (reference EmnistDataSetIterator enum:
    # COMPLETE/byclass 62, MERGE/bymerge 47, BALANCED 47, LETTERS 26,
    # DIGITS 10, MNIST 10)
    "byclass": ("byclass", 62),
    "bymerge": ("bymerge", 47),
    "balanced": ("balanced", 47),
    "letters": ("letters", 26),
    "digits": ("digits", 10),
    "mnist": ("mnist", 10),
}


class EmnistDataFetcher:
    """EMNIST idx files from <data_dir>/emnist/:
    emnist-<split>-{train,test}-{images-idx3,labels-idx1}-ubyte[.gz]."""

    def __init__(self, split="balanced", train=True, root=None):
        if split not in EMNIST_SPLITS:
            raise ValueError(f"Unknown EMNIST split {split!r}; "
                             f"known: {sorted(EMNIST_SPLITS)}")
        tag, self.n_classes = EMNIST_SPLITS[split]
        root = root or os.path.join(data_dir(), "emnist")
        kind = "train" if train else "test"
        img = MnistDataFetcher._find(root, f"emnist-{tag}-{kind}-images-idx3-ubyte")
        lab = MnistDataFetcher._find(root, f"emnist-{tag}-{kind}-labels-idx1-ubyte")
        self.images = _read_idx(img).astype(np.float32) / 255.0
        raw = _read_idx(lab).astype(np.int64)
        if split == "letters":  # letters labels are 1-indexed
            raw = raw - 1
        self.labels = np.eye(self.n_classes, dtype=np.float32)[raw]

    def arrays(self, flatten=False):
        x = self.images.reshape(len(self.images), -1) if flatten \
            else self.images[..., None]
        return x, self.labels


# ---------------------------------------------------------------------------
# CIFAR-10 (reference: CifarDataSetIterator over DataVec's CifarLoader —
# the canonical binary batch format: 1 label byte + 3072 channel-major bytes)
# ---------------------------------------------------------------------------

class Cifar10DataFetcher:
    """CIFAR-10 binary batches from <data_dir>/cifar10/ (data_batch_1..5.bin,
    test_batch.bin). Outputs NHWC float32 in [0,1]."""

    N_CLASSES = 10

    def __init__(self, train=True, root=None, limit=None):
        root = root or os.path.join(data_dir(), "cifar10")
        names = [f"data_batch_{i}.bin" for i in range(1, 6)] if train \
            else ["test_batch.bin"]
        xs, ys = [], []
        for name in names:
            path = self._find(root, name)
            raw = np.frombuffer(open(path, "rb").read(), np.uint8)
            rec = raw.reshape(-1, 3073)
            ys.append(rec[:, 0].astype(np.int64))
            xs.append(rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        x = np.concatenate(xs).astype(np.float32) / 255.0
        y = np.concatenate(ys)
        if limit:
            x, y = x[:limit], y[:limit]
        self.images = x
        self.labels = np.eye(self.N_CLASSES, dtype=np.float32)[y]

    @staticmethod
    def _find(root, name):
        for cand in (os.path.join(root, name),
                     os.path.join(root, "cifar-10-batches-bin", name)):
            if os.path.exists(cand):
                return cand
        raise FileNotFoundError(
            f"CIFAR-10 file {name} not found under {root} (or its "
            f"cifar-10-batches-bin/ subdir). Offline environment: stage the "
            f"binary-version batches there.")

    def arrays(self):
        return self.images, self.labels


# ---------------------------------------------------------------------------
# SVHN (reference: SvhnDataFetcher.java — cropped-digits .mat format)
# ---------------------------------------------------------------------------

class SvhnDataFetcher:
    """SVHN cropped digits from <data_dir>/svhn/{train,test}_32x32.mat.
    MATLAB label '10' means digit 0 (normalized here)."""

    N_CLASSES = 10

    def __init__(self, train=True, root=None, limit=None):
        import scipy.io
        root = root or os.path.join(data_dir(), "svhn")
        name = ("train" if train else "test") + "_32x32.mat"
        path = os.path.join(root, name)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"SVHN file {name} not found under {root}. Offline "
                f"environment: stage the cropped-digits .mat files there.")
        mat = scipy.io.loadmat(path)
        x = mat["X"].transpose(3, 0, 1, 2).astype(np.float32) / 255.0  # NHWC
        y = mat["y"].reshape(-1).astype(np.int64) % 10  # 10 -> 0
        if limit:
            x, y = x[:limit], y[:limit]
        self.images = x
        self.labels = np.eye(self.N_CLASSES, dtype=np.float32)[y]

    def arrays(self):
        return self.images, self.labels


# ---------------------------------------------------------------------------
# Tiny ImageNet (reference: TinyImageNetFetcher.java — 200 classes, 64x64)
# ---------------------------------------------------------------------------

class TinyImageNetFetcher:
    """tiny-imagenet-200 directory layout under <data_dir>/tiny-imagenet-200:
    wnids.txt, train/<wnid>/images/*.JPEG, val/images + val_annotations.txt."""

    SIZE = 64

    def __init__(self, train=True, root=None, limit=None):
        from PIL import Image
        root = root or os.path.join(data_dir(), "tiny-imagenet-200")
        wnids_file = os.path.join(root, "wnids.txt")
        if not os.path.exists(wnids_file):
            raise FileNotFoundError(
                f"tiny-imagenet-200/wnids.txt not found under {root}. "
                f"Offline environment: stage the extracted dataset there.")
        wnids = [l.strip() for l in open(wnids_file) if l.strip()]
        self.n_classes = len(wnids)
        idx = {w: i for i, w in enumerate(wnids)}
        paths, labels = [], []
        if train:
            for w in wnids:
                d = os.path.join(root, "train", w, "images")
                if not os.path.isdir(d):
                    continue
                for fn in sorted(os.listdir(d)):
                    paths.append(os.path.join(d, fn))
                    labels.append(idx[w])
        else:
            ann = os.path.join(root, "val", "val_annotations.txt")
            for line in open(ann):
                parts = line.split("\t")
                if len(parts) >= 2 and parts[1] in idx:
                    paths.append(os.path.join(root, "val", "images", parts[0]))
                    labels.append(idx[parts[1]])
        if limit:
            paths, labels = paths[:limit], labels[:limit]
        imgs = []
        for p in paths:
            with Image.open(p) as im:
                imgs.append(np.asarray(im.convert("RGB"), np.float32) / 255.0)
        self.images = np.stack(imgs) if imgs else \
            np.zeros((0, self.SIZE, self.SIZE, 3), np.float32)
        self.labels = np.eye(self.n_classes, dtype=np.float32)[
            np.asarray(labels, np.int64)] if labels else \
            np.zeros((0, self.n_classes), np.float32)

    def arrays(self):
        return self.images, self.labels


# ---------------------------------------------------------------------------
# LFW (reference: LFWDataSetIterator via DataVec loader)
# ---------------------------------------------------------------------------

class LfwDataFetcher:
    """Labeled Faces in the Wild from <data_dir>/lfw/<person>/<imgs>.jpg.
    Labels are person identities (directory names, sorted)."""

    def __init__(self, root=None, image_size=64, min_images_per_person=1,
                 limit=None):
        from PIL import Image
        root = root or os.path.join(data_dir(), "lfw")
        if not os.path.isdir(root):
            raise FileNotFoundError(
                f"LFW directory not found at {root}. Offline environment: "
                f"stage the extracted lfw/ person directories there.")
        people = sorted(d for d in os.listdir(root)
                        if os.path.isdir(os.path.join(root, d)))
        people = [p for p in people
                  if len(os.listdir(os.path.join(root, p)))
                  >= min_images_per_person]
        self.people = people
        idx = {p: i for i, p in enumerate(people)}
        imgs, labels = [], []
        for p in people:
            for fn in sorted(os.listdir(os.path.join(root, p))):
                imgs.append(os.path.join(root, p, fn))
                labels.append(idx[p])
        if limit:
            imgs, labels = imgs[:limit], labels[:limit]
        arrs = []
        for path in imgs:
            with Image.open(path) as im:
                im = im.convert("RGB").resize((image_size, image_size))
                arrs.append(np.asarray(im, np.float32) / 255.0)
        self.images = np.stack(arrs) if arrs else \
            np.zeros((0, image_size, image_size, 3), np.float32)
        self.labels = np.eye(len(people), dtype=np.float32)[
            np.asarray(labels, np.int64)] if labels else \
            np.zeros((0, len(people)), np.float32)

    def arrays(self):
        return self.images, self.labels


# ---------------------------------------------------------------------------
# UCI synthetic control (reference: UciSequenceDataFetcher.java — 600 series
# of 60 steps, 6 classes of 100 consecutive rows)
# ---------------------------------------------------------------------------

class UciSequenceDataFetcher:
    """synthetic_control.data from <data_dir>/uci/: 600 whitespace-separated
    rows of 60 floats; class c = rows [100c, 100(c+1)). Returns sequences
    [N, 60, 1] and one-hot labels [N, 6]; deterministic shuffled 450/150
    train/test split (reference behavior)."""

    N_CLASSES = 6
    SEQ_LEN = 60

    def __init__(self, train=True, root=None, seed=123):
        root = root or os.path.join(data_dir(), "uci")
        path = os.path.join(root, "synthetic_control.data")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"UCI synthetic_control.data not found under {root}. Offline "
                f"environment: stage it there.")
        rows = np.loadtxt(path, dtype=np.float32)
        if rows.shape != (600, 60):
            raise ValueError(f"Expected 600x60 data, got {rows.shape}")
        labels = np.repeat(np.arange(6), 100)
        order = np.random.RandomState(seed).permutation(600)
        cut = 450
        sel = order[:cut] if train else order[cut:]
        # normalize per-series (zero mean, unit variance) for trainability
        x = rows[sel]
        x = (x - x.mean(1, keepdims=True)) / (x.std(1, keepdims=True) + 1e-8)
        self.sequences = x[..., None]
        self.labels = np.eye(6, dtype=np.float32)[labels[sel]]

    def arrays(self):
        return self.sequences, self.labels


def emnist_iterator(batch_size=128, split="balanced", train=True,
                    flatten=False, shuffle=True, seed=123):
    x, y = EmnistDataFetcher(split=split, train=train).arrays(flatten=flatten)
    return ArrayDataSetIterator(x, y, batch_size, shuffle=shuffle, seed=seed)


def cifar10_iterator(batch_size=128, train=True, shuffle=True, seed=123,
                     limit=None):
    x, y = Cifar10DataFetcher(train=train, limit=limit).arrays()
    return ArrayDataSetIterator(x, y, batch_size, shuffle=shuffle, seed=seed)


def svhn_iterator(batch_size=128, train=True, shuffle=True, seed=123,
                  limit=None):
    x, y = SvhnDataFetcher(train=train, limit=limit).arrays()
    return ArrayDataSetIterator(x, y, batch_size, shuffle=shuffle, seed=seed)


def tiny_imagenet_iterator(batch_size=128, train=True, shuffle=True,
                           seed=123, limit=None):
    x, y = TinyImageNetFetcher(train=train, limit=limit).arrays()
    return ArrayDataSetIterator(x, y, batch_size, shuffle=shuffle, seed=seed)


def uci_sequence_iterator(batch_size=64, train=True, shuffle=True, seed=123):
    x, y = UciSequenceDataFetcher(train=train).arrays()
    return ArrayDataSetIterator(x, y, batch_size, shuffle=shuffle, seed=seed)
