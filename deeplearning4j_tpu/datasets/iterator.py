"""Dataset iterators with async device prefetch.

Reference analog: datasets/iterator/ in /root/reference/deeplearning4j-nn —
DataSetIterator SPI, AsyncDataSetIterator.java (464 LoC: background prefetch
thread + workspace queue, :40-63), MultipleEpochsIterator,
EarlyTerminationDataSetIterator, impl/BenchmarkDataSetIterator.java.

TPU-native: prefetch = background thread performing host-side batch assembly
+ jax.device_put into HBM while the previous step computes — the double
buffering that keeps ETL off the step critical path (SURVEY.md §7 "where the
MFU target is usually lost"). The reference's workspace-attached prefetch
becomes plain device_put, since XLA owns device memory.
"""

from __future__ import annotations

import bisect
import dataclasses
import queue
import threading
import time

import jax
import numpy as np

from deeplearning4j_tpu import telemetry as _tm


@dataclasses.dataclass
class DataSet:
    """One minibatch (reference: org.nd4j.linalg.dataset.DataSet)."""

    features: object
    labels: object
    features_mask: object = None
    labels_mask: object = None

    def num_examples(self):
        return self.features.shape[0]


# ---------------------------------------------------------------------------
# Shape bucketing: pad ragged minibatches up to the compiled batch shape with
# a validity mask, so the tail of every epoch reuses the steady-state XLA
# executable instead of compiling a fresh one (the recompile trap
# telemetry.devices counts as ``recompiles_total``). The masked-mean loss
# divides by the REAL example count (nn/losses._apply_mask_and_mean), so
# padded results are exact, not approximate.
# ---------------------------------------------------------------------------


def _leading_dim(tree):
    """Batch size of a (pytree of) array(s)."""
    return jax.tree_util.tree_leaves(tree)[0].shape[0]


def _seq_dim(tree):
    """Sequence length (axis 1) of a (pytree of) array(s), or None when
    the leading leaf has no time axis (plain [B, F] features)."""
    first = jax.tree_util.tree_leaves(tree)[0]
    return first.shape[1] if first.ndim >= 2 else None


def _pad_rows(tree, target):
    """Zero-pad every leaf of ``tree`` to ``target`` rows along axis 0
    (host-side: part of ETL batch assembly, before device placement)."""
    def pad(a):
        n = a.shape[0]
        if n == target:
            return a
        if n > target:
            raise ValueError(f"batch of {n} examples exceeds the bucketed "
                             f"shape {target}")
        a = np.asarray(a)
        return np.concatenate(
            [a, np.zeros((target - n,) + a.shape[1:], a.dtype)])
    return jax.tree_util.tree_map(pad, tree)


def _pad_seq(tree, target, min_ndim=2):
    """Zero-pad every leaf of ``tree`` with ``ndim >= min_ndim`` to
    ``target`` steps along axis 1 (the sequence axis). Leaves below
    ``min_ndim`` pass through untouched — a [B, C] class-label leaf has
    no time axis and must not be stretched."""
    def pad(a):
        a = np.asarray(a)
        if a.ndim < min_ndim:
            return a
        t = a.shape[1]
        if t == target:
            return a
        if t > target:
            raise ValueError(f"sequence of {t} steps exceeds the bucketed "
                             f"shape {target}")
        width = [(0, 0)] * a.ndim
        width[1] = (0, target - t)
        return np.pad(a, width)
    return jax.tree_util.tree_map(pad, tree)


def validity_mask(labels, n_valid, target, *, seq_valid=None,
                  seq_target=None):
    """[target] (or [target, T] for time-distributed labels) float mask:
    1 for the first ``n_valid`` examples, 0 for bucketing padding. With a
    2-D shape bucket (``seq_target``/``seq_valid``), the time axis is the
    PADDED length and steps past ``seq_valid`` are masked 0 too, so the
    masked-mean losses stay exact under seq-axis padding."""
    first = jax.tree_util.tree_leaves(labels)[0]
    valid = (np.arange(target) < n_valid).astype(np.float32)
    if first.ndim >= 3:  # [B, T, ...] labels score per timestep
        t = int(seq_target) if seq_target else first.shape[1]
        mask = np.repeat(valid[:, None], t, axis=1)
        if seq_valid is not None:
            mask = mask * (np.arange(t) < seq_valid).astype(np.float32)[None]
        return mask
    return valid


def pad_batch(x, y, m, target, *, seq_target=None):
    """Bucket one ``(x, y, mask)`` minibatch to ``target`` examples.

    Returns ``(x, y, mask, n_valid)`` where the mask is ALWAYS present —
    all-ones when nothing was padded and no mask was given — so a padded
    stream presents one jit signature for the whole epoch (a mask that
    appears only on the tail batch would itself force a recompile).
    ``x``/``y`` may be pytrees (the ComputationGraph dict form).

    ``seq_target`` grows the pad onto the sequence axis (2-D shape
    bucket): features pad along axis 1, time-distributed ``[B, T, ...]``
    labels pad along axis 1 too, and the returned mask zeroes both the
    padded rows AND the padded timesteps — real-row/real-step slicing and
    the masked-mean losses see bit-identical values either way.
    """
    n = _leading_dim(x)
    seq = _seq_dim(x) if seq_target is not None else None
    x = _pad_rows(x, target)
    y_padded = _pad_rows(y, target)
    if seq_target is not None:
        x = _pad_seq(x, seq_target)
        y_padded = _pad_seq(y_padded, seq_target, min_ndim=3)
    if m is None:
        m = validity_mask(y, n, target, seq_valid=seq,
                          seq_target=seq_target)
    else:
        m = _pad_rows(m, target)
        if seq_target is not None:
            m = _pad_seq(m, seq_target)
    return x, y_padded, m, n


class BucketRegistry:
    """The registered batch-size buckets a process compiles for.

    Shape bucketing (``pad_batch``) removes ragged-shape recompiles only if
    every padded size maps onto a FINITE, pre-declared set of batch shapes —
    otherwise each new request size mints a new XLA executable and
    ``recompiles_total`` climbs anyway. This registry is that declaration:
    ``bucket_for(n)`` returns the smallest registered bucket >= n (``None``
    past the largest — callers chunk by ``max``), so the serving tier can
    AOT-compile exactly ``len(sizes())`` forwards at startup and ragged
    traffic reuses them forever (the whole-program AOT stance of the
    Julia-to-TPU paper: declare the shapes, compile once, never again).
    """

    def __init__(self, sizes):
        cleaned = sorted({int(s) for s in sizes})
        if not cleaned or cleaned[0] < 1:
            raise ValueError(f"bucket sizes must be positive, got {sizes!r}")
        self._sizes = cleaned

    @classmethod
    def powers_of_two(cls, max_batch, min_batch=1):
        """1, 2, 4, ... up to (and always including) ``max_batch``."""
        sizes, b = [], int(min_batch)
        while b < max_batch:
            sizes.append(b)
            b *= 2
        sizes.append(int(max_batch))
        return cls(sizes)

    def sizes(self):
        return list(self._sizes)

    @property
    def max(self):
        return self._sizes[-1]

    def bucket_for(self, n):
        """Smallest registered bucket >= n, or None when n exceeds max."""
        if n > self._sizes[-1]:
            return None
        return self._sizes[bisect.bisect_left(self._sizes, n)]

    def round_up_to_multiple(self, m):
        """A new registry with every bucket rounded up to a multiple of
        ``m`` (mesh serving: the padded batch must split over the data
        axis), duplicates collapsed."""
        return BucketRegistry(-(-s // m) * m for s in self._sizes)

    def __iter__(self):
        return iter(self._sizes)

    def __len__(self):
        return len(self._sizes)

    def __repr__(self):
        return f"BucketRegistry({self._sizes})"


class ShapeBuckets:
    """2-D **(batch, seq)** shape grid: the finite set of padded shapes a
    transformer-serving process compiles for.

    The 1-D :class:`BucketRegistry` removes ragged-BATCH recompiles but
    still pads every request's sequence axis to ``max_seq`` — a 128-token
    prompt burns the FLOPs of the longest one. This registry declares a
    seq axis too: ``bucket_for(rows, seq)`` returns the smallest
    ``(batch_bucket, seq_bucket)`` covering the request (``None`` past
    either max), so the engine AOT-compiles exactly
    ``len(batch) * len(seq)`` executables and a short prompt runs in a
    short shape. Seq edges come from ``powers_of_two`` or from the
    demand history's token-length distribution (:meth:`from_demand`).
    """

    def __init__(self, batch_sizes, seq_sizes):
        self._batch = (batch_sizes if isinstance(batch_sizes, BucketRegistry)
                       else BucketRegistry(batch_sizes))
        self._seq = (seq_sizes if isinstance(seq_sizes, BucketRegistry)
                     else BucketRegistry(seq_sizes))

    @classmethod
    def powers_of_two(cls, max_batch, max_seq, *, min_batch=1, min_seq=None):
        """Power-of-two grid on both axes. ``min_seq`` defaults to
        ``min(16, max_seq)`` — sub-16-step buckets would mint executables
        whose padded-FLOPs savings can't pay their warmup back."""
        if min_seq is None:
            min_seq = min(16, int(max_seq))
        return cls(BucketRegistry.powers_of_two(max_batch, min_batch),
                   BucketRegistry.powers_of_two(max_seq, min_seq))

    @classmethod
    def from_demand(cls, batch_sizes, max_seq, *, history=None,
                    series="serving_request_seq_len",
                    quantiles=(0.5, 0.9)):
        """Derive seq edges from the token-length distribution retained
        in :mod:`telemetry.history`: the histogram bucket bound covering
        each demand quantile becomes a grid edge (``max_seq`` always
        included, so every admissible request still maps). With no
        retained demand the grid falls back to powers of two — a cold
        process must still serve."""
        edges = seq_edges_from_demand(max_seq, history=history,
                                      series=series, quantiles=quantiles)
        if edges is None:
            edges = BucketRegistry.powers_of_two(
                max_seq, min(16, int(max_seq)))
        return cls(batch_sizes, edges)

    def with_batch(self, batch_sizes):
        """Same seq grid, replaced batch axis."""
        return ShapeBuckets(batch_sizes, self._seq)

    @property
    def batch(self):
        """The batch-axis :class:`BucketRegistry`."""
        return self._batch

    @property
    def seq(self):
        """The seq-axis :class:`BucketRegistry`."""
        return self._seq

    @property
    def max(self):
        """Largest batch bucket (callers chunk oversized batches by it,
        exactly as with the 1-D registry)."""
        return self._batch.max

    @property
    def max_seq(self):
        """Largest seq bucket — requests longer than this are rejected,
        not chunked (a sequence can't be split without changing the
        model's math)."""
        return self._seq.max

    def bucket_for(self, rows, seq):
        """Smallest ``(batch_bucket, seq_bucket)`` with
        ``batch_bucket >= rows`` and ``seq_bucket >= seq``, or ``None``
        when either axis exceeds its max."""
        b = self._batch.bucket_for(rows)
        s = self._seq.bucket_for(seq)
        if b is None or s is None:
            return None
        return (b, s)

    def round_up_to_multiple(self, m):
        """A new grid with every BATCH bucket rounded up to a multiple of
        ``m`` (mesh serving: the padded batch must split over the data
        axis). The seq axis is untouched — sharding splits rows, never
        timesteps."""
        return ShapeBuckets(self._batch.round_up_to_multiple(m), self._seq)

    def sizes(self):
        """The full grid as ``[(batch, seq), ...]``, seq-major within
        batch (warmup iteration order)."""
        return [(b, s) for b in self._batch for s in self._seq]

    def signature(self):
        """Stable string identity of the grid — folded into warm-manifest
        keys so a grid change invalidates stale executables."""
        return ("b=" + ",".join(map(str, self._batch)) +
                ";s=" + ",".join(map(str, self._seq)))

    def __iter__(self):
        return iter(self.sizes())

    def __len__(self):
        return len(self._batch) * len(self._seq)

    def __repr__(self):
        return (f"ShapeBuckets(batch={self._batch.sizes()}, "
                f"seq={self._seq.sizes()})")


def seq_edges_from_demand(max_seq, *, history=None,
                          series="serving_request_seq_len",
                          quantiles=(0.5, 0.9)):
    """Seq grid edges from the token-length histogram retained in
    metrics history: for each demand quantile, the smallest histogram
    bucket bound covering it (clamped to ``max_seq``), plus ``max_seq``
    itself. Returns ``None`` when the history holds no samples of the
    series — callers fall back to powers of two."""
    if history is None:
        from deeplearning4j_tpu.telemetry.history import get_history
        history = get_history()
    merged = {}
    for sample in history.samples():
        doc = (sample.get("metrics") or {}).get(series)
        if not isinstance(doc, dict):
            continue
        for s in doc.get("series", ()):
            buckets = (s.get("value") or {}).get("buckets")
            if not buckets:
                continue
            for le, count in buckets.items():
                # cumulative snapshots: the LAST retained sample per
                # series wins (counts only grow)
                merged[le] = max(merged.get(le, 0), int(count))
    total = sum(merged.values())
    if not total:
        return None
    bounds = sorted((float("inf") if le == "+Inf" else float(le), count)
                    for le, count in merged.items())
    edges = set()
    for q in quantiles:
        rank = q * total
        cum = 0
        for bound, count in bounds:
            cum += count
            if cum >= rank:
                edge = int(max_seq) if bound == float("inf") \
                    else min(int(bound), int(max_seq))
                edges.add(max(1, edge))
                break
    edges.add(int(max_seq))
    return sorted(edges)


class DataSetIterator:
    """Iterator protocol: yields DataSet; reset() for a new epoch."""

    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> DataSet:
        raise NotImplementedError

    def reset(self):
        pass

    @property
    def batch_size(self):
        raise NotImplementedError


class ArrayDataSetIterator(DataSetIterator):
    """``pad_last=True`` buckets the ragged final batch to the full
    ``batch_size`` (zero rows + validity folded into the masks) and emits
    masks on EVERY batch, so one jit signature covers the whole epoch —
    the tail batch stops costing a fresh XLA compile (shape bucketing;
    exact under the masked-mean losses)."""

    def __init__(self, features, labels, batch_size=32, *, features_mask=None,
                 labels_mask=None, shuffle=False, seed=123, drop_last=False,
                 pad_last=False):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        self.features_mask = None if features_mask is None else np.asarray(features_mask)
        self.labels_mask = None if labels_mask is None else np.asarray(labels_mask)
        self._batch = batch_size
        self.shuffle = shuffle
        self.rng = np.random.RandomState(seed)
        self.drop_last = drop_last
        self.pad_last = pad_last
        self._order = np.arange(len(self.features))
        self._pos = 0

    @property
    def batch_size(self):
        return self._batch

    def reset(self):
        self._pos = 0
        if self.shuffle:
            self.rng.shuffle(self._order)

    def __next__(self):
        n = len(self.features)
        if self._pos >= n:
            raise StopIteration
        end = min(self._pos + self._batch, n)
        if self.drop_last and end - self._pos < self._batch:
            raise StopIteration
        idx = self._order[self._pos:end]
        self._pos = end
        ds = DataSet(
            features=self.features[idx], labels=self.labels[idx],
            features_mask=None if self.features_mask is None else self.features_mask[idx],
            labels_mask=None if self.labels_mask is None else self.labels_mask[idx])
        if not self.pad_last:
            return ds
        x, y, fm, n = pad_batch(ds.features, ds.labels, ds.features_mask,
                                self._batch)
        lm = ds.labels_mask
        if lm is not None:
            lm = _pad_rows(lm, self._batch)
        return DataSet(features=x, labels=y, features_mask=fm,
                       labels_mask=lm)


_SENTINEL = object()


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch + device placement (reference:
    AsyncDataSetIterator.java — queue-based double buffering).

    Transient producer errors (a streaming source's socket reset, a
    quiet-stream timeout — ``retry_on``, default the connection/timeout
    family) are retried with capped exponential backoff up to
    ``retry_transient`` times per batch, counted
    ``etl_retry_total{outcome=retried|recovered|fatal}``; past the cap
    the error surfaces PROMPTLY on the consumer exactly as any producer
    error always has. OPT-IN (``retry_transient=0`` default — fail on
    first, the historical contract): retrying ``next()`` is only
    meaningful on a re-nextable ITERATOR source (a pub/sub stream, a
    queue). A plain generator closes on its first raise (PEP 255), so a
    retried ``next()`` would read as a clean-but-truncated epoch — the
    continuous ingest layer passes its own budget explicitly.
    """

    #: errors worth retrying: the connection family a streaming source
    #: (broker restart, producer respawn) throws while the stream heals.
    #: ConnectionError is an OSError subclass; TimeoutError covers the
    #: quiet-stream timeout continuous ingest raises.
    RETRY_ON = (OSError, TimeoutError)

    def __init__(self, base: DataSetIterator, queue_size=2, device_put=True,
                 sharding=None, callback=None, trace_root=None,
                 retry_transient=0, retry_backoff_s=0.05, retry_on=None):
        self.base = base
        self.queue_size = queue_size
        self.device_put = device_put
        self.sharding = sharding
        self.retry_transient = int(retry_transient)
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_on = (self.RETRY_ON if retry_on is None
                         else tuple(retry_on))
        if callback is not None and sharding is not None:
            raise ValueError(
                "callback and sharding are mutually exclusive: the callback "
                "owns device placement (e.g. InterleavedDataSetCallback)")
        self.callback = callback  # DataSetCallback, e.g. Interleaved round-robin
        #: causal-tracing opt-in (telemetry.tracectx): with a root name set
        #: and tracing on, the producer starts one trace per batch — its
        #: assembly/device_put spans record on the producer thread — and
        #: hands it off on the item (``item._trace_ctx``) for the consumer
        #: to attach and finish (nn/fused.py passes "train.dispatch").
        #: None (default): no traces, whatever the tracing toggle says —
        #: a consumer that never finishes handoffs would leak open traces.
        self.trace_root = trace_root
        self._queue = None
        self._thread = None
        self._error = None
        reg = self._reg = _tm.get_registry()
        # fetch stall = time the TRAINING thread spent blocked waiting for
        # the prefetcher — the "where the MFU target is usually lost" series
        self._m_stall = reg.histogram(
            "etl_fetch_stall_seconds",
            "consumer time blocked waiting on the prefetch queue")
        self._m_batches = reg.counter(
            "etl_batches_total", "batches delivered by async prefetch")
        self._m_depth = reg.gauge(
            "etl_queue_depth", "prefetched batches ready in the queue")
        self._m_retry = reg.counter(
            "etl_retry_total",
            "transient producer errors, by outcome (retried = one backoff "
            "attempt, recovered = a batch arrived after retries, fatal = "
            "the retry budget ran out and the error surfaced)")
        if reg.enabled:
            # pre-register the outcome series at zero: an ETL failure
            # series born mid-incident is invisible to the SLO delta
            # discipline for a full window (the prober idiom)
            for outcome in ("retried", "recovered", "fatal"):
                self._m_retry.inc(0, outcome=outcome)

    @property
    def batch_size(self):
        return self.base.batch_size

    def reset(self):
        self._shutdown()
        self.base.reset()
        if self.callback is not None:
            self.callback.reset()
        self._queue = queue.Queue(maxsize=self.queue_size)
        self._error = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()

    def _put_device(self, ds: DataSet) -> DataSet:
        if self.callback is not None:
            return self.callback.call(ds)
        if not self.device_put:
            return ds
        put = (lambda a: jax.device_put(a, self.sharding)) if self.sharding \
            else jax.device_put
        opt = lambda a: None if a is None else put(a)
        # dataclasses.replace keeps subclass payloads intact (SuperBatch's
        # step_valid/n_steps ride the same queue for the fused-dispatch
        # prefetch path); device_put recurses into dict-valued features
        # (the ComputationGraph form)
        with _tm.span("etl.device_put"):
            return dataclasses.replace(
                ds, features=opt(ds.features), labels=opt(ds.labels),
                features_mask=opt(ds.features_mask),
                labels_mask=opt(ds.labels_mask))

    def _producer(self):
        # capture THIS generation's queue/stop: a producer that outlives
        # _shutdown's join timeout (slow source, wedged device_put) must
        # not inject a stale batch or premature sentinel into the fresh
        # queue the next reset() installs
        q, stop = self._queue, self._stop
        tctx = None
        try:
            while not stop.is_set():
                tctx = (None if self.trace_root is None
                        else _tm.tracectx.maybe_start(self.trace_root))
                with _tm.tracectx.attach(tctx):
                    with _tm.span("etl.prefetch"):
                        try:
                            ds = self._next_with_retry(stop)
                        except StopIteration:
                            break
                        item = self._put_device(ds)
                if tctx is not None:
                    # handoff rides the queue with the batch; the consumer
                    # attaches (its dispatch spans parent under this
                    # trace) and owns finish()
                    item._trace_ctx = tctx.handoff()
                    tctx = None
                q.put(item)
        except Exception as e:  # surfaced on the consumer side
            if self._queue is q:  # our generation is still live
                self._error = e
        finally:
            # thread-exit path: a producer dying mid-span (source raised,
            # wedged device_put interrupted) must not leave its trace open
            # forever — close it without ringing
            if tctx is not None:
                tctx.abandon()
            if stop.is_set():
                # stopped generation: the consumer's close() may have done
                # its final drain BEFORE our last q.put landed (join timed
                # out on a wedged batch). Nobody will read this queue
                # again — abandon any handoffs still in it ourselves.
                try:
                    while True:
                        item = q.get_nowait()
                        t = getattr(item, "_trace_ctx", None)
                        if t is not None:
                            t.abandon()
                except queue.Empty:
                    pass
            q.put(_SENTINEL)

    def _next_with_retry(self, stop):
        """``next(self.base)`` with the bounded transient-retry policy
        (producer thread only). StopIteration passes through untouched;
        a retryable error sleeps a capped exponential backoff (stop-flag
        aware, so ``close()`` is never held hostage) and tries again up
        to the budget — then re-raises, counted fatal, and the consumer
        sees it promptly via the usual error path."""
        attempts = 0
        while True:
            try:
                ds = next(self.base)
            except StopIteration:
                raise
            except self.retry_on:
                attempts += 1
                if stop.is_set():
                    raise  # closing, not a stream verdict: don't count
                if attempts > self.retry_transient:
                    if self._reg.enabled:
                        self._m_retry.inc(outcome="fatal")
                    raise
                if self._reg.enabled:
                    self._m_retry.inc(outcome="retried")
                delay = min(self.retry_backoff_s * (2 ** (attempts - 1)),
                            2.0)
                if stop.wait(delay):  # closing: don't burn the budget
                    raise
            else:
                if attempts and self._reg.enabled:
                    self._m_retry.inc(outcome="recovered")
                return ds

    def __next__(self):
        if self._queue is None:
            self.reset()
        if self._error is not None:
            # producer died: surface PROMPTLY (an epoch fed by a dead
            # producer is broken — don't drain the surviving queued
            # batches first and report the failure minutes later)
            raise self._error
        if self._reg.enabled:
            t0 = time.perf_counter()
            item = self._queue.get()
            self._m_stall.observe(time.perf_counter() - t0)
            self._m_depth.set(self._queue.qsize())
        else:
            item = self._queue.get()
        if item is _SENTINEL:
            if self._error is not None:
                raise self._error
            raise StopIteration
        if self._reg.enabled:
            self._m_batches.inc()
        return item

    def close(self):
        """Stop and join the producer thread. The fit loops call this in
        their ``finally`` when they own the iterator, so an exception
        mid-epoch doesn't leave a dangling producer ``device_put``-ing
        batches into a dead epoch; safe to call repeatedly, and the
        iterator restarts cleanly on the next ``reset()``/``iter()``."""
        self._shutdown()

    def _shutdown(self):
        if self._thread is not None:
            # flag first, then drain: a producer blocked in put() wakes,
            # observes the stop flag and exits instead of producing the
            # rest of the (possibly huge) epoch into the void. Drain even
            # when the thread ALREADY exited (a short epoch fits in the
            # queue): its queued handoffs must not stay open — nobody
            # will ever consume them, and reset() replaces the queue.
            self._stop.set()
            self._drain_abandoning()
            if self._thread.is_alive():
                self._thread.join(timeout=5)
            # drain AGAIN: a producer that was mid-batch when we drained
            # above may have enqueued one more item (+ sentinel) before
            # observing the stop flag — its handoff must not stay open
            self._drain_abandoning()
        self._thread = None
        self._queue = None

    def _drain_abandoning(self):
        try:
            while True:
                item = self._queue.get_nowait()
                if item is _SENTINEL:
                    continue  # keep draining: items may follow a stale
                    #           sentinel from a raced generation
                # a queued batch nobody will consume: close its trace
                # (open handoffs are the dangling state close() owns)
                tctx = getattr(item, "_trace_ctx", None)
                if tctx is not None:
                    tctx.abandon()
        except queue.Empty:
            pass


@dataclasses.dataclass
class SuperBatch(DataSet):
    """K stacked minibatches for ONE fused ``lax.scan`` dispatch
    (nn/fused.py): ``features``/``labels`` are ``[K, B, ...]`` (pytrees
    stack leaf-wise), ``labels_mask`` is the ``[K, B(, T)]`` per-example
    validity x user mask. ``step_valid`` is the K-tail bucketing vector —
    1.0 for real minibatches, 0.0 for the zeroed no-op steps padding a
    ragged tail to the compiled K — and ``n_steps`` counts the real ones.
    """

    step_valid: object = None
    n_steps: int = 0


class SuperBatchIterator(DataSetIterator):
    """Stack K minibatches into super-batches for fused multi-step
    dispatch: each yield feeds one ``lax.scan`` over K train steps
    (nn/fused.py). Shape bucketing keeps every super-batch of a fit on
    ONE compiled signature: ragged minibatches pad to the bucketed batch
    shape (validity folded into ``labels_mask`` — exact under the
    masked-mean losses) and a ragged K-tail pads with zeroed steps whose
    updates the scan discards via ``step_valid``.

    ``source`` is a DataSetIterator, or a zero-arg callable returning a
    fresh ``(x, y, mask)`` iterable per epoch (the fit loops pass their
    batch-generator factory); ``reset()`` re-enters either.
    Host-side only — wrap in :class:`AsyncDataSetIterator` to overlap the
    stacking + ``device_put`` with the running dispatch (double
    buffering). Stacking is np-based batch assembly: a source yielding
    DEVICE arrays pays a device->host fetch per leaf (off the dispatch
    critical path, on the producer thread, but still bus traffic) —
    feed host arrays for peak prefetch throughput.
    """

    def __init__(self, source, k, *, batch_size=None):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.source = source
        self.k = int(k)
        self._nominal = batch_size
        self._target = None  # bucketed batch shape, fixed at first batch
        self._it = None

    @property
    def batch_size(self):
        if self._nominal:
            return self._nominal
        return getattr(self.source, "batch_size", None)

    def reset(self):
        if isinstance(self.source, DataSetIterator) or not callable(self.source):
            self._it = iter(iter_batches(self.source))
        else:
            self._it = iter(self.source())

    def __next__(self):
        if self._it is None:
            self.reset()
        got = []
        for _ in range(self.k):
            try:
                got.append(next(self._it))
            except StopIteration:
                break
        if not got:
            raise StopIteration
        if self._target is None:
            nominal = self.batch_size
            self._target = int(max(_leading_dim(got[0][0]), nominal or 0))
        padded = [pad_batch(x, y, m, self._target) for x, y, m in got]
        n = len(padded)
        xs = [p[0] for p in padded]
        ys = [p[1] for p in padded]
        ms = [np.asarray(p[2]) for p in padded]
        if n < self.k:  # ragged K-tail: zeroed no-op steps
            zx = jax.tree_util.tree_map(np.zeros_like, xs[0])
            zy = jax.tree_util.tree_map(np.zeros_like, ys[0])
            zm = np.zeros_like(ms[0])
            xs += [zx] * (self.k - n)
            ys += [zy] * (self.k - n)
            ms += [zm] * (self.k - n)
        stack = lambda parts: jax.tree_util.tree_map(
            lambda *leaves: np.stack(leaves), *parts)
        return SuperBatch(
            features=stack(xs), labels=stack(ys), labels_mask=np.stack(ms),
            step_valid=(np.arange(self.k) < n).astype(np.float32),
            n_steps=n)


class MultipleEpochsIterator(DataSetIterator):
    """(reference: MultipleEpochsIterator.java)"""

    def __init__(self, base: DataSetIterator, epochs: int):
        self.base = base
        self.epochs = epochs
        self._epoch = 0

    @property
    def batch_size(self):
        return self.base.batch_size

    def reset(self):
        self._epoch = 0
        self.base.reset()

    def __next__(self):
        try:
            return next(self.base)
        except StopIteration:
            self._epoch += 1
            if self._epoch >= self.epochs:
                raise
            self.base.reset()
            return next(self.base)


class EarlyTerminationIterator(DataSetIterator):
    """Cap the number of minibatches (reference:
    EarlyTerminationDataSetIterator.java)."""

    def __init__(self, base: DataSetIterator, max_batches: int):
        self.base = base
        self.max_batches = max_batches
        self._count = 0

    @property
    def batch_size(self):
        return self.base.batch_size

    def reset(self):
        self._count = 0
        self.base.reset()

    def __next__(self):
        if self._count >= self.max_batches:
            raise StopIteration
        self._count += 1
        return next(self.base)


class BenchmarkDataSetIterator(DataSetIterator):
    """Synthetic fixed batch repeated N times (reference:
    impl/BenchmarkDataSetIterator.java — zero-ETL benchmark feeder)."""

    def __init__(self, feature_shape, n_classes, n_batches, seed=0, labels_shape=None):
        rs = np.random.RandomState(seed)
        self._features = rs.rand(*feature_shape).astype(np.float32)
        if labels_shape is None:
            idx = rs.randint(0, n_classes, feature_shape[0])
            self._labels = np.eye(n_classes, dtype=np.float32)[idx]
        else:
            self._labels = rs.rand(*labels_shape).astype(np.float32)
        self.n_batches = n_batches
        self._count = 0

    @property
    def batch_size(self):
        return self._features.shape[0]

    def reset(self):
        self._count = 0

    def __next__(self):
        if self._count >= self.n_batches:
            raise StopIteration
        self._count += 1
        return DataSet(features=self._features, labels=self._labels)


class DataSetCallback:
    """Hook applied to each prefetched batch before it reaches the consumer
    (reference: datasets/iterator/callbacks/DataSetCallback.java)."""

    def call(self, ds: DataSet) -> DataSet:
        return ds

    def reset(self):
        """Called on iterator reset so per-epoch state (e.g. round-robin
        position) realigns with batch indices."""


class InterleavedDataSetCallback(DataSetCallback):
    """Round-robin prefetched batches across local devices (reference:
    callbacks/InterleavedDataSetCallback.java — workspace-migrates each
    incoming batch onto the next device so ParallelWrapper replicas read
    device-local data). TPU-native: jax.device_put onto
    jax.local_devices()[i % n] — the replica consuming batch i finds it
    already resident on its chip, off the step critical path."""

    def __init__(self, devices=None):
        import jax
        self.devices = list(devices) if devices else jax.local_devices()
        self._counter = 0

    def reset(self):
        self._counter = 0

    def call(self, ds: DataSet) -> DataSet:
        import jax
        dev = self.devices[self._counter % len(self.devices)]
        self._counter += 1
        put = lambda a: None if a is None else jax.device_put(a, dev)
        return DataSet(features=put(ds.features), labels=put(ds.labels),
                       features_mask=put(ds.features_mask),
                       labels_mask=put(ds.labels_mask))


class ShardedDataSetIterator(DataSetIterator):
    """Per-process shard of a source iterator for multi-host training.

    Reference analog: the Spark tier's RDD partitioning — each executor
    consumes its own partition of the dataset (ParameterAveragingTraining-
    Master's splits). On a jax.distributed multi-host run, each process
    wraps its iterator in one of these with its own
    ``jax.process_index()``/``jax.process_count()``: batch k is consumed by
    process k % count, everything else is skipped, so the processes stream
    disjoint data with no coordinator.

    Defaults read the live jax runtime so single-process runs degrade to a
    pass-through (index 0 of 1).

    ETL cost: if the source exposes ``skip(n)`` (cheap positional seek),
    peers' batches are skipped without decoding, so per-host ETL cost is
    1/process_count of the stream. Otherwise every process decodes all
    process_count batches per round and discards the peers' — put this
    shard filter UPSTREAM of expensive decode steps, or give the source a
    ``skip``.
    """

    def __init__(self, source, process_index=None, process_count=None):
        self.source = source
        self.process_index = (jax.process_index() if process_index is None
                              else process_index)
        self.process_count = (jax.process_count() if process_count is None
                              else process_count)
        assert 0 <= self.process_index < self.process_count

    def reset(self):
        self.source.reset()

    def __next__(self):
        # consume one FULL round of process_count batches and return ours:
        # an incomplete final round raises StopIteration before anything is
        # returned, so every process sees the SAME number of batches — an
        # uneven split would leave some processes stepping into collectives
        # their peers never join (multi-host deadlock)
        if callable(getattr(self.source, "skip", None)):
            # seek fast path: decode only our batch. skip(n) either raises
            # StopIteration when fewer than n batches remain, or returns
            # the count actually skipped (clamp-style seek, e.g. a
            # tf.data-like source) — an under-skip is converted to
            # StopIteration here. Either way every process abandons a
            # ragged final round in the SAME __next__ call (lower ranks in
            # the trailing skip, higher ranks in the leading one), which
            # preserves the equal-batch-count invariant above.
            self._skip(self.process_index)
            mine = next(self.source)
            self._skip(self.process_count - self.process_index - 1)
            return mine
        mine = None
        for i in range(self.process_count):
            batch = next(self.source)  # StopIteration drops the round
            if i == self.process_index:
                mine = batch
        return mine

    def _skip(self, n):
        if n <= 0:
            return
        skipped = self.source.skip(n)
        if skipped is not None and skipped < n:
            raise StopIteration

    @property
    def batch_size(self):
        return self.source.batch_size


def iter_batches(data, labels=None, batch_size=None, mask=None, pad_to=None):
    """Unified minibatch source shared by the training facades
    (MultiLayerNetwork.fit, ParallelTrainer.fit): yields (x, y, mask)
    from a DataSetIterator-style iterable (DataSet objects, dicts,
    2/3-tuples), an (x, y) pair, or feature+label arrays sliced by
    ``batch_size``.

    ``pad_to``: bucket every yielded batch to that many examples (``True``
    = the first batch's size), zero-padding ragged tails and ALWAYS
    yielding a mask so one jit signature covers the whole epoch — exact
    under the masked-mean losses (shape bucketing, nn/fused.py)."""
    if pad_to is not None and pad_to is not False:
        target = None if pad_to is True else int(pad_to)
        for x, y, m in iter_batches(data, labels, batch_size, mask):
            if target is None:
                target = _leading_dim(x)
            x, y, m, _ = pad_batch(x, y, m, target)
            yield x, y, m
        return
    import jax.numpy as jnp
    import numpy as np

    if labels is None and hasattr(data, "__iter__") \
            and not isinstance(data, (tuple, list, np.ndarray,
                                      jnp.ndarray)):
        for item in data:
            if hasattr(item, "features") and hasattr(item, "labels"):
                yield item.features, item.labels, item.features_mask
            elif isinstance(item, dict):
                yield item["features"], item["labels"], item.get("mask")
            elif len(item) == 3:
                yield item
            else:
                yield item[0], item[1], None
        return
    if labels is None and hasattr(data, "shape"):
        raise ValueError("labels are required with array features "
                         "(pass an iterator or (x, y) pair otherwise)")
    x, y = (data, labels) if labels is not None else data
    n = x.shape[0]
    bs = batch_size or n
    for i in range(0, n, bs):
        m = mask[i:i + bs] if mask is not None else None
        yield x[i:i + bs], y[i:i + bs], m
