"""Download+cache+checksum framework for dataset fetchers.

Reference analog: CacheableExtractableDataSetFetcher
(/root/reference/deeplearning4j-core/src/main/java/org/deeplearning4j/
datasets/fetchers/CacheableExtractableDataSetFetcher.java) — download to a
local cache dir, verify checksum, extract archives, delete-and-fail-hard on
mismatch (same policy as ZooModel.java:77-83).

Offline-first: this build environment has zero egress, so downloading is
gated behind ``DL4J_TPU_ALLOW_DOWNLOAD=1``. Without it, a missing file raises
``FileNotFoundError`` describing the expected layout so users can stage data
out-of-band (the normal mode on TPU pods, where data lives on a mounted GCS
bucket anyway).
"""

from __future__ import annotations

import hashlib
import os
import shutil
import tarfile
import zipfile

from deeplearning4j_tpu import telemetry as _tm
from deeplearning4j_tpu.datasets import fetchers as _f


def _cache_counter():
    reg = _tm.get_registry()
    c = reg.counter(
        "dataset_cache_requests_total",
        "dataset cache lookups, labeled outcome=hit|miss")
    if reg.enabled:
        # pre-register both outcome series at zero so a miss (or a hit)
        # that never happens still charts as an explicit 0
        for outcome in ("hit", "miss"):
            c.inc(0, outcome=outcome)
    return c


class ChecksumError(RuntimeError):
    pass


def downloads_allowed():
    return os.environ.get("DL4J_TPU_ALLOW_DOWNLOAD") == "1"


def _md5(path, chunk=1 << 20):
    h = hashlib.md5()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def ensure_file(relpath, url=None, md5=None, root=None):
    """Return the local path of ``relpath`` under the data dir, downloading
    it (gated) if absent. Checksum mismatch deletes the file and raises
    (reference ZooModel.java:77-83 policy)."""
    root = root or _f.data_dir()
    path = os.path.join(root, relpath)
    if not os.path.exists(path):
        _cache_counter().inc(outcome="miss")
        if url is None or not downloads_allowed():
            raise FileNotFoundError(
                f"Dataset file {relpath} not found under {root}. This "
                f"environment is offline-first: stage the file there manually"
                + (f" (source: {url})" if url else "")
                + ", or set DL4J_TPU_ALLOW_DOWNLOAD=1 to fetch it.")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        import urllib.request
        tmp = path + ".part"
        with _tm.span("etl.download", file=relpath):
            urllib.request.urlretrieve(url, tmp)
        os.replace(tmp, path)
    else:
        _cache_counter().inc(outcome="hit")
    if md5 is not None:
        # memoize verification in a sidecar marker so repeated fetcher
        # construction doesn't re-hash multi-GB archives every call; the
        # marker binds to (md5, size, mtime_ns) so any in-place modification
        # invalidates it and the mismatch path still fires
        st = os.stat(path)
        stamp = f"{md5} {st.st_size} {st.st_mtime_ns}"
        marker = path + ".md5ok"
        if os.path.exists(marker):
            with open(marker) as f:
                if f.read().strip() == stamp:
                    return path
        with _tm.span("etl.checksum", file=relpath):
            got = _md5(path)
        if got != md5:
            os.remove(path)
            if os.path.exists(marker):
                os.remove(marker)
            raise ChecksumError(
                f"Checksum mismatch for {path}: expected {md5}, got {got}; "
                f"cached file deleted — re-stage it.")
        try:  # best-effort cache: staged data may live on a read-only mount
            with open(marker, "w") as f:
                f.write(stamp)
        except OSError:
            pass
    return path


def ensure_extracted(relpath, archive_relpath, url=None, md5=None, root=None):
    """Ensure directory ``relpath`` exists, extracting ``archive_relpath``
    (zip/tar[.gz]) if needed."""
    root = root or _f.data_dir()
    target = os.path.join(root, relpath)
    if os.path.isdir(target) and os.listdir(target):
        return target
    archive = ensure_file(archive_relpath, url=url, md5=md5, root=root)
    tmp = target + ".extracting"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp, exist_ok=True)
    if zipfile.is_zipfile(archive):
        with zipfile.ZipFile(archive) as z:
            z.extractall(tmp)
    else:
        with tarfile.open(archive) as t:
            t.extractall(tmp, filter="data")
    os.makedirs(os.path.dirname(target) or root, exist_ok=True)
    shutil.rmtree(target, ignore_errors=True)
    os.replace(tmp, target)
    return target
