"""CSV record readers: the DataVec CSVRecordReader family's role.

Reference analogs: org.datavec.api CSVRecordReader /
CSVSequenceRecordReader + deeplearning4j's RecordReaderDataSetIterator /
SequenceRecordReaderDataSetIterator wrappers, which the reference's own
Spark data-plumbing tests drive against the fixtures at
dl4j-spark/src/test/resources/csvsequence* and dl4j-streaming's iris.dat
(TestDataVecDataSetFunctions.java:155-250) — the same genuine files
validate this module in tests/test_records.py.

TPU-first shapes: sequence batches come back PADDED to the longest
sequence with an explicit [B, T] mask (static shapes for jit; the
reference's ALIGN_END/variable-length handling maps onto the mask
convention every recurrent layer here already consumes).
"""

from __future__ import annotations

import glob
import os

import numpy as np


def read_csv_records(path, *, skip_lines=0, delimiter=","):
    """[N, C] float array from one CSV file (CSVRecordReader)."""
    rows = []
    with open(path) as f:
        for i, line in enumerate(f):
            if i < skip_lines:
                continue
            line = line.strip()
            if line:
                rows.append([float(v) for v in line.split(delimiter)])
    if not rows:
        raise ValueError(f"{path}: no data rows "
                         f"(skip_lines={skip_lines} consumed everything?)")
    return np.asarray(rows, np.float32)


def csv_dataset(path, *, label_column=-1, n_classes=None, skip_lines=0,
                delimiter=","):
    """(features [N, F], labels) from a column-labelled CSV — the
    RecordReaderDataSetIterator(reader, batch, labelIdx, numClasses)
    contract. Integer labels one-hot when ``n_classes`` is given."""
    arr = read_csv_records(path, skip_lines=skip_lines, delimiter=delimiter)
    if label_column is None:
        return arr, None
    lab = arr[:, label_column]
    feats = np.delete(arr, label_column, axis=1)
    if n_classes:
        lab = _one_hot(lab, n_classes, path)
    return feats, lab


def _one_hot(values, n_classes, source):
    ids = np.asarray(values).astype(int).reshape(-1)
    if ids.min(initial=0) < 0 or ids.max(initial=0) >= n_classes:
        bad = ids[(ids < 0) | (ids >= n_classes)][0]
        raise ValueError(f"{source}: label {bad} outside [0, {n_classes})")
    return np.eye(n_classes, dtype=np.float32)[ids]


class CSVSequenceRecordReader:
    """One sequence per file: [T, C] float arrays
    (CSVSequenceRecordReader(numLinesToSkip, delimiter))."""

    def __init__(self, skip_lines=0, delimiter=","):
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def read(self, path):
        return read_csv_records(path, skip_lines=self.skip_lines,
                                delimiter=self.delimiter)

    def read_all(self, paths_or_glob):
        if isinstance(paths_or_glob, str):
            paths = sorted(glob.glob(paths_or_glob)) \
                if any(ch in paths_or_glob for ch in "*?[") else \
                sorted(glob.glob(os.path.join(paths_or_glob, "*")))
        else:
            paths = list(paths_or_glob)
        return [self.read(p) for p in paths]


def sequence_dataset(feature_files, label_files, *, n_classes=None,
                     skip_lines=0, delimiter=",",
                     regression=False, align="equal"):
    """(features [B, T, F], labels [B, T, C], feature_mask [B, T],
    label_mask [B, T]) from parallel per-sequence feature/label file
    lists — the SequenceRecordReaderDataSetIterator contract (features
    file i pairs with labels file i). Classification labels (one int per
    timestep) one-hot; ``regression=True`` keeps raw label columns.

    ``align``:
    * ``"equal"`` — every pair must have matching lengths (the
      reference's default; mismatch is an error);
    * ``"end"`` — shorter label sequences align to the END of their
      features (AlignmentMode.ALIGN_END — many-to-one sequence
      classification; the reference's csvsequencelabelsShort fixtures
      pair with csvsequence exactly this way), label_mask marking only
      the aligned steps.
    Variable-length sequences pad to the longest with mask=0 past each
    end."""
    if align not in ("equal", "end"):
        raise ValueError(f"unknown align {align!r}")
    if not regression and not n_classes:
        raise ValueError("n_classes is required for classification labels "
                         "(or pass regression=True)")
    rr = CSVSequenceRecordReader(skip_lines, delimiter)
    feats = rr.read_all(feature_files)
    labs = rr.read_all(label_files)
    if not feats:
        raise ValueError(f"no feature sequences found for {feature_files!r}")
    if len(feats) != len(labs):
        raise ValueError(f"{len(feats)} feature sequences vs "
                         f"{len(labs)} label sequences")
    for i, (x, y) in enumerate(zip(feats, labs)):
        if align == "equal" and len(x) != len(y):
            raise ValueError(f"sequence {i}: {len(x)} feature steps vs "
                             f"{len(y)} label steps (use align='end' for "
                             "many-to-one label files)")
        if len(y) > len(x):
            raise ValueError(f"sequence {i}: more label steps ({len(y)}) "
                             f"than feature steps ({len(x)})")
    b = len(feats)
    t_max = max(len(x) for x in feats)
    f_dim = feats[0].shape[1]
    x_out = np.zeros((b, t_max, f_dim), np.float32)
    feat_mask = np.zeros((b, t_max), np.float32)
    y_dim = labs[0].shape[1] if regression else n_classes
    y_out = np.zeros((b, t_max, y_dim), np.float32)
    lab_mask = np.zeros((b, t_max), np.float32)
    for i, (x, y) in enumerate(zip(feats, labs)):
        t = len(x)
        x_out[i, :t] = x
        feat_mask[i, :t] = 1.0
        start = t - len(y)  # 0 under align="equal"
        yy = y if regression else _one_hot(y[:, 0], n_classes,
                                           f"sequence {i}")
        y_out[i, start:t] = yy
        lab_mask[i, start:t] = 1.0
    return x_out, y_out, feat_mask, lab_mask
