"""Dataset normalizers: the ND4J DataNormalization family.

Reference analogs (used throughout /root/reference's training examples
and tests, e.g. ModelSerializerTest.java, RecordReaderDataSetiteratorTest
.java): ``NormalizerStandardize`` (per-feature z-score),
``NormalizerMinMaxScaler`` (per-feature affine to [lo, hi]) and
``ImagePreProcessingScaler`` (fixed 0-255 pixel scaling). The reference
fits over a DataSetIterator in one pass and then attaches the fitted
normalizer to train/eval pipelines (and optionally into the model zip via
ModelSerializer.addNormalizerToModel — see utils/serialization.py).

TPU-native shape: fit is numpy (host-side ETL, one streaming pass —
Welford/min-max over batches); transform/revert are jnp-friendly pure
functions usable inside jit or in the input pipeline. Feature statistics
are computed over ALL leading axes (batch, time, spatial), per trailing
feature channel — matching the reference's per-column semantics for 2d
data and per-channel semantics for images (NHWC here).
"""

from __future__ import annotations

import json

import numpy as np


class _FittedNormalizer:
    """Shared fit-over-iterator plumbing + serde."""

    _KIND = None  # subclass tag for serde

    def fit_iterator(self, iterator):
        """One pass over a DataSetIterator-style iterable of (x, y) (or
        objects with .features/.labels), like DataNormalization.fit(iter)."""
        for batch in iterator:
            x = getattr(batch, "features", None)
            if x is None:
                x = batch[0]
            self.partial_fit(np.asarray(x))
        return self

    # --- serde (JSON — see utils/serialization.add_normalizer_to_model) ---
    def to_json(self):
        return json.dumps({"kind": self._KIND, **self._state()})

    @staticmethod
    def from_json(s):
        d = json.loads(s)
        kinds = {c._KIND: c for c in
                 (NormalizerStandardize, NormalizerMinMaxScaler,
                  ImagePreProcessingScaler)}
        cls = kinds[d.pop("kind")]
        return cls._from_state(d)


class NormalizerStandardize(_FittedNormalizer):
    """Per-feature z-score: (x - mean) / std.

    Reference: org.nd4j.linalg.dataset.api.preprocessor
    .NormalizerStandardize — streaming fit, transform, revert. Batches
    merge by Chan's parallel-Welford update on (n, mean, M2): the naive
    sumsq/n - mean^2 form catastrophically cancels for large-offset
    features (a timestamp column ~1.7e9 with std ~1 would zero out)."""

    _KIND = "standardize"

    def __init__(self):
        self._n = 0
        self._mean = None   # running per-feature mean (float64)
        self._m2 = None     # running per-feature sum of squared deviations
        self.mean = None
        self.std = None

    def fit(self, x):
        self._n, self._mean, self._m2 = 0, None, None
        self.partial_fit(x)
        return self

    def partial_fit(self, x):
        flat = np.asarray(x, np.float64).reshape(-1, np.shape(x)[-1])
        n_b = flat.shape[0]
        mean_b = flat.mean(0)
        m2_b = ((flat - mean_b) ** 2).sum(0)
        if self._mean is None:
            self._n, self._mean, self._m2 = n_b, mean_b, m2_b
        else:
            n_ab = self._n + n_b
            delta = mean_b - self._mean
            self._mean = self._mean + delta * (n_b / n_ab)
            self._m2 = (self._m2 + m2_b
                        + delta * delta * (self._n * n_b / n_ab))
            self._n = n_ab
        self.mean = self._mean.astype(np.float32)
        # the reference floors std to avoid divide-by-zero on constant cols
        self.std = np.sqrt(self._m2 / self._n).astype(np.float32)
        self.std = np.where(self.std < 1e-7, 1.0, self.std)
        return self

    def transform(self, x):
        return (x - self.mean) / self.std

    def revert(self, x):
        return x * self.std + self.mean

    def _state(self):
        return {"mean": self.mean.tolist(), "std": self.std.tolist(),
                "n": self._n,
                "running_mean": np.asarray(self._mean).tolist(),
                "m2": np.asarray(self._m2).tolist()}

    @classmethod
    def _from_state(cls, d):
        self = cls()
        self.mean = np.asarray(d["mean"], np.float32)
        self.std = np.asarray(d["std"], np.float32)
        self._n = d["n"]
        self._mean = np.asarray(d["running_mean"], np.float64)
        self._m2 = np.asarray(d["m2"], np.float64)
        return self


class NormalizerMinMaxScaler(_FittedNormalizer):
    """Per-feature affine map of the observed [min, max] onto [lo, hi]
    (default [0, 1]). Reference: NormalizerMinMaxScaler."""

    _KIND = "minmax"

    def __init__(self, lo=0.0, hi=1.0):
        self.lo, self.hi = float(lo), float(hi)
        self.data_min = None
        self.data_max = None

    def fit(self, x):
        self.data_min = self.data_max = None
        self.partial_fit(x)
        return self

    def partial_fit(self, x):
        x = np.asarray(x, np.float64)
        flat = x.reshape(-1, x.shape[-1])
        mn, mx = flat.min(0), flat.max(0)
        if self.data_min is None:
            self.data_min, self.data_max = mn, mx
        else:
            self.data_min = np.minimum(self.data_min, mn)
            self.data_max = np.maximum(self.data_max, mx)
        return self

    def _scale(self):
        rng = np.maximum(self.data_max - self.data_min, 1e-12)
        return ((self.hi - self.lo) / rng).astype(np.float32)

    def transform(self, x):
        return (x - self.data_min.astype(np.float32)) * self._scale() + self.lo

    def revert(self, x):
        return (x - self.lo) / self._scale() + self.data_min.astype(np.float32)

    def _state(self):
        return {"lo": self.lo, "hi": self.hi,
                "min": np.asarray(self.data_min).tolist(),
                "max": np.asarray(self.data_max).tolist()}

    @classmethod
    def _from_state(cls, d):
        self = cls(d["lo"], d["hi"])
        self.data_min = np.asarray(d["min"], np.float64)
        self.data_max = np.asarray(d["max"], np.float64)
        return self


class ImagePreProcessingScaler(_FittedNormalizer):
    """Fixed pixel scaling 0-255 -> [lo, hi] (default [0, 1]); no fit
    needed. Reference: ImagePreProcessingScaler (maxBits=8)."""

    _KIND = "image"

    def __init__(self, lo=0.0, hi=1.0, max_pixel=255.0):
        self.lo, self.hi = float(lo), float(hi)
        self.max_pixel = float(max_pixel)

    def fit(self, x):  # stateless — parity with the reference's no-op fit
        return self

    def partial_fit(self, x):
        return self

    def transform(self, x):
        return x / self.max_pixel * (self.hi - self.lo) + self.lo

    def revert(self, x):
        return (x - self.lo) / (self.hi - self.lo) * self.max_pixel

    def _state(self):
        return {"lo": self.lo, "hi": self.hi, "max_pixel": self.max_pixel}

    @classmethod
    def _from_state(cls, d):
        return cls(d["lo"], d["hi"], d["max_pixel"])
