"""Fused conv+BN(+residual+activation) graph vertex.

Reference analog: the cuDNN helper swap-in at ConvolutionLayer.java:74-84 —
the reference keeps the layer graph unchanged and substitutes a fused fast
path per layer. Here the fusion spans what in the unfused graph is a
ConvolutionLayer -> BatchNormalization (-> ElementWiseVertex(add) ->
ActivationLayer) chain, collapsed into ONE vertex so the Pallas phase-1
kernel (ops/conv_pallas.py) can fuse the BN statistics reduction into the
conv epilogue. ``models/resnet.py`` builds with these vertices under
``fused=True`` (the BENCH_FUSED_CONV A/B).

The vertex is self-sufficient on any backend: when the kernel seam is
closed (CPU, unsupported geometry, eval mode) it runs the same math as the
unfused chain via XLA — so checkpoints and eval paths never depend on
Pallas.
"""

from __future__ import annotations

import dataclasses
import os

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import initializers as _init
from deeplearning4j_tpu.nn.conf import inputs as _inputs
from deeplearning4j_tpu.nn.graph import GraphVertex
from deeplearning4j_tpu.nn.layers.conv import (
    DIMNUMS_2D, _conv_out_size, _explicit_padding, _pair, conv)
from deeplearning4j_tpu.ops import conv_pallas
from deeplearning4j_tpu.utils import dtypes as _dtypes
from deeplearning4j_tpu.utils.serde import register_config


@register_config
@dataclasses.dataclass(frozen=True)
class FusedConvBNVertex(GraphVertex):
    """conv (no bias) + batch-norm + optional residual add + activation.

    Inputs: (x,) or (x, residual) when ``residual=True``; the residual is
    added AFTER the affine, before the activation — exactly the ResNet
    bottleneck tail (conv_c -> BN -> add -> relu).
    """

    n_out: int = 0
    kernel: tuple = (1, 1)
    stride: tuple = (1, 1)
    padding: str = "same"
    activation: str = "relu"
    residual: bool = False
    eps: float = 1e-5
    decay: float = 0.9
    weight_init: object = "relu"

    def output_type(self, input_types):
        it = input_types[0]
        assert isinstance(it, _inputs.ConvolutionalType)
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        h = _conv_out_size(it.height, kh, sh, self.padding, 0)
        w = _conv_out_size(it.width, kw, sw, self.padding, 0)
        return _inputs.ConvolutionalType(h, w, self.n_out)

    def init(self, key, input_types, dtype=jnp.float32):
        kh, kw = _pair(self.kernel)
        cin = input_types[0].channels
        return {
            "W": _init.init_weight(self.weight_init, key,
                                   (kh, kw, cin, self.n_out),
                                   cin * kh * kw, self.n_out * kh * kw,
                                   dtype),
            "gamma": jnp.ones((self.n_out,), dtype),
            "beta": jnp.zeros((self.n_out,), dtype),
        }

    def init_state(self, input_types, dtype=jnp.float32):
        return {"mean": jnp.zeros((self.n_out,), dtype),
                "var": jnp.ones((self.n_out,), dtype)}

    def _kernel_applies(self, train, x_shape):
        if not train:
            return False, False
        # test seam: force the Pallas path in interpret mode on CPU
        if os.environ.get("DL4J_TPU_FUSED_CONV_INTERPRET", "0") == "1":
            interp = True
        elif conv_pallas.enabled():
            interp = False
        else:
            return False, False
        ok = conv_pallas.supported(_pair(self.kernel), _pair(self.stride),
                                   self.padding, (1, 1), self.activation,
                                   x_shape=x_shape)
        return ok, interp

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        x = xs[0]
        r = xs[1] if self.residual else None
        use_kernel, interpret = self._kernel_applies(train, x.shape)
        if use_kernel:
            # kernel interface runs in the COMPUTE dtype (bf16 under the
            # mixed policy — 4x the f32 MXU rate, half the W/x traffic);
            # stats/normalize stay f32 inside fused_conv_bn_act
            cd, _ = _dtypes.compute_dtypes_for(x.dtype)
            y, mean, var = conv_pallas.fused_conv_bn_act(
                x.astype(cd), params["W"].astype(cd),
                params["gamma"], params["beta"],
                None if r is None else r.astype(cd),
                _pair(self.stride), self.eps, self.activation, interpret)
            new_state = {
                "mean": self.decay * state["mean"]
                        + (1 - self.decay) * mean.astype(state["mean"].dtype),
                "var": self.decay * state["var"]
                       + (1 - self.decay) * var.astype(state["var"].dtype),
            }
            return y, new_state
        # XLA fallback: same math as the unfused conv->BN->add->act chain
        z = conv(x, params["W"], window_strides=_pair(self.stride),
                 padding=_explicit_padding(self.padding, (0, 0)),
                 dimension_numbers=DIMNUMS_2D)
        _, ad = _dtypes.compute_dtypes_for(z.dtype)
        zf = z.astype(ad)
        axes = (0, 1, 2)
        if train:
            mean = jnp.mean(zf, axis=axes)
            var = jnp.var(zf, axis=axes)
            new_state = {
                "mean": self.decay * state["mean"]
                        + (1 - self.decay) * mean.astype(state["mean"].dtype),
                "var": self.decay * state["var"]
                       + (1 - self.decay) * var.astype(state["var"].dtype),
            }
        else:
            mean, var = state["mean"].astype(ad), state["var"].astype(ad)
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        ypre = (zf - mean) * inv * params["gamma"].astype(ad) \
            + params["beta"].astype(ad)
        if r is not None:
            ypre = ypre + r.astype(ad)
        from deeplearning4j_tpu.nn import activations as _acts
        ypre = _acts.get(self.activation)(ypre)
        return ypre.astype(z.dtype), new_state

    WEIGHT_KEYS = ("W", "gamma")

    def regularization_penalty(self, params):
        return 0.0
