"""Early stopping.

Reference analog: earlystopping/ in /root/reference/deeplearning4j-nn —
EarlyStoppingConfiguration.java, trainer/BaseEarlyStoppingTrainer.java:76
(fit()), termination conditions (epoch/iteration/score), savers
(in-memory/local FS), score calculators.
"""

from __future__ import annotations

import dataclasses
import os
import time


# ---- termination conditions (reference: earlystopping/termination/) ----


@dataclasses.dataclass(frozen=True)
class MaxEpochsTermination:
    max_epochs: int = 10

    def terminate_epoch(self, epoch, score, best_score):
        return epoch >= self.max_epochs


@dataclasses.dataclass(frozen=True)
class ScoreImprovementEpochsTermination:
    """Stop after N epochs with no score improvement."""

    max_epochs_no_improvement: int = 5
    min_improvement: float = 0.0

    def __post_init__(self):
        object.__setattr__(self, "_best", None)
        object.__setattr__(self, "_stale", 0)

    def terminate_epoch(self, epoch, score, best_score):
        if self._best is None or score < self._best - self.min_improvement:
            object.__setattr__(self, "_best", score)
            object.__setattr__(self, "_stale", 0)
            return False
        object.__setattr__(self, "_stale", self._stale + 1)
        return self._stale >= self.max_epochs_no_improvement


@dataclasses.dataclass(frozen=True)
class BestScoreTermination:
    """Stop once score is at or below a target."""

    target: float = 0.0

    def terminate_epoch(self, epoch, score, best_score):
        return score <= self.target


@dataclasses.dataclass(frozen=True)
class MaxTimeTermination:
    max_seconds: float = 3600.0

    def __post_init__(self):
        object.__setattr__(self, "_start", time.time())

    def terminate_epoch(self, epoch, score, best_score):
        return time.time() - self._start > self.max_seconds


@dataclasses.dataclass(frozen=True)
class MaxScoreIterationTermination:
    """Abort mid-training if score blows past a ceiling (divergence guard)."""

    max_score: float = 1e9

    def terminate_iteration(self, iteration, score):
        return score > self.max_score


# ---- savers (reference: earlystopping/saver/) ----


class InMemoryModelSaver:
    def __init__(self):
        self.best = None
        self.latest = None

    def save_best(self, net, score, epoch):
        import copy
        self.best = (self._snapshot(net), score, epoch)

    def save_latest(self, net, score, epoch):
        self.latest = (self._snapshot(net), score, epoch)

    @staticmethod
    def _snapshot(net):
        import jax
        import jax.numpy as jnp
        # real copies: the live net's donated train-step buffers must not
        # invalidate the snapshot
        return {"params": jax.tree_util.tree_map(jnp.copy, net.params),
                "state": jax.tree_util.tree_map(jnp.copy, net.state)}

    def restore_best(self, net):
        import jax
        import jax.numpy as jnp
        snap, _, _ = self.best
        # copy OUT too: handing the snapshot's own buffers to a donating
        # trainer would delete them on its next train step
        net.params = jax.tree_util.tree_map(jnp.copy, snap["params"])
        net.state = jax.tree_util.tree_map(jnp.copy, snap["state"])
        return net


class LocalFileModelSaver:
    def __init__(self, directory):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def save_best(self, net, score, epoch):
        from deeplearning4j_tpu.utils.serialization import save_model
        save_model(net, os.path.join(self.directory, "bestModel.zip"))

    def save_latest(self, net, score, epoch):
        from deeplearning4j_tpu.utils.serialization import save_model
        save_model(net, os.path.join(self.directory, "latestModel.zip"))

    def restore_best(self, net):
        from deeplearning4j_tpu.utils.serialization import load_model
        return load_model(os.path.join(self.directory, "bestModel.zip"))


# ---- score calculators (reference: earlystopping/scorecalc/) ----


class DataSetLossCalculator:
    def __init__(self, x, y, mask=None):
        self.x, self.y, self.mask = x, y, mask

    def __call__(self, net):
        return net.score(self.x, self.y, mask=self.mask)


# ---- configuration + trainer ----


@dataclasses.dataclass
class EarlyStoppingConfiguration:
    score_calculator: object = None
    epoch_terminations: tuple = ()
    iteration_terminations: tuple = ()
    saver: object = dataclasses.field(default_factory=InMemoryModelSaver)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: str = ""
    termination_details: str = ""
    total_epochs: int = 0
    best_epoch: int = -1
    best_score: float = float("inf")
    score_vs_epoch: dict = dataclasses.field(default_factory=dict)
    best_model: object = None


class EarlyStoppingTrainer:
    """(reference: trainer/BaseEarlyStoppingTrainer.java:76 fit loop)."""

    def __init__(self, config: EarlyStoppingConfiguration, net, x, y, *,
                 batch_size=None, mask=None):
        self.config = config
        self.net = net
        self.x, self.y, self.mask = x, y, mask
        self.batch_size = batch_size

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        result = EarlyStoppingResult()
        if self.net.params is None:
            self.net.init()
        epoch = 0
        while True:
            self.net.fit(self.x, self.y, epochs=1, batch_size=self.batch_size,
                         mask=self.mask)
            # iteration-level divergence guard
            score_now = getattr(self.net, "score_value", None)
            if score_now is not None:
                for t in cfg.iteration_terminations:
                    if t.terminate_iteration(self.net.iteration, float(score_now)):
                        result.termination_reason = "IterationTermination"
                        result.termination_details = type(t).__name__
                        result.total_epochs = epoch + 1
                        result.best_model = self.net
                        return result
            epoch += 1
            if epoch % cfg.evaluate_every_n_epochs == 0:
                score = cfg.score_calculator(self.net)
                result.score_vs_epoch[epoch] = score
                if score < result.best_score:
                    result.best_score = score
                    result.best_epoch = epoch
                    cfg.saver.save_best(self.net, score, epoch)
                if cfg.save_last_model:
                    cfg.saver.save_latest(self.net, score, epoch)
                for t in cfg.epoch_terminations:
                    if t.terminate_epoch(epoch, score, result.best_score):
                        result.termination_reason = "EpochTermination"
                        result.termination_details = type(t).__name__
                        result.total_epochs = epoch
                        result.best_model = cfg.saver.restore_best(self.net) \
                            if getattr(cfg.saver, "best", True) is not None else self.net
                        return result
