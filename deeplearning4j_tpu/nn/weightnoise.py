"""Weight noise (applied to params during training forward passes).

Reference analog: nn/conf/weightnoise/ in /root/reference/deeplearning4j-nn —
WeightNoise (additive/multiplicative distribution noise), DropConnect
(per-weight dropout). Functional design: the network perturbs a layer's
params pytree before apply() when training; the gradient flows through the
perturbed weights exactly as the reference's noisy-param path.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.initializers import Distribution
from deeplearning4j_tpu.utils.serde import register_config


@register_config
@dataclasses.dataclass(frozen=True)
class WeightNoise:
    distribution: Distribution = dataclasses.field(
        default_factory=lambda: Distribution(kind="normal", mean=0.0, std=0.01))
    additive: bool = True
    apply_to_bias: bool = False

    def perturb(self, rng, layer, params):
        out = {}
        for k, v in params.items():
            is_bias = k in getattr(layer, "BIAS_KEYS", ("b",))
            if is_bias and not self.apply_to_bias:
                out[k] = v
                continue
            rng, sub = jax.random.split(rng)
            noise = self.distribution.sample(sub, v.shape, v.dtype)
            out[k] = v + noise if self.additive else v * noise
        return out


@register_config
@dataclasses.dataclass(frozen=True)
class DropConnect:
    """Per-weight bernoulli dropout with inverted scaling (reference:
    nn/conf/weightnoise/DropConnect.java)."""

    weight_retain_prob: float = 0.5
    apply_to_bias: bool = False

    def perturb(self, rng, layer, params):
        out = {}
        keep = self.weight_retain_prob
        for k, v in params.items():
            is_bias = k in getattr(layer, "BIAS_KEYS", ("b",))
            if is_bias and not self.apply_to_bias:
                out[k] = v
                continue
            rng, sub = jax.random.split(rng)
            mask = jax.random.bernoulli(sub, keep, v.shape)
            out[k] = jnp.where(mask, v / keep, 0.0)
        return out
