"""Fused multi-step training: K train steps per dispatch via ``lax.scan``.

The per-step tax of the Python-over-XLA split — one jit dispatch, one
host->device batch copy, one listener round-trip per minibatch — caps the
step rate of fast models well below what the device sustains (SURVEY.md
§7; the prefetch-overlap cure is the tf.data pattern, arxiv 1605.08695).
This module amortizes that tax K-fold:

* ``make_train_steps(net, k)`` wraps the net's single train step in a
  ``jax.lax.scan`` over a stacked super-batch ``[K, B, ...]``: params,
  state, opt_state, the iteration counter and the RNG chain are carried
  ON DEVICE across the K steps, so K steps cost ONE dispatch.
* Ragged shapes never recompile (shape bucketing):
  ``datasets.iterator.SuperBatchIterator`` pads ragged minibatches to the
  bucketed batch shape — validity folded into the loss mask, exact
  because the masked mean divides by the real example count — and pads a
  ragged K-tail with zeroed no-op steps whose updates the scan discards
  via ``step_valid`` (a zero-mask batch still carries regularization
  gradients and updater-state decay, so masking the loss alone would NOT
  be a no-op; the carry must be ``where()``-kept).
* The input pipeline overlaps compute: super-batch stacking +
  ``device_put`` run on ``AsyncDataSetIterator``'s producer thread
  (double-buffered, ``queue_size=2``) while the current fused dispatch
  executes, and the consumed super-batch's buffers are donated back to
  XLA so its HBM is free for the next prefetch.
* Scores and health bundles come back as STACKED ``[K]`` arrays fetched
  one DISPATCH late through the existing ``ScorePipeline`` /
  ``HealthMonitor`` — the same pipelining discipline as the K=1 loop,
  now one fetch per K steps. Listener skew grows accordingly: callbacks
  for the K steps of dispatch *i* fire while dispatch *i+1* runs (see
  PROFILE.md / the StepRecordEmitter note).

Caveat (documented, not hidden): bucketing padding is exact for the loss
and gradients, but batch-statistics layers (BatchNorm in train mode) see
the zero rows in their batch moments on the padded tail step. Datasets
divisible by the batch size — or ``drop_last`` — sidestep this, exactly
as they did for the reference's ragged-batch handling.
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.utils import compile_cache as _cc

__all__ = ["make_train_steps", "fit_fused"]


def _silence_unusable_donation(fn):
    """Donated super-batch buffers rarely match an output shape, so XLA
    cannot reuse them and jax warns once per compile; the donation is
    still wanted — it releases the consumed super-batch's device memory
    for the prefetcher's next ``device_put``. Filter exactly that
    warning, keeping ``_cache_size`` visible for recompile telemetry."""
    @functools.wraps(fn)
    def call(*args, **kwargs):
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return fn(*args, **kwargs)
    if hasattr(fn, "_cache_size"):
        call._cache_size = fn._cache_size
    return call


class _ManifestDispatch:
    """Manifest-first dispatch for the fused K-step engine: the first call
    at each input signature deserializes the scan executable from the warm
    manifest (zero compiles on a warm restart) or live-compiles through
    ``compile_cache.aot_compile`` — which serializes the result back into
    the manifest, so saving the bundle after a cold run makes the next
    restart warm. Any signature the AOT path cannot serve (serialization
    unsupported on this backend, arg-convention mismatch) falls back to
    the plain jit permanently — correctness never depends on the cache."""

    def __init__(self, jitted, manifest, kind):
        self._jit = jitted
        self._manifest = manifest
        self._kind = kind
        self._by_sig = {}  # signature -> executable | False (jit fallback)

    def _cache_size(self):
        # recompile telemetry (devices.note_jit_cache) keys off the inner
        # jit's cache: manifest-served signatures never touch it, so a
        # warm restart reads 0 new compiles — exactly the claim under test
        return self._jit._cache_size()

    def __call__(self, *args):
        # params/state/opt_state (args[:3]) are the net's own device
        # trees, shape-invariant for this engine's lifetime (conf-fixed
        # architecture) — so the per-dispatch key normalizes and hashes
        # the BATCH args only: O(batch leaves), not O(model leaves).
        # asarray gives one signature for Python-int scalars (step0) at
        # lower time AND call time; leaf-wise — xs/ys may be dict
        # pytrees (CG inputs)
        leaves, treedef = jax.tree_util.tree_flatten(args[3:])
        leaves = [jnp.asarray(l) for l in leaves]
        args = args[:3] + tuple(jax.tree_util.tree_unflatten(treedef,
                                                             leaves))
        key = (treedef, tuple((l.shape, l.dtype.name) for l in leaves))
        ex = self._by_sig.get(key)
        if ex is None:
            sig = _cc.signature_of(args)
            try:
                ex, _src = _cc.aot_compile(self._jit, *args,
                                           manifest=self._manifest,
                                           kind=self._kind, signature=sig)
            except Exception:
                ex = False  # lowering rejected: serve via the jit path,
                #             which surfaces any real error
            self._by_sig[key] = ex
        if ex is not False:
            try:
                return ex(*args)
            except TypeError:
                # AOT arg-passing quirk on this jax version: permanent
                # jit fallback for this signature (never per-call retry)
                self._by_sig[key] = False
        return self._jit(*args)


def make_train_steps(net, k, donate=True, jit=True, with_health=False,
                     donate_batch=True, base_step=None):
    """Build the fused K-step engine over ``net``'s single train step:

    ``(params, state, opt_state, xs, ys, step0, rng, masks, step_valid)
    -> (params, state, opt_state, losses[K][, health{key: [K]}])``

    ``xs``/``ys``/``masks`` are stacked ``[K, B, ...]`` super-batches
    (pytrees stack leaf-wise — the ComputationGraph dict form works
    unchanged); ``step_valid`` is the K-tail bucketing vector. The scan
    carries params/state/opt_state, the iteration counter and the RNG
    chain on device, splitting a fresh subkey per step, so the K steps
    run back-to-back inside ONE XLA computation — one dispatch, no
    host round-trips between steps. Works for any net exposing the
    ``make_train_step`` contract (MultiLayerNetwork, ComputationGraph).

    ``base_step`` substitutes the single-step body (same signature as
    ``make_train_step(jit=False)``): ParallelTrainer injects its ZeRO
    step here, so the sharded optimizer state and the explicit
    reduce-scatter/all-gather grad→update boundary are carried through
    all K scanned steps, not just the K=1 path. The fsdp_stream tier
    rides the same seam: its injected step holds an INNER ``lax.scan``
    over the stacked trunk (per-block gather-use-discard), so a K-step
    dispatch is a scan-of-scans whose carry — params, opt state, RNG
    chain — stays in the streamed ``P('data')`` storage layout for all
    K steps; the full param tree never materializes across the whole
    dispatch, not just within one step (parity pinned K=4 == K=1
    replicated in tests/test_zero.py).
    """
    if base_step is not None and with_health:
        # the injected step's contract is the PLAIN 4-tuple; the scan
        # body would otherwise fail mid-trace with an opaque unpack
        # error ("expected 5, got 4") when the watchdog is armed
        raise ValueError(
            "make_train_steps: base_step and with_health=True don't "
            "compose — an injected step returns (params, state, opt, "
            "loss) without the health bundle; build the health variant "
            "into base_step or leave it to net.make_train_step")
    base = (base_step if base_step is not None
            else net.make_train_step(donate=False, jit=False,
                                     with_health=with_health))

    def steps_fn(params, state, opt_state, xs, ys, step0, rng, masks,
                 step_valid):
        def body(carry, inp):
            params, state, opt_state, step, rng = carry
            x, y, m, sv = inp
            rng, sub = jax.random.split(rng)
            out = base(params, state, opt_state, x, y, step, sub, m)
            if with_health:
                new_p, new_s, new_o, loss, hb = out
            else:
                (new_p, new_s, new_o, loss), hb = out, ()
            # K-tail no-op: a zero-mask padded step still has
            # regularization gradients and updater-state decay, so the
            # carry must be where()-kept, not just loss-masked
            keep = functools.partial(
                jax.tree_util.tree_map,
                lambda new, old: jnp.where(sv > 0, new, old))
            carry = (keep(new_p, params), keep(new_s, state),
                     keep(new_o, opt_state),
                     step + (sv > 0).astype(jnp.int32), rng)
            return carry, (loss, hb)

        carry0 = (params, state, opt_state, jnp.asarray(step0, jnp.int32),
                  rng)
        (params, state, opt_state, _, _), (losses, health) = jax.lax.scan(
            body, carry0, (xs, ys, masks, step_valid))
        if with_health:
            return params, state, opt_state, losses, health
        return params, state, opt_state, losses

    if not jit:
        return steps_fn
    manifest = getattr(net, "_warm_manifest", None)
    if manifest is not None:
        # a serializable executable must NOT bake in donation: a
        # deserialized executable loses jax's dispatch-time aliasing
        # guard, so donating a numpy-backed (zero-copy) super-batch or a
        # checkpoint-restored param tree frees memory the CALLER still
        # owns — heap corruption at best. The warm path trades the
        # donation's HBM reuse for restart-safe executables; K=1 and
        # manifest-less fused fits keep the donating engine unchanged.
        if donate:
            # say so: a model fit near device-memory capacity that
            # resumes via a bundle would otherwise see peak HBM grow
            # (params/opt_state no longer reused in-place) with nothing
            # in the logs explaining why
            warnings.warn(
                "warm manifest attached: buffer donation is disabled for "
                "the fused train engine (serialized executables lose "
                "jax's aliasing guard), so peak device memory for "
                "params/opt_state is higher than a manifest-less fit — "
                "detach the manifest (attach_manifest(net, None)) if "
                "memory-bound", stacklevel=2)
        donate = False
    donate_argnums = (0, 1, 2) if donate else ()
    if donate and donate_batch:
        donate_argnums += (3, 4, 7)  # the consumed super-batch
    fused = jax.jit(steps_fn, donate_argnums=donate_argnums)
    if manifest is not None:
        # warm restart: the K-step scan executable deserializes from the
        # checkpoint's manifest (utils/compile_cache) instead of paying
        # the fused retrace+compile — and a live compile serializes back
        # in, so the NEXT restart is warm
        fused = _ManifestDispatch(fused, manifest,
                                  kind=f"fused:k={int(k)}"
                                       f":health={int(bool(with_health))}")
    return _silence_unusable_donation(fused) if donate_argnums else fused


def _steps_fn_for(net, k, with_health):
    """Per-net cache of compiled fused engines, keyed (k, with_health).

    Each entry remembers the manifest it was built against, so
    ``attach_manifest`` after a cold fit rebuilds the engine on the next
    one — REPLACING the stale entry (never accumulating one engine, and
    one manifest's worth of executable blobs, per attach cycle)."""
    cache = getattr(net, "_train_steps_fused", None)
    if cache is None:
        cache = net._train_steps_fused = {}
    manifest = getattr(net, "_warm_manifest", None)
    key = (int(k), bool(with_health))
    entry = cache.get(key)
    if entry is not None and entry[1] is manifest:
        return entry[0]
    fn = make_train_steps(net, k, with_health=with_health)
    cache[key] = (fn, manifest)
    return fn


def fit_fused(net, batch_factory, *, epochs, k, batch_size=None,
              prefetch=True):
    """The fused-dispatch fit loop shared by MultiLayerNetwork and
    ComputationGraph (both expose the same trainer-state contract:
    params/state/opt_state/iteration/epoch/listeners/_rng/score_value).

    ``batch_factory`` is a zero-arg callable returning a fresh
    ``(x, y, mask)`` iterable per epoch (the net's batch generator). The
    stream is bucketed + stacked by ``SuperBatchIterator`` and, with
    ``prefetch``, assembled and ``device_put`` on an
    ``AsyncDataSetIterator`` producer thread while the current dispatch
    runs (double buffering) — the thread is joined in ``finally`` so a
    fit exception never leaves a dangling producer.

    The loop itself lives in ``continuous/driver.py`` (``StepDriver``
    with the fused engine — the resumable round API the continuous
    trainer checkpoints between); this wrapper is the historical entry
    point the ``fit(steps_per_dispatch=K)`` facades call.
    """
    from deeplearning4j_tpu.continuous.driver import StepDriver
    drv = StepDriver(net, batch_factory, k=k, batch_size=batch_size,
                     prefetch=prefetch,
                     fit_span_kw={"net": type(net).__name__, "fused_k": k})
    return drv.run(epochs)
