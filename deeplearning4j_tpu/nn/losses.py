"""Loss-function catalog with per-example masking and label weights.

Reference analog: ND4J ``LossFunctions.LossFunction`` enum + ILossFunction
implementations consumed by dl4j output layers (/root/reference/
deeplearning4j-nn/.../nn/conf/layers/OutputLayer.java lossFn field; score
computed at MultiLayerNetwork.java:2307). All losses here take
``(predictions, labels, mask)`` where predictions are post-activation network
outputs, and return the scalar mean-over-examples score the reference reports,
plus elementwise variants for evaluation plumbing.

Masking follows the reference's time-series convention: mask has shape
[batch] or [batch, time] and zeroes out padded steps from both score and
gradient (MaskedReductionUtil in the reference).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-8


def _flatten_tail(x):
    """[B, ..., F] -> [B*, F] collapsing any time dims into batch."""
    return x.reshape((-1, x.shape[-1]))


def _apply_mask_and_mean(per_example, mask):
    """per_example: [N] loss per (example, step); mask broadcastable to it."""
    if mask is None:
        return jnp.mean(per_example)
    mask = mask.reshape(-1).astype(per_example.dtype)
    total = jnp.sum(per_example * mask)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return total / denom


def mse(pred, labels, mask=None, weights=None):
    d = (pred - labels) ** 2
    if weights is not None:
        d = d * weights
    per = jnp.mean(_flatten_tail(d), axis=-1)
    return _apply_mask_and_mean(per, mask)


def mae(pred, labels, mask=None, weights=None):
    d = jnp.abs(pred - labels)
    if weights is not None:
        d = d * weights
    per = jnp.mean(_flatten_tail(d), axis=-1)
    return _apply_mask_and_mean(per, mask)


l1 = mae
l2 = mse


def xent(pred, labels, mask=None, weights=None):
    """Binary cross-entropy on sigmoid outputs (reference: LossBinaryXENT)."""
    p = jnp.clip(pred, _EPS, 1.0 - _EPS)
    ce = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p))
    if weights is not None:
        ce = ce * weights
    per = jnp.sum(_flatten_tail(ce), axis=-1)
    return _apply_mask_and_mean(per, mask)


def mcxent(pred, labels, mask=None, weights=None):
    """Multi-class cross-entropy on softmax outputs (reference: LossMCXENT).

    ``pred`` is a probability distribution (post-softmax), matching the
    reference convention where the output layer applies its activation before
    the loss. Internally uses logs with clipping for stability.
    """
    logp = jnp.log(jnp.clip(pred, _EPS, 1.0))
    ce = -labels * logp
    if weights is not None:
        ce = ce * weights
    per = jnp.sum(_flatten_tail(ce), axis=-1)
    return _apply_mask_and_mean(per, mask)


negativeloglikelihood = mcxent


def sparse_mcxent(pred, labels, mask=None, weights=None):
    """mcxent with integer class labels (TPU-friendly: no one-hot transfer)."""
    logp = jnp.log(jnp.clip(pred, _EPS, 1.0))
    flat = _flatten_tail(logp)
    idx = labels.reshape(-1).astype(jnp.int32)
    per = -jnp.take_along_axis(flat, idx[:, None], axis=-1)[:, 0]
    if weights is not None:
        per = per * weights.reshape(-1)
    return _apply_mask_and_mean(per, mask)


def hinge(pred, labels, mask=None, weights=None):
    """labels in {-1, +1} (reference: LossHinge)."""
    h = jnp.maximum(0.0, 1.0 - labels * pred)
    if weights is not None:
        h = h * weights
    per = jnp.sum(_flatten_tail(h), axis=-1)
    return _apply_mask_and_mean(per, mask)


def squared_hinge(pred, labels, mask=None, weights=None):
    h = jnp.maximum(0.0, 1.0 - labels * pred) ** 2
    if weights is not None:
        h = h * weights
    per = jnp.sum(_flatten_tail(h), axis=-1)
    return _apply_mask_and_mean(per, mask)


def kl_divergence(pred, labels, mask=None, weights=None):
    p = jnp.clip(pred, _EPS, 1.0)
    q = jnp.clip(labels, _EPS, 1.0)
    kl = labels * (jnp.log(q) - jnp.log(p))
    if weights is not None:
        kl = kl * weights
    per = jnp.sum(_flatten_tail(kl), axis=-1)
    return _apply_mask_and_mean(per, mask)


def cosine_proximity(pred, labels, mask=None, weights=None):
    pf, lf = _flatten_tail(pred), _flatten_tail(labels)
    pn = pf / (jnp.linalg.norm(pf, axis=-1, keepdims=True) + _EPS)
    ln = lf / (jnp.linalg.norm(lf, axis=-1, keepdims=True) + _EPS)
    per = -jnp.sum(pn * ln, axis=-1)
    return _apply_mask_and_mean(per, mask)


def poisson(pred, labels, mask=None, weights=None):
    p = jnp.clip(pred, _EPS, None)
    loss = p - labels * jnp.log(p)
    if weights is not None:
        loss = loss * weights
    per = jnp.sum(_flatten_tail(loss), axis=-1)
    return _apply_mask_and_mean(per, mask)


def mean_squared_log_error(pred, labels, mask=None, weights=None):
    d = (jnp.log1p(jnp.clip(pred, 0, None)) - jnp.log1p(jnp.clip(labels, 0, None))) ** 2
    if weights is not None:
        d = d * weights
    per = jnp.mean(_flatten_tail(d), axis=-1)
    return _apply_mask_and_mean(per, mask)


def mean_absolute_percentage_error(pred, labels, mask=None, weights=None):
    d = 100.0 * jnp.abs((labels - pred) / jnp.clip(jnp.abs(labels), _EPS, None))
    if weights is not None:
        d = d * weights
    per = jnp.mean(_flatten_tail(d), axis=-1)
    return _apply_mask_and_mean(per, mask)


_CATALOG = {
    "mse": mse,
    "mae": mae,
    "l1": l1,
    "l2": l2,
    "xent": xent,
    "mcxent": mcxent,
    "sparse_mcxent": sparse_mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "kl_divergence": kl_divergence,
    "cosine_proximity": cosine_proximity,
    "poisson": poisson,
    "mean_squared_log_error": mean_squared_log_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
}


def get(name):
    if callable(name):
        return name
    try:
        return _CATALOG[name.lower()]
    except KeyError:
        raise KeyError(f"Unknown loss {name!r}. Known: {sorted(_CATALOG)}") from None


def names():
    return sorted(_CATALOG)
