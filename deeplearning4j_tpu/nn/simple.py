"""Simple classification result wrappers.

Reference analog: nn/simple/multiclass/RankClassificationResult.java and
nn/simple/binary/BinaryClassificationResult.java in
/root/reference/deeplearning4j-nn — thin conveniences turning raw network
output matrices into ranked labels/probabilities for application code.
"""

from __future__ import annotations

import numpy as np


class RankClassificationResult:
    """Rank classes by probability per example (reference:
    RankClassificationResult.java — sortWithIndices descending + labels)."""

    def __init__(self, outcome, labels=None):
        outcome = np.asarray(outcome)
        if outcome.ndim == 1:
            outcome = outcome[None, :]
        if outcome.ndim != 2:
            raise ValueError("only vectors and matrices are supported")
        self.probabilities = outcome.astype(np.float32)
        self.ranked_indices = np.argsort(-outcome, axis=1, kind="stable")
        self.labels = (list(labels) if labels is not None
                       else [str(i) for i in range(outcome.shape[1])])

    def ranked_labels(self, row):
        """Class labels for one example, most probable first."""
        return [self.labels[i] for i in self.ranked_indices[row]]

    def max_label(self, row):
        return self.labels[self.ranked_indices[row][0]]

    def max_labels(self):
        return [self.max_label(r) for r in range(len(self.ranked_indices))]

    def probability_for_label(self, row, label):
        return float(self.probabilities[row, self.labels.index(label)])


class BinaryClassificationResult:
    """Thresholded binary outcome (reference:
    BinaryClassificationResult.java)."""

    def __init__(self, probability, threshold=0.5):
        self.probability = float(probability)
        self.threshold = float(threshold)

    @property
    def is_positive(self):
        return self.probability >= self.threshold

    def __repr__(self):
        return (f"BinaryClassificationResult(p={self.probability:.4f}, "
                f"positive={self.is_positive})")
