"""Convex optimizers: line-search gradient descent, conjugate gradient, L-BFGS.

Reference analog: the Solver/ConvexOptimizer stack in
/root/reference/deeplearning4j-nn/src/main/java/org/deeplearning4j/optimize/ —
``Solver.java:43`` (builder), ``solvers/BaseOptimizer.java:171``
(gradientAndScore), ``solvers/ConjugateGradient.java`` (Polak-Ribiere CG,
after Bengio et al. ch.8 / Nocedal & Wright ch.5), ``solvers/LBFGS.java``
(two-loop recursion, memory m=4), ``solvers/BackTrackLineSearch.java``
(Armijo backtracking with interpolation, stepMax=100), and the step functions
in ``optimize/stepfunctions/`` (Default/Negative/Gradient variants).

TPU-native design: the reference mutates a flat native param buffer through
JNI one BLAS call at a time. Here the parameter pytree is raveled once into a
single flat vector (``jax.flatten_util.ravel_pytree``) — the moral equivalent
of the reference's flat param view — and the ENTIRE optimizer iteration
(value+grad, search direction, full backtracking line search, parameter step)
is one jitted XLA computation: the line search is a ``lax.while_loop``, so no
host round-trips happen inside an iteration. The host loop only checks
convergence between iterations.

These optimizers are full-batch/deterministic by construction (a line search
is meaningless on a stochastic objective) — matching the reference, where
CG/LBFGS were legacy whole-batch trainers while SGD was the workhorse
(StochasticGradientDescent.java:58; here the jitted train step in
multilayer.py / graph.py).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

# Termination defaults mirroring BaseOptimizer (scoreTolerance) and
# BackTrackLineSearch (ABS_TOLX / RELTOLX / stepMax / maxIterations).
DEFAULT_SCORE_TOLERANCE = 1e-5
DEFAULT_STEP_MAX = 100.0
DEFAULT_LS_ITERATIONS = 5
_ABS_TOLX = 1e-8
_RELTOLX = 1e-6
_ALF = 1e-4  # Armijo sufficient-decrease constant (c1)


# ---------------------------------------------------------------------------
# step functions (reference: optimize/stepfunctions/*.java)
# ---------------------------------------------------------------------------

def default_step(params, search_dir, step):
    """params + step*dir (reference DefaultStepFunction)."""
    return params + step * search_dir


def negative_default_step(params, search_dir, step):
    """params - step*dir (reference NegativeDefaultStepFunction)."""
    return params - step * search_dir


def gradient_step(params, search_dir, step):
    """params + dir, ignoring step size (reference GradientStepFunction)."""
    del step
    return params + search_dir


def negative_gradient_step(params, search_dir, step):
    del step
    return params - search_dir


STEP_FUNCTIONS = {
    "default": default_step,
    "negative_default": negative_default_step,
    "gradient": gradient_step,
    "negative_gradient": negative_gradient_step,
}

# Step functions are named against the reference's convention of applying
# them to the RAW gradient (negative_* descend). This optimizer's
# _direction() hooks return pre-negated DESCENT directions, so the function
# actually applied is the sign-mirrored one: the user-visible name keeps
# reference semantics while the math stays in descent form.
_MIRRORED_STEP_FUNCTIONS = {
    "default": negative_default_step,
    "negative_default": default_step,
    "gradient": negative_gradient_step,
    "negative_gradient": gradient_step,
}


# ---------------------------------------------------------------------------
# line search (reference: BackTrackLineSearch.java — NR-style lnsrch)
# ---------------------------------------------------------------------------

def backtrack_line_search(flat_loss, x, f0, g, direction, *,
                          max_iterations=DEFAULT_LS_ITERATIONS,
                          step_max=DEFAULT_STEP_MAX):
    """Armijo backtracking with quadratic/cubic interpolation.

    All-device: runs as a ``lax.while_loop``. Returns (step, f_new).
    Mirrors BackTrackLineSearch.optimize: scales oversized directions to
    stepMax (:195-197), interpolates a trial step, accepts on sufficient
    decrease, keeps the best step seen for the maxIterations exit (:244).
    """
    dnorm = jnp.linalg.norm(direction)
    scale = jnp.where(dnorm > step_max, step_max / jnp.maximum(dnorm, 1e-30), 1.0)
    direction = direction * scale
    slope = jnp.vdot(g, direction)

    # minimum meaningful step (reference: alamin from ABS_TOLX/RELTOLX)
    denom = jnp.maximum(jnp.max(jnp.abs(direction) /
                                jnp.maximum(jnp.abs(x), 1.0)), 1e-30)
    alamin = _ABS_TOLX / denom

    def cond(carry):
        it, alam, _alam2, _f2, done, _best_alam, _best_f = carry
        return jnp.logical_and(~done, it < max_iterations)

    def body(carry):
        it, alam, alam2, f2, _done, best_alam, best_f = carry
        f_new = flat_loss(x + alam * direction)
        better = f_new < best_f
        best_alam = jnp.where(better, alam, best_alam)
        best_f = jnp.where(better, f_new, best_f)
        # sufficient decrease (Armijo) or step underflow
        accept = jnp.logical_or(f_new <= f0 + _ALF * alam * slope,
                                alam < alamin)
        # interpolate next trial step
        first = it == 0
        tmp_quad = -slope / (2.0 * (f_new - f0 - slope))
        rhs1 = f_new - f0 - alam * slope
        rhs2 = f2 - f0 - alam2 * slope
        da = alam - alam2
        a = (rhs1 / (alam * alam) - rhs2 / (alam2 * alam2)) / jnp.where(da == 0, 1e-30, da)
        b = (-alam2 * rhs1 / (alam * alam) + alam * rhs2 / (alam2 * alam2)) / jnp.where(da == 0, 1e-30, da)
        disc = jnp.maximum(b * b - 3.0 * a * slope, 0.0)
        tmp_cubic = jnp.where(jnp.abs(a) < 1e-30,
                              -slope / (2.0 * jnp.where(b == 0, 1e-30, b)),
                              (-b + jnp.sqrt(disc)) / (3.0 * jnp.where(a == 0, 1e-30, a)))
        tmp = jnp.where(first, tmp_quad, tmp_cubic)
        tmp = jnp.clip(tmp, 0.1 * alam, 0.5 * alam)  # NR bounds
        tmp = jnp.where(jnp.isfinite(tmp), tmp, 0.5 * alam)
        return (it + 1, jnp.where(accept, alam, tmp), alam, f_new,
                accept, best_alam, best_f)

    big = jnp.asarray(jnp.inf, f0.dtype)
    init = (jnp.asarray(0, jnp.int32), jnp.asarray(1.0, f0.dtype),
            jnp.asarray(1.0, f0.dtype), f0, jnp.asarray(False), jnp.asarray(0.0, f0.dtype), big)
    _, alam, _, f_last, done, best_alam, best_f = jax.lax.while_loop(cond, body, init)
    # on maxIterations exit use best step seen (reference :350-360); if the
    # search never improved on f0, take a zero step.
    step = jnp.where(done, alam, best_alam)
    f_out = jnp.where(done, f_last, jnp.where(jnp.isfinite(best_f), best_f, f0))
    improved = f_out <= f0
    # returned step is relative to the CALLER's (unscaled) direction
    return jnp.where(improved, step * scale, 0.0), jnp.where(improved, f_out, f0)


# ---------------------------------------------------------------------------
# optimizers
# ---------------------------------------------------------------------------

class BaseConvexOptimizer:
    """Shared driver: host loop over a jitted (direction, line-search, step)
    iteration, terminating on score tolerance (BaseOptimizer semantics)."""

    def __init__(self, loss_fn, *, max_iterations=100,
                 tolerance=DEFAULT_SCORE_TOLERANCE,
                 line_search_iterations=DEFAULT_LS_ITERATIONS,
                 step_max=DEFAULT_STEP_MAX, step_function="negative_default"):
        self.loss_fn = loss_fn
        self.max_iterations = max_iterations
        self.tolerance = tolerance
        self.ls_iterations = line_search_iterations
        self.step_max = step_max
        if step_function not in STEP_FUNCTIONS:
            raise ValueError(f"Unknown step_function {step_function!r}; "
                             f"choose from {sorted(STEP_FUNCTIONS)}")
        # name follows reference raw-gradient semantics; the function applied
        # to the pre-negated descent direction is the sign-mirrored one
        self.step_function_name = step_function
        self._apply_step = _MIRRORED_STEP_FUNCTIONS[step_function]

    # subclass hooks ---------------------------------------------------
    def _init_aux(self, n, dtype):
        return ()

    def _direction(self, g, aux):
        """Return (descent_direction, new_aux). direction is the DESCENT step
        (already negated), applied as x + step*direction."""
        raise NotImplementedError

    def _post_step(self, x_new, x_old, g_new, g_old, aux):
        return aux

    # driver -----------------------------------------------------------
    def optimize(self, params, *args):
        """Minimize loss_fn(params, *args). Returns (params, final_score,
        iterations_run)."""
        flat0, unravel = ravel_pytree(params)

        @jax.jit
        def flat_loss(x):
            return self.loss_fn(unravel(x), *args)

        vg = jax.jit(jax.value_and_grad(flat_loss))

        @jax.jit
        def iteration(x, g, f0, aux):
            direction, aux = self._direction(g, aux)
            step, f_new = backtrack_line_search(
                flat_loss, x, f0, g, direction,
                max_iterations=self.ls_iterations, step_max=self.step_max)
            x_new = self._apply_step(x, direction, step)
            return x_new, f_new, aux

        x = flat0
        f, g = vg(x)
        aux = self._init_aux(x.shape[0], x.dtype)
        prev = float(f)
        it = 0
        for it in range(1, self.max_iterations + 1):
            x_new, f_new, aux = iteration(x, g, f, aux)
            f2, g_new = vg(x_new)
            aux = self._post_step(x_new, x, g_new, g, aux)
            x, g, f = x_new, g_new, f2
            score = float(f)
            if abs(prev - score) < self.tolerance:
                break
            prev = score
        return unravel(x), float(f), it


class LineGradientDescent(BaseConvexOptimizer):
    """Steepest descent + line search (reference LineGradientDescent.java)."""

    def _direction(self, g, aux):
        return -g, aux


class ConjugateGradient(BaseConvexOptimizer):
    """Polak-Ribiere nonlinear CG with automatic restart on non-descent
    (reference ConjugateGradient.java preProcessLine/postStep: beta = max(0,
    g_new.(g_new-g_old)/g_old.g_old), searchDir = -g + beta*dirPrev)."""

    def _init_aux(self, n, dtype):
        return (jnp.zeros(n, dtype), jnp.zeros(n, dtype))  # (g_prev, dir_prev)

    def _direction(self, g, aux):
        g_prev, dir_prev = aux
        gg_prev = jnp.vdot(g_prev, g_prev)
        beta = jnp.where(gg_prev > 0,
                         jnp.maximum(jnp.vdot(g, g - g_prev) / jnp.maximum(gg_prev, 1e-30), 0.0),
                         0.0)
        direction = -g + beta * dir_prev
        # restart on non-descent direction
        direction = jnp.where(jnp.vdot(direction, g) < 0, direction, -g)
        return direction, (g_prev, direction)

    def _post_step(self, x_new, x_old, g_new, g_old, aux):
        _, dir_prev = aux
        return (g_old, dir_prev)


class LBFGS(BaseConvexOptimizer):
    """Limited-memory BFGS, two-loop recursion, memory m (reference
    LBFGS.java, m=4 at :41). History kept as fixed-shape device rings so the
    iteration stays a single compiled computation."""

    def __init__(self, loss_fn, m=4, **kw):
        super().__init__(loss_fn, **kw)
        self.m = m

    def _init_aux(self, n, dtype):
        m = self.m
        return (jnp.zeros((m, n), dtype),   # s ring
                jnp.zeros((m, n), dtype),   # y ring
                jnp.zeros((m,), dtype),     # rho ring
                jnp.asarray(0, jnp.int32))  # count
    def _direction(self, g, aux):
        s, y, rho, count = aux
        m = self.m

        def two_loop(q):
            alphas = jnp.zeros((m,), q.dtype)
            # newest-to-oldest: ring index (count-1-i) mod m, valid for i<count
            def bwd(i, carry):
                q, alphas = carry
                idx = jnp.mod(count - 1 - i, m)
                valid = i < jnp.minimum(count, m)
                alpha = jnp.where(valid, rho[idx] * jnp.vdot(s[idx], q), 0.0)
                q = q - jnp.where(valid, alpha, 0.0) * y[idx]
                return q, alphas.at[idx].set(alpha)
            q, alphas = jax.lax.fori_loop(0, m, bwd, (q, alphas))
            # initial Hessian scaling gamma = s.y / y.y of newest pair
            newest = jnp.mod(count - 1, m)
            yy = jnp.vdot(y[newest], y[newest])
            gamma = jnp.where(jnp.logical_and(count > 0, yy > 0),
                              jnp.vdot(s[newest], y[newest]) / jnp.maximum(yy, 1e-30), 1.0)
            r = gamma * q
            def fwd(i, r):
                j = jnp.minimum(count, m) - 1 - i  # oldest-to-newest
                idx = jnp.mod(count - 1 - j, m)
                valid = j >= 0
                beta = jnp.where(valid, rho[idx] * jnp.vdot(y[idx], r), 0.0)
                return r + jnp.where(valid, alphas[idx] - beta, 0.0) * s[idx]
            return jax.lax.fori_loop(0, m, fwd, r)

        direction = -two_loop(g)
        direction = jnp.where(jnp.vdot(direction, g) < 0, direction, -g)
        return direction, aux

    def _post_step(self, x_new, x_old, g_new, g_old, aux):
        s_ring, y_ring, rho, count = aux
        s = x_new - x_old
        y = g_new - g_old
        sy = jnp.vdot(s, y)
        idx = jnp.mod(count, self.m)
        ok = sy > 1e-10  # curvature condition; skip update otherwise
        s_ring = jnp.where(ok, s_ring.at[idx].set(s), s_ring)
        y_ring = jnp.where(ok, y_ring.at[idx].set(y), y_ring)
        rho = jnp.where(ok, rho.at[idx].set(1.0 / jnp.maximum(sy, 1e-30)), rho)
        count = jnp.where(ok, count + 1, count)
        return (s_ring, y_ring, rho, count)


ALGORITHMS = {
    "line_gradient_descent": LineGradientDescent,
    "conjugate_gradient": ConjugateGradient,
    "lbfgs": LBFGS,
}


class Solver:
    """Facade wiring a network to a convex optimizer (reference
    optimize/Solver.java:43 builder). ``optimize`` runs full-batch training of
    the network's loss and writes the result back into the network."""

    def __init__(self, net, algorithm="lbfgs", **kw):
        if algorithm == "stochastic_gradient_descent":
            raise ValueError("SGD is the network's jitted train step "
                             "(make_train_step); Solver hosts the full-batch "
                             "legacy algorithms: " + ", ".join(ALGORITHMS))
        self.net = net
        self.algorithm = algorithm
        self.kw = kw

    def optimize(self, x, y, mask=None):
        net = self.net
        if net.params is None:
            net.init()
        state = net.state

        def loss_fn(params, x, y):
            kw = {}
            if mask is not None:
                kw["mask"] = mask
            loss, _ = net.loss_fn(params, state, x, y, train=True,
                                  rng=jax.random.PRNGKey(0), **kw)
            return loss

        opt = ALGORITHMS[self.algorithm](loss_fn, **self.kw)
        params, score, iters = opt.optimize(net.params, x, y)
        net.params = params
        net.iteration += iters
        return score
