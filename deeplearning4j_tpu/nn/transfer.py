"""Transfer learning.

Reference analog: nn/transferlearning/ in /root/reference/deeplearning4j-nn —
TransferLearning.java (847 LoC: Builder rebuilding a trained net with frozen
layers / replaced outputs), FineTuneConfiguration.java (global overrides),
TransferLearningHelper.java (featurization: split at frozen boundary).

TPU-native: "freezing" is functional — frozen layers' gradients are zeroed via
stop_gradient in the train step (no FrozenLayer wrapper class mutating state);
the featurize path jit-compiles the frozen prefix once and caches activations.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


@dataclasses.dataclass
class FineTuneConfiguration:
    """Overrides applied to every layer when fine-tuning (reference:
    FineTuneConfiguration.java)."""

    updater: object = None
    l1: float = None
    l2: float = None
    dropout: float = None
    seed: int = None

    def apply_to(self, conf: MultiLayerConfiguration) -> MultiLayerConfiguration:
        layer_updates = {}
        for f in ("l1", "l2", "dropout"):
            v = getattr(self, f)
            if v is not None:
                layer_updates[f] = v
        new_layers = tuple(
            dataclasses.replace(l, **{k: v for k, v in layer_updates.items()
                                      if hasattr(l, k)}) if layer_updates else l
            for l in conf.layers)
        kwargs = {"layers": new_layers}
        if self.updater is not None:
            kwargs["updater"] = self.updater
        if self.seed is not None:
            kwargs["seed"] = self.seed
        return dataclasses.replace(conf, **kwargs)


class TransferLearning:
    """Builder (reference: TransferLearning.Builder)."""

    def __init__(self, net: MultiLayerNetwork):
        assert net.params is not None, "source network must be initialized/trained"
        self._src = net
        self._freeze_until = -1  # layers [0, freeze_until] frozen
        self._fine_tune = None
        self._removed_from = None
        self._appended = []
        self._replaced = {}

    def fine_tune_configuration(self, ftc: FineTuneConfiguration):
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, layer_idx):
        """Freeze layers 0..layer_idx inclusive."""
        self._freeze_until = layer_idx
        return self

    def remove_output_layer(self):
        self._removed_from = len(self._src.conf.layers) - 1
        return self

    def remove_layers_from(self, layer_idx):
        self._removed_from = layer_idx
        return self

    def replace_layer(self, idx, new_layer):
        self._replaced[idx] = new_layer
        return self

    def add_layer(self, layer):
        self._appended.append(layer)
        return self

    def build(self) -> MultiLayerNetwork:
        src_conf = self._src.conf
        keep = len(src_conf.layers) if self._removed_from is None else self._removed_from
        layers = [self._replaced.get(i, l) for i, l in enumerate(src_conf.layers[:keep])]
        layers += self._appended
        conf = dataclasses.replace(src_conf, layers=tuple(layers))
        if self._fine_tune is not None:
            conf = self._fine_tune.apply_to(conf)
        net = MultiLayerNetwork(conf)
        net.frozen_layers = tuple(range(self._freeze_until + 1))
        net.init()
        # copy weights for kept, non-replaced layers (real copies: the new
        # net's train step donates its buffers, which must not invalidate
        # the source network's arrays)
        for i in range(keep):
            if i not in self._replaced:
                net.params[i] = jax.tree_util.tree_map(jnp.copy, self._src.params[i])
                net.state[i] = jax.tree_util.tree_map(jnp.copy, self._src.state[i])
        net.opt_state = conf.updater.init(net.params)
        _install_freeze(net)
        return net


def _install_freeze(net):
    """Wrap the network's train step so frozen layers receive zero updates
    (reference: FrozenLayer.java semantics — no backprop into frozen params)."""
    frozen = set(getattr(net, "frozen_layers", ()))
    if not frozen:
        return
    orig_make = net.make_train_step

    def make_train_step(donate=True, jit=True):
        base = orig_make(donate=False, jit=False)

        def step(params, state, opt_state, x, y, it, rng, mask=None):
            new_params, new_state, new_opt, loss = base(params, state, opt_state,
                                                        x, y, it, rng, mask)
            # restore frozen params exactly (zero effective update)
            new_params = [params[i] if i in frozen else p
                          for i, p in enumerate(new_params)]
            return new_params, new_state, new_opt, loss

        if not jit:
            return step
        return jax.jit(step, donate_argnums=(0, 1, 2) if donate else ())

    net.make_train_step = make_train_step


class TransferLearningHelper:
    """Featurization at the frozen boundary (reference:
    TransferLearningHelper.java): run inputs through the frozen prefix once,
    then train only the unfrozen tail on cached features."""

    def __init__(self, net: MultiLayerNetwork, frozen_until: int):
        self.net = net
        self.frozen_until = frozen_until
        self._prefix = jax.jit(
            lambda p, s, x: net.apply_fn(p, s, x, train=False,
                                         layer_limit=frozen_until + 1)[0])

    def featurize(self, x):
        return self._prefix(self.net.params, self.net.state, jnp.asarray(x))

    def unfrozen_net(self):
        """A network over the unfrozen tail layers, sharing params."""
        conf = self.net.conf
        tail_layers = conf.layers[self.frozen_until + 1:]
        types, _ = conf.layer_input_types()
        tail_input = types[self.frozen_until + 1] if self.frozen_until + 1 < len(types) \
            else conf.input_type
        tail_conf = dataclasses.replace(conf, layers=tuple(tail_layers),
                                        input_type=tail_input)
        tail = MultiLayerNetwork(tail_conf)
        tail.params = [jax.tree_util.tree_map(jnp.copy, p)
                       for p in self.net.params[self.frozen_until + 1:]]
        tail.state = [jax.tree_util.tree_map(jnp.copy, s)
                      for s in self.net.state[self.frozen_until + 1:]]
        tail.opt_state = tail_conf.updater.init(tail.params)
        return tail
