"""Transfer learning.

Reference analog: nn/transferlearning/ in /root/reference/deeplearning4j-nn —
TransferLearning.java (847 LoC: Builder rebuilding a trained net with frozen
layers / replaced outputs), FineTuneConfiguration.java (global overrides),
TransferLearningHelper.java (featurization: split at frozen boundary).

TPU-native: "freezing" is functional — frozen layers' gradients are zeroed via
stop_gradient in the train step (no FrozenLayer wrapper class mutating state);
the featurize path jit-compiles the frozen prefix once and caches activations.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


@dataclasses.dataclass
class FineTuneConfiguration:
    """Overrides applied to every layer when fine-tuning (reference:
    FineTuneConfiguration.java)."""

    updater: object = None
    l1: float = None
    l2: float = None
    dropout: float = None
    seed: int = None

    def apply_to(self, conf: MultiLayerConfiguration) -> MultiLayerConfiguration:
        layer_updates = {}
        for f in ("l1", "l2", "dropout"):
            v = getattr(self, f)
            if v is not None:
                layer_updates[f] = v
        new_layers = tuple(
            dataclasses.replace(l, **{k: v for k, v in layer_updates.items()
                                      if hasattr(l, k)}) if layer_updates else l
            for l in conf.layers)
        kwargs = {"layers": new_layers}
        if self.updater is not None:
            kwargs["updater"] = self.updater
        if self.seed is not None:
            kwargs["seed"] = self.seed
        return dataclasses.replace(conf, **kwargs)


class TransferLearning:
    """Builder (reference: TransferLearning.Builder)."""

    def __init__(self, net: MultiLayerNetwork):
        assert net.params is not None, "source network must be initialized/trained"
        self._src = net
        self._freeze_until = -1  # layers [0, freeze_until] frozen
        self._fine_tune = None
        self._removed_from = None
        self._appended = []
        self._replaced = {}

    def fine_tune_configuration(self, ftc: FineTuneConfiguration):
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, layer_idx):
        """Freeze layers 0..layer_idx inclusive."""
        self._freeze_until = layer_idx
        return self

    def remove_output_layer(self):
        self._removed_from = len(self._src.conf.layers) - 1
        return self

    def remove_layers_from(self, layer_idx):
        self._removed_from = layer_idx
        return self

    def replace_layer(self, idx, new_layer):
        self._replaced[idx] = new_layer
        return self

    def add_layer(self, layer):
        self._appended.append(layer)
        return self

    def build(self) -> MultiLayerNetwork:
        src_conf = self._src.conf
        keep = len(src_conf.layers) if self._removed_from is None else self._removed_from
        layers = [self._replaced.get(i, l) for i, l in enumerate(src_conf.layers[:keep])]
        layers += self._appended
        conf = dataclasses.replace(src_conf, layers=tuple(layers))
        if self._fine_tune is not None:
            conf = self._fine_tune.apply_to(conf)
        net = MultiLayerNetwork(conf)
        net.frozen_layers = tuple(range(self._freeze_until + 1))
        net.init()
        # copy weights for kept, non-replaced layers (real copies: the new
        # net's train step donates its buffers, which must not invalidate
        # the source network's arrays)
        for i in range(keep):
            if i not in self._replaced:
                net.params[i] = jax.tree_util.tree_map(jnp.copy, self._src.params[i])
                net.state[i] = jax.tree_util.tree_map(jnp.copy, self._src.state[i])
        net.opt_state = conf.updater.init(net.params)
        _install_freeze(net)
        return net


def _install_freeze(net):
    """Wrap the network's train step so frozen layers receive zero updates
    (reference: FrozenLayer.java semantics — no backprop into frozen params)."""
    frozen = set(getattr(net, "frozen_layers", ()))
    if not frozen:
        return
    orig_make = net.make_train_step

    def make_train_step(donate=True, jit=True, with_health=False):
        base = orig_make(donate=False, jit=False, with_health=with_health)

        def step(params, state, opt_state, x, y, it, rng, mask=None):
            out = base(params, state, opt_state, x, y, it, rng, mask)
            new_params, new_state, new_opt, loss = out[:4]
            # restore frozen params exactly (zero effective update)
            new_params = [params[i] if i in frozen else p
                          for i, p in enumerate(new_params)]
            # out[4:] carries the watchdog health bundle when requested
            return (new_params, new_state, new_opt, loss) + tuple(out[4:])

        if not jit:
            return step
        return jax.jit(step, donate_argnums=(0, 1, 2) if donate else ())

    net.make_train_step = make_train_step
    net._train_step_health = None  # pre-freeze compiled variant is stale


class TransferLearningHelper:
    """Featurization at the frozen boundary (reference:
    TransferLearningHelper.java): run inputs through the frozen prefix once,
    then train only the unfrozen tail on cached features."""

    def __init__(self, net: MultiLayerNetwork, frozen_until: int):
        self.net = net
        self.frozen_until = frozen_until
        self._prefix = jax.jit(  # graftlint: disable=R3 -- built ONCE per helper in __init__ and cached on self; one helper = one featurizer compile
            lambda p, s, x: net.apply_fn(p, s, x, train=False,
                                         layer_limit=frozen_until + 1)[0])

    def featurize(self, x):
        return self._prefix(self.net.params, self.net.state, jnp.asarray(x))

    def unfrozen_net(self):
        """A network over the unfrozen tail layers, sharing params."""
        conf = self.net.conf
        tail_layers = conf.layers[self.frozen_until + 1:]
        types, _ = conf.layer_input_types()
        tail_input = types[self.frozen_until + 1] if self.frozen_until + 1 < len(types) \
            else conf.input_type
        tail_conf = dataclasses.replace(conf, layers=tuple(tail_layers),
                                        input_type=tail_input)
        tail = MultiLayerNetwork(tail_conf)
        tail.params = [jax.tree_util.tree_map(jnp.copy, p)
                       for p in self.net.params[self.frozen_until + 1:]]
        tail.state = [jax.tree_util.tree_map(jnp.copy, s)
                      for s in self.net.state[self.frozen_until + 1:]]
        tail.opt_state = tail_conf.updater.init(tail.params)
        return tail


class TransferLearningGraph:
    """Transfer learning for ComputationGraph (reference:
    TransferLearning.GraphBuilder — the path zoo users take to fine-tune a
    pretrained DAG model: freeze a feature-extractor prefix, replace the
    head, optionally extend the graph).

    Freezing is by vertex NAME; ``set_feature_extractor(v)`` freezes ``v``
    and every vertex topologically before it, matching the reference's
    "frozen up to and including" semantics.
    """

    def __init__(self, cg):
        assert cg.params is not None, "source graph must be initialized/trained"
        from deeplearning4j_tpu.nn.graph import ComputationGraph  # cycle-free
        self._cg_cls = ComputationGraph
        self._src = cg
        self._fine_tune = None
        self._frozen = set()
        self._replaced = {}
        self._added = []       # (name, layer, inputs)
        self._outputs = None

    def fine_tune_configuration(self, ftc: FineTuneConfiguration):
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, vertex_name):
        order = self._src._order
        assert vertex_name in order, f"unknown vertex {vertex_name!r}"
        upto = order.index(vertex_name)
        self._frozen = {n for n in order[:upto + 1]
                        if n not in self._src.conf.inputs}
        return self

    def replace_layer(self, name, new_layer):
        """Swap a LayerVertex's layer (reference: nOutReplace / removeVertex
        + addLayer); its params re-initialize in build()."""
        self._replaced[name] = new_layer
        return self

    def add_layer(self, name, layer, *inputs):
        self._added.append((name, layer, tuple(inputs)))
        return self

    def set_outputs(self, *names):
        self._outputs = tuple(names)
        return self

    def build(self):
        from deeplearning4j_tpu.nn.graph import LayerVertex, VertexDef
        conf = self._src.conf
        vertices = []
        for v in conf.vertices:
            if v.name in self._replaced:
                vertices.append(VertexDef(
                    v.name, LayerVertex(layer=self._replaced[v.name]),
                    v.inputs))
            else:
                vertices.append(v)
        for name, layer, inputs in self._added:
            vertices.append(VertexDef(name, LayerVertex(layer=layer), inputs))
        bad = (set(self._replaced) | {n for n, _, _ in self._added}) \
            & self._frozen
        if bad:
            raise ValueError(
                f"vertices {sorted(bad)} are both frozen and replaced/added —"
                " a replaced layer inside the frozen prefix would train-freeze"
                " at its random initialization")
        if self._fine_tune is not None:
            ft = self._fine_tune
            overrides = {f: getattr(ft, f) for f in ("l1", "l2", "dropout")
                         if getattr(ft, f) is not None}
            if overrides:
                from deeplearning4j_tpu.nn.graph import LayerVertex, VertexDef
                vertices = [
                    VertexDef(v.name, LayerVertex(layer=dataclasses.replace(
                        v.vertex.layer,
                        **{k: val for k, val in overrides.items()
                           if hasattr(v.vertex.layer, k)})), v.inputs)
                    if isinstance(v.vertex, LayerVertex) else v
                    for v in vertices]
        kwargs = {"vertices": tuple(vertices)}
        if self._outputs is not None:
            kwargs["outputs"] = self._outputs
        new_conf = dataclasses.replace(conf, **kwargs)
        if self._fine_tune is not None:
            if ft.updater is not None:
                new_conf = dataclasses.replace(new_conf, updater=ft.updater)
            if ft.seed is not None:
                new_conf = dataclasses.replace(new_conf, seed=ft.seed)
        net = self._cg_cls(new_conf)
        net.frozen_vertices = set(self._frozen)
        net.init()
        added = {n for n, _, _ in self._added}

        def shapes_match(a, b):
            try:
                return jax.tree_util.tree_all(jax.tree_util.tree_map(
                    lambda x, y: x.shape == y.shape, a, b))
            except ValueError:  # differing tree structure
                return False

        for name in net.params:
            if name in self._src.params and name not in self._replaced \
                    and name not in added:
                # skip on shape mismatch (a vertex downstream of a replaced
                # layer whose width changed keeps its fresh init — copying
                # the stale source weights would fail inside jit later)
                if not shapes_match(net.params[name], self._src.params[name]):
                    continue
                net.params[name] = jax.tree_util.tree_map(
                    jnp.copy, self._src.params[name])
                net.state[name] = jax.tree_util.tree_map(
                    jnp.copy, self._src.state[name])
        net.opt_state = new_conf.updater.init(net.params)
        _install_freeze_graph(net)
        return net


def _install_freeze_graph(net):
    """Graph twin of _install_freeze: frozen vertices get their params
    restored after each update (zero effective update, FrozenLayer.java
    semantics)."""
    frozen = set(getattr(net, "frozen_vertices", ()))
    if not frozen:
        return
    orig_make = net.make_train_step

    def make_train_step(donate=True, jit=True, with_health=False):
        base = orig_make(donate=False, jit=False, with_health=with_health)

        def step(params, state, opt_state, x, y, it, rng, mask=None):
            out = base(params, state, opt_state, x, y, it, rng, mask)
            new_params, new_state, new_opt, loss = out[:4]
            new_params = {name: (params[name] if name in frozen else p)
                          for name, p in new_params.items()}
            # out[4:] carries the watchdog health bundle when requested
            return (new_params, new_state, new_opt, loss) + tuple(out[4:])

        if not jit:
            return step
        return jax.jit(step, donate_argnums=(0, 1, 2) if donate else ())

    net.make_train_step = make_train_step
    net._train_step = None
    net._train_step_health = None
