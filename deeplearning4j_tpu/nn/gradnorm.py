"""Gradient normalization / clipping.

Reference analog: ``GradientNormalization`` enum applied in
BaseUpdater.updateGradientAccordingToParams (/root/reference/deeplearning4j-nn/
.../nn/updater/BaseMultiLayerUpdater.java; modes defined in
nn/conf/GradientNormalization.java): RenormalizeL2PerLayer,
RenormalizeL2PerParamType, ClipElementWiseAbsoluteValue, ClipL2PerLayer,
ClipL2PerParamType. "Layer" here = one layer's params dict; "ParamType" = one
named param array.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _tree_l2(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(l * l) for l in leaves) + 1e-32)


def normalize_layer_grads(mode, layer_grads, threshold=1.0):
    """Apply normalization to one layer's gradient dict."""
    if mode in (None, "none"):
        return layer_grads
    if mode == "renormalize_l2_per_layer":
        norm = _tree_l2(layer_grads)
        return jax.tree_util.tree_map(lambda g: g / norm, layer_grads)
    if mode == "renormalize_l2_per_param_type":
        return {k: v / jnp.sqrt(jnp.sum(v * v) + 1e-32) for k, v in layer_grads.items()}
    if mode == "clip_elementwise_absolute_value":
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, -threshold, threshold), layer_grads)
    if mode == "clip_l2_per_layer":
        norm = _tree_l2(layer_grads)
        scale = jnp.minimum(1.0, threshold / norm)
        return jax.tree_util.tree_map(lambda g: g * scale, layer_grads)
    if mode == "clip_l2_per_param_type":
        out = {}
        for k, v in layer_grads.items():
            norm = jnp.sqrt(jnp.sum(v * v) + 1e-32)
            out[k] = v * jnp.minimum(1.0, threshold / norm)
        return out
    raise ValueError(f"Unknown gradient normalization mode {mode!r}")


def normalize_grads(mode, grads, threshold=1.0):
    """Apply per-layer normalization across a list-of-dicts gradient pytree."""
    if mode in (None, "none"):
        return grads
    return [normalize_layer_grads(mode, g, threshold) if g else g for g in grads]
