"""Updaters (optimizers) + learning-rate schedules.

Reference analog: the ND4J ``GradientUpdater`` implementations dispatched via
dl4j's Updater enum (/root/reference/deeplearning4j-nn/.../nn/conf/
Updater.java:12 — SGD, ADAM, ADAMAX, ADADELTA, NESTEROVS, NADAM, ADAGRAD,
RMSPROP, NONE) and the view-based state management in
nn/updater/BaseMultiLayerUpdater.java. TPU-native design: optimizer state is a
pytree mirroring the params pytree; the update is a pure function folded into
the jitted train step so XLA fuses the elementwise math into one pass over
HBM. State averaging across replicas (ParallelWrapper.java:338-370) collapses
to replicated state under per-step psum data-parallelism.

Each updater config is a frozen dataclass with:
  init(params)  -> opt_state pytree
  update(grads, opt_state, params, step) -> (updates, new_opt_state)
where ``updates`` are deltas to ADD to params (sign convention: update already
includes the negative learning rate, like optax).
"""

from __future__ import annotations

import dataclasses
import typing

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.utils.serde import register_config

# --------------------------------------------------------------------------
# Learning-rate schedules (reference: org.nd4j.linalg.schedule ISchedule —
# Exponential, Inverse, Poly, Sigmoid, Step, Map; dl4j LearningRatePolicy)
# --------------------------------------------------------------------------


@register_config
@dataclasses.dataclass(frozen=True)
class FixedSchedule:
    value: float = 0.1

    def __call__(self, step):
        return jnp.asarray(self.value)


@register_config
@dataclasses.dataclass(frozen=True)
class ExponentialSchedule:
    initial: float = 0.1
    gamma: float = 0.99

    def __call__(self, step):
        return self.initial * self.gamma ** jnp.asarray(step, jnp.float32)


@register_config
@dataclasses.dataclass(frozen=True)
class InverseSchedule:
    initial: float = 0.1
    gamma: float = 0.99
    power: float = 1.0

    def __call__(self, step):
        return self.initial / (1.0 + self.gamma * jnp.asarray(step, jnp.float32)) ** self.power


@register_config
@dataclasses.dataclass(frozen=True)
class PolySchedule:
    initial: float = 0.1
    power: float = 1.0
    max_iter: int = 10000

    def __call__(self, step):
        frac = jnp.clip(jnp.asarray(step, jnp.float32) / self.max_iter, 0.0, 1.0)
        return self.initial * (1.0 - frac) ** self.power


@register_config
@dataclasses.dataclass(frozen=True)
class SigmoidSchedule:
    initial: float = 0.1
    gamma: float = 0.99
    step_size: int = 100

    def __call__(self, step):
        s = jnp.asarray(step, jnp.float32)
        return self.initial / (1.0 + jnp.exp(-self.gamma * (s - self.step_size)))


@register_config
@dataclasses.dataclass(frozen=True)
class StepSchedule:
    initial: float = 0.1
    decay_rate: float = 0.5
    step_size: int = 1000

    def __call__(self, step):
        s = jnp.asarray(step, jnp.float32)
        return self.initial * self.decay_rate ** jnp.floor(s / self.step_size)


@register_config
@dataclasses.dataclass(frozen=True)
class WarmupCosineSchedule:
    """TPU-era addition: linear warmup + cosine decay (not in reference)."""

    peak: float = 1e-3
    warmup_steps: int = 100
    total_steps: int = 10000
    floor: float = 0.0

    def __call__(self, step):
        s = jnp.asarray(step, jnp.float32)
        warm = self.peak * s / jnp.maximum(self.warmup_steps, 1)
        frac = jnp.clip((s - self.warmup_steps) / jnp.maximum(self.total_steps - self.warmup_steps, 1), 0.0, 1.0)
        cos = self.floor + 0.5 * (self.peak - self.floor) * (1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(s < self.warmup_steps, warm, cos)


def resolve_lr(lr, step):
    if callable(lr):
        return lr(step)
    return jnp.asarray(lr)


# --------------------------------------------------------------------------
# Updaters
# --------------------------------------------------------------------------

Schedule = typing.Union[float, FixedSchedule, ExponentialSchedule, InverseSchedule,
                        PolySchedule, SigmoidSchedule, StepSchedule, WarmupCosineSchedule]


def _zeros_like_tree(params):
    return jax.tree_util.tree_map(jnp.zeros_like, params)


@register_config
@dataclasses.dataclass(frozen=True)
class Sgd:
    learning_rate: Schedule = 0.1

    def init(self, params):
        return ()

    def update(self, grads, state, params, step):
        lr = resolve_lr(self.learning_rate, step)
        return jax.tree_util.tree_map(lambda g: -lr * g, grads), state


@register_config
@dataclasses.dataclass(frozen=True)
class Nesterovs:
    learning_rate: Schedule = 0.1
    momentum: float = 0.9

    def init(self, params):
        return _zeros_like_tree(params)

    def update(self, grads, state, params, step):
        lr = resolve_lr(self.learning_rate, step)
        mu = self.momentum
        new_v = jax.tree_util.tree_map(lambda v, g: mu * v - lr * g, state, grads)
        # Nesterov look-ahead: update = mu*v_new - lr*g (ND4J NesterovsUpdater semantics)
        updates = jax.tree_util.tree_map(lambda v, g: mu * v - lr * g, new_v, grads)
        return updates, new_v


@register_config
@dataclasses.dataclass(frozen=True)
class Adam:
    learning_rate: Schedule = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init(self, params):
        return {"m": _zeros_like_tree(params), "v": _zeros_like_tree(params)}

    def update(self, grads, state, params, step):
        lr = resolve_lr(self.learning_rate, step)
        t = jnp.asarray(step, jnp.float32) + 1.0
        b1, b2 = self.beta1, self.beta2
        m = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        bc = jnp.sqrt(1 - b2**t) / (1 - b1**t)
        updates = jax.tree_util.tree_map(lambda m, v: -lr * bc * m / (jnp.sqrt(v) + self.epsilon), m, v)
        return updates, {"m": m, "v": v}


@register_config
@dataclasses.dataclass(frozen=True)
class AdaMax:
    learning_rate: Schedule = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init(self, params):
        return {"m": _zeros_like_tree(params), "u": _zeros_like_tree(params)}

    def update(self, grads, state, params, step):
        lr = resolve_lr(self.learning_rate, step)
        t = jnp.asarray(step, jnp.float32) + 1.0
        b1, b2 = self.beta1, self.beta2
        m = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        u = jax.tree_util.tree_map(lambda u, g: jnp.maximum(b2 * u, jnp.abs(g)), state["u"], grads)
        scale = lr / (1 - b1**t)
        updates = jax.tree_util.tree_map(lambda m, u: -scale * m / (u + self.epsilon), m, u)
        return updates, {"m": m, "u": u}


@register_config
@dataclasses.dataclass(frozen=True)
class Nadam:
    learning_rate: Schedule = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init(self, params):
        return {"m": _zeros_like_tree(params), "v": _zeros_like_tree(params)}

    def update(self, grads, state, params, step):
        lr = resolve_lr(self.learning_rate, step)
        t = jnp.asarray(step, jnp.float32) + 1.0
        b1, b2 = self.beta1, self.beta2
        m = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        mhat = jax.tree_util.tree_map(
            lambda m, g: b1 * m / (1 - b1 ** (t + 1)) + (1 - b1) * g / (1 - b1**t), m, grads)
        vhat = jax.tree_util.tree_map(lambda v: v / (1 - b2**t), v)
        updates = jax.tree_util.tree_map(lambda mh, vh: -lr * mh / (jnp.sqrt(vh) + self.epsilon), mhat, vhat)
        return updates, {"m": m, "v": v}


@register_config
@dataclasses.dataclass(frozen=True)
class AdaGrad:
    learning_rate: Schedule = 0.1
    epsilon: float = 1e-6

    def init(self, params):
        return _zeros_like_tree(params)

    def update(self, grads, state, params, step):
        lr = resolve_lr(self.learning_rate, step)
        h = jax.tree_util.tree_map(lambda h, g: h + g * g, state, grads)
        updates = jax.tree_util.tree_map(lambda h, g: -lr * g / (jnp.sqrt(h) + self.epsilon), h, grads)
        return updates, h


@register_config
@dataclasses.dataclass(frozen=True)
class AdaDelta:
    rho: float = 0.95
    epsilon: float = 1e-6

    def init(self, params):
        return {"g2": _zeros_like_tree(params), "dx2": _zeros_like_tree(params)}

    def update(self, grads, state, params, step):
        rho, eps = self.rho, self.epsilon
        g2 = jax.tree_util.tree_map(lambda a, g: rho * a + (1 - rho) * g * g, state["g2"], grads)
        updates = jax.tree_util.tree_map(
            lambda g, a, d: -g * jnp.sqrt(d + eps) / jnp.sqrt(a + eps), grads, g2, state["dx2"])
        dx2 = jax.tree_util.tree_map(lambda d, u: rho * d + (1 - rho) * u * u, state["dx2"], updates)
        return updates, {"g2": g2, "dx2": dx2}


@register_config
@dataclasses.dataclass(frozen=True)
class RmsProp:
    learning_rate: Schedule = 1e-3
    decay: float = 0.95
    epsilon: float = 1e-8

    def init(self, params):
        return _zeros_like_tree(params)

    def update(self, grads, state, params, step):
        lr = resolve_lr(self.learning_rate, step)
        d = self.decay
        avg = jax.tree_util.tree_map(lambda a, g: d * a + (1 - d) * g * g, state, grads)
        updates = jax.tree_util.tree_map(lambda a, g: -lr * g / (jnp.sqrt(a) + self.epsilon), avg, grads)
        return updates, avg


@register_config
@dataclasses.dataclass(frozen=True)
class AmsGrad:
    learning_rate: Schedule = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init(self, params):
        z = _zeros_like_tree(params)
        return {"m": z, "v": _zeros_like_tree(params), "vhat": _zeros_like_tree(params)}

    def update(self, grads, state, params, step):
        lr = resolve_lr(self.learning_rate, step)
        b1, b2 = self.beta1, self.beta2
        m = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        vhat = jax.tree_util.tree_map(jnp.maximum, state["vhat"], v)
        updates = jax.tree_util.tree_map(lambda m, vh: -lr * m / (jnp.sqrt(vh) + self.epsilon), m, vhat)
        return updates, {"m": m, "v": v, "vhat": vhat}


@register_config
@dataclasses.dataclass(frozen=True)
class NoOp:
    def init(self, params):
        return ()

    def update(self, grads, state, params, step):
        return jax.tree_util.tree_map(jnp.zeros_like, grads), state


UPDATERS = {
    "sgd": Sgd, "adam": Adam, "adamax": AdaMax, "adadelta": AdaDelta,
    "nesterovs": Nesterovs, "nadam": Nadam, "adagrad": AdaGrad,
    "rmsprop": RmsProp, "amsgrad": AmsGrad, "none": NoOp,
}


def get(name, **kwargs):
    if not isinstance(name, str):
        return name
    cls = UPDATERS.get(name.lower())
    if cls is None:
        raise KeyError(f"Unknown updater {name!r}. Known: {sorted(UPDATERS)}")
    return cls(**kwargs)
