"""Weight initializer catalog.

Reference analog: ``WeightInit`` enum + ``WeightInitUtil``
(/root/reference/deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/
weights/WeightInit.java, WeightInitUtil.java). Each initializer is a function
``(key, shape, fan_in, fan_out, dtype) -> array``; the reference computes
fan_in/fan_out per layer family, and so do the layer configs here.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.utils.serde import register_config


def zero(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def normal(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # ND4J NORMAL: N(0, 1/sqrt(fan_in))
    return jax.random.normal(key, shape, dtype) / jnp.sqrt(jnp.asarray(fan_in, dtype))


def uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = (3.0 / fan_in) ** 0.5
    return jax.random.uniform(key, shape, dtype, -a, a)


def xavier(key, shape, fan_in, fan_out, dtype=jnp.float32):
    std = (2.0 / (fan_in + fan_out)) ** 0.5
    return std * jax.random.normal(key, shape, dtype)


def xavier_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(key, shape, dtype, -a, a)


def xavier_fan_in(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype) / jnp.sqrt(jnp.asarray(fan_in, dtype))


def relu_init(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # He normal: N(0, 2/fan_in)
    return (2.0 / fan_in) ** 0.5 * jax.random.normal(key, shape, dtype)


def relu_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = (6.0 / fan_in) ** 0.5
    return jax.random.uniform(key, shape, dtype, -a, a)


def lecun_normal(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return (1.0 / fan_in) ** 0.5 * jax.random.normal(key, shape, dtype)


def lecun_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = (3.0 / fan_in) ** 0.5
    return jax.random.uniform(key, shape, dtype, -a, a)


def sigmoid_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = 4.0 * (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(key, shape, dtype, -a, a)


def identity_init(key, shape, fan_in, fan_out, dtype=jnp.float32):
    if len(shape) == 2 and shape[0] == shape[1]:
        return jnp.eye(shape[0], dtype=dtype)
    raise ValueError(f"IDENTITY init requires a square 2-D shape, got {shape}")


def var_scaling_normal_fan_in(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return (1.0 / fan_in) ** 0.5 * jax.random.normal(key, shape, dtype)


def var_scaling_normal_fan_out(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return (1.0 / fan_out) ** 0.5 * jax.random.normal(key, shape, dtype)


def var_scaling_normal_fan_avg(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return (2.0 / (fan_in + fan_out)) ** 0.5 * jax.random.normal(key, shape, dtype)


def var_scaling_uniform_fan_in(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = (3.0 / fan_in) ** 0.5
    return jax.random.uniform(key, shape, dtype, -a, a)


def var_scaling_uniform_fan_out(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = (3.0 / fan_out) ** 0.5
    return jax.random.uniform(key, shape, dtype, -a, a)


def var_scaling_uniform_fan_avg(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(key, shape, dtype, -a, a)


_CATALOG = {
    "zero": zero,
    "ones": ones,
    "normal": normal,
    "uniform": uniform,
    "xavier": xavier,
    "xavier_uniform": xavier_uniform,
    "xavier_fan_in": xavier_fan_in,
    "relu": relu_init,
    "relu_uniform": relu_uniform,
    "lecun_normal": lecun_normal,
    "lecun_uniform": lecun_uniform,
    "sigmoid_uniform": sigmoid_uniform,
    "identity": identity_init,
    "var_scaling_normal_fan_in": var_scaling_normal_fan_in,
    "var_scaling_normal_fan_out": var_scaling_normal_fan_out,
    "var_scaling_normal_fan_avg": var_scaling_normal_fan_avg,
    "var_scaling_uniform_fan_in": var_scaling_uniform_fan_in,
    "var_scaling_uniform_fan_out": var_scaling_uniform_fan_out,
    "var_scaling_uniform_fan_avg": var_scaling_uniform_fan_avg,
}


@register_config
@dataclasses.dataclass(frozen=True)
class Distribution:
    """Explicit-distribution init (reference: WeightInit.DISTRIBUTION + dl4j
    nn/conf/distribution/)."""

    kind: str = "normal"  # normal | uniform | constant | truncated_normal | orthogonal
    mean: float = 0.0
    std: float = 1.0
    lower: float = -1.0
    upper: float = 1.0
    value: float = 0.0
    gain: float = 1.0

    def sample(self, key, shape, dtype=jnp.float32):
        if self.kind == "normal":
            return self.mean + self.std * jax.random.normal(key, shape, dtype)
        if self.kind == "uniform":
            return jax.random.uniform(key, shape, dtype, self.lower, self.upper)
        if self.kind == "constant":
            return jnp.full(shape, self.value, dtype)
        if self.kind == "truncated_normal":
            return self.mean + self.std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)
        if self.kind == "orthogonal":
            return self.gain * jax.nn.initializers.orthogonal()(key, shape, dtype)
        raise ValueError(f"Unknown distribution kind {self.kind!r}")


def init_weight(name_or_dist, key, shape, fan_in, fan_out, dtype=jnp.float32):
    """Initialize a weight tensor by catalog name or explicit Distribution."""
    if isinstance(name_or_dist, Distribution):
        return name_or_dist.sample(key, shape, dtype)
    fn = _CATALOG.get(str(name_or_dist).lower())
    if fn is None:
        raise KeyError(f"Unknown weight init {name_or_dist!r}. Known: {sorted(_CATALOG)}")
    return fn(key, shape, fan_in, fan_out, dtype)


def names():
    return sorted(_CATALOG)
