from deeplearning4j_tpu.nn import activations, initializers, losses, updaters  # noqa: F401
