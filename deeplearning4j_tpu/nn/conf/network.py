"""Network configuration DSL.

Reference analog: NeuralNetConfiguration.Builder -> MultiLayerConfiguration
(/root/reference/deeplearning4j-nn/.../nn/conf/NeuralNetConfiguration.java:569
Builder, :724 list(); MultiLayerConfiguration.java toJson:120/fromJson:138).

The TPU-native shape: configs are frozen dataclasses; ``NeuralNetConfig`` is
the builder carrying global defaults (activation, weight init, updater, l1/l2,
dropout, seed) that cascade into per-layer configs exactly like the
reference's Builder.list(...) flow — a layer field left at its class default
is overridden by the global default. JSON round-trip via the serde registry.
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn import updaters as _updaters
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.utils import serde

# fields that cascade from global defaults into layers when left unset
_CASCADE_FIELDS = ("activation", "weight_init", "bias_init", "l1", "l2",
                   "l1_bias", "l2_bias", "dropout", "constraints")


@serde.register_config
@dataclasses.dataclass(frozen=True)
class MultiLayerConfiguration:
    """Immutable, JSON-round-trippable sequential-network config."""

    layers: tuple = ()
    input_type: InputType | None = None
    updater: object = dataclasses.field(default_factory=_updaters.Sgd)
    gradient_normalization: str = "none"
    gradient_normalization_threshold: float = 1.0
    backprop_type: str = "standard"  # standard | tbptt
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    seed: int = 12345
    mini_batch: bool = True  # reference: miniBatch flag (score averaging)
    # remat each layer's forward during backprop: HBM for FLOPs (SURVEY §0
    # "jax.checkpoint / rematerialisation" bullet; no reference analog —
    # workspaces solved a different memory problem)
    gradient_checkpointing: bool = False

    def to_json(self, indent=2):
        return serde.to_json(self, indent=indent)

    @staticmethod
    def from_json(s):
        conf = serde.from_json(s)
        assert isinstance(conf, MultiLayerConfiguration)
        return conf

    def layer_input_types(self):
        """Shape inference along the stack (reference: preprocessor insertion logic
        in MultiLayerConfiguration.Builder — here conversions are implicit,
        see nn/conf/inputs.py adapt())."""
        from deeplearning4j_tpu.nn.conf import inputs as _inputs
        types = []
        cur = self.input_type
        if cur is None:
            raise ValueError("MultiLayerConfiguration requires input_type for shape inference")
        for layer in self.layers:
            fam = layer.input_family
            if fam is not None and not isinstance(cur, fam):
                cur = _inputs.adapted_type(cur, fam)
            types.append(cur)
            cur = layer.output_type(cur)
        return types, cur


@dataclasses.dataclass
class NeuralNetConfig:
    """Builder with cascading global defaults (reference:
    NeuralNetConfiguration.Builder, default updater Sgd at :580)."""

    seed: int = 12345
    activation: object = None
    weight_init: object = None
    bias_init: float = None
    l1: float = None
    l2: float = None
    dropout: float = None
    updater: object = dataclasses.field(default_factory=_updaters.Sgd)
    gradient_normalization: str = "none"
    gradient_normalization_threshold: float = 1.0

    def list(self, *layers, input_type=None, backprop_type="standard",
             tbptt_fwd_length=20, tbptt_back_length=20,
             gradient_checkpointing=False) -> MultiLayerConfiguration:
        cascaded = tuple(self._cascade(l) for l in layers)
        return MultiLayerConfiguration(
            layers=cascaded, input_type=input_type,
            updater=self.updater if not isinstance(self.updater, str) else _updaters.get(self.updater),
            gradient_normalization=self.gradient_normalization,
            gradient_normalization_threshold=self.gradient_normalization_threshold,
            backprop_type=backprop_type, tbptt_fwd_length=tbptt_fwd_length,
            tbptt_back_length=tbptt_back_length, seed=self.seed,
            gradient_checkpointing=gradient_checkpointing,
        )

    def _cascade(self, layer):
        updates = {}
        fields = {f.name: f for f in dataclasses.fields(layer)}
        for name in _CASCADE_FIELDS:
            global_val = getattr(self, name, None)
            if global_val is None or name not in fields:
                continue
            f = fields[name]
            default = f.default if f.default is not dataclasses.MISSING else None
            if getattr(layer, name) == default:
                updates[name] = global_val
        return dataclasses.replace(layer, **updates) if updates else layer
