from deeplearning4j_tpu.nn.conf.inputs import (  # noqa: F401
    InputType, FeedForwardType, RecurrentType, ConvolutionalType,
)
