"""Memory estimation reports.

Reference analogs: nn/conf/memory/LayerMemoryReport.java and
NetworkMemoryReport.java (/root/reference/deeplearning4j-nn/src/main/java/org/
deeplearning4j/nn/conf/memory/) — per-layer and whole-network breakdowns of
parameter, activation, gradient and updater-state memory for a given
``MemoryUseMode`` (inference vs training).

TPU-native shape: everything is computed symbolically with ``jax.eval_shape``
— no device allocation happens. The report accounts HBM the way XLA sees it:
params + opt state are persistent buffers; activations are the residuals the
backward pass keeps live (the dominant transient term); gradients alias the
param pytree. The reference's "workspace" overhead rows have no analog —
XLA's arena is compiler-managed — so the report instead surfaces the numbers
that matter for HBM budgeting on TPU.
"""

from __future__ import annotations

import dataclasses
import json

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import inputs as _inputs


def _nbytes(tree) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree))


def _count(tree) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


@dataclasses.dataclass(frozen=True)
class LayerMemoryReport:
    """Per-layer memory breakdown, in bytes except ``param_count``.

    ``activation_bytes_per_example`` is the layer's output activation; during
    training it is a residual held until the backward pass consumes it.
    """

    layer_name: str
    layer_type: str
    param_count: int
    param_bytes: int
    updater_state_bytes: int
    activation_bytes_per_example: int

    def training_fixed_bytes(self) -> int:
        # params + gradients (same size) + updater state
        return 2 * self.param_bytes + self.updater_state_bytes

    def inference_fixed_bytes(self) -> int:
        return self.param_bytes


@dataclasses.dataclass(frozen=True)
class NetworkMemoryReport:
    """Whole-network report (reference: NetworkMemoryReport.java)."""

    layer_reports: tuple
    input_bytes_per_example: int
    model_name: str = "network"

    @property
    def total_param_count(self) -> int:
        return sum(r.param_count for r in self.layer_reports)

    @property
    def total_param_bytes(self) -> int:
        return sum(r.param_bytes for r in self.layer_reports)

    @property
    def total_updater_state_bytes(self) -> int:
        return sum(r.updater_state_bytes for r in self.layer_reports)

    def total_memory_bytes(self, batch_size: int, *, training: bool = True) -> int:
        """Estimated peak HBM for one step at ``batch_size``.

        Training keeps every layer's output activation live (residuals for the
        backward pass); inference only needs the two largest neighbouring
        activations at once (XLA double-buffers through the stack).
        """
        acts = [r.activation_bytes_per_example for r in self.layer_reports]
        if training:
            fixed = sum(r.training_fixed_bytes() for r in self.layer_reports)
            transient = self.input_bytes_per_example + sum(acts)
        else:
            fixed = sum(r.inference_fixed_bytes() for r in self.layer_reports)
            pairs = [self.input_bytes_per_example] + acts
            transient = max(
                (pairs[i] + pairs[i + 1] for i in range(len(pairs) - 1)),
                default=self.input_bytes_per_example,
            )
        return fixed + batch_size * transient

    def to_json(self, indent=2) -> str:
        return json.dumps(
            {
                "model_name": self.model_name,
                "total_param_count": self.total_param_count,
                "total_param_bytes": self.total_param_bytes,
                "total_updater_state_bytes": self.total_updater_state_bytes,
                "input_bytes_per_example": self.input_bytes_per_example,
                "layers": [dataclasses.asdict(r) for r in self.layer_reports],
            },
            indent=indent,
        )

    def summary(self, batch_size: int = 32) -> str:
        lines = [
            f"{'layer':<28}{'type':<26}{'params':>12}{'act/ex (B)':>12}",
            "-" * 78,
        ]
        for r in self.layer_reports:
            lines.append(
                f"{r.layer_name:<28}{r.layer_type:<26}{r.param_count:>12}"
                f"{r.activation_bytes_per_example:>12}"
            )
        lines.append("-" * 78)
        lines.append(
            f"total params: {self.total_param_count:,} "
            f"({self.total_param_bytes / 1e6:.2f} MB), "
            f"train @ batch {batch_size}: "
            f"{self.total_memory_bytes(batch_size) / 1e6:.2f} MB, "
            f"infer @ batch {batch_size}: "
            f"{self.total_memory_bytes(batch_size, training=False) / 1e6:.2f} MB"
        )
        return "\n".join(lines)


def _example_bytes(input_type, dtype) -> int:
    n = 1
    for d in input_type.shape(batch=1):
        n *= d
    return n * jnp.dtype(dtype).itemsize


def memory_report(conf, *, dtype=jnp.float32, model_name="network") -> NetworkMemoryReport:
    """Build a NetworkMemoryReport for a MultiLayerConfiguration.

    Symbolic only — uses ``jax.eval_shape`` over each layer's ``init`` and the
    network updater's ``init``, so no device memory is touched.
    """
    in_types, _ = conf.layer_input_types()
    key = jax.random.PRNGKey(0)
    reports = []
    updater = conf.updater
    for i, (layer, it) in enumerate(zip(conf.layers, in_types)):
        p_shapes = jax.eval_shape(lambda l=layer, t=it: l.init(key, t, dtype))
        try:
            u_shapes = jax.eval_shape(lambda s=p_shapes: updater.init(s))
            u_bytes = _nbytes(u_shapes)
        except Exception:
            u_bytes = 0
        out_t = layer.output_type(
            _inputs.adapted_type(it, layer.input_family) if layer.input_family
            and not isinstance(it, layer.input_family) else it
        )
        reports.append(
            LayerMemoryReport(
                layer_name=f"layer_{i}",
                layer_type=type(layer).__name__,
                param_count=_count(p_shapes),
                param_bytes=_nbytes(p_shapes),
                updater_state_bytes=u_bytes,
                activation_bytes_per_example=_example_bytes(out_t, dtype),
            )
        )
    return NetworkMemoryReport(
        layer_reports=tuple(reports),
        input_bytes_per_example=_example_bytes(conf.input_type, dtype),
        model_name=model_name,
    )
