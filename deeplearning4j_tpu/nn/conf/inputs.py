"""Input typing & shape inference.

Reference analog: ``InputType`` + the preprocessor zoo
(/root/reference/deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/conf/
inputs/InputType.java and nn/conf/preprocessor/ — CnnToFeedForwardPreProcessor
etc., SURVEY.md §2.1 row 3). Three families:

- FeedForward: activations [batch, size]
- Recurrent:   activations [batch, time, size]   (batch-major, scan over time;
               the reference uses [b, f, t] — we use time-in-middle, which is
               the natural layout for lax.scan + MXU-friendly [b*t, f] matmuls)
- Convolutional: activations [batch, height, width, channels] (NHWC — XLA:TPU's
               preferred conv layout; the reference is NCHW)

Conversions between families are pure reshapes/transposes, auto-inserted by
the network builder exactly like the reference's preprocessors.
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.utils.serde import register_config


@dataclasses.dataclass(frozen=True)
class InputType:
    pass


@register_config
@dataclasses.dataclass(frozen=True)
class FeedForwardType(InputType):
    size: int = 0

    def shape(self, batch=1):
        return (batch, self.size)


@register_config
@dataclasses.dataclass(frozen=True)
class RecurrentType(InputType):
    size: int = 0
    timesteps: int | None = None  # None = variable length

    def shape(self, batch=1):
        return (batch, self.timesteps or 1, self.size)


@register_config
@dataclasses.dataclass(frozen=True)
class ConvolutionalType(InputType):
    height: int = 0
    width: int = 0
    channels: int = 0

    def shape(self, batch=1):
        return (batch, self.height, self.width, self.channels)

    @property
    def flat_size(self):
        return self.height * self.width * self.channels


# convenience constructors mirroring InputType.feedForward(...) etc.
def feed_forward(size):
    return FeedForwardType(size)


def recurrent(size, timesteps=None):
    return RecurrentType(size, timesteps)


def convolutional(height, width, channels):
    return ConvolutionalType(height, width, channels)


# --------------------------------------------------------------------------
# Preprocessors: pure-function family converters. Auto-inserted by the
# network builder when consecutive layers' families differ.
# --------------------------------------------------------------------------


def cnn_to_feed_forward(x):
    """[B,H,W,C] -> [B, H*W*C]"""
    return x.reshape((x.shape[0], -1))


def feed_forward_to_cnn(x, height, width, channels):
    return x.reshape((x.shape[0], height, width, channels))


def feed_forward_to_rnn(x, timesteps):
    """[B*T, F] -> [B, T, F]"""
    return x.reshape((-1, timesteps, x.shape[-1]))


def rnn_to_feed_forward(x):
    """[B, T, F] -> [B*T, F]"""
    return x.reshape((-1, x.shape[-1]))


def cnn_to_rnn(x):
    """[B,H,W,C] -> [B, H, W*C] treating height as time."""
    return x.reshape((x.shape[0], x.shape[1], -1))


def rnn_to_cnn(x, height, width, channels):
    return x.reshape((x.shape[0], height, width, channels))


def adapt(x, from_type: InputType, to_family: type):
    """Reshape activations from ``from_type`` to the family ``to_family``.

    Returns reshaped activations. Used by the sequential network to emulate
    the reference's auto-inserted preprocessors.
    """
    if isinstance(from_type, to_family):
        return x
    if isinstance(from_type, ConvolutionalType) and to_family is FeedForwardType:
        return cnn_to_feed_forward(x)
    if isinstance(from_type, RecurrentType) and to_family is FeedForwardType:
        return rnn_to_feed_forward(x)
    if isinstance(from_type, FeedForwardType) and to_family is ConvolutionalType:
        raise ValueError("FeedForward->CNN adaptation requires explicit target dims; "
                         "set an explicit preprocessor or input_type on the layer")
    raise ValueError(f"No automatic adaptation from {from_type} to {to_family.__name__}")


def adapted_type(from_type: InputType, to_family: type) -> InputType:
    """Shape-inference companion of ``adapt``."""
    if isinstance(from_type, to_family):
        return from_type
    if isinstance(from_type, ConvolutionalType) and to_family is FeedForwardType:
        return FeedForwardType(from_type.flat_size)
    if isinstance(from_type, RecurrentType) and to_family is FeedForwardType:
        return FeedForwardType(from_type.size)
    raise ValueError(f"No automatic adaptation from {from_type} to {to_family.__name__}")
