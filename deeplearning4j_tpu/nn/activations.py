"""Activation catalog.

Reference analog: the ND4J ``Activation`` enum + ``IActivation`` classes used
throughout the layer configs (e.g. /root/reference/deeplearning4j-nn/src/main/
java/org/deeplearning4j/nn/conf/layers/BaseLayer.java activationFn). Here each
activation is a pure jnp function; jit/XLA fuses them into the surrounding
matmul, which is the TPU-native replacement for libnd4j's fused transform ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def identity(x):
    return x


def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jax.nn.relu6(x)


def leakyrelu(x, alpha=0.01):
    return jax.nn.leaky_relu(x, negative_slope=alpha)


def elu(x):
    return jax.nn.elu(x)


def selu(x):
    return jax.nn.selu(x)


def gelu(x):
    return jax.nn.gelu(x)


def swish(x):
    return jax.nn.silu(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def tanh(x):
    return jnp.tanh(x)


def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def rationaltanh(x):
    # 1.7159 * tanh(2x/3) approximation used by ND4J's RationalTanh
    a = jnp.abs(2.0 * x / 3.0)
    tanh_approx = jnp.sign(x) * (1.0 - 1.0 / (1.0 + a + a * a + 1.41645 * a**4))
    return 1.7159 * tanh_approx


def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def softmax(x):
    return jax.nn.softmax(x, axis=-1)


def logsoftmax(x):
    return jax.nn.log_softmax(x, axis=-1)


def cube(x):
    return x**3


def thresholdedrelu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


_CATALOG = {
    "identity": identity,
    "linear": identity,
    "relu": relu,
    "relu6": relu6,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "selu": selu,
    "gelu": gelu,
    "swish": swish,
    "silu": swish,
    "sigmoid": sigmoid,
    "hardsigmoid": hardsigmoid,
    "tanh": tanh,
    "hardtanh": hardtanh,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "softplus": softplus,
    "softsign": softsign,
    "softmax": softmax,
    "logsoftmax": logsoftmax,
    "cube": cube,
    "thresholdedrelu": thresholdedrelu,
    "mish": mish,
}


def get(name):
    """Resolve an activation by name (or pass a callable through).

    Parameterized spelling: ``("leakyrelu", {"alpha": 0.3})`` (list or tuple,
    JSON-serde friendly) binds keyword arguments onto the named activation —
    the analog of DL4J's parameterized IActivation instances (e.g.
    ActivationLReLU(alpha))."""
    if isinstance(name, (tuple, list)) and name:
        import functools
        kwargs = dict(name[1]) if len(name) > 1 and name[1] else {}
        return functools.partial(get(name[0]), **kwargs)
    if callable(name):
        return name
    try:
        return _CATALOG[name.lower()]
    except KeyError:
        raise KeyError(f"Unknown activation {name!r}. Known: {sorted(_CATALOG)}") from None


def names():
    return sorted(_CATALOG)
