"""ComputationGraph: arbitrary-DAG networks.

Reference analog: nn/graph/ComputationGraph.java (3422 LoC;
topologicalSortOrder:1194, feedForward:1384, computeGradientAndScore:1302) +
ComputationGraphConfiguration.java + vertex impls nn/graph/vertex/impl/
(ElementWise, Merge, Subset, Stack/Unstack, Scale, Shift, L2Normalize, L2,
Reshape, PoolHelper, Preprocessor, Layer, Input) and RNN vertices
nn/conf/graph/rnn/ (LastTimeStepVertex, DuplicateToTimeSeriesVertex), all in
/root/reference/deeplearning4j-nn.

TPU-native: the DAG is topologically sorted once at build; the whole forward
(+backward in the train step) is a single jitted XLA computation — vertices
are pure functions over pytrees, so XLA fuses across vertex boundaries (the
reference executes vertex-by-vertex through JNI).

Multi-input/multi-output supported: ``fit({'in': x}, {'out': y})``; loss =
sum of output-layer losses (matching the reference's multi-output score).
"""

from __future__ import annotations

import dataclasses
import functools
import inspect
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import telemetry as _tm
from deeplearning4j_tpu.telemetry import flight as _flight
from deeplearning4j_tpu.telemetry import health as _health
from deeplearning4j_tpu.nn import gradnorm as _gradnorm
from deeplearning4j_tpu.nn import listeners as _listeners
from deeplearning4j_tpu.nn import updaters as _updaters
from deeplearning4j_tpu.nn.conf import inputs as _inputs
from deeplearning4j_tpu.nn.layers import base as _base_layers
from deeplearning4j_tpu.utils import dtypes as _dtypes
from deeplearning4j_tpu.utils import serde


def _loss_mask_for(mask, label):
    """The batch mask as an output's label mask ONLY when its layout
    matches that output's per-example loss: [B] pairs with pooled
    (<=2-d) labels, [B, T] with time-distributed (>=3-d) labels. A
    mixed-layout graph (one temporal feature mask, pooled heads) keeps
    the head unmasked rather than mis-broadcasting — pass explicit
    ``label_masks`` to override."""
    if mask is None:
        return None
    if mask.ndim == 1 and label.ndim <= 2:
        return mask
    if mask.ndim == 2 and label.ndim >= 3:
        return mask
    return None


# --------------------------------------------------------------------------
# Graph vertices
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GraphVertex:
    """Base: pure function over a list of input activations."""

    def output_type(self, input_types):
        assert len(input_types) == 1
        return input_types[0]

    def init(self, key, input_types, dtype=jnp.float32):
        return {}

    def init_state(self, input_types, dtype=jnp.float32):
        return {}

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        return xs[0], state

    def regularization_penalty(self, params):
        return 0.0


@serde.register_config
@dataclasses.dataclass(frozen=True)
class LayerVertex(GraphVertex):
    """Wraps any layer from the catalog (reference: vertex/impl/LayerVertex.java)."""

    layer: object = None

    def _adapted(self, input_types):
        it = input_types[0]
        fam = self.layer.input_family
        if fam is not None and not isinstance(it, fam):
            return _inputs.adapted_type(it, fam)
        return it

    def output_type(self, input_types):
        return self.layer.output_type(self._adapted(input_types))

    def init(self, key, input_types, dtype=jnp.float32):
        return self.layer.init(key, self._adapted(input_types), dtype)

    def init_state(self, input_types, dtype=jnp.float32):
        return self.layer.init_state(self._adapted(input_types), dtype)

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        x = xs[0]
        fam = self.layer.input_family
        # family adaptation by rank (jit-safe: static shapes)
        if fam is _inputs.FeedForwardType and x.ndim > 2:
            x = x.reshape((x.shape[0], -1))
        kwargs = {}
        # 1-d masks are example-validity (shape bucketing), not [B, T]
        # timestep masks — mask-aware layers only get the latter
        if mask is not None and mask.ndim >= 2 \
                and "mask" in inspect.signature(type(self.layer).apply).parameters:
            kwargs["mask"] = mask
        return self.layer.apply(params, state, x, train=train, rng=rng, **kwargs)

    # recurrent-carry plumbing (TBPTT / rnnTimeStep): delegate to the
    # wrapped layer when it is recurrent
    def has_carry(self):
        return hasattr(self.layer, "apply_with_carry")

    def zero_carry(self, batch, dtype=jnp.float32):
        return self.layer.zero_carry(batch, dtype)

    def apply_with_carry(self, params, carry, xs, *, mask=None):
        return self.layer.apply_with_carry(params, carry, xs[0], mask=mask)

    def regularization_penalty(self, params):
        return self.layer.regularization_penalty(params) if params else 0.0


@serde.register_config
@dataclasses.dataclass(frozen=True)
class MergeVertex(GraphVertex):
    """Concatenate along the feature/channel axis (reference: MergeVertex.java)."""

    def output_type(self, input_types):
        t0 = input_types[0]
        if isinstance(t0, _inputs.ConvolutionalType):
            return _inputs.ConvolutionalType(t0.height, t0.width,
                                             sum(t.channels for t in input_types))
        if isinstance(t0, _inputs.RecurrentType):
            return _inputs.RecurrentType(sum(t.size for t in input_types), t0.timesteps)
        return _inputs.FeedForwardType(sum(t.size for t in input_types))

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        return jnp.concatenate(xs, axis=-1), state


@serde.register_config
@dataclasses.dataclass(frozen=True)
class ElementWiseVertex(GraphVertex):
    """add | subtract | product | average | max (reference: ElementWiseVertex.java)."""

    op: str = "add"

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        if self.op == "add":
            return functools.reduce(jnp.add, xs), state
        if self.op == "subtract":
            assert len(xs) == 2
            return xs[0] - xs[1], state
        if self.op == "product":
            return functools.reduce(jnp.multiply, xs), state
        if self.op == "average":
            return functools.reduce(jnp.add, xs) / len(xs), state
        if self.op == "max":
            return functools.reduce(jnp.maximum, xs), state
        raise ValueError(f"Unknown elementwise op {self.op!r}")


@serde.register_config
@dataclasses.dataclass(frozen=True)
class SubsetVertex(GraphVertex):
    """Feature-range slice [from, to] inclusive (reference: SubsetVertex.java)."""

    from_idx: int = 0
    to_idx: int = 0

    def output_type(self, input_types):
        n = self.to_idx - self.from_idx + 1
        t = input_types[0]
        if isinstance(t, _inputs.RecurrentType):
            return _inputs.RecurrentType(n, t.timesteps)
        if isinstance(t, _inputs.ConvolutionalType):
            return _inputs.ConvolutionalType(t.height, t.width, n)
        return _inputs.FeedForwardType(n)

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        return xs[0][..., self.from_idx:self.to_idx + 1], state


@serde.register_config
@dataclasses.dataclass(frozen=True)
class StackVertex(GraphVertex):
    """Stack along batch dim (reference: StackVertex.java)."""

    def output_type(self, input_types):
        return input_types[0]  # batch dim is not part of InputType

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        return jnp.concatenate(xs, axis=0), state


@serde.register_config
@dataclasses.dataclass(frozen=True)
class UnstackVertex(GraphVertex):
    """Take slice ``index`` of ``stack_size`` along batch (reference: UnstackVertex.java)."""

    index: int = 0
    stack_size: int = 1

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        x = xs[0]
        step = x.shape[0] // self.stack_size
        return x[self.index * step:(self.index + 1) * step], state


@serde.register_config
@dataclasses.dataclass(frozen=True)
class ScaleVertex(GraphVertex):
    factor: float = 1.0

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        return xs[0] * self.factor, state


@serde.register_config
@dataclasses.dataclass(frozen=True)
class ShiftVertex(GraphVertex):
    amount: float = 0.0

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        return xs[0] + self.amount, state


@serde.register_config
@dataclasses.dataclass(frozen=True)
class L2NormalizeVertex(GraphVertex):
    eps: float = 1e-8

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        x = xs[0]
        norm = jnp.sqrt(jnp.sum(x * x, axis=tuple(range(1, x.ndim)), keepdims=True))
        return x / (norm + self.eps), state


@serde.register_config
@dataclasses.dataclass(frozen=True)
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs -> [batch, 1] (reference: L2Vertex.java)."""

    eps: float = 1e-8

    def output_type(self, input_types):
        return _inputs.FeedForwardType(1)

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        a, b = xs
        d = (a - b).reshape((a.shape[0], -1))
        return jnp.sqrt(jnp.sum(d * d, axis=1, keepdims=True) + self.eps), state


@serde.register_config
@dataclasses.dataclass(frozen=True)
class ReshapeVertex(GraphVertex):
    """Reshape trailing dims, batch preserved (reference: ReshapeVertex.java)."""

    shape: tuple = ()
    output_input_type: object = None

    def output_type(self, input_types):
        return self.output_input_type or input_types[0]

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        return xs[0].reshape((xs[0].shape[0],) + tuple(self.shape)), state


@serde.register_config
@dataclasses.dataclass(frozen=True)
class LastTimeStepVertex(GraphVertex):
    """[B,T,F] -> [B,F] mask-aware (reference: rnn/LastTimeStepVertex.java)."""

    def output_type(self, input_types):
        return _inputs.FeedForwardType(input_types[0].size)

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        x = xs[0]
        if mask is None:
            return x[:, -1, :], state
        idx = jnp.maximum(jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0)
        return x[jnp.arange(x.shape[0]), idx, :], state


@serde.register_config
@dataclasses.dataclass(frozen=True)
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[B,F] -> [B,T,F] broadcast over time (reference:
    rnn/DuplicateToTimeSeriesVertex.java). T taken from a reference input."""

    timesteps: int = 1

    def output_type(self, input_types):
        return _inputs.RecurrentType(input_types[0].size, self.timesteps)

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        return jnp.broadcast_to(xs[0][:, None, :],
                                (xs[0].shape[0], self.timesteps, xs[0].shape[-1])), state


@serde.register_config
@dataclasses.dataclass(frozen=True)
class PoolHelperVertex(GraphVertex):
    """Strip first row/col (reference: PoolHelperVertex.java — GoogLeNet
    import compatibility)."""

    def output_type(self, input_types):
        t = input_types[0]
        return _inputs.ConvolutionalType(t.height - 1, t.width - 1, t.channels)

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        return xs[0][:, 1:, 1:, :], state


@serde.register_config
@dataclasses.dataclass(frozen=True)
class PreprocessorVertex(GraphVertex):
    """Explicit family conversion (reference: PreprocessorVertex.java).
    kind: cnn_to_ff | ff_to_cnn | rnn_to_ff | ff_to_rnn | cnn_to_rnn"""

    kind: str = "cnn_to_ff"
    height: int = 0
    width: int = 0
    channels: int = 0
    timesteps: int = 0

    def output_type(self, input_types):
        t = input_types[0]
        if self.kind == "cnn_to_ff":
            return _inputs.FeedForwardType(t.flat_size)
        if self.kind == "ff_to_cnn":
            return _inputs.ConvolutionalType(self.height, self.width, self.channels)
        if self.kind == "rnn_to_ff":
            return _inputs.FeedForwardType(t.size)
        if self.kind == "ff_to_rnn":
            return _inputs.RecurrentType(t.size, self.timesteps)
        if self.kind == "cnn_to_rnn":
            return _inputs.RecurrentType(t.width * t.channels, t.height)
        raise ValueError(self.kind)

    def apply(self, params, state, xs, *, train=False, rng=None, mask=None):
        x = xs[0]
        if self.kind == "cnn_to_ff":
            return x.reshape((x.shape[0], -1)), state
        if self.kind == "ff_to_cnn":
            return x.reshape((x.shape[0], self.height, self.width, self.channels)), state
        if self.kind == "rnn_to_ff":
            return x.reshape((-1, x.shape[-1])), state
        if self.kind == "ff_to_rnn":
            return x.reshape((-1, self.timesteps, x.shape[-1])), state
        if self.kind == "cnn_to_rnn":
            return x.reshape((x.shape[0], x.shape[1], -1)), state
        raise ValueError(self.kind)


# --------------------------------------------------------------------------
# Graph configuration
# --------------------------------------------------------------------------


@serde.register_config
@dataclasses.dataclass(frozen=True)
class VertexDef:
    name: str = ""
    vertex: object = None
    inputs: tuple = ()


@serde.register_config
@dataclasses.dataclass(frozen=True)
class GraphConfiguration:
    """(reference: ComputationGraphConfiguration + its GraphBuilder)."""

    inputs: tuple = ()          # input names
    input_types: tuple = ()     # matching InputTypes
    vertices: tuple = ()        # VertexDef tuple (definition order)
    outputs: tuple = ()         # names of output vertices
    updater: object = dataclasses.field(default_factory=_updaters.Sgd)
    gradient_normalization: str = "none"
    gradient_normalization_threshold: float = 1.0
    seed: int = 12345
    # remat each vertex's forward during backprop: HBM for FLOPs
    gradient_checkpointing: bool = False
    # truncated BPTT (reference: ComputationGraph.doTruncatedBPTT:2595 +
    # the fit branches at :937/:1038/:1162)
    backprop_type: str = "standard"  # standard | tbptt
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    # coarser remat: group vertices sharing a name prefix (up to the first
    # '_') into ONE jax.checkpoint region on the training path, so only
    # block BOUNDARY activations are stashed for backward and everything
    # inside a block (conv outputs, BN pre-activations) is recomputed.
    # For an HBM-bound model (PROFILE.md: ResNet50 at v5e bandwidth peak)
    # this trades idle-MXU FLOPs for the activation-stash traffic that
    # bounds the step. "prefix" is the only mode; None disables.
    checkpoint_scope: str | None = None

    def to_json(self, indent=2):
        return serde.to_json(self, indent=indent)

    @staticmethod
    def from_json(s):
        conf = serde.from_json(s)
        assert isinstance(conf, GraphConfiguration)
        return conf

    def topological_order(self):
        """Kahn topo sort (reference: topologicalSortOrder:1194)."""
        defs = {v.name: v for v in self.vertices}
        indeg = {v.name: 0 for v in self.vertices}
        dependents = {name: [] for name in list(defs) + list(self.inputs)}
        for v in self.vertices:
            for inp in v.inputs:
                if inp not in defs and inp not in self.inputs:
                    raise ValueError(f"Vertex {v.name!r} input {inp!r} undefined")
                if inp in defs:
                    indeg[v.name] += 1
                dependents[inp].append(v.name)
        order = [n for n, d in sorted(indeg.items()) if d == 0]
        queue = list(order)
        seen = set(order)
        result = []
        while queue:
            n = queue.pop(0)
            result.append(n)
            for dep in dependents[n]:
                indeg[dep] -= 1
                if indeg[dep] == 0 and dep not in seen:
                    seen.add(dep)
                    queue.append(dep)
        if len(result) != len(self.vertices):
            raise ValueError("Graph has a cycle")
        return result

    def vertex_types(self):
        """Shape inference over the DAG. Returns {name: output InputType}."""
        defs = {v.name: v for v in self.vertices}
        types = dict(zip(self.inputs, self.input_types))
        for name in self.topological_order():
            v = defs[name]
            in_types = [types[i] for i in v.inputs]
            types[name] = v.vertex.output_type(in_types)
        return types


class GraphBuilder:
    """Fluent builder (reference: ComputationGraphConfiguration.GraphBuilder)."""

    def __init__(self, updater=None, seed=12345, gradient_normalization="none",
                 gradient_normalization_threshold=1.0,
                 gradient_checkpointing=False, checkpoint_scope=None,
                 backprop_type="standard", tbptt_fwd_length=20,
                 tbptt_back_length=20):
        self._inputs = []
        self._input_types = []
        self._vertices = []
        self._outputs = []
        self._updater = updater or _updaters.Sgd()
        self._seed = seed
        self._gn = gradient_normalization
        self._gnt = gradient_normalization_threshold
        self._remat = gradient_checkpointing
        self._ckpt_scope = checkpoint_scope
        self._backprop_type = backprop_type
        self._tbptt_fwd = tbptt_fwd_length
        self._tbptt_back = tbptt_back_length

    def add_inputs(self, *names):
        self._inputs.extend(names)
        return self

    def set_input_types(self, *types):
        self._input_types.extend(types)
        return self

    def add_layer(self, name, layer, *inputs):
        self._vertices.append(VertexDef(name, LayerVertex(layer=layer), tuple(inputs)))
        return self

    def add_vertex(self, name, vertex, *inputs):
        self._vertices.append(VertexDef(name, vertex, tuple(inputs)))
        return self

    def set_outputs(self, *names):
        self._outputs.extend(names)
        return self

    def add_module(self, module, layer_name, input_size, config, input_layer):
        """Append a reusable graph fragment via the GraphBuilderModule SPI
        (reference: GraphBuilderModule.updateBuilder)."""
        return module.update_builder(self, layer_name, input_size, config,
                                     input_layer)

    def last_vertex_name(self):
        """Name of the most recently added vertex (modules add their output
        vertex last, so chains continue from here)."""
        return self._vertices[-1].name if self._vertices else None

    def build(self) -> GraphConfiguration:
        conf = GraphConfiguration(
            inputs=tuple(self._inputs), input_types=tuple(self._input_types),
            vertices=tuple(self._vertices), outputs=tuple(self._outputs),
            updater=self._updater, seed=self._seed,
            gradient_normalization=self._gn,
            gradient_normalization_threshold=self._gnt,
            gradient_checkpointing=self._remat,
            checkpoint_scope=self._ckpt_scope,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back)
        conf.topological_order()  # validate
        return conf


# --------------------------------------------------------------------------
# ComputationGraph
# --------------------------------------------------------------------------


class ComputationGraph:
    def __init__(self, conf: GraphConfiguration):
        self.conf = conf
        self._defs = {v.name: v for v in conf.vertices}
        self._order = conf.topological_order()
        self._types = conf.vertex_types()
        self._segments = (self._build_segments()
                          if conf.checkpoint_scope == "prefix" else None)
        self.params = None
        self.state = None
        self.opt_state = None
        self.iteration = 0
        self.epoch = 0
        self.listeners = []
        self.score_value = None
        self._train_step = None
        self._train_step_health = None
        self._rng = jax.random.PRNGKey(conf.seed)

    def init(self, rng=None, dtype=None):
        rng = self._rng if rng is None else rng
        dtype = dtype or _dtypes.get_policy().param_dtype
        params, state = {}, {}
        for name in self._order:
            v = self._defs[name]
            in_types = [self._types[i] for i in v.inputs]
            rng, sub = jax.random.split(rng)
            params[name] = v.vertex.init(sub, in_types, dtype)
            state[name] = v.vertex.init_state(in_types, dtype)
        self.params, self.state = params, state
        self.opt_state = self.conf.updater.init(params)
        return params, state

    def _build_segments(self):
        """Partition the topo order into checkpoint segments for the
        ``checkpoint_scope="prefix"`` mode: a maximal contiguous run of >= 2
        vertices sharing the name prefix before the first '_' becomes one
        ("group", names, external_inputs, boundary_outputs) region; loss /
        network-output vertices always stay singles. Only activations at
        group boundaries are stashed for backward — the bottleneck-block
        granularity ResNet-style graphs need (per-vertex jax.checkpoint
        stores every vertex input and saves nothing)."""
        dependents = {}
        for v in self.conf.vertices:
            for inp in v.inputs:
                dependents.setdefault(inp, set()).add(v.name)

        def scope_of(name):
            if name in self.conf.outputs:
                return None
            v = self._defs[name]
            layer = v.vertex.layer if isinstance(v.vertex, LayerVertex) \
                else None
            if layer is not None and hasattr(layer, "loss_from_features"):
                return None
            return name.split("_", 1)[0] if "_" in name else None

        segments = []
        i = 0
        order = self._order
        while i < len(order):
            sc = scope_of(order[i])
            j = i + 1
            while sc is not None and j < len(order) \
                    and scope_of(order[j]) == sc:
                j += 1
            if sc is None or j - i < 2:
                segments.append(("single", order[i]))
                i += 1
                continue
            names = order[i:j]
            produced = set(names)
            ext = []
            for n in names:
                for inp in self._defs[n].inputs:
                    if inp not in produced and inp not in ext:
                        ext.append(inp)
            after = set(order[j:])
            bnd = [n for n in names
                   if n in self.conf.outputs
                   or dependents.get(n, set()) & after]
            segments.append(("group", tuple(names), tuple(ext), tuple(bnd)))
            i = j
        return segments

    def _run_group(self, seg, params, state, acts, new_state, subs, mask,
                   train):
        """Execute one checkpoint group: recompute-in-backward region over
        its member vertices. Only boundary outputs land in ``acts``."""
        _, names, ext, bnd = seg

        frozen = getattr(self, "frozen_vertices", set())

        def run(gp, gs, ext_vals, subs_, m):
            local = dict(zip(ext, ext_vals))
            ns = {}
            for k, n in enumerate(names):
                v = self._defs[n]
                xs = [local[i] for i in v.inputs]
                local[n], ns[n] = v.vertex.apply(
                    gp[n], gs[n], xs, train=train and n not in frozen,
                    rng=subs_[k], mask=m)
            return [local[n] for n in bnd], ns

        run = jax.checkpoint(run)
        outs, ns = run({n: params[n] for n in names},
                       {n: state[n] for n in names},
                       [acts[i] for i in ext], subs, mask)
        for n, val in zip(bnd, outs):
            acts[n] = val
        new_state.update(ns)

    def _forward_pass(self, params, state, inputs, *, train=False, rng=None,
                      mask=None, labels=None, label_masks=None,
                      carries=None):
        """THE single topological traversal all forward entry points share.
        Returns (acts, new_state, loss[, new_carries]); ``loss`` is None
        unless ``labels`` is given, in which case output-vertex losses
        accumulate (feature-loss heads like CenterLossOutputLayer receive
        their input activations). ``carries``: optional {vertex: carry}
        dict threading recurrent hidden state (TBPTT / rnnTimeStep —
        reference: doTruncatedBPTT:2595, rnnTimeStep on ComputationGraph);
        when given, recurrent LayerVertices run apply_with_carry and the
        updated carries are returned as a fourth element."""
        if not isinstance(inputs, dict):
            inputs = {self.conf.inputs[0]: jnp.asarray(inputs)}
        acts = dict(inputs)
        new_state = dict(state)
        new_carries = dict(carries) if carries is not None else None
        loss = 0.0 if labels is not None else None
        # scope-level remat applies on the loss/training path only —
        # feed_forward()'s contract (an activation for EVERY vertex) needs
        # the ungrouped traversal, and there is no backward there anyway;
        # carry-threaded passes also walk ungrouped
        use_groups = (self._segments is not None and labels is not None
                      and carries is None)
        walk = (self._segments if use_groups
                else [("single", n) for n in self._order])
        frozen = getattr(self, "frozen_vertices", set())
        for seg in walk:
            if seg[0] == "group":
                subs = []
                for _ in seg[1]:
                    if rng is not None:
                        rng, sub = jax.random.split(rng)
                        subs.append(sub)
                    else:
                        subs.append(None)
                self._run_group(seg, params, state, acts, new_state,
                                tuple(subs), mask, train)
                continue
            name = seg[1]
            v = self._defs[name]
            xs = [acts[i] for i in v.inputs]
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            layer = v.vertex.layer if isinstance(v.vertex, LayerVertex) else None
            if (labels is not None and name in self.conf.outputs
                    and layer is not None
                    and hasattr(layer, "loss_from_features")):
                x = xs[0]
                if (layer.input_family is _inputs.FeedForwardType
                        and x.ndim > 2):
                    x = x.reshape((x.shape[0], -1))
                # the MLN/reference convention: the batch mask doubles as
                # the label mask unless per-output label_masks are given
                # (MaskedReductionUtil zeroes padded steps from the score)
                lm = (label_masks or {}).get(name)
                if lm is None:
                    lm = _loss_mask_for(mask, labels[name])
                l_i, preds, st = layer.loss_from_features(
                    params[name], state[name], x, labels[name], lm,
                    train=train and name not in frozen)
                loss = loss + l_i
                acts[name], new_state[name] = preds, st
            elif (new_carries is not None and isinstance(v.vertex,
                                                         LayerVertex)
                  and v.vertex.has_carry()):
                acts[name], new_carries[name] = v.vertex.apply_with_carry(
                    params[name], new_carries.get(name), xs, mask=mask)
            else:
                # FrozenLayer.java:23: frozen vertices forward in TEST mode
                # regardless of the network's mode (running-stat BN, no
                # stat updates, no dropout)
                l_train = train and name not in frozen

                def run(p, s, x_list, r, m, _v=v.vertex, _train=l_train):
                    return _v.apply(p, s, x_list, train=_train, rng=r,
                                    mask=m)

                if self.conf.gradient_checkpointing:
                    run = jax.checkpoint(run)  # remat: HBM for FLOPs
                acts[name], new_state[name] = run(
                    params[name], state[name], xs, sub, mask)
                if labels is not None and name in self.conf.outputs:
                    l_layer = layer if layer is not None else v.vertex
                    if not hasattr(l_layer, "compute_loss"):
                        raise ValueError(f"Output vertex {name!r} has no loss")
                    lm = (label_masks or {}).get(name)
                    if lm is None:  # MLN convention, shape-guarded
                        lm = _loss_mask_for(mask, labels[name])
                    loss = loss + l_layer.compute_loss(acts[name],
                                                       labels[name], lm)
        if carries is not None:
            return acts, new_state, loss, new_carries
        return acts, new_state, loss

    def apply_fn(self, params, state, inputs, *, train=False, rng=None, mask=None):
        """inputs: dict name->array (or single array if one input).
        Returns (dict of output activations, new_state)."""
        acts, new_state, _ = self._forward_pass(params, state, inputs,
                                                train=train, rng=rng, mask=mask)
        return {o: acts[o] for o in self.conf.outputs}, new_state

    def feed_forward(self, inputs, *, train=False, mask=None):
        """Activations of EVERY vertex, name->array (reference:
        ComputationGraph.feedForward:1384 returns the full activation map)."""
        acts, _, _ = self._forward_pass(self.params, self.state, inputs,
                                        train=train, mask=mask)
        return acts

    def loss_fn(self, params, state, inputs, labels, *, train=True, rng=None,
                mask=None, label_masks=None, carries=None):
        """Sum of output-layer losses + regularization (reference:
        computeGradientAndScore:1302). With ``carries`` (TBPTT chunks) the
        aux gains the updated carries: (new_state, outs, new_carries)."""
        if not isinstance(labels, dict):
            labels = {self.conf.outputs[0]: labels}
        fwd = self._forward_pass(
            params, state, inputs, train=train, rng=rng, mask=mask,
            labels=labels, label_masks=label_masks, carries=carries)
        acts, new_state, loss = fwd[:3]
        for name in self._order:
            v = self._defs[name]
            if params[name]:
                loss = loss + v.vertex.regularization_penalty(params[name])
        loss, new_state = _base_layers.pop_aux_losses(loss, new_state)
        outs = {o: acts[o] for o in self.conf.outputs}
        if carries is not None:
            return loss, (new_state, outs, fwd[3])
        return loss, (new_state, outs)

    # ------------------------------------------------------------------
    # truncated BPTT + streaming inference (reference:
    # ComputationGraph.doTruncatedBPTT:2595, rnnTimeStep) — carries thread
    # through recurrent LayerVertices with stop_gradient at chunk edges
    # ------------------------------------------------------------------

    def _zero_carries(self, batch, dtype):
        from deeplearning4j_tpu.nn.layers.rnn import (
            Bidirectional, GravesBidirectionalLSTM)
        for v in self.conf.vertices:
            layer = getattr(v.vertex, "layer", None)
            if isinstance(layer, (Bidirectional, GravesBidirectionalLSTM)):
                # the backward direction needs the FULL future sequence —
                # the reference's rnnTimeStep throws for bidirectional
                # layers too; silent per-chunk state resets would produce
                # wrong numerics without an error
                raise ValueError(
                    f"vertex {v.name!r}: bidirectional layers do not "
                    "support TBPTT / rnn_time_step streaming")
        return {v.name: v.vertex.zero_carry(batch, dtype)
                for v in self.conf.vertices
                if isinstance(v.vertex, LayerVertex) and v.vertex.has_carry()}

    def make_tbptt_step(self, jit=True):
        conf = self.conf

        def tbptt_step(params, state, opt_state, carries, inputs, labels,
                       step, rng, mask=None):
            carries = jax.tree_util.tree_map(jax.lax.stop_gradient, carries)
            (loss, (new_state, _, new_carries)), grads = jax.value_and_grad(
                self.loss_fn, has_aux=True)(
                    params, state, inputs, labels, train=True, rng=rng,
                    mask=mask, carries=carries)
            if conf.gradient_normalization not in (None, "none"):
                grads = {k: _gradnorm.normalize_layer_grads(
                    conf.gradient_normalization, g,
                    conf.gradient_normalization_threshold)
                    if g else g for k, g in grads.items()}
            new_params, new_opt = self.apply_update(params, opt_state,
                                                    grads, step)
            return new_params, new_state, new_opt, new_carries, loss

        return jax.jit(tbptt_step) if jit else tbptt_step

    @staticmethod
    def _chunk_time(tree, t0, t1):
        """Slice [B, T, ...] arrays along time; static [B, F] entries (and
        2D labels of a LastTimeStep-style head) pass through whole — the
        MLN path's y.ndim == 3 guard, per-entry."""
        return {k: (jnp.asarray(v)[:, t0:t1]
                    if np.ndim(v) == 3 else jnp.asarray(v))
                for k, v in tree.items()}

    @staticmethod
    def _time_major(inputs):
        """The [B, T, ...] entry driving chunking (a multi-input graph may
        list a static [B, F] input first — scan, don't take the first)."""
        for v in inputs.values():
            if np.ndim(v) == 3:
                return v
        return None

    def _fit_tbptt(self, inputs, labels, mask):
        if getattr(self, "_tbptt_step", None) is None:
            self._tbptt_step = self.make_tbptt_step()
        first = self._time_major(inputs)
        T = first.shape[1]
        L = self.conf.tbptt_fwd_length
        carries = self._zero_carries(first.shape[0], jnp.asarray(first).dtype)
        total = 0.0
        n_chunks = 0
        chunk_scores = []  # (iteration, device loss) for listener replay
        for t0 in range(0, T, L):
            ci = self._chunk_time(inputs, t0, t0 + L)
            cl = self._chunk_time(labels, t0, t0 + L)
            cm = jnp.asarray(mask[:, t0:t0 + L]) if mask is not None else None
            self._rng, sub = jax.random.split(self._rng)
            (self.params, self.state, self.opt_state, carries, loss) = \
                self._tbptt_step(self.params, self.state, self.opt_state,
                                 carries, ci, cl, self.iteration, sub, cm)
            total = total + loss  # device accumulate: no per-chunk sync
            n_chunks += 1
            self.iteration += 1
            self.score_value = loss
            if self.listeners:
                chunk_scores.append((self.iteration, loss))
        if chunk_scores:
            # ONE batched fetch for every chunk's listener callback —
            # per-chunk float(loss) would sync each TBPTT chunk
            # (graftlint R1); the callbacks fire after the macro-batch,
            # matching the device-accumulated score below
            vals = jax.device_get([s for _, s in chunk_scores])
            for (it, _), v in zip(chunk_scores, vals):
                for lst in self.listeners:
                    lst.iteration_done(self, it, float(v))
        self.score_value = float(total) / max(n_chunks, 1)
        return self.score_value

    def rnn_clear_previous_state(self):
        """(reference: ComputationGraph.rnnClearPreviousState)"""
        self._rnn_stream_state = None

    def rnn_time_step(self, inputs):
        """One timestep [B, F] (or a short [B,T,F] chunk) of streaming
        inference, carrying recurrent state between calls (reference:
        ComputationGraph.rnnTimeStep)."""
        if self.params is None:
            self.init()
        if not isinstance(inputs, dict):
            inputs = {self.conf.inputs[0]: jnp.asarray(inputs)}
        inputs = {k: jnp.asarray(v) for k, v in inputs.items()}
        first = next(iter(inputs.values()))
        squeeze = first.ndim == 2
        if squeeze:
            inputs = {k: v[:, None, :] for k, v in inputs.items()}
            first = next(iter(inputs.values()))
        carries = getattr(self, "_rnn_stream_state", None)
        if carries is None:
            carries = self._zero_carries(first.shape[0], first.dtype)
        acts, _, _, carries = self._forward_pass(
            self.params, self.state, inputs, train=False, carries=carries)
        self._rnn_stream_state = carries
        # squeeze only time-major [B,T,F] outputs; a LastTimeStep-style
        # head already emits [B,C] and must pass through untouched
        outs = {o: (acts[o][:, 0] if squeeze and acts[o].ndim == 3
                    else acts[o])
                for o in self.conf.outputs}
        if len(outs) == 1:
            return next(iter(outs.values()))
        return outs

    def compute_gradients(self, params, state, inputs, labels, *, rng=None,
                          mask=None):
        """Loss + normalized gradients (MultiLayerNetwork.compute_gradients
        contract — the distributed masters insert their gradient exchange
        between this and apply_update)."""
        conf = self.conf
        (loss, (new_state, _)), grads = jax.value_and_grad(
            self.loss_fn, has_aux=True)(params, state, inputs, labels,
                                        train=True, rng=rng, mask=mask)
        if conf.gradient_normalization not in (None, "none"):
            grads = {k: _gradnorm.normalize_layer_grads(
                conf.gradient_normalization, g,
                conf.gradient_normalization_threshold)
                if g else g for k, g in grads.items()}
        return loss, new_state, grads

    def apply_update(self, params, opt_state, grads, step):
        updates, new_opt = self.conf.updater.update(grads, opt_state, params,
                                                    step)
        new_params = jax.tree_util.tree_map(lambda p, u: p + u, params,
                                            updates)
        return new_params, new_opt

    def apply_constraints(self, params, step):
        """MultiLayerNetwork.apply_constraints counterpart: the graph's
        apply_update has no constraint pass, so this is the identity —
        here so the distributed masters' sharded update can call ONE
        method on either net kind."""
        return params

    def make_train_step(self, donate=True, jit=True, with_health=False):
        def train_step(params, state, opt_state, inputs, labels, step, rng, mask=None):
            loss, new_state, grads = self.compute_gradients(
                params, state, inputs, labels, rng=rng, mask=mask)
            if with_health:
                # numerics-watchdog bundle, fused into the step (labels the
                # per-vertex series by vertex name)
                health = _health.health_stats(grads, params, loss)
            new_params, new_opt = self.apply_update(params, opt_state, grads,
                                                    step)
            if with_health:
                return new_params, new_state, new_opt, loss, health
            return new_params, new_state, new_opt, loss

        if not jit:
            return train_step
        return jax.jit(train_step, donate_argnums=(0, 1, 2) if donate else ())

    def make_train_steps(self, k, donate=True, jit=True, with_health=False):
        """Fused K-step engine over the graph's train step: one
        ``lax.scan`` dispatch per K minibatches (nn/fused.py; dict-keyed
        inputs/labels stack leaf-wise; ``fit(steps_per_dispatch=K)``
        drives it)."""
        from deeplearning4j_tpu.nn import fused as _fused
        return _fused.make_train_steps(self, k, donate=donate, jit=jit,
                                       with_health=with_health)

    def _fit_batches(self, inputs, labels, batch_size, mask, pad_to=None):
        """Per-epoch (inputs, labels, mask) minibatch generator over the
        dict-keyed arrays; ``pad_to`` buckets every batch to the nominal
        batch size with the validity folded into the mask (exact under
        the masked-mean losses — shape bucketing, nn/fused.py)."""
        from deeplearning4j_tpu.datasets.iterator import pad_batch

        n = next(iter(inputs.values())).shape[0]
        bs = batch_size or n
        for i in range(0, n, bs):
            bi = {k: v[i:i + bs] for k, v in inputs.items()}
            bl = {k: v[i:i + bs] for k, v in labels.items()}
            bm = mask[i:i + bs] if mask is not None else None
            if pad_to:
                bi, bl, bm, _ = pad_batch(bi, bl, bm, bs)
            yield bi, bl, bm

    def fit(self, inputs, labels, *, epochs=1, batch_size=None, mask=None,
            steps_per_dispatch=1, pad_ragged=None):
        """Train over dict-keyed (or single-array) inputs/labels.
        ``steps_per_dispatch=K`` runs K steps per device dispatch through
        the fused ``lax.scan`` engine with prefetch + shape bucketing;
        ``pad_ragged=True`` buckets the K=1 loop's ragged tail batch
        (see MultiLayerNetwork.fit for both contracts)."""
        if self.params is None:
            self.init()
        if not isinstance(inputs, dict):
            inputs = {self.conf.inputs[0]: np.asarray(inputs)}
        if not isinstance(labels, dict):
            labels = {self.conf.outputs[0]: np.asarray(labels)}
        tm = self._time_major(inputs)
        use_tbptt = (self.conf.backprop_type == "tbptt" and tm is not None
                     and tm.shape[1] > self.conf.tbptt_fwd_length)
        k = int(steps_per_dispatch)
        if k > 1 or pad_ragged:
            # shape bucketing builds ONE validity mask; a graph mixing
            # pooled ([B, C]) and time-distributed ([B, T, C]) outputs
            # would leave the mismatched head silently unmasked — refuse
            # rather than break the exactness contract
            layouts = {("pooled" if v.ndim <= 2 else ("temporal",
                                                      v.shape[1]))
                       for v in labels.values()}
            if len(layouts) > 1:
                raise ValueError(
                    "shape bucketing (steps_per_dispatch > 1 / "
                    "pad_ragged) needs a single label layout; this graph "
                    "mixes pooled / differently-lengthed time-distributed "
                    "outputs — pad the dataset to the batch size yourself "
                    "or train with steps_per_dispatch=1")
        if k > 1:
            if use_tbptt:
                raise ValueError(
                    "steps_per_dispatch > 1 does not compose with TBPTT "
                    "(the chunk loop is its own on-device scan); use the "
                    "default single-step path")
            from deeplearning4j_tpu.nn import fused as _fused
            return _fused.fit_fused(
                self,
                lambda: self._fit_batches(inputs, labels, batch_size, mask),
                epochs=epochs, k=k, batch_size=batch_size)
        if use_tbptt:
            return self._fit_tbptt_loop(inputs, labels, batch_size, mask,
                                        pad_ragged, epochs)
        # the K=1 loop is the shared StepDriver (continuous/driver.py) —
        # the MLN fit-loop body exactly (one-step-late score fetch via
        # ScorePipeline, one-late health bundles, trace handoff, flight
        # records), now resumable between rounds for the
        # continuous-learning tier
        from deeplearning4j_tpu.continuous.driver import StepDriver
        drv = StepDriver(
            self,
            lambda: self._fit_batches(inputs, labels, batch_size, mask,
                                      pad_to=bool(pad_ragged)))
        return drv.run(epochs)

    def _fit_tbptt_loop(self, inputs, labels, batch_size, mask, pad_ragged,
                        epochs):
        """Whole-fit TBPTT: every minibatch runs the chunked on-device
        scan (``_fit_tbptt``) — its own loop because the chunk scan owns
        the RNG chain and score accumulation the StepDriver engines
        otherwise drive; one macro-batch = one recorded step, the MLN
        TBPTT-branch granularity."""
        reg, step_h, _etl_h, iters_c, score_g = _tm.train_metrics()
        try:
            with _tm.span("fit", net=type(self).__name__):
                for _ in range(epochs):
                    for l in self.listeners:
                        l.on_epoch_start(self)
                    for bi, bl, bm in self._fit_batches(
                            inputs, labels, batch_size, mask,
                            pad_to=bool(pad_ragged)):
                        t_tb = time.perf_counter()
                        with _tm.span("fit.step", tbptt=True):
                            tb_score = self._fit_tbptt(bi, bl, bm)
                        if reg.enabled:
                            step_h.observe(time.perf_counter() - t_tb)
                            iters_c.inc()
                            score_g.set(tb_score)
                    for l in self.listeners:
                        l.on_epoch_end(self)
                    self.epoch += 1
        except BaseException as e:
            _flight.crash_dump(e)
            raise
        finally:
            _listeners.run_fit_end_hooks(self)
        return self

    def output(self, inputs, mask=None):
        if self.params is None:
            self.init()
        if not isinstance(inputs, dict):
            inputs = {self.conf.inputs[0]: jnp.asarray(inputs)}
        outs, _ = self._jitted_apply()(self.params, self.state, inputs, mask)
        if len(self.conf.outputs) == 1:
            return outs[self.conf.outputs[0]]
        return outs

    @functools.lru_cache(maxsize=1)
    def _jitted_apply(self):
        def fwd(params, state, inputs, mask):
            return self.apply_fn(params, state, inputs, train=False, mask=mask)
        return jax.jit(fwd)

    def score(self, inputs, labels, mask=None):
        if self.params is None:
            self.init()
        if not isinstance(inputs, dict):
            inputs = {self.conf.inputs[0]: jnp.asarray(inputs)}
        loss, _ = self.loss_fn(self.params, self.state, inputs, labels,
                               train=False, mask=mask)
        return float(loss)

    def _eval_batches(self, data, labels, batch_size):
        """(x, y, mask) batches for the evaluate family: dict-keyed
        inputs/labels (the multi-input graph form iter_batches cannot
        slice) batch by slicing every entry in step; everything else goes
        through the shared iter_batches."""
        from deeplearning4j_tpu.datasets.iterator import iter_batches

        if isinstance(data, dict):
            n = next(iter(data.values())).shape[0]
            bs = batch_size or n
            for i in range(0, n, bs):
                bx = {k: v[i:i + bs] for k, v in data.items()}
                by = ({k: v[i:i + bs] for k, v in labels.items()}
                      if isinstance(labels, dict) else labels[i:i + bs])
                yield bx, by, None
            return
        yield from iter_batches(data, labels, batch_size, None)

    def evaluate(self, data, labels=None, *, batch_size=None,
                 evaluation=None, output_name=None):
        """Classification Evaluation over arrays, an (x, y) pair, dict
        inputs/labels (multi-input graphs), or any DataSetIterator
        (reference: ComputationGraph.evaluate(DataSetIterator);
        ``output_name`` selects a head on multi-output graphs)."""
        from deeplearning4j_tpu.eval.classification import Evaluation

        e = evaluation if evaluation is not None else Evaluation()
        head = output_name or self.conf.outputs[0]
        for bx, by, bm in self._eval_batches(data, labels, batch_size):
            out = self.output(bx, mask=bm)
            pred = out[head] if isinstance(out, dict) else out
            if isinstance(by, dict):
                by = by[head]
            e.eval(np.asarray(by), np.asarray(pred),
                   mask=None if bm is None else np.asarray(bm))
        return e

    def evaluate_regression(self, data, labels=None, *, batch_size=None,
                            output_name=None):
        """RegressionEvaluation (reference:
        ComputationGraph.evaluateRegression)."""
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation

        e = RegressionEvaluation()
        head = output_name or self.conf.outputs[0]
        for bx, by, bm in self._eval_batches(data, labels, batch_size):
            out = self.output(bx, mask=bm)
            pred = out[head] if isinstance(out, dict) else out
            if isinstance(by, dict):
                by = by[head]
            e.eval(np.asarray(by), np.asarray(pred),
                   mask=None if bm is None else np.asarray(bm))
        return e

    def evaluate_roc(self, data, labels=None, *, batch_size=None,
                     threshold_steps=0, output_name=None):
        """ROC / ROCMultiClass (reference: ComputationGraph.evaluateROC /
        evaluateROCMultiClass)."""
        from deeplearning4j_tpu.eval.roc import ROC, ROCMultiClass

        roc = None
        head = output_name or self.conf.outputs[0]
        for bx, by, bm in self._eval_batches(data, labels, batch_size):
            out = self.output(bx, mask=bm)
            pred = np.asarray(out[head] if isinstance(out, dict) else out)
            if isinstance(by, dict):
                by = by[head]
            if roc is None:
                roc = (ROC(threshold_steps) if pred.shape[-1] <= 2
                       else ROCMultiClass(threshold_steps))
            roc.eval(np.asarray(by), pred,
                     mask=None if bm is None else np.asarray(bm))
        if roc is None:
            raise ValueError("no data to evaluate")
        return roc

    def num_params(self):
        return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(self.params))

    def add_listener(self, *ls):
        self.listeners.extend(ls)
        return self


class GraphBuilderModule:
    """SPI for reusable graph fragments (reference: nn/conf/module/
    GraphBuilderModule.java — "plugins and modules to generate configurations
    and layers"). Implementations append a named sub-graph (e.g. an
    inception block) to a GraphBuilder and return it, so model definitions
    compose from modules instead of repeating vertex boilerplate."""

    def module_name(self):
        """Lowercase module name, used to prefix generated layer names."""
        raise NotImplementedError

    def update_builder(self, builder, layer_name, input_size, config,
                       input_layer):
        """Append this module's layers to ``builder``.

        layer_name: base name for the generated vertices
        input_size: channel count of ``input_layer``'s activations
        config: module-specific structure (the reference passes int[][]
            filter-bank tables)
        input_layer: name of the vertex the module consumes
        Returns the builder (with the module's OUTPUT vertex added last, so
        callers can chain on builder's most recent name)."""
        raise NotImplementedError
