"""Recurrent layers: LSTM, GravesLSTM (peepholes), bidirectional, SimpleRnn,
RNN output/loss heads, LastTimeStep.

Reference analogs in /root/reference/deeplearning4j-nn/src/main/java/org/
deeplearning4j/nn/: layers/recurrent/LSTMHelpers.java:68 (activateHelper) /
:392 (backpropGradientHelper) shared by LSTM.java, GravesLSTM.java (peephole
connections), GravesBidirectionalLSTM.java; conf/layers/RnnOutputLayer.java.
The reference's fast path is CudnnLSTMHelper (fused cudnnRNN); the TPU-native
replacement is a single fused gate matmul per step inside lax.scan — x-side
projections for ALL timesteps are computed in one big MXU matmul outside the
scan, so the scan body only does the [B,H]x[H,4H] recurrent matmul.

Data layout: [batch, time, features] (batch-major); scan runs time-major
internally. Masking: a [batch, time] mask freezes state and zeroes output at
padded steps (reference: masking plumbed through activateHelper).

Gate order in the fused 4H axis: input (i), forget (f), cell candidate (g),
output (o).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import activations as _act
from deeplearning4j_tpu.nn import initializers as _init
from deeplearning4j_tpu.nn import losses as _losses
from deeplearning4j_tpu.nn.conf import inputs as _inputs
from deeplearning4j_tpu.nn.layers.base import ParamLayer, Layer
from deeplearning4j_tpu.utils import dtypes as _dtypes
from deeplearning4j_tpu.nn.layers.core import matmul
from deeplearning4j_tpu.utils.serde import register_config


@register_config
@dataclasses.dataclass(frozen=True)
class LSTM(ParamLayer):
    """params: Wx [nIn,4H], Wh [H,4H], b [4H]. forget_gate_bias init per
    reference default (GravesLSTM forgetGateBiasInit, typically 1.0)."""

    n_out: int = 0
    forget_gate_bias: float = 1.0
    gate_activation: object = "sigmoid"
    activation: object = dataclasses.field(default="tanh", kw_only=True)
    peephole: bool = False

    input_family = _inputs.RecurrentType

    WEIGHT_KEYS = ("Wx", "Wh", "Wp")
    BIAS_KEYS = ("b",)

    def output_type(self, input_type):
        assert isinstance(input_type, _inputs.RecurrentType), \
            f"{type(self).__name__} needs RNN input, got {input_type}"
        return _inputs.RecurrentType(self.n_out, input_type.timesteps)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in, h = input_type.size, self.n_out
        k1, k2, k3 = jax.random.split(key, 3)
        b = jnp.zeros((4 * h,), dtype)
        b = b.at[h:2 * h].set(self.forget_gate_bias)  # forget-gate slice
        p = {
            "Wx": _init.init_weight(self.weight_init, k1, (n_in, 4 * h), n_in, h, dtype),
            "Wh": _init.init_weight(self.weight_init, k2, (h, 4 * h), h, h, dtype),
            "b": b,
        }
        if self.peephole:
            # diagonal peephole weights for i, f, o gates (GravesLSTM)
            p["Wp"] = 0.1 * jax.random.normal(k3, (3, h), dtype)
        return p

    def _step(self, params, carry, xz_t, mask_t):
        """One scan step. xz_t: precomputed x-projection [B, 4H]."""
        h_prev, c_prev = carry
        hsz = self.n_out
        z = xz_t + matmul(h_prev, params["Wh"])
        zi, zf, zg, zo = jnp.split(z, 4, axis=-1)
        gate = _act.get(self.gate_activation)
        act = self.activation_fn()
        if self.peephole:
            wp = params["Wp"]
            zi = zi + wp[0] * c_prev
            zf = zf + wp[1] * c_prev
        i, f = gate(zi), gate(zf)
        g = act(zg)
        c = f * c_prev + i * g
        if self.peephole:
            zo = zo + params["Wp"][2] * c
        o = gate(zo)
        h = o * act(c)
        if mask_t is not None:
            m = mask_t[:, None].astype(h.dtype)
            h = m * h + (1 - m) * h_prev
            c = m * c + (1 - m) * c_prev
        return (h, c), h

    def _fused_eligible(self, x, mask):
        """Fused Pallas sequence kernel applies? (TPU backend only; the
        dispatch seam mirroring the reference's reflective cuDNN-helper
        loading at ConvolutionLayer.java:74-84 — here explicit.)"""
        try:
            from deeplearning4j_tpu.ops import lstm_pallas
        except ImportError:
            return False
        if not lstm_pallas.enabled():  # env flag + TPU backend, one place
            return False
        return lstm_pallas.supported(
            x.shape, self.n_out, peephole=self.peephole, mask=mask,
            gate_activation=self.gate_activation
            if isinstance(self.gate_activation, str) else None,
            activation=self.activation
            if isinstance(self.activation, str) else None)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None,
              initial_state=None):
        b, t, _ = x.shape
        hsz = self.n_out
        # one big MXU matmul for all timesteps' input projections
        xz = matmul(x.reshape(b * t, -1), params["Wx"]) + params["b"]
        xz = xz.reshape(b, t, 4 * hsz).transpose(1, 0, 2)  # time-major
        mask_tm = None if mask is None else mask.transpose(1, 0)
        if initial_state is None:
            h0 = jnp.zeros((b, hsz), xz.dtype)
            c0 = jnp.zeros((b, hsz), xz.dtype)
        else:
            h0, c0 = initial_state

        if self._fused_eligible(x, mask):
            from deeplearning4j_tpu.ops.lstm_pallas import fused_sequence_padded
            # the kernel interface runs in the COMPUTE dtype (bf16 under the
            # mixed policy): halves the xz/dxz HBM traffic — the f32 dxz
            # stack alone was 38% of the train step in the round-2 profile —
            # and puts the recurrent matmul on the bf16 MXU path. Cell state
            # stays f32 inside the kernel. Masked batches ride the kernel
            # too (time-major [T, B] mask; state freezes at padded steps).
            cd, _ = _dtypes.compute_dtypes_for(x.dtype)
            wp = params.get("Wp")
            hs, (hT, cT) = fused_sequence_padded(
                xz.astype(cd), params["Wh"].astype(cd), h0.astype(cd),
                c0.astype(cd), wp=None if wp is None else wp.astype(cd),
                mask=mask_tm)
        elif mask_tm is None:
            def body(carry, xz_t):
                return self._step(params, carry, xz_t, None)
            (hT, cT), hs = lax.scan(body, (h0, c0), xz)
        else:
            def body(carry, inp):
                xz_t, m_t = inp
                return self._step(params, carry, xz_t, m_t)
            (hT, cT), hs = lax.scan(body, (h0, c0), (xz, mask_tm))
        y = hs.transpose(1, 0, 2)  # back to batch-major
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        return y, state

    def step_stateful(self, params, h_c, x_t):
        """Single-step inference API (reference: RecurrentLayer.rnnTimeStep)."""
        xz = matmul(x_t, params["Wx"]) + params["b"]
        return self._step(params, h_c, xz, None)

    def zero_carry(self, batch, dtype=jnp.float32):
        z = jnp.zeros((batch, self.n_out), dtype)
        return (z, z)

    def apply_with_carry(self, params, carry, x, *, mask=None):
        """Sequence apply that also returns the final (h, c) carry — the
        TBPTT building block (reference: rnnActivateUsingStoredState /
        doTruncatedBPTT at MultiLayerNetwork.java:1252-1254)."""
        b, t, _ = x.shape
        hsz = self.n_out
        xz = matmul(x.reshape(b * t, -1), params["Wx"]) + params["b"]
        xz = xz.reshape(b, t, 4 * hsz).transpose(1, 0, 2)
        mask_tm = None if mask is None else mask.transpose(1, 0)
        if carry is None:
            carry = self.zero_carry(b, xz.dtype)

        if mask_tm is None:
            def body(c, xz_t):
                return self._step(params, c, xz_t, None)
            final, hs = lax.scan(body, carry, xz)
        else:
            def body(c, inp):
                xz_t, m_t = inp
                return self._step(params, c, xz_t, m_t)
            final, hs = lax.scan(body, carry, (xz, mask_tm))
        y = hs.transpose(1, 0, 2)
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        return y, final


@register_config
@dataclasses.dataclass(frozen=True)
class GravesLSTM(LSTM):
    """LSTM with peephole connections (reference: GravesLSTM.java, after
    Graves 2013)."""

    peephole: bool = True


@register_config
@dataclasses.dataclass(frozen=True)
class SimpleRnn(ParamLayer):
    """Vanilla tanh RNN (reference: conf/layers/... BaseRecurrentLayer simple
    form). params: Wx [nIn,H], Wh [H,H], b [H]."""

    n_out: int = 0
    activation: object = dataclasses.field(default="tanh", kw_only=True)

    input_family = _inputs.RecurrentType

    WEIGHT_KEYS = ("Wx", "Wh")
    BIAS_KEYS = ("b",)

    def output_type(self, input_type):
        return _inputs.RecurrentType(self.n_out, input_type.timesteps)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in, h = input_type.size, self.n_out
        k1, k2 = jax.random.split(key)
        return {
            "Wx": _init.init_weight(self.weight_init, k1, (n_in, h), n_in, h, dtype),
            "Wh": _init.init_weight(self.weight_init, k2, (h, h), h, h, dtype),
            "b": jnp.zeros((h,), dtype),
        }

    def apply(self, params, state, x, *, train=False, rng=None, mask=None,
              initial_state=None):
        b, t, _ = x.shape
        act = self.activation_fn()
        xz = (matmul(x.reshape(b * t, -1), params["Wx"]) + params["b"]).reshape(b, t, -1)
        xz = xz.transpose(1, 0, 2)
        mask_tm = None if mask is None else mask.transpose(1, 0)
        h0 = initial_state if initial_state is not None else jnp.zeros((b, self.n_out), xz.dtype)

        def body(h_prev, inp):
            if mask_tm is None:
                xz_t, m_t = inp, None
            else:
                xz_t, m_t = inp
            h = act(xz_t + matmul(h_prev, params["Wh"]))
            if m_t is not None:
                m = m_t[:, None].astype(h.dtype)
                h = m * h + (1 - m) * h_prev
            return h, h

        _, hs = lax.scan(body, h0, xz if mask_tm is None else (xz, mask_tm))
        y = hs.transpose(1, 0, 2)
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        return y, state

    def zero_carry(self, batch, dtype=jnp.float32):
        return jnp.zeros((batch, self.n_out), dtype)

    def apply_with_carry(self, params, carry, x, *, mask=None):
        b = x.shape[0]
        if carry is None:
            carry = self.zero_carry(b, x.dtype)
        y, _ = self.apply(params, {}, x, mask=mask, initial_state=carry)
        # final hidden = last (mask-aware) output
        if mask is None:
            final = y[:, -1, :]
        else:
            idx = jnp.maximum(jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0)
            final = y[jnp.arange(b), idx, :]
        return y, final


@register_config
@dataclasses.dataclass(frozen=True)
class Bidirectional(Layer):
    """Wrapper running a recurrent layer forward + backward over time.

    Reference: nn/conf/layers/recurrent Bidirectional wrapper &
    GravesBidirectionalLSTM.java. ``mode``: concat | add | mul | ave.
    Backward pass respects the mask by reversing only valid steps.
    """

    layer: object = None
    mode: str = "concat"

    input_family = _inputs.RecurrentType

    def output_type(self, input_type):
        inner = self.layer.output_type(input_type)
        if self.mode == "concat":
            return _inputs.RecurrentType(inner.size * 2, inner.timesteps)
        return inner

    def init(self, key, input_type, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        return {"fwd": self.layer.init(k1, input_type, dtype),
                "bwd": self.layer.init(k2, input_type, dtype)}

    def regularization_penalty(self, params):
        return (self.layer.regularization_penalty(params["fwd"]) +
                self.layer.regularization_penalty(params["bwd"]))

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        yf, _ = self.layer.apply(params["fwd"], {}, x, train=train, rng=rng, mask=mask)
        xr = jnp.flip(x, axis=1)
        mr = None if mask is None else jnp.flip(mask, axis=1)
        yb, _ = self.layer.apply(params["bwd"], {}, xr, train=train, rng=rng, mask=mr)
        yb = jnp.flip(yb, axis=1)
        if self.mode == "concat":
            y = jnp.concatenate([yf, yb], axis=-1)
        elif self.mode == "add":
            y = yf + yb
        elif self.mode == "mul":
            y = yf * yb
        elif self.mode == "ave":
            y = 0.5 * (yf + yb)
        else:
            raise ValueError(f"Unknown Bidirectional mode {self.mode!r}")
        return y, state


@register_config
@dataclasses.dataclass(frozen=True)
class GravesBidirectionalLSTM(Layer):
    """Convenience: Bidirectional(GravesLSTM) with concat output
    (reference: GravesBidirectionalLSTM.java)."""

    n_out: int = 0
    activation: object = "tanh"
    weight_init: object = "xavier"

    input_family = _inputs.RecurrentType

    def _inner(self):
        return Bidirectional(layer=GravesLSTM(n_out=self.n_out, activation=self.activation,
                                              weight_init=self.weight_init), mode="concat")

    def output_type(self, input_type):
        return self._inner().output_type(input_type)

    def init(self, key, input_type, dtype=jnp.float32):
        return self._inner().init(key, input_type, dtype)

    def regularization_penalty(self, params):
        return self._inner().regularization_penalty(params)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self._inner().apply(params, state, x, train=train, rng=rng, mask=mask)


@register_config
@dataclasses.dataclass(frozen=True)
class RnnOutputLayer(ParamLayer):
    """Per-timestep dense + loss (reference: conf/layers/RnnOutputLayer.java).
    Applies [B,T,F]x[F,O] as one flattened MXU matmul."""

    n_out: int = 0
    loss: object = "mcxent"
    activation: object = dataclasses.field(default="softmax", kw_only=True)

    input_family = _inputs.RecurrentType

    def output_type(self, input_type):
        return _inputs.RecurrentType(self.n_out, input_type.timesteps)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = input_type.size
        return {"W": _init.init_weight(self.weight_init, key, (n_in, self.n_out),
                                       n_in, self.n_out, dtype),
                "b": jnp.full((self.n_out,), self.bias_init, dtype)}

    def apply(self, params, state, x, *, train=False, rng=None):
        b, t, f = x.shape
        z = matmul(x.reshape(b * t, f), params["W"]) + params["b"]
        return self.activation_fn()(z.reshape(b, t, self.n_out)), state

    def compute_loss(self, predictions, labels, mask=None):
        return _losses.get(self.loss)(predictions, labels, mask)


@register_config
@dataclasses.dataclass(frozen=True)
class RnnLossLayer(Layer):
    """Parameterless per-timestep loss (reference: conf/layers/RnnLossLayer.java)."""

    loss: object = "mcxent"
    activation: object = "identity"

    input_family = _inputs.RecurrentType

    def output_type(self, input_type):
        return input_type

    def apply(self, params, state, x, *, train=False, rng=None):
        return _act.get(self.activation)(x), state

    def compute_loss(self, predictions, labels, mask=None):
        return _losses.get(self.loss)(predictions, labels, mask)


@register_config
@dataclasses.dataclass(frozen=True)
class LastTimeStep(Layer):
    """Extract the last (mask-aware) timestep: [B,T,F] -> [B,F]
    (reference: conf/graph/rnn/LastTimeStepVertex.java)."""

    input_family = _inputs.RecurrentType

    def output_type(self, input_type):
        return _inputs.FeedForwardType(input_type.size)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if mask is None:
            return x[:, -1, :], state
        idx = jnp.maximum(jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0)
        return x[jnp.arange(x.shape[0]), idx, :], state
