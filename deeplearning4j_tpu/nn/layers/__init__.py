from deeplearning4j_tpu.nn.layers.base import Layer, ParamLayer  # noqa: F401
from deeplearning4j_tpu.nn.layers.core import (  # noqa: F401
    DenseLayer, OutputLayer, LossLayer, ActivationLayer, DropoutLayer,
    EmbeddingLayer, EmbeddingSequenceLayer, AutoEncoder,
    TimeDistributedDenseLayer,
)
from deeplearning4j_tpu.nn.layers.conv import (  # noqa: F401
    ConvolutionLayer, Convolution1DLayer, Deconvolution2DLayer,
    SeparableConvolution2DLayer, SubsamplingLayer, Subsampling1DLayer,
    Upsampling1DLayer, Upsampling2DLayer, ZeroPaddingLayer, ZeroPadding1DLayer,
    BatchNormalization, LocalResponseNormalization, GlobalPoolingLayer,
    SpaceToDepthLayer, SpaceToBatchLayer, ResidualBottleneck,
)
from deeplearning4j_tpu.nn.layers.rnn import (  # noqa: F401
    LSTM, GravesLSTM, GravesBidirectionalLSTM, SimpleRnn, RnnOutputLayer,
    RnnLossLayer, LastTimeStep, Bidirectional,
)
from deeplearning4j_tpu.nn.layers.vae import (  # noqa: F401
    VariationalAutoencoder, GaussianReconstruction, BernoulliReconstruction,
    ExponentialReconstruction, CompositeReconstruction,
    LossWrapperReconstruction,
)
from deeplearning4j_tpu.nn.layers.objdetect import Yolo2OutputLayer  # noqa: F401
from deeplearning4j_tpu.nn.layers.centerloss import CenterLossOutputLayer  # noqa: F401
from deeplearning4j_tpu.nn.layers.attention import (  # noqa: F401
    LayerNormalization, MultiHeadAttention, TransformerBlock,
)
from deeplearning4j_tpu.nn.layers.moe import MoETransformerBlock  # noqa: F401
