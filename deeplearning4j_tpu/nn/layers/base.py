"""Layer protocol.

Reference analog: the conf/impl split in dl4j (nn/conf/layers/*.java configs +
nn/layers/*.java implementations, /root/reference/deeplearning4j-nn). In the
TPU-native design a layer IS its config: a frozen dataclass carrying
hyperparameters plus pure functions

    output_type(input_type)                  -> InputType      (shape inference)
    init(key, input_type, dtype)             -> params dict    (pytree leaf dicts)
    init_state(input_type, dtype)            -> state dict     (e.g. BN running stats)
    apply(params, state, x, *, train, rng)   -> (y, new_state)

There is no mutable object state: parameters and mutable statistics live in
pytrees threaded by the network, so the whole forward/backward is jit-compiled
in one XLA computation (the reference instead crosses JVM->JNI per op).

Regularization fields (l1/l2/dropout/constraints) are consumed by the network:
l1/l2 are added to the loss over this layer's regularizable params
(reference: BaseLayer.calcL1/calcL2), dropout is applied to the layer INPUT
during training (reference: BaseLayer.applyDropOutIfNecessary semantics, with
inverted scaling), constraints are projections applied post-update.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as _act
from deeplearning4j_tpu.nn.conf import inputs as _inputs


@dataclasses.dataclass(frozen=True)
class Layer:
    """Base: a parameterless layer. Fields are hyperparameters only."""

    name: str | None = dataclasses.field(default=None, kw_only=True)
    dropout: float = dataclasses.field(default=0.0, kw_only=True)  # drop probability on layer input

    # which input family this layer consumes; the network auto-adapts
    input_family = _inputs.FeedForwardType

    def output_type(self, input_type):
        return input_type

    def init(self, key, input_type, dtype=jnp.float32):
        return {}

    def init_state(self, input_type, dtype=jnp.float32):
        return {}

    def apply(self, params, state, x, *, train=False, rng=None):
        return x, state

    # ---- regularization hooks consumed by the network ----
    def regularization_penalty(self, params):
        return 0.0

    def apply_constraints(self, params, iteration, epoch):
        return params


@dataclasses.dataclass(frozen=True)
class ParamLayer(Layer):
    """Base for layers with weights: activation + init + L1/L2 + constraints."""

    activation: object = dataclasses.field(default="identity", kw_only=True)
    weight_init: object = dataclasses.field(default="xavier", kw_only=True)
    bias_init: float = dataclasses.field(default=0.0, kw_only=True)
    l1: float = dataclasses.field(default=0.0, kw_only=True)
    l2: float = dataclasses.field(default=0.0, kw_only=True)
    l1_bias: float = dataclasses.field(default=0.0, kw_only=True)
    l2_bias: float = dataclasses.field(default=0.0, kw_only=True)
    constraints: tuple = dataclasses.field(default=(), kw_only=True)
    weight_noise: object = dataclasses.field(default=None, kw_only=True)

    WEIGHT_KEYS = ("W",)
    BIAS_KEYS = ("b",)

    def activation_fn(self):
        return _act.get(self.activation)

    def regularization_penalty(self, params):
        """L1/L2 on weights, separate coefficients for biases (reference:
        BaseLayer.calcL1/calcL2 exclude biases unless l1Bias/l2Bias set)."""
        pen = 0.0
        for k, v in params.items():
            if k in self.BIAS_KEYS:
                if self.l1_bias:
                    pen = pen + self.l1_bias * jnp.sum(jnp.abs(v))
                if self.l2_bias:
                    pen = pen + 0.5 * self.l2_bias * jnp.sum(v * v)
            else:
                if self.l1:
                    pen = pen + self.l1 * jnp.sum(jnp.abs(v))
                if self.l2:
                    pen = pen + 0.5 * self.l2 * jnp.sum(v * v)
        return pen

    def apply_constraints(self, params, iteration, epoch):
        out = params
        for c in self.constraints:
            out = c.apply(self, out, iteration, epoch)
        return out


def pop_aux_losses(loss, states):
    """(loss + popped aux terms, cleaned states).

    Contract for input-dependent layer losses (MoE load balancing): a layer
    stashes the term in its per-step state under ``"aux_loss"``; the
    container's loss function pops it here so the PERSISTENT state structure
    stays stable across steps (jit/scan/donation invariant). ``states`` is a
    list of per-layer dicts (MultiLayerNetwork) or a dict keyed by vertex
    name (ComputationGraph).
    """
    items = (list(states.items()) if isinstance(states, dict)
             else list(enumerate(states)))
    out = dict(states) if isinstance(states, dict) else list(states)
    for k, s in items:
        if isinstance(s, dict) and "aux_loss" in s:
            s = dict(s)
            loss = loss + s.pop("aux_loss")
            out[k] = s
    return loss, out


def dropout_mask(rng, x, rate):
    """Inverted dropout: scale retained units by 1/(1-rate)."""
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)
