"""Mixture-of-Experts transformer block with expert parallelism.

Reference analog: none — DL4J has no MoE (nor attention); net-new for the
TPU scale goals, completing the dp/tp/sp/pp/ep parallelism set (driver
contract: __graft_entry__.dryrun_multichip exercises every axis).

Design (Switch-Transformer style, TPU-first):
* Top-1 router with a capacity limit: tokens route to their argmax expert,
  each expert processes at most C = ceil(tokens/E * capacity_factor);
  overflow tokens pass through the residual unchanged (standard Switch
  semantics — keeps every shape static for XLA).
* Dispatch/combine are dense einsums against a [N, E, C] one-hot dispatch
  tensor — gather-free, MXU-friendly, and differentiable through the
  router probabilities (combine carries the router prob).
* Expert weights are STACKED with a leading expert axis. Under a mesh,
  sharding that axis over ``model`` (see parallel/data_parallel.py's
  param-spec rule) makes GSPMD partition the per-expert einsums and insert
  the all-to-alls — expert parallelism without manual collectives.
* Load-balancing auxiliary loss (Switch eq. 4): E * sum_e f_e * p_e, where
  f_e is the fraction of tokens dispatched to expert e and p_e the mean
  router probability — exposed via ``aux_loss`` in the layer state so the
  container can add it to the objective.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as _act
from deeplearning4j_tpu.nn import initializers as _init
from deeplearning4j_tpu.nn.conf import inputs as _inputs
from deeplearning4j_tpu.nn.layers.attention import (LayerNormalization,
                                                    MultiHeadAttention)
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.utils.serde import register_config


@register_config
@dataclasses.dataclass(frozen=True)
class MoETransformerBlock(Layer):
    """Pre-norm block: LN -> MHA -> residual, LN -> MoE-MLP -> residual.

    The MoE-MLP replaces TransformerBlock's dense MLP with ``n_experts``
    expert MLPs behind a top-1 router.
    """

    n_out: int = 0
    n_heads: int = 4
    n_experts: int = 4
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    causal: bool = False
    activation: object = "gelu"

    input_family = _inputs.RecurrentType

    def _parts(self):
        return (LayerNormalization(),
                MultiHeadAttention(n_out=self.n_out, n_heads=self.n_heads,
                                   causal=self.causal),
                LayerNormalization())

    def output_type(self, input_type):
        return _inputs.RecurrentType(self.n_out, input_type.timesteps)

    def init(self, key, input_type, dtype=jnp.float32):
        assert input_type.size == self.n_out, \
            "MoETransformerBlock requires input size == n_out (residual)"
        ln1, mha, ln2 = self._parts()
        k1, k1b, k2, k3, k4, k5 = jax.random.split(key, 6)
        d, e = self.n_out, self.n_experts
        hidden = d * self.mlp_ratio
        it = _inputs.RecurrentType(d, input_type.timesteps)

        def expert_stack(k, shape, fan_in, fan_out):
            ks = jax.random.split(k, e)
            return jnp.stack([_init.init_weight("xavier", kk, shape,
                                                fan_in, fan_out, dtype)
                              for kk in ks])

        return {
            "ln1": ln1.init(k1, it, dtype),
            "mha": mha.init(k1b, it, dtype),
            "ln2": ln2.init(k2, it, dtype),
            "router_W": _init.init_weight("xavier", k3, (d, e), d, e, dtype),
            "expert_W1": expert_stack(k4, (d, hidden), d, hidden),
            "expert_b1": jnp.zeros((e, hidden), dtype),
            "expert_W2": expert_stack(k5, (hidden, d), hidden, d),
            "expert_b2": jnp.zeros((e, d), dtype),
        }

    def _moe_mlp(self, params, x2d):
        """x2d [N, d] -> (y [N, d], aux_loss scalar)."""
        e = self.n_experts
        n = x2d.shape[0]
        cap = int(-(-n // e) * self.capacity_factor) or 1

        logits = x2d.astype(jnp.float32) @ params["router_W"].astype(
            jnp.float32)                                   # [N, E]
        probs = jax.nn.softmax(logits, axis=-1)
        top = jnp.argmax(probs, axis=-1)                   # [N]
        onehot = jax.nn.one_hot(top, e, dtype=jnp.float32)  # [N, E]

        # position of each token within its expert's queue (Switch capacity)
        pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0    # [N, E], -1 if not routed
        keep = (pos >= 0) & (pos < cap)
        pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, cap - 1).astype(jnp.int32),
                                cap, dtype=jnp.float32)    # [N, E, C]
        dispatch = pos_oh * keep[..., None]                # [N, E, C]
        gate = jnp.sum(probs * onehot, axis=-1)            # [N] router prob
        combine = dispatch * gate[:, None, None]           # [N, E, C]

        # dispatch -> per-expert batches -> expert MLPs -> combine
        xe = jnp.einsum("nec,nd->ecd", dispatch, x2d.astype(jnp.float32))
        act = _act.get(self.activation)
        h = act(jnp.einsum("ecd,edh->ech", xe,
                           params["expert_W1"].astype(jnp.float32))
                + params["expert_b1"][:, None].astype(jnp.float32))
        ye = jnp.einsum("ech,ehd->ecd", h,
                        params["expert_W2"].astype(jnp.float32)) \
            + params["expert_b2"][:, None].astype(jnp.float32)
        y = jnp.einsum("nec,ecd->nd", combine, ye)         # [N, d]

        # Switch load-balancing loss: E * sum_e (fraction routed) * (mean prob)
        frac = jnp.mean(onehot, axis=0)
        mean_p = jnp.mean(probs, axis=0)
        aux = e * jnp.sum(frac * mean_p)
        return y.astype(x2d.dtype), aux

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        ln1, mha, ln2 = self._parts()
        h, _ = ln1.apply(params["ln1"], {}, x)
        attn, _ = mha.apply(params["mha"], {}, h, mask=mask)
        x = x + attn
        h, _ = ln2.apply(params["ln2"], {}, x)
        b, t, d = h.shape
        y, aux = self._moe_mlp(params, h.reshape(b * t, d))
        out_state = state
        if train:
            # input-dependent loss term: stashed in state for ONE step; the
            # container's loss_fn pops it (state structure stays stable)
            out_state = dict(state)
            out_state["aux_loss"] = self.aux_loss_weight * aux
        return x + y.reshape(b, t, d), out_state

    def regularization_penalty(self, params):
        return 0.0
