"""Core feed-forward layers: Dense, Output/Loss, Activation, Dropout, Embedding, AutoEncoder.

Reference analogs in /root/reference/deeplearning4j-nn/src/main/java/org/
deeplearning4j/nn/: conf/layers/DenseLayer.java + layers/BaseLayer.java:123
(preOutput: z = xW + b), conf/layers/OutputLayer.java + layers/BaseOutputLayer
(loss attached), conf/layers/EmbeddingLayer.java, conf/layers/AutoEncoder.java.

TPU notes: matmuls run in the compute dtype (bf16 on TPU) with f32
accumulation via preferred_element_type — the MXU-native path. The embedding
forward is a gather (jnp.take), whose VJP is a scatter-add that XLA lowers
natively; no host round-trip like the reference's JNI hop.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import initializers as _init
from deeplearning4j_tpu.nn import losses as _losses
from deeplearning4j_tpu.nn.conf import inputs as _inputs
from deeplearning4j_tpu.nn.layers.base import ParamLayer, Layer
from deeplearning4j_tpu.utils import dtypes as _dtypes
from deeplearning4j_tpu.utils.serde import register_config


def matmul(x, w):
    """Compute-dtype matmul with f32 accumulation (MXU path); float64 stays
    float64 for gradient checking."""
    cd, ad = _dtypes.compute_dtypes_for(x.dtype)
    return lax.dot(x.astype(cd), w.astype(cd), preferred_element_type=ad)


@register_config
@dataclasses.dataclass(frozen=True)
class DenseLayer(ParamLayer):
    n_out: int = 0
    has_bias: bool = True

    input_family = _inputs.FeedForwardType

    def output_type(self, input_type):
        it = _inputs.adapted_type(input_type, _inputs.FeedForwardType)
        return _inputs.FeedForwardType(self.n_out)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = _inputs.adapted_type(input_type, _inputs.FeedForwardType).size
        p = {"W": _init.init_weight(self.weight_init, key, (n_in, self.n_out),
                                    n_in, self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params, state, x, *, train=False, rng=None):
        z = matmul(x, params["W"])
        if self.has_bias:
            z = z + params["b"]
        return self.activation_fn()(z), state


@register_config
@dataclasses.dataclass(frozen=True)
class OutputLayer(DenseLayer):
    """Dense + loss head (reference: conf/layers/OutputLayer.java; score at
    MultiLayerNetwork.java:2307)."""

    loss: object = "mcxent"
    activation: object = dataclasses.field(default="softmax", kw_only=True)

    def compute_loss(self, predictions, labels, mask=None):
        return _losses.get(self.loss)(predictions, labels, mask)


@register_config
@dataclasses.dataclass(frozen=True)
class LossLayer(Layer):
    """Parameterless loss head (reference: conf/layers/LossLayer.java)."""

    loss: object = "mcxent"
    activation: object = "identity"

    input_family = _inputs.FeedForwardType

    def output_type(self, input_type):
        return _inputs.adapted_type(input_type, _inputs.FeedForwardType)

    def apply(self, params, state, x, *, train=False, rng=None):
        from deeplearning4j_tpu.nn import activations as _act
        return _act.get(self.activation)(x), state

    def compute_loss(self, predictions, labels, mask=None):
        return _losses.get(self.loss)(predictions, labels, mask)


@register_config
@dataclasses.dataclass(frozen=True)
class ActivationLayer(Layer):
    """(reference: conf/layers/ActivationLayer.java)"""

    activation: object = "relu"

    input_family = None  # accepts any family unchanged

    def output_type(self, input_type):
        return input_type

    def apply(self, params, state, x, *, train=False, rng=None):
        from deeplearning4j_tpu.nn import activations as _act
        return _act.get(self.activation)(x), state


@register_config
@dataclasses.dataclass(frozen=True)
class DropoutLayer(Layer):
    """Standalone dropout (reference: conf/layers/DropoutLayer.java). The
    ``kind`` selects the reference's dropout variants (nn/conf/dropout/):
    dropout | alpha (SELU-preserving) | gaussian_dropout | gaussian_noise."""

    rate: float = 0.5
    kind: str = "dropout"

    input_family = None  # accepts any family unchanged

    def output_type(self, input_type):
        return input_type

    def apply(self, params, state, x, *, train=False, rng=None):
        if not train or self.rate <= 0.0 or rng is None:
            return x, state
        import jax
        if self.kind == "dropout":
            keep = 1.0 - self.rate
            mask = jax.random.bernoulli(rng, keep, x.shape)
            return jnp.where(mask, x / keep, 0.0), state
        if self.kind == "alpha":
            # SELU alpha-dropout (reference: nn/conf/dropout/AlphaDropout.java)
            alpha_p = -1.7580993408473766
            keep = 1.0 - self.rate
            a = (keep + alpha_p**2 * keep * (1 - keep)) ** -0.5
            b = -a * alpha_p * (1 - keep)
            mask = jax.random.bernoulli(rng, keep, x.shape)
            return a * jnp.where(mask, x, alpha_p) + b, state
        if self.kind == "gaussian_dropout":
            std = (self.rate / (1.0 - self.rate)) ** 0.5
            noise = 1.0 + std * jax.random.normal(rng, x.shape, x.dtype)
            return x * noise, state
        if self.kind == "gaussian_noise":
            return x + self.rate * jax.random.normal(rng, x.shape, x.dtype), state
        raise ValueError(f"Unknown dropout kind {self.kind!r}")


@register_config
@dataclasses.dataclass(frozen=True)
class EmbeddingLayer(ParamLayer):
    """Index -> vector lookup (reference: conf/layers/EmbeddingLayer.java;
    input is integer class indices, output [batch, n_out]).

    Forward = gather; backward = scatter-add, both native XLA ops on TPU
    (the reference routes this through libnd4j JNI)."""

    n_in: int = 0  # vocab size
    n_out: int = 0
    has_bias: bool = False
    weight_init: object = dataclasses.field(default="xavier", kw_only=True)

    input_family = _inputs.FeedForwardType

    def output_type(self, input_type):
        return _inputs.FeedForwardType(self.n_out)

    def init(self, key, input_type, dtype=jnp.float32):
        p = {"W": _init.init_weight(self.weight_init, key, (self.n_in, self.n_out),
                                    self.n_in, self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params, state, x, *, train=False, rng=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[:, 0]
        z = jnp.take(params["W"], idx, axis=0)
        if self.has_bias:
            z = z + params["b"]
        return self.activation_fn()(z), state


@register_config
@dataclasses.dataclass(frozen=True)
class TimeDistributedDenseLayer(DenseLayer):
    """Dense applied independently at every timestep: [B, T, F] ->
    [B, T, n_out], time axis preserved (reference analog: Keras-1
    TimeDistributedDense / DL4J's DenseLayer wrapped in RnnToFeedForward +
    FeedForwardToRnn preprocessors — here the matmul simply broadcasts
    over the leading axes, no fold/unfold round-trip)."""

    input_family = _inputs.RecurrentType

    def output_type(self, input_type):
        return _inputs.RecurrentType(self.n_out, input_type.timesteps)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = input_type.size
        p = {"W": _init.init_weight(self.weight_init, key,
                                    (n_in, self.n_out),
                                    n_in, self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params, state, x, *, train=False, rng=None):
        b, t, f = x.shape
        z = matmul(x.reshape(b * t, f), params["W"]).reshape(
            b, t, self.n_out)
        if self.has_bias:
            z = z + params["b"]
        return self.activation_fn()(z), state


@register_config
@dataclasses.dataclass(frozen=True)
class EmbeddingSequenceLayer(ParamLayer):
    """Per-timestep index -> vector lookup for sequence models: [B, T] (or
    [B, T, 1]) integer ids -> [B, T, n_out], with an optional learned
    positional embedding added (reference analog: EmbeddingSequenceLayer —
    the sequence form of EmbeddingLayer; positions are net-new for the
    transformer tier)."""

    n_in: int = 0   # vocab size
    n_out: int = 0
    add_positional: bool = False
    weight_init: object = dataclasses.field(default="xavier", kw_only=True)

    input_family = _inputs.RecurrentType

    def output_type(self, input_type):
        return _inputs.RecurrentType(self.n_out, input_type.timesteps)

    def init(self, key, input_type, dtype=jnp.float32):
        import jax
        k1, k2 = jax.random.split(key)
        p = {"W": _init.init_weight(self.weight_init, k1,
                                    (self.n_in, self.n_out),
                                    self.n_in, self.n_out, dtype)}
        if self.add_positional:
            if input_type.timesteps is None:
                raise ValueError("add_positional requires a fixed timesteps "
                                 "in the RecurrentType input")
            p["P"] = _init.init_weight(
                self.weight_init, k2, (input_type.timesteps, self.n_out),
                input_type.timesteps, self.n_out, dtype)
        return p

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3:
            idx = idx[..., 0]
        z = jnp.take(params["W"], idx, axis=0)      # [B, T, D]
        if "P" in params:
            z = z + params["P"][None, :z.shape[1]]
        if mask is not None:
            z = z * mask[..., None].astype(z.dtype)
        return self.activation_fn()(z), state


@register_config
@dataclasses.dataclass(frozen=True)
class AutoEncoder(ParamLayer):
    """Denoising autoencoder layer (reference: conf/layers/AutoEncoder.java +
    layers/feedforward/autoencoder/AutoEncoder.java). In supervised stacks it
    behaves as a dense encoder; ``reconstruct``/``pretrain_loss`` expose the
    unsupervised path (corrupt -> encode -> decode -> reconstruction loss)."""

    n_out: int = 0
    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: object = "mse"
    activation: object = dataclasses.field(default="sigmoid", kw_only=True)

    input_family = _inputs.FeedForwardType

    def output_type(self, input_type):
        return _inputs.FeedForwardType(self.n_out)

    def init(self, key, input_type, dtype=jnp.float32):
        import jax
        n_in = _inputs.adapted_type(input_type, _inputs.FeedForwardType).size
        k1, _ = jax.random.split(key)
        return {
            "W": _init.init_weight(self.weight_init, k1, (n_in, self.n_out), n_in, self.n_out, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
            "vb": jnp.zeros((n_in,), dtype),  # visible bias for the decode path
        }

    def apply(self, params, state, x, *, train=False, rng=None):
        z = matmul(x, params["W"]) + params["b"]
        return self.activation_fn()(z), state

    def reconstruct(self, params, x):
        h, _ = self.apply(params, {}, x)
        z = matmul(h, params["W"].T) + params["vb"]
        return self.activation_fn()(z)

    def pretrain_loss(self, params, x, rng):
        import jax
        corrupted = x
        if self.corruption_level > 0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        recon = self.reconstruct(params, corrupted)
        return _losses.get(self.loss)(recon, x)
