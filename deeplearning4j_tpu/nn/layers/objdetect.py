"""YOLOv2 object-detection output layer.

Reference analog: nn/conf/layers/objdetect/Yolo2OutputLayer.java + nn/layers/
objdetect/Yolo2OutputLayer.java (721 LoC) + DetectedObject.java in
/root/reference/deeplearning4j-nn.

Input: conv activations [B, H, W, A*(5+C)] (NHWC; A = anchors, 5 = tx ty tw
th confidence). Labels: [B, H, W, 5+C] per grid cell — (indicator, cx, cy, w,
h in grid units) + one-hot class; indicator 1 marks the cell containing an
object center. Loss (Redmon et al. YOLOv2, same structure as the reference):
  lambda_coord * position/size MSE (sqrt on w/h)
+ confidence MSE toward IOU (lambda_noobj on empty cells)
+ class cross-entropy on object cells.
The responsible anchor per object cell is the one with best IOU against the
ground-truth box — computed with pure array ops (argmax over the anchor
axis), jit-friendly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import inputs as _inputs
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.utils.serde import register_config


def _iou_wh(w1, h1, w2, h2):
    """IOU of boxes sharing a center."""
    inter = jnp.minimum(w1, w2) * jnp.minimum(h1, h2)
    union = w1 * h1 + w2 * h2 - inter
    return inter / jnp.maximum(union, 1e-9)


@register_config
@dataclasses.dataclass(frozen=True)
class Yolo2OutputLayer(Layer):
    anchors: tuple = ((1.0, 1.0), (2.0, 2.0))  # (w, h) in grid units
    lambda_coord: float = 5.0
    lambda_noobj: float = 0.5

    input_family = _inputs.ConvolutionalType

    @property
    def n_anchors(self):
        return len(self.anchors)

    def output_type(self, input_type):
        return input_type

    def apply(self, params, state, x, *, train=False, rng=None):
        return x, state

    def _decode(self, x):
        """Raw conv output -> per-anchor (xy in [0,1], wh in grid units,
        confidence, class probs)."""
        b, h, w, _ = x.shape
        a = self.n_anchors
        x = x.reshape(b, h, w, a, -1)
        txy = jax.nn.sigmoid(x[..., 0:2])
        anchors = jnp.asarray(self.anchors, x.dtype)  # [A, 2]
        twh = jnp.exp(jnp.clip(x[..., 2:4], -8, 8)) * anchors
        conf = jax.nn.sigmoid(x[..., 4])
        cls = jax.nn.softmax(x[..., 5:], axis=-1)
        return txy, twh, conf, cls

    def compute_loss(self, predictions, labels, mask=None):
        txy, twh, conf, cls = self._decode(predictions)
        b, h, w, a, _ = txy.shape
        indicator = labels[..., 0]                     # [B,H,W]
        gt_xy = labels[..., 1:3]                       # offsets within cell [0,1]
        gt_wh = labels[..., 3:5]                       # grid units
        gt_cls = labels[..., 5:]

        # responsible anchor: best IOU(anchor prior, gt box) per object cell
        anchors = jnp.asarray(self.anchors, predictions.dtype)
        prior_iou = _iou_wh(anchors[None, None, None, :, 0], anchors[None, None, None, :, 1],
                            gt_wh[..., None, 0], gt_wh[..., None, 1])  # [B,H,W,A]
        best = jnp.argmax(prior_iou, axis=-1)          # [B,H,W]
        resp = jax.nn.one_hot(best, a, dtype=predictions.dtype) * indicator[..., None]

        # position/size loss (sqrt w/h like the paper & reference)
        pos = jnp.sum((txy - gt_xy[..., None, :]) ** 2, axis=-1)
        size = jnp.sum((jnp.sqrt(twh) - jnp.sqrt(gt_wh[..., None, :])) ** 2, axis=-1)
        loss_coord = self.lambda_coord * jnp.sum(resp * (pos + size))

        # confidence toward IOU(predicted box, gt box)
        pred_iou = _iou_wh(twh[..., 0], twh[..., 1],
                           gt_wh[..., None, 0], gt_wh[..., None, 1])
        loss_obj = jnp.sum(resp * (conf - pred_iou) ** 2)
        loss_noobj = self.lambda_noobj * jnp.sum((1.0 - resp) * conf**2)

        # class cross-entropy on object cells
        ce = -jnp.sum(gt_cls[..., None, :] * jnp.log(jnp.clip(cls, 1e-9, 1.0)), axis=-1)
        loss_cls = jnp.sum(resp * ce)

        return (loss_coord + loss_obj + loss_noobj + loss_cls) / b

    def get_predicted_objects(self, predictions, threshold=0.5):
        """Detections above a confidence threshold (host-side; reference:
        YoloUtils.getPredictedObjects). Returns list per batch element of
        (conf, cx, cy, w, h, class_idx) in grid units."""
        import numpy as np
        txy, twh, conf, cls = self._decode(predictions)
        txy, twh = np.asarray(txy), np.asarray(twh)
        conf, cls = np.asarray(conf), np.asarray(cls)
        b, h, w, a = conf.shape
        out = []
        for bi in range(b):
            dets = []
            ys, xs, ans = np.where(conf[bi] > threshold)
            for y, x, an in zip(ys, xs, ans):
                cx = x + txy[bi, y, x, an, 0]
                cy = y + txy[bi, y, x, an, 1]
                bw, bh = twh[bi, y, x, an]
                dets.append((float(conf[bi, y, x, an]), float(cx), float(cy),
                             float(bw), float(bh), int(np.argmax(cls[bi, y, x, an]))))
            out.append(dets)
        return out


def box_iou(box1, box2):
    """IoU of two (cx, cy, w, h) boxes (grid units)."""
    l1, r1 = box1[0] - box1[2] / 2, box1[0] + box1[2] / 2
    t1, b1 = box1[1] - box1[3] / 2, box1[1] + box1[3] / 2
    l2, r2 = box2[0] - box2[2] / 2, box2[0] + box2[2] / 2
    t2, b2 = box2[1] - box2[3] / 2, box2[1] + box2[3] / 2
    iw = max(0.0, min(r1, r2) - max(l1, l2))
    ih = max(0.0, min(b1, b2) - max(t1, t2))
    inter = iw * ih
    union = box1[2] * box1[3] + box2[2] * box2[3] - inter
    return inter / union if union > 0 else 0.0


def non_max_suppression(detections, iou_threshold=0.5):
    """Greedy per-class NMS over (conf, cx, cy, w, h, class_idx) detections
    (one image's list, as produced by get_predicted_objects): keep the
    highest-confidence box, drop same-class boxes overlapping it above the
    IoU threshold, repeat."""
    remaining = sorted(detections, key=lambda d: -d[0])
    kept = []
    while remaining:
        best = remaining.pop(0)
        kept.append(best)
        remaining = [d for d in remaining
                     if d[5] != best[5]
                     or box_iou(best[1:5], d[1:5]) < iou_threshold]
    return kept
