"""Convolutional family: conv, pooling, upsampling, padding, BN, LRN, global pooling.

Reference analogs in /root/reference/deeplearning4j-nn/src/main/java/org/
deeplearning4j/nn/: conf/layers/ConvolutionLayer.java + layers/convolution/
ConvolutionLayer.java (im2col path + cuDNN helper dispatch at :74-84),
SubsamplingLayer, Upsampling1D/2D, ZeroPadding1D/2D,
conf/layers/BatchNormalization.java + layers/normalization/
BatchNormalization.java (462 LoC), LocalResponseNormalization,
GlobalPoolingLayer, SpaceToDepth/SpaceToBatch.

TPU-first design: NHWC layout (XLA:TPU native), lax.conv_general_dilated with
bf16 inputs + f32 accumulation lands directly on the MXU — this *is* the
cuDNN-helper replacement (SURVEY.md §2.2: "XLA's native conv/BN lowering plays
this role"). Pooling = lax.reduce_window. No im2col materialization.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import initializers as _init
from deeplearning4j_tpu.nn.conf import inputs as _inputs
from deeplearning4j_tpu.nn.layers.base import ParamLayer, Layer
from deeplearning4j_tpu.utils import dtypes as _dtypes
from deeplearning4j_tpu.utils.serde import register_config

DIMNUMS_2D = ("NHWC", "HWIO", "NHWC")


def conv(x, w, **kw):
    """Policy-aware lax.conv_general_dilated.

    Under mixed precision (bf16 compute, f32 accum) jax's conv *transpose*
    rule rejects the f32-``preferred_element_type`` upcast during autodiff
    (bf16 operands vs f32 cotangent), so convs compute bf16->bf16 — XLA:TPU's
    MXU accumulates bf16 convolutions in f32 internally regardless, which is
    what the cuDNN helpers' CUDNN_DATA_HALF+float-math config did for the
    reference (CudnnConvolutionHelper.java:389). Full precision (f32/f64,
    e.g. gradient checks) keeps the explicit accumulation dtype.
    """
    cd, ad = _dtypes.compute_dtypes_for(x.dtype)
    pet = {} if cd != ad else {"preferred_element_type": ad}
    return lax.conv_general_dilated(x.astype(cd), w.astype(cd), **kw, **pet)


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _conv_out_size(size, kernel, stride, pad_mode, pad):
    if pad_mode == "same":
        return -(-size // stride)
    if pad_mode == "valid":
        return (size - kernel) // stride + 1
    return (size + 2 * pad - kernel) // stride + 1


def _explicit_padding(pad_mode, pad_hw):
    if pad_mode in ("same", "valid"):
        return pad_mode.upper()
    ph, pw = pad_hw
    return [(ph, ph), (pw, pw)]


@register_config
@dataclasses.dataclass(frozen=True)
class ConvolutionLayer(ParamLayer):
    """2-D convolution. Kernel layout HWIO; params: W [kh,kw,cin,cout], b [cout]."""

    n_out: int = 0  # number of filters
    kernel: tuple = (3, 3)
    stride: tuple = (1, 1)
    padding: str = "valid"  # "same" | "valid" | "explicit"
    pad: tuple = (0, 0)
    dilation: tuple = (1, 1)
    has_bias: bool = True
    weight_init: object = dataclasses.field(default="relu", kw_only=True)

    input_family = _inputs.ConvolutionalType

    def output_type(self, input_type):
        assert isinstance(input_type, _inputs.ConvolutionalType), \
            f"{type(self).__name__} needs CNN input, got {input_type}"
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.pad)
        h = _conv_out_size(input_type.height, kh + (kh - 1) * (self.dilation[0] - 1), sh, self.padding, ph)
        w = _conv_out_size(input_type.width, kw + (kw - 1) * (self.dilation[1] - 1), sw, self.padding, pw)
        return _inputs.ConvolutionalType(h, w, self.n_out)

    def init(self, key, input_type, dtype=jnp.float32):
        kh, kw = _pair(self.kernel)
        cin = input_type.channels
        fan_in = cin * kh * kw
        fan_out = self.n_out * kh * kw
        p = {"W": _init.init_weight(self.weight_init, key, (kh, kw, cin, self.n_out),
                                    fan_in, fan_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params, state, x, *, train=False, rng=None):
        z = conv(
            x, params["W"],
            window_strides=_pair(self.stride),
            padding=_explicit_padding(self.padding, _pair(self.pad)),
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=DIMNUMS_2D,
        )
        if self.has_bias:
            z = z + params["b"].astype(z.dtype)
        return self.activation_fn()(z), state


@register_config
@dataclasses.dataclass(frozen=True)
class Convolution1DLayer(ParamLayer):
    """1-D conv over time (reference: conf/layers/Convolution1DLayer.java).
    Input [B, T, F]; implemented as conv_general_dilated over a width-1 axis."""

    n_out: int = 0
    kernel: int = 3
    stride: int = 1
    padding: str = "valid"
    pad: int = 0
    dilation: int = 1
    has_bias: bool = True
    weight_init: object = dataclasses.field(default="relu", kw_only=True)

    input_family = _inputs.RecurrentType

    def output_type(self, input_type):
        assert isinstance(input_type, _inputs.RecurrentType)
        t = input_type.timesteps
        if t is not None:
            k_eff = self.kernel + (self.kernel - 1) * (self.dilation - 1)
            t = _conv_out_size(t, k_eff, self.stride, self.padding, self.pad)
        return _inputs.RecurrentType(self.n_out, t)

    def init(self, key, input_type, dtype=jnp.float32):
        cin = input_type.size
        fan_in = cin * self.kernel
        fan_out = self.n_out * self.kernel
        p = {"W": _init.init_weight(self.weight_init, key, (self.kernel, cin, self.n_out),
                                    fan_in, fan_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params, state, x, *, train=False, rng=None):
        pad = self.padding.upper() if self.padding in ("same", "valid") else [(self.pad, self.pad)]
        z = conv(
            x, params["W"],
            window_strides=(self.stride,), padding=pad,
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        if self.has_bias:
            z = z + params["b"].astype(z.dtype)
        return self.activation_fn()(z), state


@register_config
@dataclasses.dataclass(frozen=True)
class Deconvolution2DLayer(ConvolutionLayer):
    """Transposed conv (reference: conf/layers/Deconvolution2D.java)."""

    def output_type(self, input_type):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.pad)
        if self.padding == "same":
            h, w = input_type.height * sh, input_type.width * sw
        else:
            pads = (0, 0) if self.padding == "valid" else (ph, pw)
            h = sh * (input_type.height - 1) + kh - 2 * pads[0]
            w = sw * (input_type.width - 1) + kw - 2 * pads[1]
        return _inputs.ConvolutionalType(h, w, self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None):
        cd, ad = _dtypes.compute_dtypes_for(x.dtype)
        pad = self.padding.upper() if self.padding in ("same", "valid") else \
            [(p, p) for p in _pair(self.pad)]
        pet = {} if cd != ad else {"preferred_element_type": ad}  # see conv()
        z = lax.conv_transpose(
            x.astype(cd), params["W"].astype(cd),
            strides=_pair(self.stride), padding=pad,
            dimension_numbers=DIMNUMS_2D, **pet,
        )
        if self.has_bias:
            z = z + params["b"].astype(z.dtype)
        return self.activation_fn()(z), state


@register_config
@dataclasses.dataclass(frozen=True)
class SeparableConvolution2DLayer(ParamLayer):
    """Depthwise-separable conv (reference: conf/layers/SeparableConvolution2D.java).
    params: D [kh,kw,cin,mult] depthwise, P [1,1,cin*mult,cout] pointwise."""

    n_out: int = 0
    kernel: tuple = (3, 3)
    stride: tuple = (1, 1)
    padding: str = "valid"
    pad: tuple = (0, 0)
    depth_multiplier: int = 1
    has_bias: bool = True
    weight_init: object = dataclasses.field(default="relu", kw_only=True)

    input_family = _inputs.ConvolutionalType

    def output_type(self, input_type):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.pad)
        h = _conv_out_size(input_type.height, kh, sh, self.padding, ph)
        w = _conv_out_size(input_type.width, kw, sw, self.padding, pw)
        return _inputs.ConvolutionalType(h, w, self.n_out)

    def init(self, key, input_type, dtype=jnp.float32):
        kh, kw = _pair(self.kernel)
        cin = input_type.channels
        k1, k2 = jax.random.split(key)
        p = {
            "D": _init.init_weight(self.weight_init, k1,
                                   (kh, kw, 1, cin * self.depth_multiplier),
                                   cin * kh * kw, cin * self.depth_multiplier, dtype),
            "P": _init.init_weight(self.weight_init, k2,
                                   (1, 1, cin * self.depth_multiplier, self.n_out),
                                   cin * self.depth_multiplier, self.n_out, dtype),
        }
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params, state, x, *, train=False, rng=None):
        cin = x.shape[-1]
        z = conv(
            x, params["D"],
            window_strides=_pair(self.stride),
            padding=_explicit_padding(self.padding, _pair(self.pad)),
            dimension_numbers=DIMNUMS_2D, feature_group_count=cin,
        )
        z = conv(
            z, params["P"],
            window_strides=(1, 1), padding="VALID",
            dimension_numbers=DIMNUMS_2D,
        )
        if self.has_bias:
            z = z + params["b"].astype(z.dtype)
        return self.activation_fn()(z), state


@register_config
@dataclasses.dataclass(frozen=True)
class SubsamplingLayer(Layer):
    """Pooling (reference: conf/layers/SubsamplingLayer.java — MAX/AVG/PNORM).
    lax.reduce_window; for PNORM, (sum |x|^p)^(1/p)."""

    kernel: tuple = (2, 2)
    stride: tuple = (2, 2)
    padding: str = "valid"
    pad: tuple = (0, 0)
    mode: str = "max"  # max | avg | sum | pnorm
    pnorm: int = 2

    input_family = _inputs.ConvolutionalType

    def output_type(self, input_type):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.pad)
        h = _conv_out_size(input_type.height, kh, sh, self.padding, ph)
        w = _conv_out_size(input_type.width, kw, sw, self.padding, pw)
        return _inputs.ConvolutionalType(h, w, input_type.channels)

    def apply(self, params, state, x, *, train=False, rng=None):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        if self.padding in ("same", "valid"):
            pads = self.padding.upper()
        else:
            ph, pw = _pair(self.pad)
            pads = [(0, 0), (ph, ph), (pw, pw), (0, 0)]
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        if self.mode == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
        elif self.mode in ("avg", "sum"):
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            if self.mode == "avg":
                y = y / (kh * kw)
        elif self.mode == "pnorm":
            p = float(self.pnorm)
            y = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, pads) ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling mode {self.mode!r}")
        return y, state


@register_config
@dataclasses.dataclass(frozen=True)
class Subsampling1DLayer(Layer):
    """1-D pooling over time (reference: conf/layers/Subsampling1DLayer.java)."""

    kernel: int = 2
    stride: int = 2
    padding: str = "valid"
    mode: str = "max"

    input_family = _inputs.RecurrentType

    def output_type(self, input_type):
        t = input_type.timesteps
        if t is not None:
            t = _conv_out_size(t, self.kernel, self.stride, self.padding, 0)
        return _inputs.RecurrentType(input_type.size, t)

    def apply(self, params, state, x, *, train=False, rng=None):
        window, strides = (1, self.kernel, 1), (1, self.stride, 1)
        pads = self.padding.upper()
        if self.mode == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
        else:
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            if self.mode == "avg":
                y = y / self.kernel
        return y, state


@register_config
@dataclasses.dataclass(frozen=True)
class Upsampling2DLayer(Layer):
    """(reference: conf/layers/Upsampling2D.java) — nearest-neighbor repeat."""

    size: tuple = (2, 2)

    input_family = _inputs.ConvolutionalType

    def output_type(self, input_type):
        sh, sw = _pair(self.size)
        return _inputs.ConvolutionalType(input_type.height * sh, input_type.width * sw,
                                         input_type.channels)

    def apply(self, params, state, x, *, train=False, rng=None):
        sh, sw = _pair(self.size)
        return jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2), state


@register_config
@dataclasses.dataclass(frozen=True)
class Upsampling1DLayer(Layer):
    size: int = 2

    input_family = _inputs.RecurrentType

    def output_type(self, input_type):
        t = None if input_type.timesteps is None else input_type.timesteps * self.size
        return _inputs.RecurrentType(input_type.size, t)

    def apply(self, params, state, x, *, train=False, rng=None):
        return jnp.repeat(x, self.size, axis=1), state


@register_config
@dataclasses.dataclass(frozen=True)
class ZeroPaddingLayer(Layer):
    """(reference: conf/layers/ZeroPaddingLayer.java) pad = (top, bottom, left, right)."""

    pad: tuple = (1, 1, 1, 1)

    input_family = _inputs.ConvolutionalType

    def output_type(self, input_type):
        t, b, l, r = self.pad
        return _inputs.ConvolutionalType(input_type.height + t + b,
                                         input_type.width + l + r, input_type.channels)

    def apply(self, params, state, x, *, train=False, rng=None):
        t, b, l, r = self.pad
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


@register_config
@dataclasses.dataclass(frozen=True)
class ZeroPadding1DLayer(Layer):
    pad: tuple = (1, 1)

    input_family = _inputs.RecurrentType

    def output_type(self, input_type):
        l, r = self.pad
        t = None if input_type.timesteps is None else input_type.timesteps + l + r
        return _inputs.RecurrentType(input_type.size, t)

    def apply(self, params, state, x, *, train=False, rng=None):
        l, r = self.pad
        return jnp.pad(x, ((0, 0), (l, r), (0, 0))), state


@register_config
@dataclasses.dataclass(frozen=True)
class BatchNormalization(ParamLayer):
    """Batch normalization over the channel/feature axis.

    Reference: conf/layers/BatchNormalization.java + layers/normalization/
    BatchNormalization.java (+ CudnnBatchNormalizationHelper — XLA's fused BN
    lowering is the TPU replacement). ``decay`` matches the reference's
    running-average momentum (default 0.9); state holds running mean/var used
    at inference.
    """

    decay: float = 0.9
    eps: float = 1e-5
    use_gamma_beta: bool = True  # reference: lockGammaBeta inverts this
    activation: object = dataclasses.field(default="identity", kw_only=True)

    input_family = None  # works on FF [B,F], RNN [B,T,F] and CNN [B,H,W,C]

    def _nfeat(self, input_type):
        if isinstance(input_type, _inputs.ConvolutionalType):
            return input_type.channels
        return input_type.size

    def output_type(self, input_type):
        return input_type

    def init(self, key, input_type, dtype=jnp.float32):
        n = self._nfeat(input_type)
        if not self.use_gamma_beta:
            return {}
        return {"gamma": jnp.ones((n,), dtype), "beta": jnp.zeros((n,), dtype)}

    def init_state(self, input_type, dtype=jnp.float32):
        n = self._nfeat(input_type)
        return {"mean": jnp.zeros((n,), dtype), "var": jnp.ones((n,), dtype)}

    WEIGHT_KEYS = ("gamma",)
    BIAS_KEYS = ("beta",)

    def apply(self, params, state, x, *, train=False, rng=None):
        axes = tuple(range(x.ndim - 1))  # all but channel/feature
        # batch statistics in the accumulation dtype: bf16 variance is too
        # coarse (same reason cudnnBatchNormalization forces float math);
        # the output is cast back so bf16 activations stay bf16 downstream
        out_dtype = x.dtype
        _, ad = _dtypes.compute_dtypes_for(x.dtype)
        x = x.astype(ad)
        if train:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                "var": self.decay * state["var"] + (1 - self.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        y = (x - mean) * inv
        if self.use_gamma_beta:
            y = y * params["gamma"] + params["beta"]
        return self.activation_fn()(y).astype(out_dtype), new_state


@register_config
@dataclasses.dataclass(frozen=True)
class LocalResponseNormalization(Layer):
    """Cross-channel LRN (reference: conf/layers/LocalResponseNormalization.java;
    defaults k=2, n=5, alpha=1e-4, beta=0.75 per the AlexNet formulation)."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    input_family = _inputs.ConvolutionalType

    def output_type(self, input_type):
        return input_type

    def apply(self, params, state, x, *, train=False, rng=None):
        half = self.n // 2
        sq = x * x
        # sliding window over the channel axis via reduce_window
        ssum = lax.reduce_window(sq, 0.0, lax.add, (1, 1, 1, self.n), (1, 1, 1, 1),
                                 [(0, 0), (0, 0), (0, 0), (half, self.n - 1 - half)])
        denom = (self.k + self.alpha * ssum) ** self.beta
        return x / denom, state


@register_config
@dataclasses.dataclass(frozen=True)
class ResidualBottleneck(ParamLayer):
    """ResNet-v1 bottleneck (1x1 reduce -> 3x3 -> 1x1 expand-4x, shortcut
    add, relu) packaged as ONE composite sequential layer.

    Reference analog: the s{i}b{j} block subgraphs ResNet50.java builds
    (/root/reference/deeplearning4j-zoo/.../zoo/model/ResNet50.java);
    models/resnet.py expresses them as ComputationGraph vertices. This
    layer packages the same block as a MultiLayerNetwork layer so residual
    CNNs are expressible as a flat layer STACK — which makes the flagship
    conv-BN family stageable by parallel/pipeline_general.PipelinedNetwork
    (skip connections are block-internal, so they never cross a stage
    boundary). Geometry mirrors models/resnet._bottleneck exactly:
    filters f -> (f, f, 4f), stride on the first 1x1, projection shortcut
    (1x1 stride conv + BN) whenever the shortcut shape changes.
    """

    filters: int = 64
    stride: tuple = (1, 1)
    project: bool = False  # force a projection shortcut (auto when shapes differ)
    decay: float = 0.9  # BN running-average momentum
    eps: float = 1e-5

    input_family = _inputs.ConvolutionalType

    def _needs_proj(self, input_type):
        return (self.project or input_type.channels != 4 * self.filters
                or _pair(self.stride) != (1, 1))

    def _plan(self, input_type):
        """[(name, sublayer, its input type)] — main chain then shortcut."""
        f = self.filters
        subs, t = [], input_type
        for tag, k, s, act, nout in (("a", (1, 1), self.stride, "relu", f),
                                     ("b", (3, 3), (1, 1), "relu", f),
                                     ("c", (1, 1), (1, 1), "identity", 4 * f)):
            cl = ConvolutionLayer(n_out=nout, kernel=k, stride=s,
                                  padding="same", has_bias=False,
                                  weight_init="relu")
            subs.append((f"{tag}_conv", cl, t))
            t = cl.output_type(t)
            subs.append((f"{tag}_bn",
                         BatchNormalization(decay=self.decay, eps=self.eps,
                                            activation=act), t))
        if self._needs_proj(input_type):
            pc = ConvolutionLayer(n_out=4 * f, kernel=(1, 1),
                                  stride=self.stride, padding="same",
                                  has_bias=False, weight_init="relu")
            subs.append(("proj_conv", pc, input_type))
            subs.append(("proj_bn",
                         BatchNormalization(decay=self.decay, eps=self.eps,
                                            activation="identity"),
                         pc.output_type(input_type)))
        return subs

    def output_type(self, input_type):
        assert isinstance(input_type, _inputs.ConvolutionalType), \
            f"{type(self).__name__} needs CNN input, got {input_type}"
        sh, sw = _pair(self.stride)
        return _inputs.ConvolutionalType(-(-input_type.height // sh),
                                         -(-input_type.width // sw),
                                         4 * self.filters)

    def init(self, key, input_type, dtype=jnp.float32):
        out = {}
        for name, sub, t in self._plan(input_type):
            key, sk = jax.random.split(key)
            p = sub.init(sk, t, dtype)
            if p:
                out[name] = p
        return out

    def init_state(self, input_type, dtype=jnp.float32):
        return {name: sub.init_state(t, dtype)
                for name, sub, t in self._plan(input_type)
                if isinstance(sub, BatchNormalization)}

    def apply(self, params, state, x, *, train=False, rng=None):
        it = _inputs.ConvolutionalType(x.shape[1], x.shape[2], x.shape[3])
        new_state = dict(state)
        h, shortcut = x, x
        for name, sub, _t in self._plan(it):
            on_shortcut = name.startswith("proj")
            y, st = sub.apply(params.get(name, {}), state.get(name, {}),
                              shortcut if on_shortcut else h,
                              train=train, rng=rng)
            if name in state:
                new_state[name] = st
            if on_shortcut:
                shortcut = y
            else:
                h = y
        return jax.nn.relu(h + shortcut), new_state

    def regularization_penalty(self, params):
        """L1/L2 on the conv kernels only — BN gamma/beta excluded, matching
        the reference's default of unregularized BatchNormalization params."""
        if not (self.l1 or self.l2):
            return 0.0
        pen = 0.0
        for name, sub in params.items():
            if name.endswith("_conv"):
                w = sub["W"]
                if self.l1:
                    pen = pen + self.l1 * jnp.sum(jnp.abs(w))
                if self.l2:
                    pen = pen + 0.5 * self.l2 * jnp.sum(w * w)
        return pen


@register_config
@dataclasses.dataclass(frozen=True)
class GlobalPoolingLayer(Layer):
    """Pool over time (RNN) or space (CNN) (reference: conf/layers/
    GlobalPoolingLayer.java — MAX/AVG/SUM/PNORM with mask support)."""

    mode: str = "max"
    pnorm: int = 2
    collapse_dimensions: bool = True

    input_family = None

    def output_type(self, input_type):
        if isinstance(input_type, _inputs.RecurrentType):
            return _inputs.FeedForwardType(input_type.size)
        if isinstance(input_type, _inputs.ConvolutionalType):
            return _inputs.FeedForwardType(input_type.channels)
        return input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        axes = (1,) if x.ndim == 3 else (1, 2) if x.ndim == 4 else None
        if axes is None:
            return x, state
        if mask is not None and x.ndim == 3:
            m = mask[..., None].astype(x.dtype)
            if self.mode == "max":
                y = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1)
            elif self.mode == "sum":
                y = jnp.sum(x * m, axis=1)
            elif self.mode == "avg":
                y = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
            else:
                p = float(self.pnorm)
                y = jnp.sum(jnp.abs(x * m) ** p, axis=1) ** (1.0 / p)
            return y, state
        if self.mode == "max":
            y = jnp.max(x, axis=axes)
        elif self.mode == "avg":
            y = jnp.mean(x, axis=axes)
        elif self.mode == "sum":
            y = jnp.sum(x, axis=axes)
        elif self.mode == "pnorm":
            p = float(self.pnorm)
            y = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling mode {self.mode!r}")
        return y, state


@register_config
@dataclasses.dataclass(frozen=True)
class SpaceToDepthLayer(Layer):
    """(reference: conf/layers/SpaceToDepthLayer.java; used by YOLO passthrough)"""

    blocks: int = 2

    input_family = _inputs.ConvolutionalType

    def output_type(self, input_type):
        b = self.blocks
        return _inputs.ConvolutionalType(input_type.height // b, input_type.width // b,
                                         input_type.channels * b * b)

    def apply(self, params, state, x, *, train=False, rng=None):
        b = self.blocks
        n, h, w, c = x.shape
        y = x.reshape(n, h // b, b, w // b, b, c).transpose(0, 1, 3, 2, 4, 5)
        return y.reshape(n, h // b, w // b, b * b * c), state


@register_config
@dataclasses.dataclass(frozen=True)
class SpaceToBatchLayer(Layer):
    """(reference: conf/layers/SpaceToBatchLayer.java)"""

    blocks: tuple = (2, 2)

    input_family = _inputs.ConvolutionalType

    def output_type(self, input_type):
        bh, bw = _pair(self.blocks)
        return _inputs.ConvolutionalType(input_type.height // bh, input_type.width // bw,
                                         input_type.channels)

    def apply(self, params, state, x, *, train=False, rng=None):
        bh, bw = _pair(self.blocks)
        n, h, w, c = x.shape
        y = x.reshape(n, h // bh, bh, w // bw, bw, c).transpose(2, 4, 0, 1, 3, 5)
        return y.reshape(n * bh * bw, h // bh, w // bw, c), state
