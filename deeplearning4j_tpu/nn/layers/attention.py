"""Attention layers + layer normalization.

The reference has NO attention anywhere (SURVEY.md §5 long-context row: its
only long-sequence mechanisms are masking + truncated BPTT). These layers are
the north-star-mandated long-context capability, designed TPU-first:

- scaled dot-product attention runs as batched MXU matmuls in bf16 with f32
  accumulation;
- RecurrentAttentionLayer-style usage = MultiHeadAttention over [B,T,F];
- sequence parallelism (ring attention over the mesh 'seq' axis) lives in
  deeplearning4j_tpu/parallel/sequence.py and reuses this layer's projections.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import initializers as _init
from deeplearning4j_tpu.nn.conf import inputs as _inputs
from deeplearning4j_tpu.nn.layers.base import ParamLayer, Layer
from deeplearning4j_tpu.nn.layers.core import matmul
from deeplearning4j_tpu.utils import dtypes as _dtypes
from deeplearning4j_tpu.utils.serde import register_config


@register_config
@dataclasses.dataclass(frozen=True)
class LayerNormalization(ParamLayer):
    """Per-feature layer norm (gamma/beta over the last axis)."""

    eps: float = 1e-5
    activation: object = dataclasses.field(default="identity", kw_only=True)

    input_family = None

    WEIGHT_KEYS = ("gamma",)
    BIAS_KEYS = ("beta",)

    def _nfeat(self, input_type):
        if isinstance(input_type, _inputs.ConvolutionalType):
            return input_type.channels
        return input_type.size

    def output_type(self, input_type):
        return input_type

    def init(self, key, input_type, dtype=jnp.float32):
        n = self._nfeat(input_type)
        return {"gamma": jnp.ones((n,), dtype), "beta": jnp.zeros((n,), dtype)}

    def apply(self, params, state, x, *, train=False, rng=None):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * params["gamma"] + params["beta"]
        return self.activation_fn()(y), state


def dot_product_attention(q, k, v, *, mask=None, causal=False, scale=None):
    """q,k,v: [B, T, H, D]. Returns [B, T, H, D]. bf16 matmuls, f32 softmax.

    On TPU, attention (incl. [B, Tk] key-padding-masked batches) dispatches
    to the fused flash kernel (ops/attention_pallas.py) — O(T*D) HBM
    traffic instead of the [B,H,T,T] logits tensor; the dispatch seam
    mirrors the LSTM fused path."""
    from deeplearning4j_tpu.ops import attention_pallas as _ap
    resolved = (_ap.resolve_attention(q.shape, k.shape, mask, q.dtype)
                if (_ap.enabled() and (scale is None
                                       or isinstance(scale, (int, float))))
                else None)
    if resolved is not None:
        # one DB lookup decides dispatch AND geometry: TuningDB winner >
        # the DL4J_TPU_FLASH_BLOCK_Q/K env knobs (live-window A/B
        # sweeps) > the hand-picked 512x512. Read once per trace — jit
        # caches the chosen blocks into the compiled step. A tuned
        # remat=True wraps the kernel in jax.checkpoint: the backward
        # recomputes the forward instead of saving out/lse residuals
        # (the searched memory-for-time dimension).
        bq, bk, remat = resolved

        def flash(q, k, v):
            return _ap.flash_attention(q, k, v, mask=mask, causal=causal,
                                       scale=scale, block_q=bq, block_k=bk)

        return (jax.checkpoint(flash) if remat else flash)(q, k, v)
    cd, ad = _dtypes.compute_dtypes_for(q.dtype)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(jnp.asarray(d, ad))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(cd), k.astype(cd),
                        preferred_element_type=ad) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(causal_mask, logits, -jnp.inf)
    if mask is not None:
        # mask: [B, Tk] -> key-side masking
        logits = jnp.where(mask[:, None, None, :] > 0, logits, -jnp.inf)
    if mask is not None:
        # fully-masked query rows (e.g. left padding under causal): softmax
        # over all -inf is NaN fwd AND bwd — substitute a finite row before
        # the softmax and zero its output after, matching the fused
        # kernel's contract so dispatch choice never changes NaN behavior.
        # (Pure-causal rows always see >= 1 valid key; no guard needed.)
        any_valid = (logits > -jnp.inf).any(axis=-1, keepdims=True)
        logits = jnp.where(any_valid, logits, 0.0)
        weights = jax.nn.softmax(logits, axis=-1)
        weights = jnp.where(any_valid, weights, 0.0)
    else:
        weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(cd), v.astype(cd),
                     preferred_element_type=ad)
    return out


@register_config
@dataclasses.dataclass(frozen=True)
class MultiHeadAttention(ParamLayer):
    """Self-attention over [B,T,F] with fused QKV projection."""

    n_out: int = 0     # model dim (also output dim)
    n_heads: int = 4
    causal: bool = False
    weight_init: object = dataclasses.field(default="xavier", kw_only=True)

    input_family = _inputs.RecurrentType

    WEIGHT_KEYS = ("Wqkv", "Wo")
    BIAS_KEYS = ("bqkv", "bo")

    def output_type(self, input_type):
        return _inputs.RecurrentType(self.n_out, input_type.timesteps)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = input_type.size
        assert self.n_out % self.n_heads == 0
        k1, k2 = jax.random.split(key)
        return {
            "Wqkv": _init.init_weight(self.weight_init, k1, (n_in, 3 * self.n_out),
                                      n_in, 3 * self.n_out, dtype),
            "bqkv": jnp.zeros((3 * self.n_out,), dtype),
            "Wo": _init.init_weight(self.weight_init, k2, (self.n_out, self.n_out),
                                    self.n_out, self.n_out, dtype),
            "bo": jnp.zeros((self.n_out,), dtype),
        }

    def heads(self, params, x):
        """Project to q,k,v [B,T,H,D]."""
        b, t, _ = x.shape
        h, d = self.n_heads, self.n_out // self.n_heads
        qkv = matmul(x.reshape(b * t, -1), params["Wqkv"]) + params["bqkv"]
        qkv = qkv.reshape(b, t, 3, h, d)
        return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

    def out_proj(self, params, attn):
        b, t, h, d = attn.shape
        y = matmul(attn.reshape(b * t, h * d), params["Wo"]) + params["bo"]
        return y.reshape(b, t, h * d)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        q, k, v = self.heads(params, x)
        attn = dot_product_attention(q, k, v, mask=mask, causal=self.causal)
        y = self.out_proj(params, attn)
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        return y, state


@register_config
@dataclasses.dataclass(frozen=True)
class TransformerBlock(Layer):
    """Pre-norm transformer block: LN -> MHA -> residual, LN -> MLP -> residual."""

    n_out: int = 0
    n_heads: int = 4
    mlp_ratio: int = 4
    causal: bool = False
    activation: object = "gelu"

    input_family = _inputs.RecurrentType

    def _parts(self):
        return (LayerNormalization(),
                MultiHeadAttention(n_out=self.n_out, n_heads=self.n_heads,
                                   causal=self.causal),
                LayerNormalization())

    def output_type(self, input_type):
        return _inputs.RecurrentType(self.n_out, input_type.timesteps)

    def init(self, key, input_type, dtype=jnp.float32):
        assert input_type.size == self.n_out, \
            "TransformerBlock requires input size == n_out (residual)"
        ln1, mha, ln2 = self._parts()
        k1, k2, k3, k4 = jax.random.split(key, 4)
        hidden = self.n_out * self.mlp_ratio
        it = _inputs.RecurrentType(self.n_out, input_type.timesteps)
        return {
            "ln1": ln1.init(k1, it, dtype),
            "mha": mha.init(k1, it, dtype),
            "ln2": ln2.init(k2, it, dtype),
            "mlp_W1": _init.init_weight("xavier", k3, (self.n_out, hidden),
                                        self.n_out, hidden, dtype),
            "mlp_b1": jnp.zeros((hidden,), dtype),
            "mlp_W2": _init.init_weight("xavier", k4, (hidden, self.n_out),
                                        hidden, self.n_out, dtype),
            "mlp_b2": jnp.zeros((self.n_out,), dtype),
        }

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.nn import activations as _act
        ln1, mha, ln2 = self._parts()
        h, _ = ln1.apply(params["ln1"], {}, x)
        attn, _ = mha.apply(params["mha"], {}, h, mask=mask)
        x = x + attn
        h, _ = ln2.apply(params["ln2"], {}, x)
        b, t, f = h.shape
        act = _act.get(self.activation)
        m = act(matmul(h.reshape(b * t, f), params["mlp_W1"]) + params["mlp_b1"])
        m = matmul(m, params["mlp_W2"]) + params["mlp_b2"]
        return x + m.reshape(b, t, f), state

    def regularization_penalty(self, params):
        return 0.0
