"""Center-loss output layer.

Reference analog: nn/conf/layers/CenterLossOutputLayer.java + nn/layers/
training/CenterLossOutputLayer.java in /root/reference/deeplearning4j-nn
(Wen et al. 2016): softmax cross-entropy + lambda/2 * ||f - c_y||^2, where
per-class centers c are EMA-updated with rate alpha from the batch features.

Centers are non-trainable statistics living in the layer state (like BN
running stats); the update happens inside the jitted train step via the
returned new_state — no host round-trip.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import initializers as _init
from deeplearning4j_tpu.nn import losses as _losses
from deeplearning4j_tpu.nn.conf import inputs as _inputs
from deeplearning4j_tpu.nn.layers.base import ParamLayer
from deeplearning4j_tpu.nn.layers.core import matmul
from deeplearning4j_tpu.utils.serde import register_config


@register_config
@dataclasses.dataclass(frozen=True)
class CenterLossOutputLayer(ParamLayer):
    n_out: int = 0
    alpha: float = 0.05   # center EMA rate
    lambda_: float = 2e-4  # center-loss weight
    loss: object = "mcxent"
    activation: object = dataclasses.field(default="softmax", kw_only=True)

    input_family = _inputs.FeedForwardType

    def output_type(self, input_type):
        return _inputs.FeedForwardType(self.n_out)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = _inputs.adapted_type(input_type, _inputs.FeedForwardType).size
        return {"W": _init.init_weight(self.weight_init, key, (n_in, self.n_out),
                                       n_in, self.n_out, dtype),
                "b": jnp.zeros((self.n_out,), dtype)}

    def init_state(self, input_type, dtype=jnp.float32):
        n_in = _inputs.adapted_type(input_type, _inputs.FeedForwardType).size
        return {"centers": jnp.zeros((self.n_out, n_in), dtype)}

    def apply(self, params, state, x, *, train=False, rng=None):
        z = matmul(x, params["W"]) + params["b"]
        return self.activation_fn()(z), state

    # the network routes through this when the last layer defines it:
    # features (layer input) are needed for the center term
    def loss_from_features(self, params, state, feats, labels, mask=None, train=True):
        preds, _ = self.apply(params, state, feats)
        ce = _losses.get(self.loss)(preds, labels, mask)
        centers = state["centers"]
        cls = jnp.argmax(labels, axis=-1)
        c_y = jnp.take(centers, cls, axis=0)                # [B, n_in]
        diff = feats - c_y
        center_loss = 0.5 * self.lambda_ * jnp.mean(jnp.sum(diff * diff, axis=-1))
        if train:
            # EMA center update: c_j += alpha * mean_{i: y_i=j}(f_i - c_j)
            onehot = labels.astype(feats.dtype)              # [B, n_out]
            counts = jnp.maximum(jnp.sum(onehot, axis=0), 1.0)
            delta = jnp.einsum("bc,bf->cf", onehot, diff) / counts[:, None]
            new_centers = centers + self.alpha * delta
            new_state = {"centers": new_centers}
        else:
            new_state = state
        return ce + center_loss, preds, new_state
