"""Variational autoencoder layer.

Reference analog: nn/conf/layers/variational/ (7 config files incl.
VariationalAutoencoder.java, GaussianReconstructionDistribution,
BernoulliReconstructionDistribution) + nn/layers/variational/
VariationalAutoencoder.java (1163 LoC) in /root/reference/deeplearning4j-nn.

Encoder MLP -> (mean, logvar) of q(z|x); reparameterized sample; decoder MLP
-> reconstruction-distribution parameters. Supervised forward (the layer used
inside a net) outputs the posterior mean, matching the reference's activate().
``pretrain_loss`` = -ELBO = -E[log p(x|z)] + KL(q(z|x) || N(0,I)).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as _act
from deeplearning4j_tpu.nn import initializers as _init
from deeplearning4j_tpu.nn.conf import inputs as _inputs
from deeplearning4j_tpu.nn.layers.base import ParamLayer
from deeplearning4j_tpu.nn.layers.core import matmul
from deeplearning4j_tpu.utils.serde import register_config


@register_config
@dataclasses.dataclass(frozen=True)
class VariationalAutoencoder(ParamLayer):
    n_latent: int = 2
    encoder_layer_sizes: tuple = (64,)
    decoder_layer_sizes: tuple = (64,)
    reconstruction: str = "gaussian"  # gaussian (learned diag var) | bernoulli
    num_samples: int = 1
    activation: object = dataclasses.field(default="relu", kw_only=True)

    input_family = _inputs.FeedForwardType

    def output_type(self, input_type):
        return _inputs.FeedForwardType(self.n_latent)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = _inputs.adapted_type(input_type, _inputs.FeedForwardType).size
        p = {}

        def dense(key, name, a, b):
            k1, k2 = jax.random.split(key)
            p[f"{name}_W"] = _init.init_weight(self.weight_init, k1, (a, b), a, b, dtype)
            p[f"{name}_b"] = jnp.zeros((b,), dtype)

        sizes = [n_in, *self.encoder_layer_sizes]
        for i in range(len(sizes) - 1):
            key, sub = jax.random.split(key)
            dense(sub, f"enc{i}", sizes[i], sizes[i + 1])
        key, k_mean, k_var = jax.random.split(key, 3)
        dense(k_mean, "z_mean", sizes[-1], self.n_latent)
        dense(k_var, "z_logvar", sizes[-1], self.n_latent)
        dsizes = [self.n_latent, *self.decoder_layer_sizes]
        for i in range(len(dsizes) - 1):
            key, sub = jax.random.split(key)
            dense(sub, f"dec{i}", dsizes[i], dsizes[i + 1])
        out_dim = 2 * n_in if self.reconstruction == "gaussian" else n_in
        key, k_out = jax.random.split(key)
        dense(k_out, "x_out", dsizes[-1], out_dim)
        return p

    # ---- internals ----

    def _mlp(self, params, prefix, n, h):
        act = self.activation_fn()
        for i in range(n):
            h = act(matmul(h, params[f"{prefix}{i}_W"]) + params[f"{prefix}{i}_b"])
        return h

    def encode(self, params, x):
        h = self._mlp(params, "enc", len(self.encoder_layer_sizes), x)
        mean = matmul(h, params["z_mean_W"]) + params["z_mean_b"]
        logvar = matmul(h, params["z_logvar_W"]) + params["z_logvar_b"]
        return mean, logvar

    def decode(self, params, z):
        h = self._mlp(params, "dec", len(self.decoder_layer_sizes), z)
        return matmul(h, params["x_out_W"]) + params["x_out_b"]

    def apply(self, params, state, x, *, train=False, rng=None):
        mean, _ = self.encode(params, x)
        return mean, state

    def reconstruct(self, params, x, rng=None):
        mean, logvar = self.encode(params, x)
        z = mean if rng is None else \
            mean + jnp.exp(0.5 * logvar) * jax.random.normal(rng, mean.shape, mean.dtype)
        out = self.decode(params, z)
        if self.reconstruction == "bernoulli":
            return jax.nn.sigmoid(out)
        return out[..., :out.shape[-1] // 2]  # gaussian mean half

    def pretrain_loss(self, params, x, rng):
        """-ELBO averaged over the batch (reference: computeGradientAndScore
        of the VAE layer in pretrain mode)."""
        mean, logvar = self.encode(params, x)
        kl = 0.5 * jnp.sum(jnp.exp(logvar) + mean**2 - 1.0 - logvar, axis=-1)
        rec = 0.0
        for s in range(self.num_samples):
            if rng is not None:
                rng, sub = jax.random.split(rng)
                eps = jax.random.normal(sub, mean.shape, mean.dtype)
            else:
                eps = 0.0
            z = mean + jnp.exp(0.5 * logvar) * eps
            out = self.decode(params, z)
            if self.reconstruction == "gaussian":
                n_in = out.shape[-1] // 2
                x_mean, x_logvar = out[..., :n_in], out[..., n_in:]
                ll = -0.5 * jnp.sum(
                    x_logvar + (x - x_mean) ** 2 / jnp.exp(x_logvar)
                    + jnp.log(2 * jnp.pi), axis=-1)
            else:
                p = jnp.clip(jax.nn.sigmoid(out), 1e-7, 1 - 1e-7)
                ll = jnp.sum(x * jnp.log(p) + (1 - x) * jnp.log(1 - p), axis=-1)
            rec = rec + ll
        rec = rec / self.num_samples
        return jnp.mean(kl - rec)

    def reconstruction_probability(self, params, x, rng, num_samples=8):
        """Monte-Carlo estimate of log p(x) used for anomaly scoring
        (reference: VariationalAutoencoder.reconstructionProbability)."""
        mean, logvar = self.encode(params, x)
        total = None
        for s in range(num_samples):
            rng, sub = jax.random.split(rng)
            eps = jax.random.normal(sub, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            out = self.decode(params, z)
            if self.reconstruction == "gaussian":
                n_in = out.shape[-1] // 2
                x_mean, x_logvar = out[..., :n_in], out[..., n_in:]
                ll = -0.5 * jnp.sum(x_logvar + (x - x_mean) ** 2 / jnp.exp(x_logvar)
                                    + jnp.log(2 * jnp.pi), axis=-1)
            else:
                p = jnp.clip(jax.nn.sigmoid(out), 1e-7, 1 - 1e-7)
                ll = jnp.sum(x * jnp.log(p) + (1 - x) * jnp.log(1 - p), axis=-1)
            total = ll if total is None else jnp.logaddexp(total, ll)
        return total - jnp.log(float(num_samples))
