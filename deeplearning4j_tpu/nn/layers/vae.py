"""Variational autoencoder layer + reconstruction-distribution family.

Reference analog: nn/conf/layers/variational/ (ReconstructionDistribution
SPI + Gaussian/Bernoulli/Exponential/Composite/LossFunctionWrapper impls)
+ nn/layers/variational/VariationalAutoencoder.java (1163 LoC) in
/root/reference/deeplearning4j-nn.

Encoder MLP -> (mean, logvar) of q(z|x); reparameterized sample; decoder MLP
-> reconstruction-distribution parameters. Supervised forward (the layer used
inside a net) outputs the posterior mean, matching the reference's activate().
``pretrain_loss`` = -ELBO = -E[log p(x|z)] + KL(q(z|x) || N(0,I)).

The reconstruction distribution is pluggable, mirroring the reference SPI
(``distributionInputSize`` -> ``param_size``, ``negLogProbability`` ->
``log_prob``, ``generateAtMean``/``generateRandom`` -> ``mean``/``sample``);
gradients come from AD instead of the reference's hand-written
``gradient()`` methods. ``reconstruction="gaussian"|"bernoulli"`` strings
keep the original shorthand.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as _act
from deeplearning4j_tpu.nn import initializers as _init
from deeplearning4j_tpu.nn import losses as _losses
from deeplearning4j_tpu.nn.conf import inputs as _inputs
from deeplearning4j_tpu.nn.layers.base import ParamLayer
from deeplearning4j_tpu.nn.layers.core import matmul
from deeplearning4j_tpu.utils.serde import register_config


# ---------------------------------------------------------------------------
# Reconstruction distributions (reference: ReconstructionDistribution SPI)
# ---------------------------------------------------------------------------

@register_config
@dataclasses.dataclass(frozen=True)
class GaussianReconstruction:
    """p(x|z) = N(mean, diag(var)); decoder emits [mean | logvar]
    (reference: GaussianReconstructionDistribution, activation applied to
    the MEAN half only, matching the Java impl)."""

    activation: str = "identity"

    def param_size(self, n):
        return 2 * n

    def _split(self, pre):
        n = pre.shape[-1] // 2
        return _act.get(self.activation)(pre[..., :n]), pre[..., n:]

    def log_prob(self, pre, x):
        mean, logvar = self._split(pre)
        return -0.5 * jnp.sum(logvar + (x - mean) ** 2 / jnp.exp(logvar)
                              + jnp.log(2 * jnp.pi), axis=-1)

    def mean(self, pre):
        return self._split(pre)[0]

    def sample(self, pre, rng):
        mean, logvar = self._split(pre)
        return mean + jnp.exp(0.5 * logvar) * jax.random.normal(
            rng, mean.shape, mean.dtype)


@register_config
@dataclasses.dataclass(frozen=True)
class BernoulliReconstruction:
    """p(x|z) = prod Bernoulli(p); decoder emits logits through
    ``activation`` (sigmoid by default, like the reference)."""

    activation: str = "sigmoid"

    def param_size(self, n):
        return n

    def _p(self, pre):
        return jnp.clip(_act.get(self.activation)(pre), 1e-7, 1.0 - 1e-7)

    def log_prob(self, pre, x):
        p = self._p(pre)
        return jnp.sum(x * jnp.log(p) + (1.0 - x) * jnp.log(1.0 - p),
                       axis=-1)

    def mean(self, pre):
        return self._p(pre)

    def sample(self, pre, rng):
        p = self._p(pre)
        return jax.random.bernoulli(rng, p).astype(p.dtype)


@register_config
@dataclasses.dataclass(frozen=True)
class ExponentialReconstruction:
    """p(x|z) = lambda * exp(-lambda x), lambda = exp(activation(pre)) —
    log p = gamma - lambda*x (reference:
    ExponentialReconstructionDistribution.negLogProbability)."""

    activation: str = "identity"

    def param_size(self, n):
        return n

    def log_prob(self, pre, x):
        gamma = _act.get(self.activation)(pre)
        return jnp.sum(gamma - jnp.exp(gamma) * x, axis=-1)

    def mean(self, pre):
        gamma = _act.get(self.activation)(pre)
        return jnp.exp(-gamma)  # E[x] = 1/lambda

    def sample(self, pre, rng):
        gamma = _act.get(self.activation)(pre)
        u = jax.random.uniform(rng, gamma.shape, gamma.dtype,
                               minval=1e-7, maxval=1.0 - 1e-7)
        return -jnp.log1p(-u) * jnp.exp(-gamma)  # inverse CDF


@register_config
@dataclasses.dataclass(frozen=True)
class LossWrapperReconstruction:
    """Use a plain loss function as the "reconstruction distribution"
    (reference: LossFunctionWrapper — an ILossFunction behind the SPI;
    log_prob := -loss, so the ELBO becomes reconstruction-error + KL)."""

    loss: str = "mse"
    activation: str = "identity"

    def param_size(self, n):
        return n

    def _out(self, pre):
        return _act.get(self.activation)(pre)

    def log_prob(self, pre, x):
        out = self._out(pre)
        fn = _losses.get(self.loss)
        # the loss fns reduce over the batch (vmap recovers per-example
        # values) and average over features — scale by n_features so the
        # term SUMS over features like every other distribution (else the
        # KL term dominates by a factor of n_features)
        per = jax.vmap(lambda o, t: fn(o[None], t[None]))(out, x)
        return -per * x.shape[-1]

    def mean(self, pre):
        return self._out(pre)

    def sample(self, pre, rng):
        return self._out(pre)  # deterministic: a loss has no sampler


@register_config
@dataclasses.dataclass(frozen=True)
class CompositeReconstruction:
    """Different distributions over different feature slices (reference:
    CompositeReconstructionDistribution.Builder.addDistribution). ``parts``
    is a tuple of (feature_count, distribution) pairs covering the input."""

    parts: tuple = ()

    def __post_init__(self):
        # normalize (serde rebuilds nested pairs as lists): keep the frozen
        # dataclass hashable and round-trip equality intact
        object.__setattr__(self, "parts",
                           tuple((int(sz), d) for sz, d in self.parts))

    def param_size(self, n):
        total = sum(sz for sz, _ in self.parts)
        if total != n:
            raise ValueError(
                f"composite covers {total} features, input has {n}")
        return sum(d.param_size(sz) for sz, d in self.parts)

    def _slices(self):
        x_off = p_off = 0
        for sz, d in self.parts:
            yield d, (x_off, x_off + sz), (p_off, p_off + d.param_size(sz))
            x_off += sz
            p_off += d.param_size(sz)

    def log_prob(self, pre, x):
        total = 0.0
        for d, (x0, x1), (p0, p1) in self._slices():
            total = total + d.log_prob(pre[..., p0:p1], x[..., x0:x1])
        return total

    def mean(self, pre):
        return jnp.concatenate([d.mean(pre[..., p0:p1])
                                for d, _, (p0, p1) in self._slices()],
                               axis=-1)

    def sample(self, pre, rng):
        outs = []
        for d, _, (p0, p1) in self._slices():
            rng, sub = jax.random.split(rng)
            outs.append(d.sample(pre[..., p0:p1], sub))
        return jnp.concatenate(outs, axis=-1)


_DIST_SHORTHAND = {
    "gaussian": GaussianReconstruction,
    "bernoulli": BernoulliReconstruction,
    "exponential": ExponentialReconstruction,
}


def resolve_distribution(spec):
    """str shorthand or a distribution instance -> distribution instance."""
    if isinstance(spec, str):
        try:
            return _DIST_SHORTHAND[spec]()
        except KeyError:
            raise ValueError(f"unknown reconstruction {spec!r}; use one of "
                             f"{sorted(_DIST_SHORTHAND)} or a distribution "
                             "instance") from None
    if isinstance(spec, (list, tuple)):  # serde round-trip of composites
        return CompositeReconstruction(parts=tuple(
            (int(sz), resolve_distribution(d)) for sz, d in spec))
    return spec


@register_config
@dataclasses.dataclass(frozen=True)
class VariationalAutoencoder(ParamLayer):
    n_latent: int = 2
    encoder_layer_sizes: tuple = (64,)
    decoder_layer_sizes: tuple = (64,)
    # "gaussian" | "bernoulli" | "exponential" | a distribution instance
    # (incl. CompositeReconstruction / LossWrapperReconstruction)
    reconstruction: object = "gaussian"
    num_samples: int = 1
    activation: object = dataclasses.field(default="relu", kw_only=True)

    input_family = _inputs.FeedForwardType

    @property
    def dist(self):
        return resolve_distribution(self.reconstruction)

    def output_type(self, input_type):
        return _inputs.FeedForwardType(self.n_latent)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = _inputs.adapted_type(input_type, _inputs.FeedForwardType).size
        p = {}

        def dense(key, name, a, b):
            k1, k2 = jax.random.split(key)
            p[f"{name}_W"] = _init.init_weight(self.weight_init, k1, (a, b), a, b, dtype)
            p[f"{name}_b"] = jnp.zeros((b,), dtype)

        sizes = [n_in, *self.encoder_layer_sizes]
        for i in range(len(sizes) - 1):
            key, sub = jax.random.split(key)
            dense(sub, f"enc{i}", sizes[i], sizes[i + 1])
        key, k_mean, k_var = jax.random.split(key, 3)
        dense(k_mean, "z_mean", sizes[-1], self.n_latent)
        dense(k_var, "z_logvar", sizes[-1], self.n_latent)
        dsizes = [self.n_latent, *self.decoder_layer_sizes]
        for i in range(len(dsizes) - 1):
            key, sub = jax.random.split(key)
            dense(sub, f"dec{i}", dsizes[i], dsizes[i + 1])
        key, k_out = jax.random.split(key)
        dense(k_out, "x_out", dsizes[-1], self.dist.param_size(n_in))
        return p

    # ---- internals ----

    def _mlp(self, params, prefix, n, h):
        act = self.activation_fn()
        for i in range(n):
            h = act(matmul(h, params[f"{prefix}{i}_W"]) + params[f"{prefix}{i}_b"])
        return h

    def encode(self, params, x):
        h = self._mlp(params, "enc", len(self.encoder_layer_sizes), x)
        mean = matmul(h, params["z_mean_W"]) + params["z_mean_b"]
        logvar = matmul(h, params["z_logvar_W"]) + params["z_logvar_b"]
        return mean, logvar

    def decode(self, params, z):
        h = self._mlp(params, "dec", len(self.decoder_layer_sizes), z)
        return matmul(h, params["x_out_W"]) + params["x_out_b"]

    def apply(self, params, state, x, *, train=False, rng=None):
        mean, _ = self.encode(params, x)
        return mean, state

    def reconstruct(self, params, x, rng=None):
        mean, logvar = self.encode(params, x)
        z = mean if rng is None else \
            mean + jnp.exp(0.5 * logvar) * jax.random.normal(rng, mean.shape, mean.dtype)
        return self.dist.mean(self.decode(params, z))

    def generate_at_mean(self, params, z):
        """Decode latent points to the distribution mean (reference:
        generateAtMeanGivenZ)."""
        return self.dist.mean(self.decode(params, z))

    def generate_random(self, params, z, rng):
        """Decode latent points and SAMPLE the reconstruction distribution
        (reference: generateRandomGivenZ)."""
        return self.dist.sample(self.decode(params, z), rng)

    def pretrain_loss(self, params, x, rng):
        """-ELBO averaged over the batch (reference: computeGradientAndScore
        of the VAE layer in pretrain mode)."""
        dist = self.dist
        mean, logvar = self.encode(params, x)
        kl = 0.5 * jnp.sum(jnp.exp(logvar) + mean**2 - 1.0 - logvar, axis=-1)
        rec = 0.0
        for s in range(self.num_samples):
            if rng is not None:
                rng, sub = jax.random.split(rng)
                eps = jax.random.normal(sub, mean.shape, mean.dtype)
            else:
                eps = 0.0
            z = mean + jnp.exp(0.5 * logvar) * eps
            rec = rec + dist.log_prob(self.decode(params, z), x)
        rec = rec / self.num_samples
        return jnp.mean(kl - rec)

    def reconstruction_probability(self, params, x, rng, num_samples=8):
        """Monte-Carlo estimate of log p(x) used for anomaly scoring
        (reference: VariationalAutoencoder.reconstructionProbability)."""
        dist = self.dist
        mean, logvar = self.encode(params, x)
        total = None
        for s in range(num_samples):
            rng, sub = jax.random.split(rng)
            eps = jax.random.normal(sub, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            ll = dist.log_prob(self.decode(params, z), x)
            total = ll if total is None else jnp.logaddexp(total, ll)
        return total - jnp.log(float(num_samples))
