"""Training listeners.

Reference analog: optimize/api/TrainingListener.java + optimize/listeners/
(ScoreIterationListener, PerformanceListener.java:109 samples/sec,
CollectScoresIterationListener, TimeIterationListener, EvaluativeListener) in
/root/reference/deeplearning4j-nn. The ETL-time split mirrors the reference's
lastEtlTime measurement inside the fit loop (MultiLayerNetwork.java:1239-1242).
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger("deeplearning4j_tpu")


def run_fit_end_hooks(model):
    """Invoke every listener's on_fit_end from the fit loops' finally
    blocks. Each hook is isolated: a raising cleanup must neither mask the
    original training exception nor starve later listeners of THEIR
    cleanup (the hook exists to release resources like an open profiler
    trace — leaking the rest of the list would defeat it)."""
    for l in getattr(model, "listeners", ()):
        hook = getattr(l, "on_fit_end", None)
        if callable(hook):
            try:
                hook(model)
            except Exception:
                logger.warning("on_fit_end failed for %s",
                               type(l).__name__, exc_info=True)


class TrainingListener:
    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        pass

    def on_fit_end(self, model):
        """Invoked by the fit loops in a ``finally`` block — fires whether
        fit() completed, returned early, or raised. Listeners holding open
        resources (a profiler trace window, a file) release them here."""


class ScoreIterationListener(TrainingListener):
    def __init__(self, frequency=10, print_fn=None):
        self.frequency = frequency
        self.print_fn = print_fn or (lambda s: logger.info(s))
        self.scores = []

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        if iteration % self.frequency == 0:
            self.print_fn(f"Score at iteration {iteration} is {score}")
        self.scores.append((iteration, score))


class PerformanceListener(TrainingListener):
    """Samples/sec + batches/sec + ETL time per iteration (reference:
    PerformanceListener.java:109)."""

    def __init__(self, frequency=10, report_batch_size=None, print_fn=None):
        self.frequency = frequency
        self.batch_size = report_batch_size
        self.print_fn = print_fn or (lambda s: logger.info(s))
        self._last = None
        self.records = []

    @staticmethod
    def _infer_batch_size(model):
        """Leading dim of the batch the fit loop just consumed (both fit
        loops stash it as ``last_input``) — so samples/sec reports without
        an explicit report_batch_size instead of being silently omitted."""
        x = getattr(model, "last_input", None)
        shape = getattr(x, "shape", None)
        return shape[0] if shape else None

    @staticmethod
    def _telemetry_fields():
        """Memory/health gauges the instrumented fit loop just refreshed —
        read back from the shared registry (no device sync, no recompute)
        when telemetry is on; {} otherwise."""
        try:
            from deeplearning4j_tpu import telemetry
        except Exception:
            return {}
        reg = telemetry.get_registry()
        if not reg.enabled:
            return {}
        out = {}
        # grad_norm only while the watchdog is actively refreshing it: a
        # stale gauge from an earlier watchdog-on fit must not misreport
        # this run
        if telemetry.health.get_monitor().active:
            g = reg.get("train_grad_norm")
            if g is not None and g.labelsets():
                out["grad_norm"] = g.value()
        g = reg.get("device_bytes_in_use")
        if g is not None:
            vals = [g.value(**ls) for ls in g.labelsets()]
            if vals:
                out["device_mb_in_use"] = max(vals) / 2**20
        g = reg.get("live_array_bytes")
        if g is not None and g.labelsets():
            out["live_array_mb"] = g.value() / 2**20
        return out

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        now = time.perf_counter()  # the ONLY clock read per iteration
        if self._last is not None:
            dt = now - self._last
            bs = self.batch_size or self._infer_batch_size(model)
            rec = {"iteration": iteration, "iter_time_s": dt, "etl_time_s": etl_time,
                   "batches_per_sec": 1.0 / dt if dt > 0 else 0.0}
            if bs:
                rec["samples_per_sec"] = bs / dt if dt > 0 else 0.0
            rec.update(self._telemetry_fields())
            self.records.append(rec)
            if iteration % self.frequency == 0:
                # one consolidated line: throughput + ETL + the telemetry
                # gauges, so a tailed log reads health without a second tool
                parts = [f"iteration {iteration}: {dt * 1e3:.2f} ms/iter"]
                if bs:
                    parts.append(
                        f"{rec.get('samples_per_sec', 0):.1f} samples/sec")
                parts.append(f"etl {etl_time * 1e3:.2f} ms")
                if "grad_norm" in rec:
                    parts.append(f"grad_norm {rec['grad_norm']:.3g}")
                if "device_mb_in_use" in rec:
                    parts.append(f"hbm {rec['device_mb_in_use']:.1f} MB")
                elif "live_array_mb" in rec:
                    parts.append(f"live {rec['live_array_mb']:.2f} MB")
                self.print_fn(", ".join(parts))
        self._last = now


class CollectScoresListener(TrainingListener):
    def __init__(self):
        self.iterations = []
        self.scores = []

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        self.iterations.append(iteration)
        self.scores.append(score)


class TimeIterationListener(TrainingListener):
    """ETA logger (reference: TimeIterationListener)."""

    def __init__(self, total_iterations, frequency=50, print_fn=None):
        self.total = total_iterations
        self.frequency = frequency
        self.print_fn = print_fn or (lambda s: logger.info(s))
        self.start = time.perf_counter()

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        if iteration and iteration % self.frequency == 0:
            elapsed = time.perf_counter() - self.start
            per_iter = elapsed / iteration
            remaining = max(self.total - iteration, 0) * per_iter
            self.print_fn(f"iteration {iteration}/{self.total}, ETA {remaining:.1f}s")


class EvaluativeListener(TrainingListener):
    """Periodic evaluation during training (reference: EvaluativeListener)."""

    def __init__(self, data, labels, frequency=100, evaluator=None):
        self.data = data
        self.labels = labels
        self.frequency = frequency
        self.evaluator = evaluator
        self.results = []

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        if iteration % self.frequency != 0:
            return
        preds = model.output(self.data)
        if self.evaluator is not None:
            self.results.append((iteration, self.evaluator(preds, self.labels)))
        else:
            self.results.append((iteration, preds))


class ProfilerListener(TrainingListener):
    """Capture a jax.profiler trace for a window of training iterations.

    SURVEY.md §5 tracing row: the reference ships OpProfiler / per-op timing
    inside libnd4j; on TPU the authoritative per-op timeline is XLA's own
    profiler (xprof/TensorBoard "trace_viewer"). This listener brackets
    iterations [start_iteration, start_iteration + n_iterations) in
    jax.profiler.start_trace / stop_trace; point TensorBoard at ``log_dir``
    (or xprof) to see per-op device time, HBM traffic, and MXU utilization.

    Also snapshots jax.profiler.device_memory_profile() at trace end when
    ``memory_profile=True`` (pprof format, <log_dir>/memory.pprof).
    """

    def __init__(self, log_dir, *, start_iteration=10, n_iterations=5,
                 memory_profile=False, print_fn=None,
                 close_on_fit_end=True):
        self.log_dir = str(log_dir)
        self.start_iteration = start_iteration
        self.n_iterations = n_iterations
        self.memory_profile = memory_profile
        self.print_fn = print_fn or (lambda s: logger.info(s))
        # close_on_fit_end=False lets one window span several fit() calls
        # (fit-per-epoch loops, early stopping) — the caller then owns
        # calling close(), and accepts the leak risk the default removes
        self.close_on_fit_end = close_on_fit_end
        self._active = False
        self.completed = False
        self.traced_iterations = 0

    def on_epoch_start(self, model):
        # start_iteration <= 1 means "from the very first step, compile
        # included" — iteration_done fires post-step, so the only hook that
        # runs before iteration 1's work is epoch start
        import jax
        if (not self._active and not self.completed
                and self.start_iteration <= 1):
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            self._t0 = time.perf_counter()

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        import jax
        # iteration_done(i) fires AFTER iteration i's step: open the trace
        # once iteration start-1 has finished so iteration `start` itself is
        # the first one captured (the window spans epoch boundaries)
        if (not self._active and not self.completed
                and iteration >= self.start_iteration - 1):
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            self._t0 = time.perf_counter()
            return
        if self._active:
            self.traced_iterations += 1
            if self.traced_iterations >= self.n_iterations:
                # block on the last result so device work lands in the trace
                jax.block_until_ready(
                    jax.tree_util.tree_leaves(
                        getattr(model, "params", []))[:1])
                self.close()

    def on_fit_end(self, model):
        # fit() returned (or raised) before the trace window completed: a
        # dangling jax.profiler.start_trace would leak the active trace
        # session into the next fit/profile attempt
        if self.close_on_fit_end:
            self.close()

    def close(self):
        """Stop the trace. Called automatically when the window completes;
        call explicitly if training can end before the window does."""
        if not self._active:
            return
        import jax
        jax.profiler.stop_trace()
        self._active = False
        self.completed = True
        if self.memory_profile:
            import os
            prof = jax.profiler.device_memory_profile()
            with open(os.path.join(self.log_dir, "memory.pprof"), "wb") as f:
                f.write(prof)
        truncated = ("" if self.traced_iterations >= self.n_iterations
                     else f" (window truncated: {self.n_iterations} "
                          f"requested; pass close_on_fit_end=False to span "
                          f"multiple fit() calls)")
        self.print_fn(
            f"profiler trace: {self.traced_iterations} iterations in "
            f"{time.perf_counter() - self._t0:.2f}s -> {self.log_dir}"
            + truncated)
