"""Training listeners.

Reference analog: optimize/api/TrainingListener.java + optimize/listeners/
(ScoreIterationListener, PerformanceListener.java:109 samples/sec,
CollectScoresIterationListener, TimeIterationListener, EvaluativeListener) in
/root/reference/deeplearning4j-nn. The ETL-time split mirrors the reference's
lastEtlTime measurement inside the fit loop (MultiLayerNetwork.java:1239-1242).
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        pass


class ScoreIterationListener(TrainingListener):
    def __init__(self, frequency=10, print_fn=None):
        self.frequency = frequency
        self.print_fn = print_fn or (lambda s: logger.info(s))
        self.scores = []

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        if iteration % self.frequency == 0:
            self.print_fn(f"Score at iteration {iteration} is {score}")
        self.scores.append((iteration, score))


class PerformanceListener(TrainingListener):
    """Samples/sec + batches/sec + ETL time per iteration (reference:
    PerformanceListener.java:109)."""

    def __init__(self, frequency=10, report_batch_size=None, print_fn=None):
        self.frequency = frequency
        self.batch_size = report_batch_size
        self.print_fn = print_fn or (lambda s: logger.info(s))
        self._last = None
        self.records = []

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        now = time.perf_counter()
        if self._last is not None:
            dt = now - self._last
            rec = {"iteration": iteration, "iter_time_s": dt, "etl_time_s": etl_time,
                   "batches_per_sec": 1.0 / dt if dt > 0 else 0.0}
            if self.batch_size:
                rec["samples_per_sec"] = self.batch_size / dt if dt > 0 else 0.0
            self.records.append(rec)
            if iteration % self.frequency == 0:
                self.print_fn(
                    f"iteration {iteration}: {dt * 1e3:.2f} ms/iter"
                    + (f", {rec.get('samples_per_sec', 0):.1f} samples/sec" if self.batch_size else "")
                    + f", etl {etl_time * 1e3:.2f} ms")
        self._last = now


class CollectScoresListener(TrainingListener):
    def __init__(self):
        self.iterations = []
        self.scores = []

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        self.iterations.append(iteration)
        self.scores.append(score)


class TimeIterationListener(TrainingListener):
    """ETA logger (reference: TimeIterationListener)."""

    def __init__(self, total_iterations, frequency=50, print_fn=None):
        self.total = total_iterations
        self.frequency = frequency
        self.print_fn = print_fn or (lambda s: logger.info(s))
        self.start = time.perf_counter()

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        if iteration and iteration % self.frequency == 0:
            elapsed = time.perf_counter() - self.start
            per_iter = elapsed / iteration
            remaining = max(self.total - iteration, 0) * per_iter
            self.print_fn(f"iteration {iteration}/{self.total}, ETA {remaining:.1f}s")


class EvaluativeListener(TrainingListener):
    """Periodic evaluation during training (reference: EvaluativeListener)."""

    def __init__(self, data, labels, frequency=100, evaluator=None):
        self.data = data
        self.labels = labels
        self.frequency = frequency
        self.evaluator = evaluator
        self.results = []

    def iteration_done(self, model, iteration, score, etl_time=0.0):
        if iteration % self.frequency != 0:
            return
        preds = model.output(self.data)
        if self.evaluator is not None:
            self.results.append((iteration, self.evaluator(preds, self.labels)))
        else:
            self.results.append((iteration, preds))
