"""MultiLayerNetwork: the sequential-stack trainer.

Reference analog: nn/multilayer/MultiLayerNetwork.java (3225 LoC) —
fit(DataSetIterator):1205, calcBackpropGradients:1315, output:1993,
computeGradientAndScore:2255 — plus the Solver/StochasticGradientDescent/
BaseOptimizer stack (optimize/solvers/*, gradientAndScore at
BaseOptimizer.java:171, updater application at :187).

TPU-native design: instead of a mutable flat param buffer with per-layer views
mutated in place through a JNI boundary per op, the entire
forward+backward+update is ONE jitted XLA computation over a params pytree
(list of per-layer dicts). Donated buffers give the same zero-copy param update
the reference gets from views. The reference's workspace machinery
(MultiLayerNetwork.java:1221-1229) is subsumed by XLA's static buffer
allocation; its AsyncDataSetIterator prefetch is datasets/iterator.py.

The stateful-object API (fit/output/score) wraps the functional core
(init_fn/apply_fn/loss_fn/train_step) — use the functional core directly for
custom training loops or pjit sharding.
"""

from __future__ import annotations

import functools
import inspect

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.telemetry import health as _health
from deeplearning4j_tpu.nn import gradnorm as _gradnorm
from deeplearning4j_tpu.nn.conf import inputs as _inputs
from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers import base as _base
from deeplearning4j_tpu.utils import dtypes as _dtypes


def _accepts_mask(layer):
    try:
        return "mask" in inspect.signature(type(layer).apply).parameters
    except (ValueError, TypeError):
        return False


class MultiLayerNetwork:
    """Sequential network: config in, functional core + convenience API out."""

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layer_inputs, self.output_type = conf.layer_input_types()
        self._mask_aware = [_accepts_mask(l) for l in conf.layers]
        self.params = None
        self.state = None
        self.opt_state = None
        self.iteration = 0
        self.epoch = 0
        self.listeners = []
        self._train_step = None
        self._train_step_health = None
        self._rng = jax.random.PRNGKey(conf.seed)

    # ------------------------------------------------------------------
    # functional core
    # ------------------------------------------------------------------

    def init(self, rng=None, dtype=None):
        """Initialize params/state/opt_state. Returns (params, state)."""
        rng = self._rng if rng is None else rng
        dtype = dtype or _dtypes.get_policy().param_dtype
        params, state = [], []
        for layer, in_type in zip(self.conf.layers, self.layer_inputs):
            rng, sub = jax.random.split(rng)
            params.append(layer.init(sub, in_type, dtype))
            state.append(layer.init_state(in_type, dtype))
        self.params, self.state = params, state
        self.opt_state = self.conf.updater.init(params)
        return params, state

    def apply_fn(self, params, state, x, *, train=False, rng=None, mask=None,
                 layer_limit=None):
        """Forward pass. Returns (output, new_state)."""
        new_state = list(state)
        cur_type = self.conf.input_type
        n = len(self.conf.layers) if layer_limit is None else layer_limit
        for i in range(n):
            x, new_state[i], rng, cur_type = self._apply_layer(
                i, params[i], state[i], x, cur_type, train=train, rng=rng,
                mask=mask)
        return x, new_state

    def _apply_layer(self, i, layer_params, state_i, x, cur_type, *, train,
                     rng, mask):
        """ONE layer of the forward loop — the definition ``apply_fn``
        iterates and the ZeRO-3 streamed-gather scan body reuses
        (parallel/data_parallel._streamed_loss runs it inside a
        ``lax.scan`` over the stacked trunk slab, so the adapt / input
        dropout / rng-split / weight-noise / remat order here IS the
        bit-exactness contract between the two paths). Returns
        ``(y, new_state_i, rng, next_type)``."""
        layer = self.conf.layers[i]
        # FrozenLayer.java:23 contract: a frozen layer "behaves as the
        # layer within it would during TEST regardless of the
        # training/test mode" — frozen BN normalizes with its running
        # statistics and does NOT update them; frozen dropout is off
        l_train = train and i not in set(getattr(self, "frozen_layers", ()))
        fam = layer.input_family
        if fam is not None and not isinstance(cur_type, fam):
            x = _inputs.adapt(x, cur_type, fam)
            cur_type = _inputs.adapted_type(cur_type, fam)
        if l_train and layer.dropout > 0.0 and rng is not None:  # graftlint: disable=R2 -- layer is conf metadata picked by a Python int index, never a tracer
            rng, sub = jax.random.split(rng)
            from deeplearning4j_tpu.nn.layers.base import dropout_mask
            x = dropout_mask(sub, x, layer.dropout)
        kwargs = {}
        if self._mask_aware[i] and mask is not None \
                and mask.ndim >= 2:
            # a 1-d mask is an example-validity mask (shape
            # bucketing): it has no timestep info to forward into
            # mask-aware layers, which require [batch, time]
            kwargs["mask"] = mask
        if rng is not None:
            rng, sub = jax.random.split(rng)
        else:
            sub = None
        wn = getattr(layer, "weight_noise", None)
        if l_train and wn is not None and sub is not None \
                and layer_params:
            sub, noise_rng = jax.random.split(sub)
            layer_params = wn.perturb(noise_rng, layer, layer_params)

        def run(p, s, xx, r, _layer=layer, _kwargs=kwargs,
                _train=l_train):
            return _layer.apply(p, s, xx, train=_train, rng=r, **_kwargs)

        if self.conf.gradient_checkpointing:
            # remat: drop this layer's activations after the forward and
            # recompute them during backprop — HBM for FLOPs
            run = jax.checkpoint(run)
        y, new_state_i = run(layer_params, state_i, x, sub)
        return y, new_state_i, rng, layer.output_type(cur_type)

    def loss_fn(self, params, state, x, y, *, train=True, rng=None, mask=None,
                label_mask=None):
        """Score = output-layer loss + L1/L2 penalties (reference:
        computeGradientAndScore at MultiLayerNetwork.java:2255 + calcL1/calcL2).
        Returns (loss, (new_state, predictions))."""
        out_layer = self.conf.layers[-1]
        lm = label_mask if label_mask is not None else mask
        if hasattr(out_layer, "loss_from_features"):
            # center-loss style heads need their input features for the loss
            feats, new_state = self.apply_fn(params, state, x, train=train,
                                             rng=rng, mask=mask,
                                             layer_limit=len(self.conf.layers) - 1)
            loss, preds, out_state = out_layer.loss_from_features(
                params[-1], state[-1], feats, y, lm, train=train)
            new_state = list(new_state)
            new_state[-1] = out_state
        else:
            preds, new_state = self.apply_fn(params, state, x, train=train,
                                             rng=rng, mask=mask)
            if not hasattr(out_layer, "compute_loss"):
                raise ValueError("Last layer must be an output/loss layer, got "
                                 f"{type(out_layer).__name__}")
            loss = out_layer.compute_loss(preds, y, lm)
        for layer, p in zip(self.conf.layers, params):
            if p:
                loss = loss + layer.regularization_penalty(p)
        loss, new_state = _base.pop_aux_losses(loss, new_state)
        return loss, (new_state, preds)

    # ------------------------------------------------------------------
    # truncated BPTT (reference: doTruncatedBPTT, MultiLayerNetwork.java:
    # 1252-1254 + BackpropType.TruncatedBPTT) — long sequences are split
    # into tbptt_fwd_length chunks; RNN hidden state carries across chunks
    # with stop_gradient at the boundary, bounding the backprop window.
    # ------------------------------------------------------------------

    def _apply_rnn(self, params, state, x, carries, *, train=False, rng=None,
                   mask=None):
        """Forward pass threading RNN carries. Returns (y, new_state, new_carries)."""
        new_state = list(state)
        new_carries = list(carries)
        cur_type = self.conf.input_type
        for i, layer in enumerate(self.conf.layers):
            fam = layer.input_family
            if fam is not None and not isinstance(cur_type, fam):
                x = _inputs.adapt(x, cur_type, fam)
                cur_type = _inputs.adapted_type(cur_type, fam)
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            if hasattr(layer, "apply_with_carry"):
                x, new_carries[i] = layer.apply_with_carry(
                    params[i], carries[i], x, mask=mask)
            else:
                kwargs = {"mask": mask} if (self._mask_aware[i] and mask is not None) else {}
                x, new_state[i] = layer.apply(params[i], state[i], x, train=train,
                                              rng=sub, **kwargs)
            cur_type = layer.output_type(cur_type)
        return x, new_state, new_carries

    def make_tbptt_step(self, jit=True):
        conf = self.conf

        def tbptt_step(params, state, opt_state, carries, x, y, step, rng, mask=None):
            carries = jax.tree_util.tree_map(jax.lax.stop_gradient, carries)

            def chunk_loss(params):
                preds, new_state, new_carries = self._apply_rnn(
                    params, state, x, carries, train=True, rng=rng, mask=mask)
                out_layer = conf.layers[-1]
                loss = out_layer.compute_loss(preds, y, mask)
                for layer, p in zip(conf.layers, params):
                    if p:
                        loss = loss + layer.regularization_penalty(p)
                loss, new_state = _base.pop_aux_losses(loss, new_state)
                return loss, (new_state, new_carries)

            (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                chunk_loss, has_aux=True)(params)
            grads = _gradnorm.normalize_grads(conf.gradient_normalization, grads,
                                              conf.gradient_normalization_threshold)
            updates, new_opt = conf.updater.update(grads, opt_state, params, step)
            new_params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            return new_params, new_state, new_opt, new_carries, loss

        return jax.jit(tbptt_step) if jit else tbptt_step

    def _fit_tbptt(self, x, y, mask):
        if not hasattr(self, "_tbptt_step") or self._tbptt_step is None:
            self._tbptt_step = self.make_tbptt_step()
        T = x.shape[1]
        L = self.conf.tbptt_fwd_length
        carries = [l.zero_carry(x.shape[0], jnp.asarray(x).dtype)
                   if hasattr(l, "zero_carry") else None
                   for l in self.conf.layers]
        total = 0.0
        n_chunks = 0
        for t0 in range(0, T, L):
            cx = jnp.asarray(x[:, t0:t0 + L])
            cy = jnp.asarray(y[:, t0:t0 + L])
            cm = jnp.asarray(mask[:, t0:t0 + L]) if mask is not None else None
            self._rng, sub = jax.random.split(self._rng)
            (self.params, self.state, self.opt_state, carries, loss) = \
                self._tbptt_step(self.params, self.state, self.opt_state,
                                 carries, cx, cy, self.iteration, sub, cm)
            # accumulate ON DEVICE: a per-chunk float(loss) would pay one
            # host round-trip per TBPTT chunk and serialize dispatch
            total = total + loss
            n_chunks += 1
            self.iteration += 1
        self.score_value = float(total) / max(n_chunks, 1)
        return self.score_value

    # ------------------------------------------------------------------
    # streaming inference (reference: RecurrentLayer.rnnTimeStep contract)
    # ------------------------------------------------------------------

    def rnn_clear_previous_state(self):
        self._rnn_stream_state = None

    def rnn_time_step(self, x):
        """One timestep [B, F] (or a short [B,T,F] chunk) of streaming
        inference, carrying hidden state between calls."""
        x = jnp.asarray(x)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]
        carries = getattr(self, "_rnn_stream_state", None)
        if carries is None:
            carries = [l.zero_carry(x.shape[0], x.dtype)
                       if hasattr(l, "zero_carry") else None
                       for l in self.conf.layers]
        y, _, carries = self._apply_rnn(self.params, self.state, x, carries,
                                        train=False)
        self._rnn_stream_state = carries
        return y[:, 0] if squeeze else y

    def compute_gradients(self, params, state, x, y, *, rng=None, mask=None):
        """Loss + normalized/clipped gradients (reference:
        computeGradientAndScore + gradient normalization inside
        updateGradientAccordingToParams). Returns (loss, new_state, grads).
        The distributed masters insert their gradient exchange between this
        and apply_update."""
        (loss, (new_state, _)), grads = jax.value_and_grad(
            self.loss_fn, has_aux=True)(params, state, x, y, train=True,
                                        rng=rng, mask=mask)
        grads = _gradnorm.normalize_grads(
            self.conf.gradient_normalization, grads,
            self.conf.gradient_normalization_threshold)
        return loss, new_state, grads

    def apply_update(self, params, opt_state, grads, step):
        """updater -> parameter add -> constraints (reference:
        BaseOptimizer.java:187 -> StochasticGradientDescent step :78 ->
        applyConstraints :97)."""
        updates, new_opt = self.conf.updater.update(grads, opt_state, params,
                                                    step)
        new_params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return self.apply_constraints(new_params, step), new_opt

    def apply_constraints(self, params, step):
        """The constraint pass of apply_update, exposed separately for
        update paths that run the updater elsewhere (the distributed
        masters' sharded weight update applies the updater to flat
        1/w shards, then constrains the reassembled params HERE — one
        definition, no drift)."""
        return [l.apply_constraints(p, step, 0) if p else p
                for l, p in zip(self.conf.layers, params)]

    def make_train_step(self, donate=True, jit=True, with_health=False):
        """Build the jitted train step:
        (params, state, opt_state, x, y, step, rng, mask) ->
        (params, state, opt_state, loss[, health]).

        Mirrors BaseOptimizer.gradientAndScore:171 -> updater :187 ->
        StochasticGradientDescent step :78, fused into one XLA computation.
        ``with_health=True`` appends the numerics-watchdog scalar bundle
        (telemetry/health.py) — a few extra fused reductions, fetched
        asynchronously by the fit loop's HealthMonitor.
        """
        def train_step(params, state, opt_state, x, y, step, rng, mask=None):
            loss, new_state, grads = self.compute_gradients(
                params, state, x, y, rng=rng, mask=mask)
            if with_health:
                health = _health.health_stats(grads, params, loss)
            new_params, new_opt = self.apply_update(params, opt_state, grads,
                                                    step)
            if with_health:
                return new_params, new_state, new_opt, loss, health
            return new_params, new_state, new_opt, loss

        if not jit:
            return train_step
        donate_argnums = (0, 1, 2) if donate else ()
        return jax.jit(train_step, donate_argnums=donate_argnums)

    def make_train_steps(self, k, donate=True, jit=True, with_health=False):
        """Fused K-step engine: ONE dispatch runs K train steps under
        ``jax.lax.scan`` over a stacked ``[K, B, ...]`` super-batch, the
        iteration counter and RNG chain carried on device (nn/fused.py;
        ``fit(steps_per_dispatch=K)`` drives it)."""
        from deeplearning4j_tpu.nn import fused as _fused
        return _fused.make_train_steps(self, k, donate=donate, jit=jit,
                                       with_health=with_health)

    # ------------------------------------------------------------------
    # convenience (stateful) API
    # ------------------------------------------------------------------

    def fit(self, data, labels=None, *, epochs=1, batch_size=None, mask=None,
            steps_per_dispatch=1, pad_ragged=None):
        """Train. ``data`` is either (features, labels) arrays or an iterator
        yielding dicts/tuples per minibatch (reference: fit(DataSetIterator)
        at MultiLayerNetwork.java:1205).

        ``steps_per_dispatch=K`` (default 1 = this loop, unchanged) runs K
        steps per device dispatch through the fused ``lax.scan`` engine
        (nn/fused.py): super-batches of K minibatches are stacked +
        ``device_put`` on a prefetch thread while the current dispatch
        runs, ragged batch/K-tail shapes are bucketed with validity masks
        (exact; ``recompiles_total`` stays flat), and scores/health come
        back one dispatch late as stacked arrays.

        ``pad_ragged=True`` applies the same shape bucketing to the K=1
        loop: every batch padded to one compiled shape with the validity
        folded into the loss mask, so the ragged tail batch of each epoch
        stops costing a fresh XLA compile."""
        if self.params is None:
            self.init()
        k = int(steps_per_dispatch)
        if k > 1:
            if self.conf.backprop_type == "tbptt":
                # reject only when TBPTT could actually engage (the K=1
                # loop gates it per batch: 3-d input with T > fwd_length,
                # the ComputationGraph.fit convention); feature arrays
                # short enough — or non-temporal — train fused fine
                pair = labels is None and isinstance(data, (tuple, list))
                feats = data[0] if pair else data
                labs = data[1] if pair else labels
                safe = (hasattr(feats, "shape") and
                        (feats.ndim != 3
                         or feats.shape[1] <= self.conf.tbptt_fwd_length
                         or (hasattr(labs, "shape") and labs.ndim != 3)))
                if not safe:
                    raise ValueError(
                        "steps_per_dispatch > 1 does not compose with "
                        "TBPTT (the chunk loop is its own on-device "
                        "scan); use the default single-step path")
            from deeplearning4j_tpu.nn import fused as _fused
            return _fused.fit_fused(
                self,
                lambda: self._batches(data, labels, batch_size, mask),
                epochs=epochs, k=k, batch_size=batch_size)
        # the K=1 loop is the shared StepDriver (continuous/driver.py):
        # the identical pipelined body (one-step-late score fetch via
        # ScorePipeline — no per-iteration float(loss) sync, graftlint R1
        # — one-late health bundles, trace handoff, flight records), now
        # resumable between rounds for the continuous-learning tier. The
        # per-batch TBPTT hook preserves the historical contract: a long
        # 3-d sequence batch runs the chunked on-device scan instead.
        from deeplearning4j_tpu.continuous.driver import StepDriver
        conf = self.conf

        def tbptt_fn(x, y):
            return (conf.backprop_type == "tbptt" and x.ndim == 3
                    and y.ndim == 3
                    and x.shape[1] > conf.tbptt_fwd_length)

        drv = StepDriver(
            self,
            lambda: self._batches(data, labels, batch_size, mask,
                                  pad_to=True if pad_ragged else None),
            tbptt_fn=tbptt_fn)
        return drv.run(epochs)

    def _batches(self, data, labels, batch_size, mask, pad_to=None):
        from deeplearning4j_tpu.datasets.iterator import iter_batches
        yield from iter_batches(data, labels, batch_size, mask,
                                pad_to=pad_to)

    def output(self, x, train=False, mask=None):
        """Inference forward pass (reference: MultiLayerNetwork.output:1993)."""
        if self.params is None:
            self.init()
        out, _ = self._jitted_apply()(self.params, self.state, jnp.asarray(x),
                                      mask if mask is None else jnp.asarray(mask))
        return out

    @functools.lru_cache(maxsize=1)
    def _jitted_apply(self):
        def fwd(params, state, x, mask):
            return self.apply_fn(params, state, x, train=False, mask=mask)
        return jax.jit(fwd)

    def feed_forward(self, x, train=False):
        """All intermediate activations (reference: feedForwardToLayer:2286)."""
        acts = []
        x = jnp.asarray(x)
        cur_type = self.conf.input_type
        state = list(self.state)
        for i, layer in enumerate(self.conf.layers):
            fam = layer.input_family
            if fam is not None and not isinstance(cur_type, fam):
                x = _inputs.adapt(x, cur_type, fam)
                cur_type = _inputs.adapted_type(cur_type, fam)
            x, state[i] = layer.apply(self.params[i], state[i], x, train=train)
            cur_type = layer.output_type(cur_type)
            acts.append(x)
        return acts

    def score(self, x, y, mask=None):
        if self.params is None:
            self.init()
        loss, _ = self.loss_fn(self.params, self.state, jnp.asarray(x),
                               jnp.asarray(y), train=False, mask=mask)
        return float(loss)

    def predict(self, x, mask=None):
        """Predicted class indices [batch] (reference:
        MultiLayerNetwork.predict(INDArray) at MultiLayerNetwork.java:
        the argmax convenience over output())."""
        out = np.asarray(self.output(x, mask=mask))
        return np.argmax(out, axis=-1)

    def f1_score(self, x, y, mask=None):
        """Macro F1 over a labelled batch (reference: the Classifier
        interface's f1Score entry). A label mask excludes padded
        timesteps/examples from the tally, matching evaluate()'s
        iterator path."""
        from deeplearning4j_tpu.eval.classification import Evaluation
        e = Evaluation()
        out = self.output(x, mask=mask)
        e.eval(np.asarray(y), np.asarray(out),
               mask=None if mask is None else np.asarray(mask))
        return e.f1()

    def evaluate(self, data, labels=None, *, batch_size=None,
                 evaluation=None):
        """Classification Evaluation over arrays, an (x, y) pair, or any
        DataSetIterator (reference: MultiLayerNetwork.evaluate(
        DataSetIterator) at MultiLayerNetwork.java:2621 — the API every
        reference example ends with: ``print(net.evaluate(it).stats())``).
        Pass ``evaluation=`` to accumulate into an existing instance
        (e.g. a cost-array or top-N one)."""
        from deeplearning4j_tpu.datasets.iterator import iter_batches
        from deeplearning4j_tpu.eval.classification import Evaluation

        e = evaluation if evaluation is not None else Evaluation()
        for bx, by, bm in iter_batches(data, labels, batch_size, None):
            out = self.output(bx, mask=bm)
            e.eval(np.asarray(by), np.asarray(out),
                   mask=None if bm is None else np.asarray(bm))
        return e

    def evaluate_regression(self, data, labels=None, *, batch_size=None):
        """RegressionEvaluation over the same input shapes (reference:
        MultiLayerNetwork.evaluateRegression)."""
        from deeplearning4j_tpu.datasets.iterator import iter_batches
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation

        e = RegressionEvaluation()
        for bx, by, bm in iter_batches(data, labels, batch_size, None):
            e.eval(np.asarray(by), np.asarray(self.output(bx, mask=bm)),
                   mask=None if bm is None else np.asarray(bm))
        return e

    def evaluate_roc(self, data, labels=None, *, batch_size=None,
                     threshold_steps=0):
        """ROC (binary) or ROCMultiClass over the same input shapes
        (reference: MultiLayerNetwork.evaluateROC / evaluateROCMultiClass)."""
        from deeplearning4j_tpu.datasets.iterator import iter_batches
        from deeplearning4j_tpu.eval.roc import ROC, ROCMultiClass

        roc = None
        for bx, by, bm in iter_batches(data, labels, batch_size, None):
            out = np.asarray(self.output(bx, mask=bm))
            if roc is None:
                binary = out.shape[-1] <= 2
                roc = (ROC(threshold_steps) if binary
                       else ROCMultiClass(threshold_steps))
            roc.eval(np.asarray(by), out,
                     mask=None if bm is None else np.asarray(bm))
        if roc is None:
            raise ValueError("no data to evaluate")
        return roc

    def num_params(self):
        return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(self.params))

    def add_listener(self, *ls):
        self.listeners.extend(ls)
        return self
