"""Parameter constraints (projections applied after each update).

Reference analog: nn/conf/constraint/ in /root/reference/deeplearning4j-nn —
MaxNormConstraint, MinMaxNormConstraint, NonNegativeConstraint,
UnitNormConstraint; applied by applyConstraints after the optimizer step
(StochasticGradientDescent.java:97).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from deeplearning4j_tpu.utils.serde import register_config


def _param_keys(layer, params, apply_to):
    if apply_to == "weights":
        return [k for k in params if k in getattr(layer, "WEIGHT_KEYS", ("W",))]
    if apply_to == "biases":
        return [k for k in params if k in getattr(layer, "BIAS_KEYS", ("b",))]
    return list(params)


def _col_norms(w):
    """L2 norm per output unit (last axis), matching the reference's
    per-output-neuron norm convention."""
    axes = tuple(range(w.ndim - 1))
    return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True) + 1e-12)


@register_config
@dataclasses.dataclass(frozen=True)
class MaxNormConstraint:
    max_norm: float = 2.0
    apply_to: str = "weights"

    def apply(self, layer, params, iteration, epoch):
        out = dict(params)
        for k in _param_keys(layer, params, self.apply_to):
            norms = _col_norms(out[k])
            out[k] = out[k] * jnp.minimum(1.0, self.max_norm / norms)
        return out


@register_config
@dataclasses.dataclass(frozen=True)
class MinMaxNormConstraint:
    min_norm: float = 0.0
    max_norm: float = 2.0
    rate: float = 1.0
    apply_to: str = "weights"

    def apply(self, layer, params, iteration, epoch):
        out = dict(params)
        for k in _param_keys(layer, params, self.apply_to):
            norms = _col_norms(out[k])
            clipped = jnp.clip(norms, self.min_norm, self.max_norm)
            target = self.rate * clipped + (1 - self.rate) * norms
            out[k] = out[k] * (target / norms)
        return out


@register_config
@dataclasses.dataclass(frozen=True)
class NonNegativeConstraint:
    apply_to: str = "all"

    def apply(self, layer, params, iteration, epoch):
        out = dict(params)
        for k in _param_keys(layer, params, self.apply_to):
            out[k] = jnp.maximum(out[k], 0.0)
        return out


@register_config
@dataclasses.dataclass(frozen=True)
class UnitNormConstraint:
    apply_to: str = "weights"

    def apply(self, layer, params, iteration, epoch):
        out = dict(params)
        for k in _param_keys(layer, params, self.apply_to):
            out[k] = out[k] / _col_norms(out[k])
        return out
