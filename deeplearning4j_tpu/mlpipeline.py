"""ML-pipeline adapters: scikit-learn Estimator/Transformer wrappers.

Reference analog: deeplearning4j-scaleout/spark/dl4j-spark-ml —
``SparkDl4jNetwork.scala`` (an org.apache.spark.ml Estimator whose
``train(DataFrame)`` fits a network and returns a ``SparkDl4jModel`` with
``predict``) and ``AutoEncoder.scala`` (an unsupervised Transformer).
That tier exists so networks drop into the host ecosystem's pipeline API
(feature scaling -> model -> grid search). The Python ecosystem's
pipeline API is scikit-learn, so the adapters implement the sklearn
estimator contract instead of the JVM one: ``get_params``/``set_params``
(clonable, GridSearchCV-compatible), ``fit``/``predict``/
``predict_proba``/``transform``, and they compose inside
``sklearn.pipeline.Pipeline``.

The wrapped network is this framework's ``MultiLayerNetwork``; configs
are the frozen dataclass DSL, so cloning an estimator shares the config
object safely.
"""

from __future__ import annotations

import jax
import numpy as np

try:
    from sklearn.base import (BaseEstimator, ClassifierMixin, RegressorMixin,
                              TransformerMixin)
except ImportError:  # pragma: no cover - sklearn is in the target image
    class BaseEstimator:  # minimal stand-ins keep import working
        def get_params(self, deep=True):
            return {k: v for k, v in self.__dict__.items()
                    if not k.endswith("_")}

        def set_params(self, **p):
            for k, v in p.items():
                setattr(self, k, v)
            return self

    class ClassifierMixin:
        pass

    class RegressorMixin:
        pass

    class TransformerMixin:
        def fit_transform(self, X, y=None, **kw):
            return self.fit(X, y, **kw).transform(X)


def _fit_network(conf, X, Y, epochs, batch_size, seed):
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    net = MultiLayerNetwork(conf)
    net.init(rng=None if seed is None else jax.random.PRNGKey(seed))
    net.fit(np.asarray(X, np.float32), Y, epochs=epochs,
            batch_size=batch_size)
    return net


class NeuralNetClassifier(ClassifierMixin, BaseEstimator):
    """sklearn classifier over a MultiLayerConfiguration (reference:
    SparkDl4jNetwork + SparkDl4jModel.predict = argmax). ``conf``'s output
    layer width must match the number of classes."""

    def __init__(self, conf=None, epochs=5, batch_size=32, seed=None):
        self.conf = conf
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed

    def fit(self, X, y):
        assert self.conf is not None, "conf= is required"
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        width = self.conf.layers[-1].n_out
        if len(self.classes_) > width:
            raise ValueError(
                f"y has {len(self.classes_)} classes but the conf's output "
                f"layer is {width} wide")
        # one-hot at the CONFIGURED width: a CV fold missing some classes
        # still trains the right objective (unseen columns get no mass)
        idx = np.searchsorted(self.classes_, y)
        onehot = np.eye(width, dtype=np.float32)[idx]
        self.net_ = _fit_network(self.conf, X, onehot, self.epochs,
                                 self.batch_size, self.seed)
        return self

    def predict_proba(self, X):
        # sklearn contract: one column PER OBSERVED CLASS, rows sum to 1
        # (the conf's output may be wider when a CV fold misses classes)
        out = np.asarray(self.net_.output(np.asarray(X, np.float32)))
        out = out[:, :len(self.classes_)]
        return out / np.clip(out.sum(-1, keepdims=True), 1e-9, None)

    def predict(self, X):
        return self.classes_[np.argmax(self.predict_proba(X), axis=-1)]


class NeuralNetRegressor(RegressorMixin, BaseEstimator):
    """sklearn regressor (reference: SparkDl4jModel 'continuous for
    regression')."""

    def __init__(self, conf=None, epochs=5, batch_size=32, seed=None):
        self.conf = conf
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed

    def fit(self, X, y):
        assert self.conf is not None, "conf= is required"
        y = np.asarray(y, np.float32)
        if y.ndim == 1:
            y = y[:, None]
        self.net_ = _fit_network(self.conf, X, y, self.epochs,
                                 self.batch_size, self.seed)
        return self

    def predict(self, X):
        out = np.asarray(self.net_.output(np.asarray(X, np.float32)))
        return out[:, 0] if out.shape[-1] == 1 else out


class AutoEncoderTransformer(TransformerMixin, BaseEstimator):
    """Unsupervised encoder (reference: AutoEncoder.scala — fit the
    autoencoder on features, transform = activations of the compressed
    layer). ``conf`` must reconstruct its input (loss vs X itself);
    ``code_layer`` indexes the layer whose OUTPUT is the code (default:
    the middle layer)."""

    def __init__(self, conf=None, code_layer=None, epochs=5, batch_size=32,
                 seed=None):
        self.conf = conf
        self.code_layer = code_layer
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed

    def fit(self, X, y=None):
        assert self.conf is not None, "conf= is required"
        X = np.asarray(X, np.float32)
        self.net_ = _fit_network(self.conf, X, X, self.epochs,
                                 self.batch_size, self.seed)
        n = len(self.conf.layers)
        self.code_layer_ = (self.code_layer if self.code_layer is not None
                            else (n - 1) // 2)
        return self

    def transform(self, X):
        # stop at the code layer — no need to run the decoder half
        code, _ = self.net_.apply_fn(
            self.net_.params, self.net_.state,
            np.asarray(X, np.float32), train=False,
            layer_limit=self.code_layer_ + 1)
        return np.asarray(code)

    def reconstruct(self, X):
        return np.asarray(self.net_.output(np.asarray(X, np.float32)))
