"""Measurement harness: chained in-jit candidate timing + parity gate.

Timing discipline is the one the measured ``_MIN_SEQ`` crossover note
(ops/attention_pallas.py) was produced with, and the same reason bench.py
threads state through its timed windows: ``jax.block_until_ready`` over
the axon tunnel returns before device work completes, and per-dispatch
host overhead swamps a single kernel launch. So each candidate is timed
as **one jitted call that runs the kernel ``iters`` times chained** — a
``lax.fori_loop`` whose carry feeds back into the next iteration's input
(a data dependence XLA cannot elide) — and the only barrier is a host
fetch of the final carry. dt = elapsed / iters, best of ``reps`` windows
(CPU/tunnel jitter does not survive a best-of; a real difference does).

Every candidate is **parity-gated against the reference path before it
may win**: the candidate's raw output is compared leafwise to the
reference's (default tol 1e-6, NaN-poisoned comparisons fail). A
candidate that fails parity counts a ``tuning_db_total{event=reject}``
and can never be persisted — a fast wrong kernel is not a winner.

Candidate compiles route through the blessed
``utils/compile_cache.aot_compile`` site (graftlint R3 exempts the
jit-into-aot_compile idiom inside the candidate loop: one deliberate,
manifest-aware compile per candidate is the autotuner working, not a
recompile hazard).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.tuning import db as _db
from deeplearning4j_tpu.utils.compile_cache import aot_compile


@dataclasses.dataclass
class Measurement:
    """One candidate's outcome: parity diff, per-iteration seconds (None
    when rejected), and the rejection reason when it never ran."""
    config: dict
    seconds_per_iter: float | None = None
    parity: float | None = None
    rejected: str | None = None

    @property
    def ok(self):
        return self.rejected is None


def chain_repeat(fn, iters):
    """``fn(*args)`` repeated ``iters`` times inside one trace, each
    iteration data-dependent on the last (the first float arg is
    perturbed by ``carry * 0``), returning a scalar whose host fetch is
    the completion barrier."""
    def chained(*args):
        chain_idx = next(
            (i for i, a in enumerate(args)
             if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)), None)

        def body(_, carry):
            a2 = list(args)
            if chain_idx is not None:
                a = a2[chain_idx]
                a2[chain_idx] = a + (carry * 0).astype(a.dtype)
            out = fn(*a2)
            leaf = jax.tree_util.tree_leaves(out)[0]
            return leaf.reshape(-1)[0].astype(jnp.float32)

        return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

    return chained


def parity_diff(out, ref):
    """Max abs elementwise difference across the two pytrees' leaves in
    f32, or inf on structure/shape mismatch — the number the ≤tol parity
    gate compares. NaN anywhere returns inf (a NaN-emitting candidate
    must fail, not slide through a ``<=`` that is False-but-passing)."""
    lo, to = jax.tree_util.tree_flatten(out)
    lr, tr = jax.tree_util.tree_flatten(ref)
    if to != tr or len(lo) != len(lr):
        return float("inf")
    worst = 0.0
    for a, b in zip(lo, lr):
        a = np.asarray(jax.device_get(a), dtype=np.float32)
        b = np.asarray(jax.device_get(b), dtype=np.float32)
        if a.shape != b.shape:
            return float("inf")
        d = float(np.max(np.abs(a - b))) if a.size else 0.0
        if not np.isfinite(d):
            return float("inf")
        worst = max(worst, d)
    return worst


def time_callable(fn, args, *, iters=4, warmup=1, reps=2):
    """Best-of-``reps`` chained in-jit seconds-per-iteration of
    ``fn(*args)``. The compile routes through ``aot_compile`` (blessed
    site); the executable is reused across windows so only device time
    is in the window."""
    chained = chain_repeat(fn, iters)
    jitted = jax.jit(chained)
    ex, _src = aot_compile(jitted, *args)

    def call():
        try:
            return ex(*args)
        except TypeError:  # AOT arg-passing quirk: fall back to the jit
            return jitted(*args)

    for _ in range(max(1, warmup)):
        jax.device_get(call())
    best = None
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        jax.device_get(call())
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    return best


def search(kernel, candidates, build, args, ref_fn, *, build_check=None,
           tol=1e-6, iters=4, warmup=1, reps=2, log=None):
    """Measure ``candidates`` and return ``(winner, results)``.

    ``build(config)`` -> the timed callable; ``build_check(config)`` (or
    ``build`` itself) -> the callable whose output is parity-compared to
    ``ref_fn(*args)``. A candidate whose check output differs from the
    reference by more than ``tol`` (or whose build/run raises) is
    REJECTED — counted, never timed, never a winner. ``winner`` is the
    fastest surviving Measurement, or None when everything rejected."""
    ref_out = ref_fn(*args)
    results, winner = [], None
    for cfg in candidates:
        m = Measurement(dict(cfg))
        try:
            check_fn = (build_check or build)(cfg)
            m.parity = parity_diff(check_fn(*args), ref_out)
            if not (m.parity <= tol):
                raise _ParityError(
                    f"parity {m.parity:.3g} exceeds tol {tol:.3g}")
            timed = build(cfg) if build_check is not None else check_fn
            # one deliberate compile per candidate, through the blessed
            # manifest-aware site (graftlint R3's autotune idiom)
            m.seconds_per_iter = time_callable(
                timed, args, iters=iters, warmup=warmup, reps=reps)
        except Exception as e:
            m.rejected = str(e) or type(e).__name__
            _db.count_event("reject")
            results.append(m)
            if log:
                log(f"  {kernel} {cfg}: REJECTED ({m.rejected})")
            continue
        results.append(m)
        if winner is None or m.seconds_per_iter < winner.seconds_per_iter:
            winner = m
        if log:
            log(f"  {kernel} {cfg}: {1e3 * m.seconds_per_iter:.3f} ms/iter"
                f" (parity {m.parity:.2g})")
    return winner, results


class _ParityError(ValueError):
    pass
