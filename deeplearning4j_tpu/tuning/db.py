"""Persistent kernel-tuning database + process-wide runtime lookup.

The search half lives in tuning/measure.py / tuning/tune.py; this module
owns what survives it: winners keyed like PR 9's ``WarmManifest`` —
**kernel id x shape bucket x dtype x backend+jax version** — persisted as
one JSON artifact (env ``DL4J_TPU_TUNING_DB``, populated by the ``tune``
CLI verb) that the ops-layer dispatch seams consult at trace time.

Trust/degradation model mirrors the compile-cache tier: a corrupt or
newer-versioned DB warns, counts a ``mismatch_drop``, and degrades to the
hand-picked kernel defaults — never a crash; a DB tuned on another
backend simply yields misses (the backend fingerprint is part of every
key). Every interaction counts into
``tuning_db_total{event=hit|miss|tune|reject|mismatch_drop}``:

* ``hit``/``miss`` — a dispatch-seam lookup found / did not find a tuned
  config for the (bucketed) call shape;
* ``tune`` — a searched winner was recorded;
* ``reject`` — a candidate failed the parity gate during search (see
  tuning/measure.py) and was discarded;
* ``mismatch_drop`` — a corrupt/newer-version DB artifact was refused.

Lookups happen at TRACE time (shapes are static), so the counters move
once per compile, not per step — and ``aot_compile`` folds the active
DB's content fingerprint into manifest signatures, so a re-tuned DB
invalidates stale warm-manifest executables instead of silently serving
kernels tuned under the old configs.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import warnings

__all__ = ["ENV_DB", "TuningDB", "active_db", "active_fingerprint",
           "bucket_shape", "count_event", "event_counts", "set_db",
           "tuned_config"]

#: environment variable naming the tuning-DB JSON artifact
ENV_DB = "DL4J_TPU_TUNING_DB"

DB_VERSION = 1


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def _counter():
    from deeplearning4j_tpu import telemetry as _tm
    return _tm.get_registry().counter(
        "tuning_db_total",
        "kernel-tuning DB interactions by event: hit (dispatch found a "
        "tuned config for the call's shape bucket), miss (no entry — "
        "hand-picked defaults apply), tune (a searched winner was "
        "recorded), reject (a candidate failed the parity gate during "
        "search), mismatch_drop (corrupt or newer-version DB artifact "
        "refused at load — defaults apply)")


def count_event(event, n=1):
    """Count one ``tuning_db_total`` interaction."""
    _counter().inc(n, event=event)


def event_counts():
    """{event: count} snapshot of ``tuning_db_total`` (bench gates and
    the CLI summary)."""
    from deeplearning4j_tpu import telemetry as _tm
    c = _tm.get_registry().get("tuning_db_total")
    if c is None:
        return {}
    return {ls.get("event", ""): c.value(**ls) for ls in c.labelsets()}


# ---------------------------------------------------------------------------
# keys
# ---------------------------------------------------------------------------

def bucket_shape(shape):
    """Each dim rounded up to the next power of two — one tuned entry
    covers the whole bucket, the same shape-coarsening the serving tier's
    batch buckets apply (a T=1000 call reuses the T=1024 winner instead
    of missing)."""
    out = []
    for d in shape:
        d = int(d)
        out.append(d if d <= 1 else 1 << (d - 1).bit_length())
    return tuple(out)


def _dtype_str(dtype):
    """Canonical dtype spelling ("float32", "bfloat16") whatever form the
    caller holds — np.dtype, the jnp scalar type, or a string."""
    try:
        import numpy as np
        return str(np.dtype(dtype))
    except Exception:
        return str(getattr(dtype, "name", dtype) or dtype)


def _key(kernel, shape, dtype, backend_fp):
    bucket = ",".join(str(d) for d in bucket_shape(shape))
    return f"{kernel}|{bucket}|{_dtype_str(dtype)}|{backend_fp}"


class TuningDB:
    """Searched kernel winners, keyed (kernel, shape bucket, dtype,
    backend fingerprint), JSON round-trip."""

    def __init__(self, path=None):
        self.path = path
        self.entries = {}  # key -> {"config": {...}, "score_ms": ...}
        self._lock = threading.Lock()

    @staticmethod
    def backend_fingerprint():
        from deeplearning4j_tpu.utils.compile_cache import backend_fingerprint
        return backend_fingerprint()

    def __len__(self):
        with self._lock:
            return len(self.entries)

    def record(self, kernel, shape, dtype, config, score_ms=None,
               meta=None):
        """Persist a parity-gated winner for this shape bucket (counts
        ``tune``). Overwrites any previous winner for the key — a
        re-tune IS the refresh."""
        entry = {"config": dict(config),
                 "kernel": kernel,
                 "shape_bucket": list(bucket_shape(shape)),
                 "dtype": _dtype_str(dtype)}
        if score_ms is not None:
            entry["score_ms"] = round(float(score_ms), 6)
        if meta:
            entry.update(meta)
        key = _key(kernel, shape, dtype, self.backend_fingerprint())
        with self._lock:
            self.entries[key] = entry
        count_event("tune")
        return entry

    def lookup(self, kernel, shape, dtype):
        """The tuned config dict for this call's shape bucket, or None.
        Counts ``hit``/``miss`` — at trace time, so once per compile."""
        key = _key(kernel, shape, dtype, self.backend_fingerprint())
        with self._lock:
            entry = self.entries.get(key)
        if entry is None:
            count_event("miss")
            return None
        count_event("hit")
        return dict(entry["config"])

    def fingerprint(self):
        """Content hash of the entries — folded into warm-manifest
        signatures (utils/compile_cache.full_signature) so a DB refresh
        invalidates executables baked with stale configs."""
        with self._lock:
            doc = json.dumps(self.entries, sort_keys=True)
        return hashlib.sha256(doc.encode()).hexdigest()[:16]

    # -- persistence ---------------------------------------------------

    def save(self, path=None):
        """Atomic JSON write (tmp + rename — a crashed tuner never
        leaves a truncated DB a later start would refuse)."""
        path = path or self.path
        if not path:
            raise ValueError("TuningDB.save: no path (pass one or "
                             "construct with path=)")
        with self._lock:
            entries = dict(self.entries)
        doc = {"tuning_db_version": DB_VERSION,
               "backend_note": self.backend_fingerprint(),
               "entries": entries}
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.path = path
        return path

    @classmethod
    def load(cls, path):
        with open(path) as f:
            doc = json.load(f)
        ver = doc.get("tuning_db_version", 0)
        if not isinstance(doc.get("entries"), dict):
            raise ValueError("not a tuning DB (no entries map)")
        if ver > DB_VERSION:
            raise ValueError(f"tuning DB version {ver} is newer than "
                             f"supported {DB_VERSION}")
        db = cls(path)
        db.entries = dict(doc["entries"])
        return db

    @classmethod
    def load_lenient(cls, path, context="tuning DB"):
        """``load`` that degrades instead of raising: a corrupt or
        newer-version artifact warns, counts ``mismatch_drop``, and
        returns None — the hand-picked defaults apply, never a crash. A
        missing file is the normal before-first-tune state (silent)."""
        try:
            return cls.load(path)
        except FileNotFoundError:
            return None
        except Exception as e:
            warnings.warn(
                f"{context} at {path!r} is unusable ({e}) — ignoring it; "
                "the hand-picked kernel defaults apply", stacklevel=3)
            count_event("mismatch_drop")
            return None


# ---------------------------------------------------------------------------
# process-wide runtime lookup (the dispatch seams' entry point)
# ---------------------------------------------------------------------------

_rt_lock = threading.Lock()
_rt = {"explicit": False, "db": None, "path": None, "mtime": None}


def set_db(db):
    """Bind ``db`` as the process's active tuning DB (tests, the tune
    CLI, bench legs). ``set_db(None)`` returns to env-var resolution."""
    with _rt_lock:
        _rt["explicit"] = db is not None
        _rt["db"] = db
        _rt["path"] = None
        _rt["mtime"] = None


def active_db():
    """The active TuningDB: an explicit ``set_db`` binding, else the
    ``$DL4J_TPU_TUNING_DB`` artifact (cached by path+mtime so trace-time
    lookups never re-read an unchanged file), else None."""
    with _rt_lock:
        if _rt["explicit"]:
            return _rt["db"]
        path = os.environ.get(ENV_DB)
        if not path:
            _rt["db"], _rt["path"], _rt["mtime"] = None, None, None
            return None
        try:
            mtime = os.stat(path).st_mtime_ns
        except OSError:
            mtime = None  # missing file: cache the miss until it appears
        if _rt["path"] == path and _rt["mtime"] == mtime:
            return _rt["db"]
        _rt["path"], _rt["mtime"] = path, mtime
        _rt["db"] = (TuningDB.load_lenient(path)
                     if mtime is not None else None)
        return _rt["db"]


def active_fingerprint():
    """Content fingerprint of the active DB, or None when no DB is
    bound — the manifest-signature ingredient (see
    utils/compile_cache.full_signature)."""
    db = active_db()
    return None if db is None or not len(db) else db.fingerprint()


def tuned_config(kernel, shape, dtype):
    """The tuned config for this call, or None (no DB bound, or no entry
    for the bucket — hand-picked defaults apply). The ONE function the
    ops dispatch seams call; it never raises."""
    try:
        db = active_db()
        if db is None:
            return None
        return db.lookup(kernel, shape, dtype)
    except Exception:  # a tuning lookup must never kill a trace
        return None
