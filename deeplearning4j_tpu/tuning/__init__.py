"""Kernel autotuner: searched Pallas configs with a persistent tuning DB.

TVM-mold pipeline (PAPERS.md arxiv 1802.04799) over the hand-tuned
Pallas kernels:

* :mod:`tuning.space` — per-kernel config spaces with static validity
  pruning (VMEM budget, the TPU (8,128) tile rule) so invalid candidates
  never pay a compile;
* :mod:`tuning.measure` — chained in-jit candidate timing with a
  parity gate (every winner verified ≤tol against the reference path);
* :mod:`tuning.db` — the persistent :class:`TuningDB`, keyed kernel id x
  shape bucket x dtype x backend+jax version, consulted by the ops-layer
  dispatch seams at trace time (env ``DL4J_TPU_TUNING_DB``), every
  interaction counted into ``tuning_db_total{event=}``;
* :mod:`tuning.tune` — the per-kernel search drivers behind the ``tune``
  CLI verb.

A populated DB composes with PR 9's warm manifests: the tuned configs
resolve at trace time, so ``aot_compile`` serializes TUNED executables —
and folds the DB's content fingerprint into the manifest signature, so a
warm restart loads tuned kernels with zero compiles while a re-tuned DB
cleanly invalidates the stale entries.
"""

from deeplearning4j_tpu.tuning.db import (ENV_DB, TuningDB, active_db,
                                          active_fingerprint, bucket_shape,
                                          event_counts, set_db,
                                          tuned_config)
from deeplearning4j_tpu.tuning.measure import (Measurement, parity_diff,
                                               search, time_callable)
from deeplearning4j_tpu.tuning.space import (SPACES, VMEM_BUDGET,
                                             enumerate_space, prune,
                                             validate)
from deeplearning4j_tpu.tuning.tune import KERNELS, tune_kernels

__all__ = ["ENV_DB", "KERNELS", "Measurement", "SPACES", "TuningDB",
           "VMEM_BUDGET", "active_db", "active_fingerprint",
           "bucket_shape", "enumerate_space", "event_counts",
           "parity_diff", "prune", "search", "set_db", "time_callable",
           "tune_kernels", "tuned_config", "validate"]
