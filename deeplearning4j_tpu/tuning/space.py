"""Per-kernel Pallas config spaces with static validity pruning.

The Pallas kernels shipped hand-picked constants — attention
``block_q/block_k`` (512x512, chosen once on one v5e window), conv
``bn/bj/bk`` tile geometry and the 3x3 batch-row target, the LSTM
``tile_cols`` column width. This module parameterizes them as searchable
spaces in the TVM mold (PAPERS.md arxiv 1802.04799): enumerate
candidates, then reject statically-invalid ones BEFORE any compile —

* the TPU **(8, 128) tile rule**: a block dimension mapped to the lane
  (minor) axis must be a 128-multiple, the sublane (second-minor) axis an
  8-multiple — real-TPU compiles reject violations with an opaque mosaic
  error, so the space prunes them for free;
* the **VMEM budget**: per-grid-step block residency (double-buffered
  in/out blocks + scratch + the score/accumulator tile) must fit the
  ~16 MiB scoped VMEM; the estimate uses the same arithmetic the kernel
  docstrings derive (14 MiB budget — the margin ops/lstm_pallas.py
  already uses);
* **redundant clamps**: blocks larger than the (128-rounded) array are
  clamped by the kernels at trace time, so such candidates duplicate a
  smaller one — measuring them would just burn live-window time;
* kernel-specific divisibility (the LSTM column tile must divide 4H —
  the kernel's own tile-picker constraint).

Pruning is backend-independent on purpose: the DB a CPU smoke populates
exercises the same validity logic a live-TPU window relies on.
"""

from __future__ import annotations

import itertools

import numpy as np

#: scoped-VMEM budget for a candidate's per-grid-step residency; the same
#: ~16 MiB-minus-margin ops/lstm_pallas.py's supported() uses
VMEM_BUDGET = 14 * 1024 * 1024
LANE = 128
SUBLANE = 8

#: searchable dimensions per kernel id. ``remat`` on the attention space
#: is honored by the measurement harness only in fwd+bwd mode (forward
#: timing cannot distinguish it) — see enumerate_space(include_remat=).
SPACES = {
    "attention": {"block_q": (128, 256, 512, 1024),
                  "block_k": (128, 256, 512, 1024),
                  "remat": (False, True)},
    "conv_matmul": {"bn": (128, 256, 512),
                    "bk": (128, 256, 512),
                    "bj": (128, 256, 512)},
    "conv3x3": {"bt_target": (128, 256, 512),
                "bj": (128, 256, 512)},
    "lstm": {"tile_cols": (256, 512, 1024, 2048)},
}


def _round_up(n, m):
    return -(-int(n) // m) * m


def _itemsize(dtype):
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        return 4


def enumerate_space(kernel, *, include_remat=False):
    """Every candidate config dict in ``kernel``'s space (cartesian
    product of the dimensions). The ``remat`` dimension is collapsed to
    False unless ``include_remat`` — forward-only measurement cannot
    tell remat variants apart, so enumerating both would double the
    candidate count for identical timings."""
    dims = dict(SPACES[kernel])
    if "remat" in dims and not include_remat:
        dims["remat"] = (False,)
    keys = sorted(dims)
    return [dict(zip(keys, vals))
            for vals in itertools.product(*(dims[k] for k in keys))]


# ---------------------------------------------------------------------------
# per-kernel validity
# ---------------------------------------------------------------------------

def _attention_valid(cfg, shape, dtype):
    """shape: layer-level [B, T, H, D]."""
    bq, bk = int(cfg["block_q"]), int(cfg["block_k"])
    _, t, _, d = shape
    if bq % LANE or bk % LANE:
        # block_q rides the LANE axis of the [1, 8, Bq] lse output block
        # and block_k the lane axis of the [Bq, Bk] score tile / mask
        # block — both must be 128-multiples (the round-2 lse lesson)
        return "tile rule: block_q/block_k must be 128-multiples"
    t128 = _round_up(t, LANE)
    if bq > t128 or bk > t128:
        return "redundant: block exceeds the 128-rounded sequence (clamps)"
    dp = _round_up(d, LANE)
    itm = _itemsize(dtype)
    vmem = (
        2 * bq * dp * itm          # q block, double-buffered
        + 2 * 2 * bk * dp * itm    # k + v blocks, double-buffered
        + 2 * bq * dp * itm        # out block
        + 2 * 8 * bq * 4           # lse block (8-sublane broadcast)
        + bq * dp * 4 + 2 * bq * 4  # acc/m/l scratch (f32)
        + bq * bk * 4              # the score tile
    )
    if vmem > VMEM_BUDGET:
        return f"vmem: ~{vmem // 1024} KiB exceeds the {VMEM_BUDGET // 1024} KiB budget"
    return None


def _conv_matmul_valid(cfg, shape, dtype):
    """shape: (n_rows, cin, cout) of the 1x1-conv GEMM."""
    bn, bk, bj = int(cfg["bn"]), int(cfg["bk"]), int(cfg["bj"])
    n, cin, cout = shape
    if bn % SUBLANE:
        return "tile rule: bn (sublane rows) must be an 8-multiple"
    if bk % LANE or bj % LANE:
        return "tile rule: bk/bj (lane dims) must be 128-multiples"
    if bn > _round_up(n, SUBLANE) or bk > _round_up(cin, LANE) \
            or bj > _round_up(cout, LANE):
        return "redundant: block exceeds the padded array (clamps)"
    itm = _itemsize(dtype)
    vmem = (bn * bj * 4 + 8 * bj * 4          # acc + stats scratch (f32)
            + 2 * (bn * bk + bk * bj) * itm   # x/w blocks, double-buffered
            + 2 * bn * bj * itm + 2 * 8 * bj * 4)  # z + stats out blocks
    if vmem > VMEM_BUDGET:
        return f"vmem: ~{vmem // 1024} KiB exceeds the {VMEM_BUDGET // 1024} KiB budget"
    return None


def conv3x3_bt(bt_target, bsz, wout):
    """The batch-row tile a ``bt_target`` resolves to at this geometry —
    the same arithmetic ops/conv_pallas.py applies (keep the row-block
    GEMM M-dim near the target without exceeding it wildly), shared so
    validation and the kernel agree."""
    bt = max(1, min(int(bsz), max(1, int(bt_target) // max(int(wout), 1))))
    while bsz % bt:
        bt -= 1
    return bt


def _conv3x3_valid(cfg, shape, dtype):
    """shape: (b, h, w, cin, cout) of the SAME 3x3 conv (stride 1)."""
    bj = int(cfg["bj"])
    b, h, w, cin, cout = shape
    if bj % LANE:
        return "tile rule: bj (lane dim) must be a 128-multiple"
    if bj > _round_up(cout, LANE):
        return "redundant: bj exceeds the padded Cout (clamps)"
    bt = conv3x3_bt(cfg["bt_target"], b, w)
    cinp = _round_up(cin, LANE)
    wp = w + 2  # stride-1 SAME halo
    itm = _itemsize(dtype)
    vmem = (3 * 2 * bt * wp * cinp * itm      # 3 halo row refs, dbl-buffered
            + 2 * 9 * cinp * bj * itm         # the [3,3,Cin,Cout] block
            + 2 * bt * w * bj * itm           # z out block
            + bt * w * bj * 4                 # the f32 row accumulator
            + 2 * 8 * bj * 4)                 # stats scratch + out
    if vmem > VMEM_BUDGET:
        return f"vmem: ~{vmem // 1024} KiB exceeds the {VMEM_BUDGET // 1024} KiB budget"
    return None


def _lstm_valid(cfg, shape, dtype):
    """shape: (t, b, hp) with hp the 128-padded hidden size. The tile
    dimension only exists for the tiled (H > 512) kernel — the resident
    kernel holds the whole Wh block."""
    tile = int(cfg["tile_cols"])
    _, b, hp = shape
    four_h = 4 * hp
    if tile % LANE:
        return "tile rule: tile_cols must be a 128-multiple"
    if tile > four_h:
        return "redundant: tile exceeds 4H (clamps)"
    if four_h % tile:
        return "tile_cols must divide 4H (the kernel's column-tile grid)"
    itm = _itemsize(dtype)
    vmem = (b * four_h * 4                    # persistent gate accumulator
            + 2 * b * hp * 4                  # h/c scratch (f32)
            + 2 * hp * tile * itm             # in-flight Wh tiles
            + b * tile * 4                    # xz block (f32 add)
            + 2 * b * hp * itm)               # h/c out blocks
    if vmem > VMEM_BUDGET:
        return f"vmem: ~{vmem // 1024} KiB exceeds the {VMEM_BUDGET // 1024} KiB budget"
    return None


_VALIDATORS = {"attention": _attention_valid,
               "conv_matmul": _conv_matmul_valid,
               "conv3x3": _conv3x3_valid,
               "lstm": _lstm_valid}


def validate(kernel, config, shape, dtype):
    """None when ``config`` may compile at ``shape``/``dtype``; otherwise
    the human-readable rejection reason (tile rule, VMEM budget,
    redundant clamp, divisibility)."""
    return _VALIDATORS[kernel](config, tuple(int(d) for d in shape), dtype)


def prune(kernel, configs, shape, dtype):
    """Split ``configs`` into (valid, rejected) where rejected carries
    ``(config, reason)`` pairs — the static gate that runs before any
    candidate pays a compile."""
    valid, rejected = [], []
    for cfg in configs:
        reason = validate(kernel, cfg, shape, dtype)
        if reason is None:
            valid.append(cfg)
        else:
            rejected.append((cfg, reason))
    return valid, rejected
