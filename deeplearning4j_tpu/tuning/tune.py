"""Per-kernel tuning drivers: enumerate → prune → measure → persist.

One driver per searchable kernel family (attention, conv_matmul,
conv3x3, lstm). Each enumerates its config space (tuning/space.py),
statically prunes invalid candidates (VMEM budget, (8,128) tile rule,
redundant clamps — counted in the summary, never compiled), measures the
survivors with the chained in-jit harness (tuning/measure.py), and
records the parity-gated winner into the TuningDB (tuning/db.py).

The attention driver additionally searches the **seq-length crossover**:
the naive XLA fused path rides along as an implicit candidate
(``{"backend": "xla"}``), so the DB entry records not just the best
block geometry but whether the Pallas kernel should run AT ALL for this
shape bucket — replacing the hand-measured ``_MIN_SEQ`` heuristic with a
measured, per-bucket decision. With ``grad=True`` the attention space
also opens the remat dimension (checkpoint the forward inside the
backward — memory for time), which forward-only timing cannot observe.

``interpret=True`` runs every Pallas candidate in interpret mode — the
CPU-mechanics path the tune CLI smoke and tier-1 use; timings are then
relative-only and the value is exercising the full pipeline, not the
numbers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.tuning import db as _dbm
from deeplearning4j_tpu.tuning import space as _space
from deeplearning4j_tpu.tuning.measure import search

_F32 = jnp.float32


def _rs(seed=0):
    return np.random.RandomState(seed)


def _summary(kernel, shape, dtype, valid, rejected_static, winner,
             results, default_config):
    rejected_parity = [m for m in results if not m.ok]
    return {
        "kernel": kernel,
        "shape": [int(d) for d in shape],
        "dtype": str(np.dtype(dtype)),
        "candidates": len(valid),
        "pruned_static": len(rejected_static),
        "pruned_reasons": sorted({r for _, r in rejected_static}),
        "rejected_parity": len(rejected_parity),
        "winner": None if winner is None else winner.config,
        "winner_ms": (None if winner is None
                      else round(1e3 * winner.seconds_per_iter, 6)),
        "default_config": default_config,
        "timings_ms": {str(m.config): round(1e3 * m.seconds_per_iter, 6)
                       for m in results if m.ok},
    }


# ---------------------------------------------------------------------------
# attention (+ the seq-length crossover and the remat dimension)
# ---------------------------------------------------------------------------

def naive_attention(q, k, v):
    """The reference path the parity gate compares against and the
    crossover's XLA candidate: plain [B,T,H,D] self-attention with f32
    softmax — the same math nn/layers/attention.py falls back to."""
    d = q.shape[-1]
    scale = 1.0 / float(d) ** 0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=_F32) * scale
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(q.dtype), v,
                      preferred_element_type=_F32).astype(q.dtype)


def tune_attention(dbase, *, b=4, t=1024, h=4, d=64, dtype=_F32,
                   interpret=False, grad=False, iters=4, warmup=1, reps=2,
                   candidates=None, tol=1e-6, include_xla=True, log=None):
    """Search attention block geometry (+ crossover + remat-under-grad)
    at [b, t, h, d] and record the winner. ``include_xla=False`` drops
    the crossover candidate — for legs that must exercise the Pallas
    block override itself (CPU interpret mode, where the interpreted
    kernel can never outrun XLA and the crossover verdict would always
    be "xla")."""
    from deeplearning4j_tpu.ops import attention_pallas as _ap
    shape = (b, t, h, d)
    rs = _rs()
    q, k, v = (jnp.asarray(rs.normal(size=shape) * 0.1, dtype)
               for _ in range(3))
    if candidates is None:
        candidates = _space.enumerate_space("attention", include_remat=grad)
    valid, rejected = _space.prune("attention", candidates, shape, dtype)
    if include_xla:
        # the crossover candidate: "don't run the Pallas kernel at all"
        valid = valid + [{"backend": "xla"}]

    def fwd_of(cfg):
        if cfg.get("backend") == "xla":
            return naive_attention
        return functools.partial(
            _ap.flash_attention, block_q=int(cfg["block_q"]),
            block_k=int(cfg["block_k"]), interpret=interpret)

    def build_timed(cfg):
        fwd = fwd_of(cfg)
        if not grad:
            return fwd
        if cfg.get("remat"):
            fwd = jax.checkpoint(fwd)

        def loss(q, k, v):
            o = fwd(q, k, v)
            return jnp.sum((o * o).astype(_F32))

        return jax.grad(loss, argnums=(0, 1, 2))

    winner, results = search(
        "attention", valid, build_timed, (q, k, v), naive_attention,
        build_check=fwd_of, tol=tol, iters=iters, warmup=warmup,
        reps=reps, log=log)
    if winner is not None:
        cfg = dict(winner.config)
        cfg.setdefault("backend", "flash")
        if dbase is not None:
            dbase.record("attention", shape, dtype, cfg,
                         score_ms=1e3 * winner.seconds_per_iter,
                         meta={"grad": bool(grad)})
    return _summary("attention", shape, dtype, valid, rejected, winner,
                    results, {"backend": "flash", "block_q": 512,
                              "block_k": 512})


# ---------------------------------------------------------------------------
# conv: the 1x1 GEMM-with-stats kernel
# ---------------------------------------------------------------------------

def _ref_matmul_stats(x2d, w2d):
    z = jnp.dot(x2d.astype(_F32), w2d.astype(_F32),
                preferred_element_type=_F32)
    stats = jnp.stack([jnp.sum(z, axis=0), jnp.sum(z * z, axis=0)])
    return z.astype(x2d.dtype), stats


def tune_conv_matmul(dbase, *, n=2048, cin=128, cout=256, dtype=_F32,
                     interpret=False, iters=4, warmup=1, reps=2,
                     candidates=None, tol=1e-6, log=None):
    """Search the 1x1-conv GEMM tile geometry (bn x bk x bj)."""
    from deeplearning4j_tpu.ops import conv_pallas as _cp
    shape = (n, cin, cout)
    rs = _rs(1)
    x2d = jnp.asarray(rs.normal(size=(n, cin)) * 0.1, dtype)
    w2d = jnp.asarray(rs.normal(size=(cin, cout)) * 0.1, dtype)
    if candidates is None:
        candidates = _space.enumerate_space("conv_matmul")
    valid, rejected = _space.prune("conv_matmul", candidates, shape, dtype)

    def build(cfg):
        return functools.partial(_cp._matmul_stats, interpret=interpret,
                                 bn=int(cfg["bn"]), bk=int(cfg["bk"]),
                                 bj=int(cfg["bj"]))

    winner, results = search(
        "conv_matmul", valid, build, (x2d, w2d), _ref_matmul_stats,
        tol=tol, iters=iters, warmup=warmup, reps=reps, log=log)
    if winner is not None and dbase is not None:
        dbase.record("conv_matmul", shape, dtype, winner.config,
                     score_ms=1e3 * winner.seconds_per_iter)
    return _summary("conv_matmul", shape, dtype, valid, rejected, winner,
                    results, {"bn": 256, "bk": 256, "bj": 512})


# ---------------------------------------------------------------------------
# conv: the SAME 3x3 batch-row kernel
# ---------------------------------------------------------------------------

def _ref_conv3x3_stats(x, w):
    z = jax.lax.conv_general_dilated(
        x.astype(_F32), w.astype(_F32), window_strides=(1, 1),
        padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    stats = jnp.stack([jnp.sum(z, axis=(0, 1, 2)),
                       jnp.sum(z * z, axis=(0, 1, 2))])
    return z.astype(x.dtype), stats


def tune_conv3x3(dbase, *, b=8, hw=32, cin=64, cout=64, dtype=_F32,
                 interpret=False, iters=4, warmup=1, reps=2,
                 candidates=None, tol=1e-6, log=None):
    """Search the 3x3 kernel's batch-row target and Cout tile."""
    from deeplearning4j_tpu.ops import conv_pallas as _cp
    shape = (b, hw, hw, cin, cout)
    rs = _rs(2)
    x = jnp.asarray(rs.normal(size=(b, hw, hw, cin)) * 0.1, dtype)
    w = jnp.asarray(rs.normal(size=(3, 3, cin, cout)) * 0.1, dtype)
    if candidates is None:
        candidates = _space.enumerate_space("conv3x3")
    valid, rejected = _space.prune("conv3x3", candidates, shape, dtype)

    def build(cfg):
        return functools.partial(_cp._conv3x3_stats, interpret=interpret,
                                 stride=1, bt_target=int(cfg["bt_target"]),
                                 bj=int(cfg["bj"]))

    winner, results = search(
        "conv3x3", valid, build, (x, w), _ref_conv3x3_stats,
        tol=tol, iters=iters, warmup=warmup, reps=reps, log=log)
    if winner is not None and dbase is not None:
        dbase.record("conv3x3", shape, dtype, winner.config,
                     score_ms=1e3 * winner.seconds_per_iter)
    return _summary("conv3x3", shape, dtype, valid, rejected, winner,
                    results, {"bt_target": 256, "bj": 512})


# ---------------------------------------------------------------------------
# lstm: the tiled-Wh column width (H > 512 kernel)
# ---------------------------------------------------------------------------

def _ref_lstm(xz, wh, h0, c0):
    """Reference scan over the SAME gate math the kernel runs
    (ops/lstm_pallas._gate_cell is pure jax) — exact parity target."""
    from deeplearning4j_tpu.ops.lstm_pallas import _gate_cell
    hsz = wh.shape[0]

    def body(carry, z_t):
        h, c = carry
        z = z_t.astype(_F32) + jnp.dot(h, wh, preferred_element_type=_F32)
        h2, c2 = _gate_cell(z, c, None, hsz)
        return (h2, c2), h2

    (hT, cT), hs = jax.lax.scan(
        body, (h0.astype(_F32), c0.astype(_F32)), xz)
    return hs.astype(xz.dtype), (hT.astype(xz.dtype), cT.astype(xz.dtype))


def tune_lstm(dbase, *, t=8, b=8, hidden=640, dtype=_F32, interpret=False,
              iters=4, warmup=1, reps=2, candidates=None, tol=1e-6,
              log=None):
    """Search the tiled-Wh column width. Only meaningful past the
    resident ceiling (hidden > 512) — below it the whole Wh block is
    VMEM-resident and there is nothing to tune."""
    from deeplearning4j_tpu.ops import lstm_pallas as _lp
    hp = _lp.pad_hidden(hidden)
    shape = (t, b, hp)
    rs = _rs(3)
    xz = jnp.asarray(rs.normal(size=(t, b, 4 * hp)) * 0.1, dtype)
    wh = jnp.asarray(rs.normal(size=(hp, 4 * hp)) * 0.1, dtype)
    h0 = jnp.zeros((b, hp), dtype)
    c0 = jnp.zeros((b, hp), dtype)
    if candidates is None:
        candidates = _space.enumerate_space("lstm")
    valid, rejected = _space.prune("lstm", candidates, shape, dtype)

    def build(cfg):
        def fn(xz, wh, h0, c0):
            return _lp.fused_sequence_padded(
                xz, wh, h0, c0, interpret=interpret,
                tile_cols=int(cfg["tile_cols"]))
        return fn

    winner, results = search(
        "lstm", valid, build, (xz, wh, h0, c0), _ref_lstm,
        tol=tol, iters=iters, warmup=warmup, reps=reps, log=log)
    if winner is not None and dbase is not None:
        dbase.record("lstm", shape, dtype, winner.config,
                     score_ms=1e3 * winner.seconds_per_iter)
    return _summary("lstm", shape, dtype, valid, rejected, winner,
                    results, {"tile_cols": 1024})


KERNELS = {"attention": tune_attention, "conv_matmul": tune_conv_matmul,
           "conv3x3": tune_conv3x3, "lstm": tune_lstm}

#: trimmed shapes + candidate sets for the CI smoke (CPU interpret mode:
#: the point is exercising the full enumerate→prune→measure→persist→
#: lookup pipeline, not the timings)
SMOKE_PRESETS = {
    "attention": dict(b=1, t=256, h=2, d=32, iters=2, reps=1,
                      include_xla=False,
                      candidates=[{"block_q": 128, "block_k": 128,
                                   "remat": False},
                                  {"block_q": 256, "block_k": 256,
                                   "remat": False}]),
    "conv_matmul": dict(n=256, cin=128, cout=128, iters=2, reps=1,
                        candidates=[{"bn": 128, "bk": 128, "bj": 128},
                                    {"bn": 256, "bk": 128, "bj": 128}]),
    "conv3x3": dict(b=2, hw=8, cin=8, cout=256, iters=2, reps=1,
                    candidates=[{"bt_target": 256, "bj": 128},
                                {"bt_target": 256, "bj": 256}]),
    "lstm": dict(t=3, b=2, hidden=640, iters=2, reps=1,
                 candidates=[{"tile_cols": 256}, {"tile_cols": 512}]),
}


def tune_kernels(dbase, kernels=None, *, smoke=False, interpret=False,
                 grad=False, log=None, **overrides):
    """Run the drivers for ``kernels`` (default: all) against ``dbase``.
    ``smoke=True`` applies the trimmed CI presets; ``overrides`` are
    per-call kwargs forwarded to every driver (iters/reps/tol/...).
    Returns {kernel: summary}."""
    out = {}
    for name in (kernels or sorted(KERNELS)):
        if name not in KERNELS:
            raise ValueError(
                f"unknown kernel {name!r}; known: {sorted(KERNELS)}")
        kw = dict(SMOKE_PRESETS[name]) if smoke else {}
        kw.update(overrides)
        kw.setdefault("interpret", interpret)
        if name == "attention":
            kw.setdefault("grad", grad)
        if log:
            log(f"tuning {name} ...")
        out[name] = KERNELS[name](dbase, log=log, **kw)
    return out
