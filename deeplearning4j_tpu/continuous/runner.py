"""Real-subprocess continuous-training runner (the chaos harness target).

``python -m deeplearning4j_tpu.continuous.runner`` runs ONE continuous
training session — streaming (subscribe to a broker topic) or offline (a
deterministic generated batch list: the reference/resume legs) — and
speaks a machine-readable line protocol on stdout:

* ready:  ``{"continuous_ready": true, "pid": ...}`` once the model is
  built (or resumed) and, in streaming mode, the subscription is live —
  the harness starts its publisher only after this line;
* rounds: ``{"round": r, "iteration": n}`` after every completed round —
  the harness uses these to time a SIGTERM *mid-round*;
* done:   ``{"continuous_done": true, "digest": ..., "summary": ...,
  "counters": ..., "flight_dumps": [...]}`` — digests are
  :func:`chaos.state_digest`, so the harness asserts bit-exact parity
  (rollback-resume, SIGTERM-resume) by string equality.

``--serve-registry`` additionally hosts an in-process ``ModelRegistry``:
every published snapshot hot-swaps it (the snapshot→serving handoff
inside the REAL subprocess), and the done line carries the max
|serving − direct| probe diff.

SIGTERM arrives with the PR 2 flight handler installed
(``--install-sigterm``): the ring dumps to ``$DL4J_TPU_FLIGHT_DIR`` and
the process dies by the default disposition — the on-disk snapshot from
the last completed round is the resume point a follow-up
``--resume`` run continues from, bit-exactly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _emit(doc):
    print(json.dumps(doc), flush=True)


def main(argv=None):
    p = argparse.ArgumentParser(description="continuous-training runner")
    p.add_argument("--snapshot", required=True,
                   help="bundle path: written every snapshot cadence, "
                        "rollback target, and --resume source")
    p.add_argument("--resume", action="store_true",
                   help="resume from --snapshot instead of a fresh net")
    # model (must match the chaos generator's shapes)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--features", type=int, default=12)
    p.add_argument("--hidden", type=int, default=16)
    p.add_argument("--classes", type=int, default=3)
    # stream source: streaming (broker) or offline (generated)
    p.add_argument("--broker-port", type=int, default=None)
    p.add_argument("--topic", default="train")
    p.add_argument("--staleness-s", type=float, default=None)
    p.add_argument("--quiet-timeout-s", type=float, default=2.0)
    p.add_argument("--ingest-retries", type=int, default=8)
    p.add_argument("--offline-n", type=int, default=None,
                   help="offline mode: train gen_batches(gen-seed, N)")
    p.add_argument("--offline-skip", default="",
                   help="offline: comma-separated indices to omit (the "
                        "faulted batches a reference run never sees)")
    p.add_argument("--offline-start", type=int, default=0,
                   help="offline: start at this index (resume legs feed "
                        "the remainder of the stream); -1 = the resumed "
                        "bundle's iteration counter (k=1, no faults: one "
                        "step per batch)")
    p.add_argument("--round-sleep-s", type=float, default=0.0,
                   help="sleep after each round (chaos harnesses use it "
                        "to land a SIGTERM mid-run deterministically)")
    p.add_argument("--gen-seed", type=int, default=123)
    p.add_argument("--batch", type=int, default=8)
    # loop shape
    p.add_argument("--k", type=int, default=1)
    p.add_argument("--dispatches-per-round", type=int, default=1)
    p.add_argument("--snapshot-every", type=int, default=1)
    p.add_argument("--until-steps", type=int, default=None)
    p.add_argument("--max-rounds", type=int, default=None)
    p.add_argument("--policy", default="raise",
                   choices=("record", "warn", "raise"))
    p.add_argument("--max-rollbacks", type=int, default=8)
    p.add_argument("--serve-registry", action="store_true")
    p.add_argument("--install-sigterm", action="store_true")
    p.add_argument("--round-lines", action="store_true")
    args = p.parse_args(argv)

    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.continuous import chaos
    from deeplearning4j_tpu.continuous.trainer import (ContinuousTrainer,
                                                       StreamingTrainSource,
                                                       registry_updater)
    from deeplearning4j_tpu.telemetry import flight as _flight
    from deeplearning4j_tpu.utils.serialization import load_bundle

    telemetry.enable()
    if args.install_sigterm:
        _flight.install_signal_handler()

    if args.resume:
        net = load_bundle(args.snapshot).net
    else:
        net = chaos.smoke_net(seed=args.seed, features=args.features,
                              hidden=args.hidden, classes=args.classes)
        net.init()

    registry = None
    serve_update = None
    if args.serve_registry:
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        registry = ModelRegistry()
        registry.register("continuous", net, buckets=[args.batch],
                          input_spec=(args.features,))
        serve_update = registry_updater(registry, "continuous")

    subscriber = None
    if args.broker_port is not None:
        from deeplearning4j_tpu.streaming.pubsub import NDArraySubscriber
        subscriber = NDArraySubscriber(args.topic, port=args.broker_port)
        source = StreamingTrainSource(
            subscriber, max_staleness_s=args.staleness_s,
            quiet_timeout_s=args.quiet_timeout_s)
    elif args.offline_n is not None:
        skip = {int(i) for i in args.offline_skip.split(",") if i.strip()}
        start = args.offline_start
        if start < 0:
            start = int(net.iteration)  # resume: the bundle knows
        batches = chaos.gen_batches(args.gen_seed, args.offline_n,
                                    batch=args.batch,
                                    features=args.features,
                                    classes=args.classes)
        source = [b for i, b in enumerate(batches)
                  if i >= start and i not in skip]
    else:
        p.error("one of --broker-port / --offline-n is required")

    trainer = ContinuousTrainer(
        net, source, snapshot_path=args.snapshot, k=args.k,
        batch_size=args.batch,
        dispatches_per_round=args.dispatches_per_round,
        snapshot_every=args.snapshot_every, health_policy=args.policy,
        max_rollbacks=args.max_rollbacks, serve_update=serve_update,
        ingest_retries=args.ingest_retries)
    if args.round_lines or args.round_sleep_s:
        def on_round(t):
            if args.round_lines:
                _emit({"round": t.rounds,
                       "iteration": int(t.net.iteration)})
            if args.round_sleep_s:
                import time
                time.sleep(args.round_sleep_s)
        trainer.on_round = on_round

    from deeplearning4j_tpu.telemetry import timeline as _timeline
    _emit({"continuous_ready": True, "pid": os.getpid(),
           "clock": _timeline.clock_pair()})
    try:
        summary = trainer.run(max_rounds=args.max_rounds,
                              until_steps=args.until_steps)
    finally:
        if subscriber is not None:
            subscriber.close()

    serving_probe_diff = None
    if registry is not None:
        import numpy as np
        probe = chaos.gen_batches(args.gen_seed + 7, 1, batch=args.batch,
                                  features=args.features,
                                  classes=args.classes)[0][0]
        served = np.asarray(registry.output("continuous", probe))
        direct = np.asarray(net.output(probe))
        serving_probe_diff = float(np.max(np.abs(served - direct)))
        registry.unregister("continuous")

    _emit({"continuous_done": True,
           "digest": chaos.state_digest(net),
           "iteration": int(net.iteration),
           "summary": summary,
           "serving_probe_diff": serving_probe_diff,
           "counters": {name: telemetry.series_map(name) for name in (
               "continuous_rounds_total", "continuous_rollback_total",
               "continuous_rolled_back_steps_total",
               "continuous_dropped_total", "continuous_snapshots_total",
               "continuous_serve_updates_total", "etl_retry_total",
               "stream_dropped_total", "recompiles_total")},
           "flight_dumps": list(_flight.get_recorder().dumps)})
    return 0


if __name__ == "__main__":
    sys.exit(main())
