"""Continuous learning: the resumable step driver + streaming trainer.

The deployment story of the TF system papers (PAPERS.md arxiv
1603.04467 §4.3, arxiv 1605.08695) is not "fit an array": it is a
training loop that consumes a live stream, checkpoints as it goes,
survives faults, and keeps handing fresh snapshots to the serving tier.
This package is that loop:

* :mod:`driver` — ``StepDriver``, the resumable dispatch loop refactored
  OUT of the three fit paths (MultiLayerNetwork / ComputationGraph /
  ParallelTrainer ``fit()`` are thin wrappers over it): explicit
  ``run_round(k_dispatches)``, checkpointable between rounds via
  ``save_bundle``, RNG-chain exact on restore.
* :mod:`trainer` — ``ContinuousTrainer``: streaming ingest with bounded
  staleness, the numerics watchdog policing every round, rollback to the
  last good bundle on ``NumericsError`` (counted, bit-exact incl. the
  RNG chain), and periodic healthy snapshots handed to the serving tier
  (``ModelRegistry.update_model`` / ``FleetSupervisor.update_model``).
* :mod:`chaos` — the fault-injection harness (poisoned batches, producer
  death, delayed ingest, SIGTERM) and the deterministic batch/digest
  plumbing the parity gates are built on.
* :mod:`runner` — the real-subprocess entry point
  (``python -m deeplearning4j_tpu.continuous.runner``) the chaos tests
  and ``bench.py continuous`` drive.
"""

__all__ = ["RoundResult", "StepDriver"]


def __getattr__(name):
    # lazy: the chaos PUBLISHER subprocess imports this package on its
    # way to chaos.py, which never touches the driver — eagerly pulling
    # driver.py would build the whole nn/telemetry import graph for a
    # process that only writes codec frames to a socket
    if name in __all__:
        from deeplearning4j_tpu.continuous import driver
        return getattr(driver, name)
    raise AttributeError(name)
