"""ContinuousTrainer: streaming training that survives its faults.

The composition the ROADMAP has been pointing at since PR 5:
``streaming/pubsub`` → bounded-staleness admission →
``AsyncDataSetIterator`` prefetch (with the transient-retry policy) →
the :class:`~deeplearning4j_tpu.continuous.driver.StepDriver` round loop
with the numerics watchdog armed → periodic healthy snapshots handed to
the serving tier. Every failure mode has a COUNTED outcome — nothing is
lost silently, and nothing hangs:

* a **stale batch** (older than ``max_staleness_s``, aged from its
  publish timestamp and queue residency) is dropped at admission,
  ``continuous_dropped_total{reason=stale}`` — trained-on-stale is worse
  than skipped;
* a **poisoned batch** (NaN/Inf reaching the step) trips the watchdog
  one round late (``NumericsError`` out of ``driver.sync()``), and the
  trainer ROLLS BACK to the last good bundle — params, opt_state AND the
  RNG chain re-armed, so the resumed chain is bit-exact with a run that
  never saw the poison — counted
  ``continuous_rollback_total{reason=numerics}`` with the lost steps in
  ``continuous_rolled_back_steps_total``;
* a **dead producer** goes quiet: ingest times out, the prefetcher
  retries with backoff (``etl_retry_total``), and the round simply
  resumes when the replacement producer appears — past the retry budget
  the run ends as a counted ``stream_quiet``, never a hang;
* a **sick snapshot never serves**: under ``policy='raise'`` a sick
  round rolls back before the snapshot point; under record/warn the
  anomaly delta gates publication
  (``continuous_snapshots_total{verdict=skipped_sick}``).

Snapshots are atomic (tmp + rename) ``save_bundle`` units — the same
artifact PR 9's instant-restart tier consumes — and double as the
rollback target and the serving handoff: ``serve_update`` (see
:func:`registry_updater` / :func:`fleet_updater`) pushes each published
snapshot into a ``ModelRegistry`` or across a ``FleetSupervisor``'s
worker fleet, warm-then-atomic, while training continues.
"""

from __future__ import annotations

import os
import queue

import numpy as np

from deeplearning4j_tpu import telemetry as _tm
from deeplearning4j_tpu.telemetry import goodput as _goodput
from deeplearning4j_tpu.telemetry import health as _health
from deeplearning4j_tpu.telemetry import slo as _slo
from deeplearning4j_tpu.continuous.driver import StepDriver
from deeplearning4j_tpu.datasets.iterator import (AsyncDataSetIterator,
                                                  DataSet, DataSetIterator)

__all__ = ["ContinuousTrainer", "StreamingTrainSource",
           "registry_updater", "fleet_updater"]


class StreamingTrainSource(DataSetIterator):
    """Bounded-staleness admission over an ``NDArraySubscriber``.

    Yields :class:`DataSet` minibatches from the subscription; a batch
    older than ``max_staleness_s`` (publish-timestamp + queue-residency
    age from ``receive_timed``) is count-dropped, not trained — the
    bounded-staleness contract of online training: a model update from
    data the stream has already superseded is negative work.

    A quiet stream raises ``TimeoutError`` after ``quiet_timeout_s`` —
    deliberately in ``AsyncDataSetIterator.RETRY_ON`` so the prefetch
    layer retries it with backoff (a producer death is a transient,
    counted, survivable event). The stream ENDS (StopIteration) only
    when the subscriber's connection closed and its queue drained.

    ``screen_nonfinite=True`` additionally drops NaN/Inf batches at
    admission (``continuous_dropped_total{reason=nonfinite}``); the
    default leaves them to the watchdog+rollback path, which also
    catches poison that admission screening can't see (a batch that
    only EXPLODES in the gradient).
    """

    def __init__(self, subscriber, *, max_staleness_s=None,
                 quiet_timeout_s=5.0, screen_nonfinite=False):
        self.sub = subscriber
        self.max_staleness_s = max_staleness_s
        self.quiet_timeout_s = float(quiet_timeout_s)
        self.screen_nonfinite = bool(screen_nonfinite)
        self.stale_dropped = 0
        self.nonfinite_dropped = 0
        self.admitted = 0
        reg = self._reg = _tm.get_registry()
        self._m_dropped = reg.counter(
            "continuous_dropped_total",
            "batches dropped at continuous-training admission, by reason "
            "(stale = older than the staleness bound, nonfinite = "
            "NaN/Inf screened before the step)")

    @property
    def batch_size(self):
        return None  # stream-defined; the first admitted batch decides

    def reset(self):
        pass  # a live stream has no epochs to rewind

    def __next__(self):
        while True:
            try:
                age, item, _ts = self.sub.receive_timed(
                    timeout=self.quiet_timeout_s)
            except queue.Empty:
                if self.sub._closed.is_set() and self.sub.queue.empty():
                    raise StopIteration  # stream ended, fully drained
                raise TimeoutError(
                    f"stream quiet for {self.quiet_timeout_s:.1f}s "
                    "(producer dead or stalled)")
            if not isinstance(item, tuple):
                raise ValueError(
                    "stream carries bare ndarrays, not datasets")
            x, y = np.asarray(item[0]), np.asarray(item[1])
            if (self.max_staleness_s is not None
                    and age > self.max_staleness_s):
                self.stale_dropped += 1
                if self._reg.enabled:
                    self._m_dropped.inc(reason="stale")
                continue
            if self.screen_nonfinite and not (
                    np.isfinite(x).all() and np.isfinite(y).all()):
                self.nonfinite_dropped += 1
                if self._reg.enabled:
                    self._m_dropped.inc(reason="nonfinite")
                continue
            self.admitted += 1
            return DataSet(features=x, labels=y)


def registry_updater(registry, name):
    """A ``serve_update`` hook: hot-swap a :class:`ModelRegistry` entry
    from each published snapshot (warm-then-atomic per the registry's
    own contract — in-flight requests finish on the old snapshot)."""
    def update(path):
        from deeplearning4j_tpu.utils.serialization import load_bundle
        registry.update_model(name, load_bundle(path).net)
    return update


def fleet_updater(supervisor, warm=None):
    """A ``serve_update`` hook: fan a published snapshot across a
    :class:`FleetSupervisor`'s workers (sequential warm-then-atomic —
    N-1 workers keep serving while each swaps)."""
    def update(path):
        out = supervisor.update_model(path, warm=warm)
        bad = {w: d for w, d in out.items() if not d.get("ok", True)}
        if bad:
            raise RuntimeError(f"fleet swap failed on {sorted(bad)}: {bad}")
        return out
    return update


class ContinuousTrainer:
    """The continuous-learning loop: rounds, snapshots, rollback, serve.

    ``source`` is any ``(x, y[, mask])`` iterable / DataSetIterator —
    typically a :class:`StreamingTrainSource`. It is wrapped in an
    ``AsyncDataSetIterator`` (host-side: prefetch + the bounded
    transient-retry policy; device placement stays with the engines), so
    a producer hiccup costs counted retries, not the run.

    One ``run()`` iteration = ``dispatches_per_round`` dispatches +
    ``driver.sync()`` (where a sick round surfaces, one round late) +
    on the snapshot cadence an atomic ``save_bundle`` to
    ``snapshot_path`` and the optional ``serve_update`` handoff. An
    initial snapshot is written BEFORE the first round, so rollback
    always has a target.
    """

    def __init__(self, net, source, *, snapshot_path, k=1, batch_size=None,
                 dispatches_per_round=1, snapshot_every=1, buckets=None,
                 rollback=True, max_rollbacks=8, health_policy="raise",
                 grad_norm_limit=None, serve_update=None,
                 ingest_retries=8, ingest_backoff_s=0.25):
        self.net = net
        self.snapshot_path = str(snapshot_path)
        self.dispatches_per_round = int(dispatches_per_round)
        self.snapshot_every = int(snapshot_every)
        self.buckets = buckets
        self.rollback_enabled = bool(rollback)
        self.max_rollbacks = int(max_rollbacks)
        self.serve_update = serve_update
        self.on_round = None  # callable(trainer) after each clean round
        #                       (the runner's progress-line hook)
        self.rounds = 0
        self.rollbacks = 0
        self.snapshots_published = 0
        if getattr(net, "params", None) is None and hasattr(net, "init"):
            net.init()  # the round-0 snapshot needs concrete trees
        # the watchdog is the rollback trigger: arm it for the run (it is
        # process-wide; a caller that armed it already keeps its policy)
        hm = self._hm = _health.get_monitor()
        if not hm.active:
            hm.enable(policy=health_policy, grad_norm_limit=grad_norm_limit)
        self._ingest = AsyncDataSetIterator(
            self._as_iterator(source), queue_size=2, device_put=False,
            retry_transient=ingest_retries, retry_backoff_s=ingest_backoff_s)
        self.driver = StepDriver(net, self._batches, k=k,
                                 batch_size=batch_size)
        reg = self._reg = _tm.get_registry()
        self._m_rounds = reg.counter(
            "continuous_rounds_total", "continuous-training rounds, by "
            "outcome (ok / rollback / stream_quiet / stream_closed)")
        self._m_rollback = reg.counter(
            "continuous_rollback_total",
            "rollbacks to the last good bundle, by reason")
        self._m_rolled_steps = reg.counter(
            "continuous_rolled_back_steps_total",
            "optimizer steps undone by rollbacks (trained-then-discarded "
            "work; every loss is counted here, never silent)")
        self._m_snap = reg.counter(
            "continuous_snapshots_total",
            "snapshot points, by verdict (published / skipped_sick / "
            "error)")
        self._m_serve = reg.counter(
            "continuous_serve_updates_total",
            "serving hot-swap handoffs of published snapshots, by outcome")
        if reg.enabled:
            # pre-register every enum series at zero (the prober idiom):
            # the SLO delta discipline ignores a series' FIRST
            # appearance, so a rollback/error series born mid-incident
            # would contribute nothing for a full window
            for outcome in ("ok", "rollback", "stream_quiet",
                            "stream_closed"):
                self._m_rounds.inc(0, outcome=outcome)
            for verdict in ("published", "skipped_sick", "error"):
                self._m_snap.inc(0, verdict=verdict)
            for outcome in ("ok", "error"):
                self._m_serve.inc(0, outcome=outcome)
        self._anoms_at_gate = None

    @staticmethod
    def _as_iterator(source):
        if isinstance(source, DataSetIterator):
            return source
        # (x, y[, m]) tuples / DataSet stream -> DataSetIterator contract
        from deeplearning4j_tpu.datasets.iterator import iter_batches

        class _Wrap(DataSetIterator):
            def __init__(self, src):
                self.src = src
                self._it = None

            @property
            def batch_size(self):
                return getattr(source, "batch_size", None)

            def reset(self):
                # iter() first: a LIST of (x, y) tuples would otherwise
                # take iter_batches' (features, labels)-pair branch
                self._it = iter(iter_batches(iter(self.src)))

            def __next__(self):
                if self._it is None:
                    self.reset()
                x, y, m = next(self._it)
                return DataSet(features=x, labels=y, labels_mask=m)

        return _Wrap(source)

    def _batches(self):
        for ds in self._ingest:
            yield ds.features, ds.labels, ds.labels_mask

    # -- snapshots -------------------------------------------------------

    def _sick_since_gate(self):
        sick = False
        hm = self._hm
        if hm.active:
            seen = hm.nonfinite_steps
            # two conditions, both required: new anomalies since the last
            # gate (a sick ROUND), or the most recently resolved record
            # still carries nonfinite flags (a sick STATE — without this,
            # a run whose anomalies stopped incrementing would republish
            # NaN params the moment the delta went quiet)
            last = hm.last or {}
            sick = ((self._anoms_at_gate is not None
                     and seen > self._anoms_at_gate)
                    or bool(last.get("loss_nonfinite"))
                    or bool(last.get("grad_nonfinite")))
            self._anoms_at_gate = seen
        if not sick:
            # the SLO engine's verdict joins the gate: a FIRING
            # gate-tagged rule (numerics anomalies, step-time
            # regression, recompile storm) blocks publication the same
            # counted skipped_sick way. Default-on-but-inert: no engine
            # running, or every rule ok, changes nothing.
            sick = bool(_slo.firing_gate_rules())
        return sick

    def snapshot(self):
        """Atomically write the bundle and (if healthy) hand it to
        serving. Skipped-sick and handoff errors are counted, never
        silent; a handoff error does not kill training."""
        try:
            # resolve anything still in flight WITHOUT the raise policy,
            # so the gate below judges the true current state — an
            # aborted round (e.g. stream_quiet after a poisoned
            # dispatch) may have left a sick pending bundle that a
            # policy'd flush would throw straight through the caller
            self.driver.sync(apply_policy=False)
        except Exception:  # noqa: BLE001 — a broken pipeline must not
            pass           # mask the health gate
        if self._sick_since_gate():
            # policy=record/warn runs reach here with anomalies on the
            # books; the serving tier must never warm-swap onto them
            if self._reg.enabled:
                self._m_snap.inc(verdict="skipped_sick")
            return None
        tmp = self.snapshot_path + ".tmp"
        try:
            self.driver.checkpoint(tmp, buckets=self.buckets)
            os.replace(tmp, self.snapshot_path)  # atomic: a reader (or a
            # rollback) never sees a half-written bundle
        except Exception:
            if self._reg.enabled:
                self._m_snap.inc(verdict="error")
            raise
        self.snapshots_published += 1
        if self._reg.enabled:
            self._m_snap.inc(verdict="published")
        if self.serve_update is not None:
            try:
                self.serve_update(self.snapshot_path)
                if self._reg.enabled:
                    self._m_serve.inc(outcome="ok")
            except Exception:  # noqa: BLE001 — serving lag must not
                #                kill training; the counter is the signal
                if self._reg.enabled:
                    self._m_serve.inc(outcome="error")
        return self.snapshot_path

    def _rollback(self, reason, exc):
        self.rollbacks += 1
        if self._reg.enabled:
            self._m_rollback.inc(reason=reason)
            self._m_rounds.inc(outcome="rollback")
        if not self.rollback_enabled or self.rollbacks > self.max_rollbacks:
            raise exc
        if self.snapshots_published == 0:
            raise exc  # nothing to roll back to
        it_before = self.net.iteration
        self.driver.restore(self.snapshot_path)
        lost = max(0, it_before - self.net.iteration)
        if lost and self._reg.enabled:
            self._m_rolled_steps.inc(lost)
            # reclassify the undone steps' wall clock in the goodput
            # ledger: trained-then-discarded seconds are rollback_lost,
            # not compute (estimated as lost steps x mean step time)
            h = self._reg.get("train_step_seconds")
            if h is not None and h.count():
                _goodput.get_ledger().note(
                    "rollback_lost", lost * h.sum() / h.count())
        # the gate counter moves on: the anomaly that caused this
        # rollback is handled, the next snapshot may publish
        self._anoms_at_gate = self._hm.nonfinite_steps
        return lost

    # -- the loop --------------------------------------------------------

    def run(self, *, max_rounds=None, until_steps=None, stop_flag=None):
        """Train until the stream closes, ``until_steps`` optimizer steps
        survive (rollbacks subtract), ``max_rounds`` rounds ran, or
        ``stop_flag`` (a ``threading.Event`` — the graceful-drain hook)
        is set. Returns a JSON-ready summary; never hangs — every exit
        path is a counted status."""
        status = "max_rounds"
        self._anoms_at_gate = self._hm.nonfinite_steps
        if self.snapshots_published == 0:
            self.snapshot()  # round-0 bundle: rollback always has a target
        try:
            while max_rounds is None or self.rounds < max_rounds:
                if stop_flag is not None and stop_flag.is_set():
                    status = "stopped"
                    break
                if (until_steps is not None
                        and self.net.iteration >= until_steps):
                    status = "target_steps"
                    break
                try:
                    rr = self.driver.run_round(self.dispatches_per_round)
                    self.driver.sync()  # a sick round raises HERE
                except _health.NumericsError as e:
                    self._rollback("numerics", e)
                    continue
                except TimeoutError:
                    # ingest retry budget exhausted: the producer never
                    # came back — a counted end, not a hang
                    status = "stream_quiet"
                    if self._reg.enabled:
                        self._m_rounds.inc(outcome="stream_quiet")
                    break
                self.rounds += 1
                if self._reg.enabled:
                    self._m_rounds.inc(outcome="ok")
                if rr.dispatches and self.rounds % self.snapshot_every == 0:
                    self.snapshot()
                if self.on_round is not None:
                    self.on_round(self)
                if rr.epoch_done:
                    # the source only exhausts when the stream CLOSED
                    # (subscriber gone / finite reference list done)
                    status = "stream_closed"
                    if self._reg.enabled:
                        self._m_rounds.inc(outcome="stream_closed")
                    break
        finally:
            self.close()
        # final state always lands in the bundle (a graceful stop resumes
        # exactly where it left off); the health gate still applies
        self.snapshot()
        return self.summary(status)

    def close(self):
        self.driver.close_source()
        self._ingest.close()

    def summary(self, status=None):
        src = self._ingest.base
        return {
            "status": status,
            "rounds": self.rounds,
            "iteration": int(self.net.iteration),
            "rollbacks": self.rollbacks,
            "snapshots_published": self.snapshots_published,
            "stale_dropped": getattr(src, "stale_dropped", 0),
            "nonfinite_dropped": getattr(src, "nonfinite_dropped", 0),
            "admitted": getattr(src, "admitted", None),
            "health": self._hm.summary(),
        }
