"""Chaos harness: deterministic streams, fault injection, parity digests.

The contract under test (ISSUE 13, tier-1 stage 9): every injected fault
— a poisoned NaN batch, a producer killed mid-stream, a batch delayed
past the staleness bound, SIGTERM mid-round — must end in
recovered-with-PARITY or a counted graceful degradation; never a hang,
never an uncounted loss. Parity is falsifiable because everything here
is deterministic:

* :func:`gen_batches` derives the stream from one seed — poisoning batch
  *i* overwrites values without consuming extra randomness, so the GOOD
  batches of a chaos stream and of a clean reference stream are
  bit-identical;
* :func:`state_digest` hashes params + state + opt_state + the RNG chain
  + the iteration counter — two runs match iff they are bit-exact
  through the whole optimizer/RNG history, which is the rollback-resume
  claim stated strongly enough to fail.

The module doubles as the **publisher subprocess**
(``python -m deeplearning4j_tpu.continuous.chaos --port ... --topic ...``)
so producer death is a real process death: the harness SIGKILLs it
mid-stream (or ``--die-after`` makes it exit abruptly on its own) and
spawns a replacement that resumes at ``--start``. ``--delay-index``
publishes one batch with its timestamp aged past the staleness bound —
the delayed-ingest fault arrives already stale, deterministically.
"""

from __future__ import annotations

import argparse
import hashlib
import sys
import time

import numpy as np

__all__ = ["gen_batches", "state_digest", "smoke_net", "publish_batches"]


def gen_batches(seed, n, batch=8, features=12, classes=3, poison=()):
    """N deterministic ``(x, y)`` float32 minibatches from one seed.
    Indices in ``poison`` get a NaN feature — injected AFTER drawing, so
    the other batches are unchanged by the injection."""
    poison = set(int(i) for i in poison)
    rs = np.random.RandomState(int(seed))
    out = []
    for i in range(int(n)):
        x = rs.rand(int(batch), int(features)).astype(np.float32)
        y = np.eye(int(classes), dtype=np.float32)[
            rs.randint(0, int(classes), int(batch))]
        if i in poison:
            x = x.copy()
            x[0, 0] = np.nan
        out.append((x, y))
    return out


def state_digest(net):
    """SHA-256 over params + state + opt_state + RNG chain + iteration:
    equal digests == bit-exact training history."""
    import jax
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(
            (net.params, net.state, net.opt_state)):
        h.update(np.asarray(leaf).tobytes())
    rng = getattr(net, "_rng", None)
    if rng is not None:
        h.update(np.asarray(rng).tobytes())
    h.update(str(int(net.iteration)).encode())
    return h.hexdigest()


def smoke_net(seed=0, features=12, hidden=16, classes=3):
    """The tiny deterministic MLP every chaos leg trains — ONE definition
    so the chaos run, the resume run and the reference run can never
    drift architecturally."""
    from deeplearning4j_tpu.nn import layers as L
    from deeplearning4j_tpu.nn import updaters as U
    from deeplearning4j_tpu.nn.conf import inputs as I
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfig
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = NeuralNetConfig(
        seed=seed, updater=U.Adam(learning_rate=0.01)).list(
        L.DenseLayer(n_out=hidden, activation="tanh"),
        L.OutputLayer(n_out=classes, loss="mcxent"),
        input_type=I.FeedForwardType(features))
    return MultiLayerNetwork(conf)


def publish_batches(port, topic, batches, *, start=0, interval_s=0.02,
                    delay_index=None, delay_s=0.0, die_after=None,
                    host="127.0.0.1"):
    """Publish ``batches[start:]`` to a broker topic, one every
    ``interval_s``. ``delay_index`` publishes that batch with its
    timestamp aged by ``delay_s`` (the delayed-ingest fault: it arrives
    already stale). ``die_after`` aborts the process abruptly after that
    many publishes (producer-death fault) — only meaningful in the
    subprocess entry. Returns the number published."""
    from deeplearning4j_tpu.streaming.pubsub import NDArrayPublisher
    pub = NDArrayPublisher(topic, host=host, port=int(port))
    sent = 0
    try:
        for i in range(int(start), len(batches)):
            if die_after is not None and sent >= int(die_after):
                import os
                os._exit(1)  # abrupt: no close frames, a REAL crash
            x, y = batches[i]
            ts = None
            if delay_index is not None and i == int(delay_index):
                ts = time.time() - float(delay_s)
            pub.publish_dataset(x, y, ts=ts)
            sent += 1
            if interval_s:
                time.sleep(float(interval_s))
    finally:
        try:
            pub.close()
        except OSError:
            pass
    return sent


def main(argv=None):
    p = argparse.ArgumentParser(
        description="chaos publisher: stream deterministic batches at a "
                    "broker, with optional fault injection")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--topic", default="train")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--gen-seed", type=int, default=123)
    p.add_argument("--n", type=int, required=True,
                   help="total batches in the deterministic stream")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--features", type=int, default=12)
    p.add_argument("--classes", type=int, default=3)
    p.add_argument("--poison", default="",
                   help="comma-separated batch indices to NaN-poison")
    p.add_argument("--start", type=int, default=0,
                   help="resume publishing at this index (replacement "
                        "producer after a kill)")
    p.add_argument("--interval-s", type=float, default=0.02)
    p.add_argument("--delay-index", type=int, default=None)
    p.add_argument("--delay-s", type=float, default=0.0)
    p.add_argument("--die-after", type=int, default=None)
    args = p.parse_args(argv)
    poison = [int(i) for i in args.poison.split(",") if i.strip()]
    batches = gen_batches(args.gen_seed, args.n, batch=args.batch,
                          features=args.features, classes=args.classes,
                          poison=poison)
    sent = publish_batches(args.port, args.topic, batches,
                           start=args.start, interval_s=args.interval_s,
                           delay_index=args.delay_index,
                           delay_s=args.delay_s, die_after=args.die_after,
                           host=args.host)
    print(f'{{"published": {sent}}}', flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
