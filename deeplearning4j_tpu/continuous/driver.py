"""StepDriver: the resumable dispatch loop shared by every fit path.

Before this module, the repo had THREE copies of the same loop — the K=1
bodies in ``nn/multilayer.py`` and ``nn/graph.py``, the fused K-step body
in ``nn/fused.py``, and the ParallelTrainer pair in
``parallel/data_parallel.py`` — each hand-maintaining the identical
pipelining discipline (one-step-late score fetch, one-late health
bundles, trace handoff, flight records). None of them could STOP: a fit
ran to epoch end or died, which is exactly what a continuous-learning
loop cannot accept (the stream never ends) and what the distributed and
serving tiers could never share.

``StepDriver`` is that loop, once, with an explicit round boundary:

* ``run_round(k_dispatches)`` consumes up to K dispatches from the
  current epoch and RETURNS — params/opt_state/RNG chain are live on the
  net, the score pipeline and health monitor each hold at most one
  pending entry.
* ``sync()`` drains both pipelines (the watchdog's policy may raise
  ``NumericsError`` here, one round late — the continuous trainer's
  rollback trigger).
* ``checkpoint(path)`` = ``sync()`` + ``save_bundle``: one resumable
  unit (checkpoint + opt_state + RNG chain + manifest) between any two
  rounds.
* ``restore(bundle)`` re-arms params/state/opt_state, the RNG chain and
  the iteration counter from a bundle — the compiled step functions are
  keyed on shapes/dtypes, so a rollback re-dispatches with ZERO
  recompiles, and the re-armed RNG chain makes resume bit-exact
  (tests/test_continuous.py pins both).
* ``run(epochs)`` is the classic fit loop: N epochs of
  ``run_round(None)`` with the historical telemetry/exception contract
  (fit span, crash flight dump, fit-end listener hooks) — what the
  ``fit()`` facades now delegate to.

Engines plug the dispatch body: ``_PlainEngine`` (the K=1 single-step
jit), ``_FusedEngine`` (the ``lax.scan`` K-step engine with prefetch),
and the ParallelTrainer pair (``_ShardedPlainEngine`` /
``_ShardedFusedEngine`` — ``instrumented=False`` preserves that loop's
deliberately lighter telemetry). The instrumented body is the audited
moved code of the MLN/CG loops — span names, trace roots, meta schema
and emit ordering are unchanged, so every existing parity/fused/health/
trace test passes against this module without edits.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import telemetry as _tm
from deeplearning4j_tpu.telemetry import devices as _devices
from deeplearning4j_tpu.telemetry import flight as _flight
from deeplearning4j_tpu.telemetry import health as _health
from deeplearning4j_tpu.nn import listeners as _listeners
from deeplearning4j_tpu.utils import compile_cache as _cc

__all__ = ["StepDriver", "RoundResult"]


@dataclasses.dataclass
class RoundResult:
    """What one ``run_round`` consumed: ``dispatches`` device dispatches
    covering ``steps`` optimizer steps; ``epoch_done`` marks source
    exhaustion (epoch-end listeners already fired)."""

    dispatches: int = 0
    steps: int = 0
    epoch_done: bool = False


# ---------------------------------------------------------------------------
# engines: what ONE dispatch is
# ---------------------------------------------------------------------------


class _PlainEngine:
    """K=1: one (x, y, mask) minibatch per dispatch through the net's
    cached single-step jit (``net._train_step`` / the health variant —
    the same cache attributes the historical loops used, so a driver fit
    and a legacy fit share compiled executables)."""

    fused = False
    trace_root = "train.step"

    def __init__(self, net, use_health, tbptt_fn=None):
        self.net = net
        self.use_health = use_health
        self.tbptt_fn = tbptt_fn
        if use_health:
            if net._train_step_health is None:
                net._train_step_health = net.make_train_step(
                    with_health=True)
            self.step_fn = net._train_step_health
        else:
            if net._train_step is None:
                net._train_step = net.make_train_step()
            self.step_fn = net._train_step

    def build_source(self, batch_factory):
        return batch_factory()  # fresh (x, y, m) generator per epoch

    def prepare(self, item):
        x, y, m = item
        # leaf-wise: x/y may be dict pytrees (the ComputationGraph form)
        x = jax.tree_util.tree_map(jnp.asarray, x)
        y = jax.tree_util.tree_map(jnp.asarray, y)
        m = jnp.asarray(m) if m is not None else None
        return x, y, m

    def note_input(self, prep):
        # listener convention (activation visualizers, PerformanceListener
        # batch-size inference): the first input array, unsliced
        x = prep[0]
        self.net.last_input = (next(iter(x.values()))
                               if isinstance(x, dict) else x)

    def n_real(self, item):
        return 1

    def dispatch(self, prep):
        net = self.net
        x, y, m = prep
        if self.tbptt_fn is not None and self.tbptt_fn(x, y):
            # TBPTT runs its own chunked on-device scan; the watchdog
            # bundle covers the plain step only
            return net._fit_tbptt(x, y, m), None
        net._rng, step_rng = jax.random.split(net._rng)
        if self.use_health:
            (net.params, net.state, net.opt_state, loss, hb) = self.step_fn(
                net.params, net.state, net.opt_state, x, y, net.iteration,
                step_rng, m)
        else:
            (net.params, net.state, net.opt_state, loss) = self.step_fn(
                net.params, net.state, net.opt_state, x, y, net.iteration,
                step_rng, m)
            hb = None
        net.score_value = loss
        net.iteration += 1
        # cold-start gauge (compile_cache): stamped once, then a dict read
        _cc.note_first_step()
        return loss, hb

    def cache_fn(self):
        return self.step_fn

    def to_host(self):
        return self.net

    def rearm(self, restored):
        _rearm_net(self.net, restored)


class _FusedEngine:
    """K>1: one stacked super-batch per dispatch through the ``lax.scan``
    K-step engine (nn/fused.py), super-batches assembled + device_put on
    the prefetch thread."""

    fused = True
    trace_root = "train.dispatch"

    def __init__(self, net, k, use_health, batch_size=None, prefetch=True):
        from deeplearning4j_tpu.nn import fused as _fused
        self.net = net
        self.k = int(k)
        self.use_health = use_health
        self.batch_size = batch_size
        self.prefetch = prefetch
        self.steps_fn = _fused._steps_fn_for(net, k, use_health)

    def build_source(self, batch_factory):
        from deeplearning4j_tpu.datasets.iterator import (
            AsyncDataSetIterator, SuperBatchIterator)
        sbit = SuperBatchIterator(batch_factory, self.k,
                                  batch_size=self.batch_size)
        return (AsyncDataSetIterator(sbit, queue_size=2,
                                     trace_root="train.dispatch")
                if self.prefetch else sbit)

    def prepare(self, sb):
        # prefetched super-batches are already on device; asarray is then
        # a no-op per leaf
        xs = jax.tree_util.tree_map(jnp.asarray, sb.features)
        ys = jax.tree_util.tree_map(jnp.asarray, sb.labels)
        ms = jnp.asarray(sb.labels_mask)
        sv = jnp.asarray(sb.step_valid)
        return xs, ys, ms, sv

    def note_input(self, prep):
        net = self.net
        if net.listeners:
            # listener convention only — the [0] slice is a device op, so
            # don't dispatch it for nobody
            xs = prep[0]
            first = (next(iter(xs.values())) if isinstance(xs, dict)
                     else xs)
            net.last_input = first[0]

    def n_real(self, item):
        return item.n_steps

    def dispatch(self, prep):
        net = self.net
        xs, ys, ms, sv = prep
        n_real = self._n_real  # the SuperBatch's n_steps, via the driver
        step0 = net.iteration
        net._rng, step_rng = jax.random.split(net._rng)
        if self.use_health:
            (net.params, net.state, net.opt_state, losses, hb) = \
                self.steps_fn(net.params, net.state, net.opt_state,
                              xs, ys, step0, step_rng, ms, sv)
        else:
            (net.params, net.state, net.opt_state, losses) = \
                self.steps_fn(net.params, net.state, net.opt_state,
                              xs, ys, step0, step_rng, ms, sv)
            hb = None
        # last REAL step's loss; device scalar, no sync
        net.score_value = losses[n_real - 1]
        net.iteration += n_real
        _cc.note_first_step()
        return losses, hb

    def cache_fn(self):
        return self.steps_fn

    def to_host(self):
        return self.net

    def rearm(self, restored):
        _rearm_net(self.net, restored)


class _ShardedPlainEngine:
    """ParallelTrainer K=1: one ``trainer.step`` per dispatch. Batches
    whose leading dim is not divisible by the mesh 'data' axis are
    SKIPPED and counted (``trainer.examples_dropped``) — the historical
    array-path behavior."""

    fused = False

    def __init__(self, trainer):
        self.trainer = trainer
        self._data_size = trainer.mesh.shape["data"]

    def build_source(self, batch_factory):
        return batch_factory()

    def dispatch(self, item):
        bx, by, bm = item
        t = self.trainer
        if bx.shape[0] % self._data_size:
            t.examples_dropped += int(bx.shape[0])
            return None  # skipped: not a dispatch
        loss = t.step(bx, by, bm)
        return loss, 1, t.iteration

    def fan(self, score, meta):
        for li in self.trainer.listeners:
            li.iteration_done(self.trainer, meta, score)

    def to_host(self):
        return self.trainer.sync_to_net()

    def rearm(self, restored):
        t = self.trainer
        _rearm_net(t.net, restored)
        t.adopt_net_state()


class _ShardedFusedEngine:
    """ParallelTrainer K>1: sharded fused dispatch, super-batches
    assembled + sharded ``device_put`` on the prefetch thread."""

    fused = True

    def __init__(self, trainer, k):
        self.trainer = trainer
        self.k = int(k)
        self._data_size = trainer.mesh.shape["data"]
        fns = getattr(trainer, "_steps_fns_fused", None)
        if fns is None:
            fns = trainer._steps_fns_fused = {}
        if k not in fns:
            fns[k] = trainer._build_steps_fused(k, trainer.donate)
        self.fused_fn = fns[k]
        self.batch_size = None  # set by the fit wrapper

    def build_source(self, batch_factory):
        from deeplearning4j_tpu.datasets.iterator import (
            AsyncDataSetIterator, SuperBatchIterator)
        from deeplearning4j_tpu.parallel import mesh as _mesh
        sbit = SuperBatchIterator(batch_factory, self.k,
                                  batch_size=self.batch_size)
        # prefetch thread assembles + device_puts the next super-batch
        # ALREADY SHARDED while the current dispatch runs
        return AsyncDataSetIterator(
            sbit, queue_size=2,
            sharding=_mesh.superbatch_sharded(self.trainer.mesh))

    def dispatch(self, sb):
        t = self.trainer
        feats = (next(iter(sb.features.values()))
                 if isinstance(sb.features, dict) else sb.features)
        if feats.shape[1] % self._data_size:
            raise ValueError(
                f"bucketed batch size {feats.shape[1]} not divisible by "
                f"the data-axis size {self._data_size}")
        (t.params, t.state, t.opt_state, losses, t._rng) = self.fused_fn(
            t.params, t.state, t.opt_state, sb.features, sb.labels,
            t.iteration, t._rng, sb.labels_mask, jnp.asarray(sb.step_valid))
        n = sb.n_steps
        t.iteration += n
        t.score_value = losses[n - 1]
        return losses, n, {"iteration": t.iteration, "k": n}

    def fan(self, scores, meta):
        self.trainer._fan_listener_scores(scores, meta)

    def to_host(self):
        return self.trainer.sync_to_net()

    def rearm(self, restored):
        t = self.trainer
        _rearm_net(t.net, restored)
        t.adopt_net_state()


def _rearm_net(net, restored):
    """Copy a restored checkpoint's trees + counters + RNG chain onto the
    LIVE net object (engines and compiled steps hold references to it) —
    restored arrays share the live trees' shapes/dtypes, so the cached
    jitted steps re-dispatch without a single recompile."""
    net.params = restored.params
    net.state = restored.state
    if restored.opt_state is not None:
        net.opt_state = restored.opt_state
    rng = getattr(restored, "_rng", None)
    if rng is not None:
        net._rng = jnp.asarray(rng)
    net.iteration = restored.iteration
    net.epoch = restored.epoch


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------


class StepDriver:
    """Resumable dispatch loop over one engine (see module docstring).

    ``batch_factory`` is a zero-arg callable returning a fresh
    ``(x, y, mask)`` iterable per epoch (the historical fit-loop
    contract); fused engines wrap it in ``SuperBatchIterator`` +
    prefetch once and re-enter it on epoch reset.

    ``instrumented=False`` is the ParallelTrainer profile: the score
    pipeline feeds its 3-arg listeners only — no spans, traces, flight
    records or health monitor — exactly the telemetry surface that loop
    has always had.
    """

    def __init__(self, net, batch_factory, *, k=1, batch_size=None,
                 prefetch=True, tbptt_fn=None, engine=None,
                 instrumented=True, fit_span_kw=None):
        self.net = net
        self.batch_factory = batch_factory
        self.k = int(k)
        self.instrumented = instrumented
        hm = self._hm = _health.get_monitor()
        # one read per driver: the watchdog variant of the step is picked
        # (and compiled) at build, not mid-epoch — the fit-entry contract
        self._use_health = instrumented and hm.active
        if engine is None:
            if self.k > 1:
                engine = _FusedEngine(net, self.k, self._use_health,
                                      batch_size=batch_size,
                                      prefetch=prefetch)
            else:
                engine = _PlainEngine(net, self._use_health,
                                      tbptt_fn=tbptt_fn)
        self.engine = engine
        self._fit_span_kw = fit_span_kw or {"net": type(net).__name__}
        self._pipe = _tm.ScorePipeline()
        if instrumented:
            reg, step_h, etl_h, iters_c, score_g = _tm.train_metrics()
            self._reg = reg
            self._frec = _flight.get_recorder()
            self._emitter = _tm.scorepipe.StepRecordEmitter(
                net, step_h, etl_h, iters_c, score_g, self._frec)
            if reg.enabled:
                # open the goodput window with the first instrumented
                # driver: every fit loop gets the wall-clock ledger
                # (compute/etl/idle split on /health) without wiring
                _tm.goodput.get_ledger().ensure_started()
        self._src = None     # persistent fused source (owns a prefetcher)
        self._it = None      # current epoch iterator
        self._tctx = None    # last dispatch's trace (exception cleanup)
        self.profile = None  # armed ProfileSchedule (profile_round)
        self.last_score = None

    # -- epoch plumbing -------------------------------------------------

    def _epoch_source(self):
        if self.engine.fused:
            if self._src is None:
                self._src = self.engine.build_source(self.batch_factory)
            return self._src
        return self.engine.build_source(self.batch_factory)

    def start_epoch(self):
        if self.instrumented:
            # the ParallelTrainer contract has never had on_epoch_start
            for l in self.net.listeners:
                l.on_epoch_start(self.net)
        self._it = iter(self._epoch_source())

    def end_epoch(self):
        # drain the score pipeline at the epoch edge so the last
        # iteration's record/callback lands before on_epoch_end (one sync
        # per epoch, not per step)
        tail = self._pipe.flush()
        if tail is not None:
            self._emit(tail)
        if self.instrumented:
            for l in self.net.listeners:
                l.on_epoch_end(self.net)
            self.net.epoch += 1
        else:
            # lite epoch edges (epoch-end listeners, the empty-epoch
            # checks, the epoch counter) belong to the trainer wrapper,
            # which sees the RoundResult first
            pass
        self._it = None

    def _emit(self, resolved):
        if self.instrumented:
            self._emitter.emit(*resolved)
        else:
            self.engine.fan(*resolved)

    # -- rounds ---------------------------------------------------------

    def profile_round(self, rounds_from_now, logdir, force=None):
        """Arm a windowed ``jax.profiler`` capture around the n-th future
        :meth:`run_round` (``rounds_from_now=1`` is the next one): exactly
        that round runs inside a profiler session writing to ``logdir``.
        Guarded no-op off-TPU (telemetry/profiling.py) — the idle cost is
        one attribute check per round, and the PR 8 span annotations only
        land on the device timeline while the window is open."""
        from deeplearning4j_tpu.telemetry import profiling as _profiling
        if self.profile is None:
            self.profile = _profiling.ProfileSchedule()
        self.profile.arm(rounds_from_now, logdir, force=force)
        return self.profile

    def run_round(self, k_dispatches=None):
        """Consume up to ``k_dispatches`` dispatches from the current
        epoch (starting one if none is open; ``None`` = run to epoch
        end). Returns a :class:`RoundResult`; the score pipeline and
        health monitor may each hold one pending entry afterwards — call
        :meth:`sync` (or :meth:`checkpoint`) to resolve them. An armed
        :meth:`profile_round` schedule brackets exactly its round in a
        profiler window."""
        if self.profile is not None and self.profile.armed:
            with self.profile.window():
                return self._run_round(k_dispatches)
        return self._run_round(k_dispatches)

    def _run_round(self, k_dispatches=None):
        if self._it is None:
            self.start_epoch()
        rr = RoundResult()
        while k_dispatches is None or rr.dispatches < k_dispatches:
            try:
                item = next(self._it)
            except StopIteration:
                rr.epoch_done = True
                break
            steps = (self._dispatch_one(item) if self.instrumented
                     else self._dispatch_lite(item))
            if steps == 0:
                continue  # skipped (lite non-divisible batch)
            rr.dispatches += 1
            rr.steps += steps
        if rr.epoch_done:
            self.end_epoch()
        return rr

    def run(self, epochs):
        """The classic fit loop: N epochs to exhaustion under the
        historical telemetry/exception contract. The ``fit()`` facades
        delegate here."""
        hm = self._hm
        try:
            if self.instrumented:
                with _tm.span("fit", **self._fit_span_kw):
                    for _ in range(epochs):
                        self.run_round(None)
                if self._use_health:
                    # resolve the tail bundle; an anomaly on the last step
                    # still runs the policy (may raise) before fit returns
                    hm.flush()
            else:
                for _ in range(epochs):
                    self.run_round(None)
        except BaseException as e:
            if self._use_health:
                try:
                    hm.flush(apply_policy=False)  # final health into ring
                except Exception:
                    pass
            if self._tctx is not None:
                # the step that crashed never reached the pipeline —
                # close its trace here (idempotent if it did)
                self._tctx.abandon()
            if self.instrumented:
                _flight.crash_dump(e)
            raise
        finally:
            self._pipe.abandon()  # no-op after flush; closes the pending
            #                       step's trace on the exception path
            self.close_source()
            if self.instrumented:
                _listeners.run_fit_end_hooks(self.net)
        return self.net

    # -- dispatch bodies ------------------------------------------------

    def _dispatch_one(self, item):
        """One instrumented dispatch — the audited moved body of the
        MLN/CG fit loops (see nn/multilayer.py history for the span/
        pipeline rationale comments)."""
        eng, net = self.engine, self.net
        reg = self._reg
        # with prefetch the trace originated on the producer thread
        # (assembly + device_put spans already recorded); attach so the
        # etl/step spans below parent under it
        tctx = getattr(item, "_trace_ctx", None)
        if tctx is None:
            tctx = _tm.tracectx.maybe_start(eng.trace_root)
        self._tctx = tctx
        with _tm.tracectx.attach(tctx):
            etl_start = time.perf_counter()
            with _tm.span("fit.etl"):
                prep = eng.prepare(item)
            etl_time = time.perf_counter() - etl_start
            eng.note_input(prep)
            hb = None
            step0 = net.iteration
            rec = reg.enabled  # one read: a mid-iteration enable() must
            #                    not see half-initialized locals
            want_score = rec or bool(net.listeners)
            resolved = meta = None
            n_real = eng.n_real(item)
            span_kw = ({"iteration": step0, "fused_k": n_real}
                       if eng.fused else {"iteration": step0})
            step_start = time.perf_counter()
            with _tm.span("fit.step", **span_kw):
                if eng.fused:
                    eng._n_real = n_real
                loss, hb = eng.dispatch(prep)
                if want_score:
                    # queue this dispatch, resolve the previous one INSIDE
                    # the span: the blocking fetch overlaps the dispatch
                    # just issued (the one-late ScorePipeline discipline)
                    meta = {"step": step0, "iteration": net.iteration,
                            "etl_time_s": etl_time, "rec": rec,
                            "health": self._use_health,
                            "step_time_s": 0.0,
                            "trace": tctx,
                            "trace_id": (None if tctx is None
                                         else tctx.trace_id)}
                    if eng.fused:
                        meta["k"] = n_real
                    t_res = time.perf_counter()
                    resolved = self._pipe.push(loss, meta)
                    if resolved is not None:
                        prev_t = resolved[1].get("trace")
                        if prev_t is not None:
                            # the one-late fetch of dispatch i-1 happens
                            # HERE, overlapped by dispatch i — record it
                            # in ITS trace, not this one's
                            prev_t.add_span("train.score_fetch", t_res,
                                            time.perf_counter())
        if meta is None and tctx is not None:
            tctx.finish()  # nobody resolves scores
        if meta is not None:
            meta["step_time_s"] = time.perf_counter() - step_start
        if resolved is not None:
            self._emitter.emit(*resolved)
        elif self._use_health and not want_score:
            # watchdog-only run: flight-record the dispatch shape without
            # fetching a score
            kw = {"fused_k": n_real} if eng.fused else {}
            self._frec.note(step=step0,
                            step_time_s=time.perf_counter() - step_start,
                            etl_time_s=etl_time, **kw)
        if rec:
            _devices.note_jit_cache("fit.step", eng.cache_fn())
        if hb is not None:
            # queues this bundle, resolves the previous one (policy may
            # raise NumericsError one dispatch late)
            if eng.fused:
                self._hm.on_step(hb, step=step0, k=n_real)
            else:
                self._hm.on_step(hb, step=step0)
        self.last_score = net.score_value
        return n_real

    def _dispatch_lite(self, item):
        """One ParallelTrainer dispatch: no spans/traces/flight — the
        score pipeline feeds the trainer's 3-arg listeners one step
        late, exactly as that loop always has."""
        out = self.engine.dispatch(item)
        if out is None:
            return 0  # skipped batch (counted by the engine)
        loss, n, meta = out
        # the representative score is whatever the engine stamped on the
        # trainer (last REAL step's device scalar), not the raw stacked
        # losses the pipeline fans
        self.last_score = self.net.score_value
        if self.net.listeners:
            resolved = self._pipe.push(loss, meta)
            if resolved is not None:
                self.engine.fan(*resolved)
        return n

    # -- resumability ---------------------------------------------------

    def sync(self, apply_policy=True):
        """Resolve everything in flight: the score pipeline's tail record
        is emitted and the health monitor's pending bundle resolves —
        under ``policy='raise'`` a sick round surfaces as
        ``NumericsError`` HERE, one round late (the continuous trainer's
        rollback trigger)."""
        tail = self._pipe.flush()
        if tail is not None:
            self._emit(tail)
        if self._use_health:
            self._hm.flush(apply_policy=apply_policy)

    def checkpoint(self, path, *, buckets=None, save_updater=True):
        """``sync()`` then write one resumable ``save_bundle`` unit —
        checkpoint + opt_state + RNG chain (+ attached warm manifest) —
        between rounds. ``restore`` of the result is bit-exact."""
        from deeplearning4j_tpu.utils import serialization as _ser
        self.sync()
        t0 = time.perf_counter()
        # the step loop holds device trees; a checkpoint is a DELIBERATE
        # host sync between rounds, not a hidden per-step one
        net = self.engine.to_host()
        out = _ser.save_bundle(net, path, buckets=buckets,
                               save_updater=save_updater)
        if self.instrumented:
            # checkpoint seconds are wall clock the step loop did not
            # compute in — the goodput ledger's `checkpoint` category
            _tm.goodput.get_ledger().note(
                "checkpoint", time.perf_counter() - t0)
        return out

    def restore(self, path_or_bundle):
        """Roll back / resume: abandon anything in flight, then re-arm
        params/state/opt_state, the RNG chain and the iteration counter
        from a bundle (path, file object, or a loaded ``Bundle``). The
        cached compiled steps re-dispatch with zero recompiles."""
        from deeplearning4j_tpu.utils import serialization as _ser
        self.abandon_pending()
        b = (path_or_bundle if hasattr(path_or_bundle, "net")
             else _ser.load_bundle(path_or_bundle))
        self.engine.rearm(b.net)
        return b

    def abandon_pending(self):
        """Drop in-flight pipeline state without resolving it (rollback /
        exception path): the pending score's trace closes, the pending
        health bundle records without re-running the policy."""
        self._pipe.abandon()
        if self._use_health:
            try:
                self._hm.flush(apply_policy=False)
            except Exception:
                pass
        self._tctx = None

    def close_source(self):
        """Stop the prefetch producer (fused sources); safe to call
        repeatedly. A later ``run_round`` rebuilds the source."""
        if self._src is not None and hasattr(self._src, "close"):
            self._src.close()
        self._src = None
        self._it = None
