"""Host-side synchronization helpers.

Round-2 TPU measurement finding: on remote/tunneled backends (the axon TPU
plugin) a per-value ``float(device_array)`` pays one full host<->device
round-trip (~70 ms over the tunnel) PER CALL, and ``jax.block_until_ready``
returns before device work completes — so training loops must keep losses on
device and fetch them in one batched transfer at the end.
"""

from __future__ import annotations

import jax


def fetch_losses(losses):
    """One batched host fetch of a list of device scalars -> list[float].

    ``jax.device_get`` on the whole list starts every transfer
    asynchronously before awaiting any of them — a single effective
    round-trip, vs one per element for per-item ``float()``.
    """
    if not losses:
        return []
    return [float(v) for v in jax.device_get(losses)]
