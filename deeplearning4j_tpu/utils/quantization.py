"""Weight-only int8 quantization for inference.

Reference analog: none — DL4J 0.9 has no quantization; net-new for the TPU
goals. Weight-only int8 halves the HBM footprint and read bandwidth of the
weight matrices (the bound resource for serving large models); activations
stay in the compute dtype, and the dequantize (int8 -> compute dtype *
per-channel scale) happens INSIDE the jitted forward so XLA fuses it into
the weight load feeding the MXU.

Scheme: symmetric per-output-channel scales (absmax / 127) on matmul-family
weight leaves; everything else (biases, norms, embeddings' positional rows)
stays untouched. Quantize once, serve many:

    qi = QuantizedInference(net)        # quantizes a trained net
    y = qi.output(x)                    # jitted forward on int8 weights
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.utils import dtypes as _dtypes

# matmul-family parameter names whose leaves quantize (per-layer dicts may
# nest, e.g. MoE blocks' mha sub-dict)
WEIGHT_KEYS = frozenset({"W", "Wx", "Wh", "Wqkv", "Wo",
                         "expert_W1", "expert_W2",
                         "mlp_W1", "mlp_W2", "router_W"})


def _leaf_name(path):
    last = path[-1]
    return getattr(last, "key", str(last))


def _is_weight(path, leaf, keys):
    return (_leaf_name(path) in keys and hasattr(leaf, "ndim")
            and leaf.ndim >= 2)


def weight_keys_for(net):
    """Quantizable weight names for a network: the module defaults plus
    every layer's own declared WEIGHT_KEYS (one source of truth with
    nn/constraints.py's use of the same attribute)."""
    keys = set(WEIGHT_KEYS)
    layers = getattr(net.conf, "layers", None)
    if layers is None:  # ComputationGraph
        layers = [getattr(getattr(v, "vertex", None), "layer", None)
                  for v in net.conf.vertices]
    for layer in layers:
        keys.update(getattr(layer, "WEIGHT_KEYS", ()) or ())
    return frozenset(keys)


def quantize_params(params, keys=WEIGHT_KEYS):
    """(qparams, scales): weight leaves -> int8 with per-output-channel
    scales (last axis = output channels; stacked 3-D expert weights [E,I,O]
    get PER-EXPERT per-channel scales — a shared scale would pin every
    expert to the largest one's range); non-weight leaves pass through
    with a None scale."""
    def quant(path, leaf):
        if not _is_weight(path, leaf, keys):
            return leaf, None
        w = jnp.asarray(leaf, jnp.float32)
        name = _leaf_name(path)
        if w.ndim == 3 and name.startswith("expert_"):
            axes = (1,)                      # [E, I, O] -> scale [E, 1, O]
        else:
            axes = tuple(range(w.ndim - 1))  # reduce everything but O
        absmax = jnp.max(jnp.abs(w), axis=axes, keepdims=True)
        scale = jnp.maximum(absmax, 1e-12) / 127.0
        q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
        return q, scale

    pairs = jax.tree_util.tree_map_with_path(quant, params)
    qparams = jax.tree_util.tree_map(lambda p: p[0], pairs,
                                     is_leaf=lambda x: isinstance(x, tuple))
    scales = jax.tree_util.tree_map(lambda p: p[1], pairs,
                                    is_leaf=lambda x: isinstance(x, tuple))
    return qparams, scales


def dequantize_params(qparams, scales, dtype=None):
    """Rebuild a compute-dtype param tree (runs inside jit: XLA fuses the
    int8 load + scale into the consuming matmul)."""
    dtype = dtype or _dtypes.get_policy().compute_dtype

    def deq(q, s):
        if s is None:
            return q
        return (q.astype(jnp.float32) * s).astype(dtype)

    return jax.tree_util.tree_map(deq, qparams, scales,
                                  is_leaf=lambda x: x is None)


def weight_bytes(params, keys=WEIGHT_KEYS):
    """Total bytes of the quantizable weight leaves (for the 2x claim)."""
    total = 0

    def add(path, leaf):
        nonlocal total
        if _is_weight(path, leaf, keys):
            total += leaf.size * leaf.dtype.itemsize
        return leaf

    jax.tree_util.tree_map_with_path(add, params)
    return total


class QuantizedInference:
    """Serve a trained MultiLayerNetwork/ComputationGraph from int8 weights.

    The stored tree is int8 + scales; each jitted forward dequantizes into
    the compute dtype on the fly. Predictions match the float net up to the
    quantization error (pinned in tests)."""

    def __init__(self, net, dtype=None):
        assert net.params is not None, "quantize a trained/initialized net"
        self.net = net
        self.qparams, self.scales = quantize_params(net.params,
                                                    weight_keys_for(net))

        def fwd(qp, sc, state, x, mask):
            p = dequantize_params(qp, sc, dtype)
            out = net.apply_fn(p, state, x, train=False, mask=mask)
            return out[0]

        self._fwd = jax.jit(fwd)

    def output(self, x, mask=None):
        """Same contract as the wrapped net's output(): dict inputs and
        single-output unwrapping for graphs, mask passthrough for padded
        sequences."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        if isinstance(self.net, ComputationGraph):
            if not isinstance(x, dict):
                x = {self.net.conf.inputs[0]: jnp.asarray(x)}
            else:
                x = {k: jnp.asarray(v) for k, v in x.items()}
            outs = self._fwd(self.qparams, self.scales, self.net.state, x,
                             mask)
            if len(self.net.conf.outputs) == 1:
                return outs[self.net.conf.outputs[0]]
            return outs
        return self._fwd(self.qparams, self.scales, self.net.state,
                         jnp.asarray(x), mask)
