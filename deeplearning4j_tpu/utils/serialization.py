"""Model persistence: zip of {config JSON, params, mutable state, updater state}.

Reference analog: util/ModelSerializer.java (/root/reference/deeplearning4j-nn/
.../util/ModelSerializer.java:51 writeModel, :136 restoreMultiLayerNetwork) —
zip container with JSON config + raw params + updater state, so optimizer
momentum survives resume (SURVEY.md §5 checkpoint row). Format is versioned
for forward-compat (the reference pins it with regression tests §4.4).

Layout inside the zip:
    format.json     {"format_version": 1, "kind": "multilayer"|"graph",
                     "iteration": N, "epoch": N}
    config.json     network configuration (serde JSON)
    arrays.npz      flat {path -> ndarray} for params/state/opt_state
                    pytrees + the step RNG chain ("rng"), so a resumed run
                    continues the SAME dropout/shuffle key sequence instead
                    of replaying from the seed
    buckets.json    (bundle only) the BucketRegistry sizes the job compiled
    warm_manifest.zip  (bundle only) serialized AOT executables
                    (utils/compile_cache.WarmManifest) — the instant-restart
                    artifact: a warm restart recompiles nothing

``save_bundle``/``load_bundle`` fold checkpoint + opt_state + RNG chain +
bucket registry + warm manifest into ONE resumable unit; ``save_model``
zips remain loadable by ``load_bundle`` (extras absent) and bundles by
``load_model`` (extras ignored).
"""

from __future__ import annotations

import io
import json
import zipfile
from dataclasses import dataclass

import jax
import numpy as np

FORMAT_VERSION = 1


def _flatten_tree(tree, prefix):
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        flat[prefix + jax.tree_util.keystr(path)] = np.asarray(leaf)
    return flat


def _unflatten_like(template, arrays, prefix):
    paths = [prefix + jax.tree_util.keystr(p)
             for p, _ in jax.tree_util.tree_flatten_with_path(template)[0]]
    treedef = jax.tree_util.tree_structure(template)
    import jax.numpy as jnp
    leaves = [jnp.asarray(arrays[p]) for p in paths]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _write_model(z, net, save_updater):
    """Write the model entries (format/config/arrays) into an open zip."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    kind = "graph" if isinstance(net, ComputationGraph) else "multilayer"
    arrays = {}
    arrays.update(_flatten_tree(net.params, "params"))
    arrays.update(_flatten_tree(net.state, "state"))
    if save_updater and net.opt_state is not None:
        arrays.update(_flatten_tree(net.opt_state, "opt"))
    rng = getattr(net, "_rng", None)
    if rng is not None:
        # the RNG chain: without it a resumed run replays the seed's
        # dropout/shuffle keys instead of continuing from step N+1 —
        # crash→resume would diverge from the uninterrupted run
        arrays["rng"] = np.asarray(rng)
    meta = {"format_version": FORMAT_VERSION, "kind": kind,
            "iteration": net.iteration, "epoch": net.epoch,
            "has_updater": bool(save_updater and net.opt_state is not None),
            "has_rng": rng is not None}
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    z.writestr("format.json", json.dumps(meta))
    z.writestr("config.json", net.conf.to_json())
    z.writestr("arrays.npz", buf.getvalue())


def save_model(net, path, *, save_updater=True):
    """Write a MultiLayerNetwork or ComputationGraph checkpoint."""
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        _write_model(z, net, save_updater)
    return path


def _read_model(z):
    meta = json.loads(z.read("format.json"))
    config_json = z.read("config.json").decode()
    arrays = dict(np.load(io.BytesIO(z.read("arrays.npz"))))
    if meta["format_version"] > FORMAT_VERSION:
        raise ValueError(f"Checkpoint format {meta['format_version']} is newer "
                         f"than supported {FORMAT_VERSION}")
    if meta["kind"] == "graph":
        from deeplearning4j_tpu.nn.graph import ComputationGraph, GraphConfiguration
        net = ComputationGraph(GraphConfiguration.from_json(config_json))
    else:
        from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(MultiLayerConfiguration.from_json(config_json))
    net.init()  # build template pytrees (then overwrite)
    net.params = _unflatten_like(net.params, arrays, "params")
    net.state = _unflatten_like(net.state, arrays, "state")
    if meta.get("has_updater"):
        net.opt_state = _unflatten_like(net.opt_state, arrays, "opt")
    if meta.get("has_rng"):
        import jax.numpy as jnp
        net._rng = jnp.asarray(arrays["rng"])
    net.iteration = meta.get("iteration", 0)
    net.epoch = meta.get("epoch", 0)
    return net


def load_model(path):
    """Restore a model (auto-detects kind). Returns the network with params,
    state, opt_state, RNG chain, iteration/epoch restored."""
    with zipfile.ZipFile(path) as z:
        return _read_model(z)


restore_multilayer_network = load_model
restore_computation_graph = load_model


def bucket_sizes(buckets):
    """Normalize a BucketRegistry or iterable of sizes to a sorted int
    list (the buckets.json wire form — shared with sharded_checkpoint)."""
    if hasattr(buckets, "sizes"):
        return buckets.sizes()
    return sorted(int(b) for b in buckets)


@dataclass
class Bundle:
    """One resumable unit: the restored net (params/state/opt_state/RNG/
    iteration), the bucket registry the job compiled for, and the warm
    manifest its executables deserialize from (already attached to the net
    when it matches this backend)."""
    net: object
    buckets: object = None    # datasets.iterator.BucketRegistry | None
    manifest: object = None   # utils.compile_cache.WarmManifest | None


def save_bundle(net, path, *, buckets=None, manifest=None,
                save_updater=True):
    """Write the INSTANT-RESTART unit: checkpoint + opt_state + RNG chain
    + bucket registry + warm AOT manifest in one zip. ``manifest``
    defaults to the net's attached manifest (compile_cache.attach_manifest
    — autofilled by fused-fit live compiles); ``buckets`` accepts a
    BucketRegistry or an iterable of sizes. ``load_bundle`` resumes from
    it with zero recompiles for every manifest-covered signature."""
    if manifest is None:
        manifest = getattr(net, "_warm_manifest", None)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        _write_model(z, net, save_updater)
        if buckets is not None:
            z.writestr("buckets.json", json.dumps(bucket_sizes(buckets)))
        if manifest is not None and len(manifest):
            z.writestr("warm_manifest.zip", manifest.to_bytes())
    return path


def load_bundle(path):
    """Restore a :class:`Bundle`. A manifest built for another
    architecture or backend (different jax version, device kind) is
    DROPPED with a warning instead of trusted — its executables would fail
    at call time with opaque XLA errors; the checkpoint itself still
    loads, and the first fit simply pays the compile (and can re-save a
    fresh manifest)."""
    from deeplearning4j_tpu.utils import compile_cache as _cc
    with zipfile.ZipFile(path) as z:
        net = _read_model(z)
        names = set(z.namelist())
        buckets = None
        if "buckets.json" in names:
            from deeplearning4j_tpu.datasets.iterator import BucketRegistry
            buckets = BucketRegistry(json.loads(z.read("buckets.json")))
        manifest = None
        if "warm_manifest.zip" in names:
            # lenient: a corrupt embedded manifest must not take the
            # checkpoint down with it — restore the net, pay compiles
            manifest = _cc.WarmManifest.load_lenient(
                z.read("warm_manifest.zip"),
                context=f"bundle {path}: embedded warm manifest")
    manifest = _cc.attach_if_matches(net, manifest, f"bundle {path}")
    return Bundle(net=net, buckets=buckets, manifest=manifest)


def add_normalizer_to_model(path, normalizer):
    """Attach a fitted normalizer to an existing checkpoint zip.

    Reference: ModelSerializer.addNormalizerToModel (util/
    ModelSerializer.java) — the reference appends a Java-serialized
    normalizer.bin; here the entry is normalizer.json (the Java object
    stream is JVM-private, so genuine DL4J normalizer.bin entries are NOT
    readable — config+params of such zips still load, see
    modelimport/dl4j.py)."""
    entry = normalizer.to_json()
    with zipfile.ZipFile(path, "a", zipfile.ZIP_DEFLATED) as z:
        if "normalizer.json" in z.namelist():
            raise ValueError(f"{path} already contains a normalizer")
        z.writestr("normalizer.json", entry)
    return path


def restore_normalizer(path):
    """The fitted normalizer attached to a checkpoint, or None.

    Reference: ModelSerializer.restoreNormalizerFromFile."""
    from deeplearning4j_tpu.datasets.normalizers import _FittedNormalizer
    with zipfile.ZipFile(path) as z:
        names = z.namelist()
        if "normalizer.json" in names:
            return _FittedNormalizer.from_json(
                z.read("normalizer.json").decode())
        if "normalizer.bin" in names:
            # a genuine DL4J zip with a Java-serialized normalizer: do NOT
            # silently return None — the user would serve un-normalized
            # inputs with no signal anything was lost
            raise ValueError(
                f"{path} contains a JVM-serialized normalizer.bin (DL4J "
                "ModelSerializer format), which is not readable here. "
                "Re-fit the normalizer (datasets.normalizers) on the "
                "training data, or export its statistics from the JVM "
                "side; the model config+params in this zip still load.")
        return None
