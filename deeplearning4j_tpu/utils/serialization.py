"""Model persistence: zip of {config JSON, params, mutable state, updater state}.

Reference analog: util/ModelSerializer.java (/root/reference/deeplearning4j-nn/
.../util/ModelSerializer.java:51 writeModel, :136 restoreMultiLayerNetwork) —
zip container with JSON config + raw params + updater state, so optimizer
momentum survives resume (SURVEY.md §5 checkpoint row). Format is versioned
for forward-compat (the reference pins it with regression tests §4.4).

Layout inside the zip:
    format.json     {"format_version": 1, "kind": "multilayer"|"graph",
                     "iteration": N, "epoch": N}
    config.json     network configuration (serde JSON)
    arrays.npz      flat {path -> ndarray} for params/state/opt_state pytrees
"""

from __future__ import annotations

import io
import json
import zipfile

import jax
import numpy as np

FORMAT_VERSION = 1


def _flatten_tree(tree, prefix):
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        flat[prefix + jax.tree_util.keystr(path)] = np.asarray(leaf)
    return flat


def _unflatten_like(template, arrays, prefix):
    paths = [prefix + jax.tree_util.keystr(p)
             for p, _ in jax.tree_util.tree_flatten_with_path(template)[0]]
    treedef = jax.tree_util.tree_structure(template)
    import jax.numpy as jnp
    leaves = [jnp.asarray(arrays[p]) for p in paths]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_model(net, path, *, save_updater=True):
    """Write a MultiLayerNetwork or ComputationGraph checkpoint."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    kind = "graph" if isinstance(net, ComputationGraph) else "multilayer"
    arrays = {}
    arrays.update(_flatten_tree(net.params, "params"))
    arrays.update(_flatten_tree(net.state, "state"))
    if save_updater and net.opt_state is not None:
        arrays.update(_flatten_tree(net.opt_state, "opt"))
    meta = {"format_version": FORMAT_VERSION, "kind": kind,
            "iteration": net.iteration, "epoch": net.epoch,
            "has_updater": bool(save_updater and net.opt_state is not None)}
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("format.json", json.dumps(meta))
        z.writestr("config.json", net.conf.to_json())
        z.writestr("arrays.npz", buf.getvalue())
    return path


def load_model(path):
    """Restore a model (auto-detects kind). Returns the network with params,
    state, opt_state, iteration/epoch restored."""
    with zipfile.ZipFile(path) as z:
        meta = json.loads(z.read("format.json"))
        config_json = z.read("config.json").decode()
        arrays = dict(np.load(io.BytesIO(z.read("arrays.npz"))))
    if meta["format_version"] > FORMAT_VERSION:
        raise ValueError(f"Checkpoint format {meta['format_version']} is newer "
                         f"than supported {FORMAT_VERSION}")
    if meta["kind"] == "graph":
        from deeplearning4j_tpu.nn.graph import ComputationGraph, GraphConfiguration
        net = ComputationGraph(GraphConfiguration.from_json(config_json))
    else:
        from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(MultiLayerConfiguration.from_json(config_json))
    net.init()  # build template pytrees (then overwrite)
    net.params = _unflatten_like(net.params, arrays, "params")
    net.state = _unflatten_like(net.state, arrays, "state")
    if meta.get("has_updater"):
        net.opt_state = _unflatten_like(net.opt_state, arrays, "opt")
    net.iteration = meta.get("iteration", 0)
    net.epoch = meta.get("epoch", 0)
    return net


restore_multilayer_network = load_model
restore_computation_graph = load_model


def add_normalizer_to_model(path, normalizer):
    """Attach a fitted normalizer to an existing checkpoint zip.

    Reference: ModelSerializer.addNormalizerToModel (util/
    ModelSerializer.java) — the reference appends a Java-serialized
    normalizer.bin; here the entry is normalizer.json (the Java object
    stream is JVM-private, so genuine DL4J normalizer.bin entries are NOT
    readable — config+params of such zips still load, see
    modelimport/dl4j.py)."""
    entry = normalizer.to_json()
    with zipfile.ZipFile(path, "a", zipfile.ZIP_DEFLATED) as z:
        if "normalizer.json" in z.namelist():
            raise ValueError(f"{path} already contains a normalizer")
        z.writestr("normalizer.json", entry)
    return path


def restore_normalizer(path):
    """The fitted normalizer attached to a checkpoint, or None.

    Reference: ModelSerializer.restoreNormalizerFromFile."""
    from deeplearning4j_tpu.datasets.normalizers import _FittedNormalizer
    with zipfile.ZipFile(path) as z:
        names = z.namelist()
        if "normalizer.json" in names:
            return _FittedNormalizer.from_json(
                z.read("normalizer.json").decode())
        if "normalizer.bin" in names:
            # a genuine DL4J zip with a Java-serialized normalizer: do NOT
            # silently return None — the user would serve un-normalized
            # inputs with no signal anything was lost
            raise ValueError(
                f"{path} contains a JVM-serialized normalizer.bin (DL4J "
                "ModelSerializer format), which is not readable here. "
                "Re-fit the normalizer (datasets.normalizers) on the "
                "training data, or export its statistics from the JVM "
                "side; the model config+params in this zip still load.")
        return None
