"""Time-series / masking utilities.

Reference analog: util/TimeSeriesUtils.java (movingAverage, 2d<->3d
reshapes, mask-vector reshapes) and util/MaskedReductionUtil.java (masked
time-series and spatial poolings) in /root/reference/deeplearning4j-nn.
The layer implementations fold most of this in via jnp broadcasting; these
standalone helpers exist for user code and for behavior-parity edge cases
(e.g. masked MAX pooling must ignore masked steps even when all values are
negative).

Layout note: this framework's time series are [batch, time, features]
(channels-last everywhere), not the reference's [batch, features, time] —
the helpers speak the native layout.
"""

from __future__ import annotations

import jax.numpy as jnp

_NEG_INF = -3.4e38  # safely below any f32/bf16 activation


def moving_average(x, n):
    """Trailing moving average over the last axis of a 1-D/2-D array; output
    length shrinks by n-1 (reference: TimeSeriesUtils.movingAverage)."""
    x = jnp.asarray(x, jnp.float32)
    c = jnp.cumsum(x, axis=-1)
    head = c[..., n - 1:n]
    rest = c[..., n:] - c[..., :-n]
    return jnp.concatenate([head, rest], axis=-1) / n


def reshape_3d_to_2d(x):
    """[B, T, F] -> [B*T, F] (reference reshape3dTo2d, adapted to BTF)."""
    b, t, f = x.shape
    return x.reshape(b * t, f)


def reshape_2d_to_3d(x, minibatch_size):
    """[B*T, F] -> [B, T, F] (reference reshape2dTo3d)."""
    n, f = x.shape
    return x.reshape(minibatch_size, n // minibatch_size, f)


def reshape_time_series_mask_to_vector(mask):
    """[B, T] mask -> [B*T] (row-major, aligned with reshape_3d_to_2d)."""
    return jnp.asarray(mask).reshape(-1)


def reshape_vector_to_time_series_mask(vec, minibatch_size):
    """[B*T] -> [B, T]."""
    v = jnp.asarray(vec)
    return v.reshape(minibatch_size, v.shape[0] // minibatch_size)


def pull_last_time_step(x, mask=None):
    """[B, T, F] -> [B, F]: the last UNMASKED step per example (reference:
    the rnnTimeStep/LastTimeStepVertex semantics)."""
    x = jnp.asarray(x)
    if mask is None:
        return x[:, -1]
    m = jnp.asarray(mask)
    idx = jnp.maximum(m.shape[1] - 1 - jnp.argmax(m[:, ::-1] > 0, axis=1), 0)
    return jnp.take_along_axis(x, idx[:, None, None].astype(jnp.int32),
                               axis=1)[:, 0]


def reverse_time_series(x, mask=None):
    """Reverse along time. With a mask, each example's VALID prefix reverses
    in place and padding stays at the tail (reference: TimeSeriesUtils
    reverse used by bidirectional RNNs)."""
    x = jnp.asarray(x)
    if mask is None:
        return x[:, ::-1]
    m = jnp.asarray(mask) > 0
    lengths = m.sum(axis=1).astype(jnp.int32)          # [B]
    t = x.shape[1]
    pos = jnp.arange(t)[None, :]                       # [1, T]
    src = jnp.where(pos < lengths[:, None],
                    lengths[:, None] - 1 - pos, pos)   # [B, T]
    return jnp.take_along_axis(x, src[..., None].astype(jnp.int32), axis=1)


def masked_pooling_time_series(pooling_type, x, mask):
    """Masked pooling over time: [B, T, F] + [B, T] -> [B, F] (reference:
    MaskedReductionUtil.maskedPoolingTimeSeries; SUM/AVG/MAX/PNORM minus
    PNORM's p parameterization which callers apply via **kwargs)."""
    x = jnp.asarray(x)
    m = (jnp.asarray(mask) > 0)[..., None]             # [B, T, 1]
    if pooling_type == "max":
        return jnp.max(jnp.where(m, x, _NEG_INF), axis=1)
    if pooling_type == "sum":
        return jnp.sum(jnp.where(m, x, 0.0), axis=1)
    if pooling_type == "avg":
        s = jnp.sum(jnp.where(m, x, 0.0), axis=1)
        return s / jnp.maximum(m.sum(axis=1), 1)
    if pooling_type == "pnorm":
        p = 2.0
        s = jnp.sum(jnp.where(m, jnp.abs(x) ** p, 0.0), axis=1)
        return s ** (1.0 / p)
    raise ValueError(f"Unknown pooling type {pooling_type!r}")


def masked_pooling_convolution(pooling_type, x, mask):
    """Masked spatial pooling: [B, H, W, C] + [B, H, W] -> [B, C]
    (reference: MaskedReductionUtil.maskedPoolingConvolution, NHWC)."""
    x = jnp.asarray(x)
    m = (jnp.asarray(mask) > 0)[..., None]             # [B, H, W, 1]
    if pooling_type == "max":
        return jnp.max(jnp.where(m, x, _NEG_INF), axis=(1, 2))
    if pooling_type == "sum":
        return jnp.sum(jnp.where(m, x, 0.0), axis=(1, 2))
    if pooling_type == "avg":
        s = jnp.sum(jnp.where(m, x, 0.0), axis=(1, 2))
        return s / jnp.maximum(m.sum(axis=(1, 2)), 1)
    if pooling_type == "pnorm":
        p = 2.0
        s = jnp.sum(jnp.where(m, jnp.abs(x) ** p, 0.0), axis=(1, 2))
        return s ** (1.0 / p)
    raise ValueError(f"Unknown pooling type {pooling_type!r}")
