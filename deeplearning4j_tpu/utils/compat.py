"""jax version-compatibility shims.

The production target is a current jax (TPU v5e image); the CI/tier-1
environment may carry an older release. Every cross-version API this repo
depends on gets ONE canonical entry point here so call sites stay clean.

``shard_map``: promoted out of jax.experimental (and ``check_rep`` renamed
to ``check_vma``) across jax releases. Call sites import from here with the
NEW calling convention; on old jax the kwarg is translated.
"""

from __future__ import annotations

import jax

_new_shard_map = getattr(jax, "shard_map", None)
if _new_shard_map is None:
    from jax.experimental.shard_map import shard_map as _old_shard_map


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """jax.shard_map with the current-jax signature on every jax."""
    if _new_shard_map is not None:
        return _new_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_vma)
    return _old_shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)
