"""Dtype policy for the framework.

TPU-first: parameters and optimizer state live in float32; matmul/conv inputs
are computed in bfloat16 on TPU by default (MXU-native), with float32
accumulation via ``preferred_element_type``. Tests (CPU) run everything in
float32/float64 for gradient checking.

Reference analog: nd4j's global dtype (Nd4j.setDataType) — but here the policy
is a pair (param_dtype, compute_dtype) as is idiomatic for mixed-precision jax.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DtypePolicy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    accum_dtype: jnp.dtype = jnp.float32


_POLICY = DtypePolicy()


def get_policy() -> DtypePolicy:
    return _POLICY


def set_policy(param_dtype=None, compute_dtype=None, accum_dtype=None) -> DtypePolicy:
    global _POLICY
    _POLICY = DtypePolicy(
        param_dtype=jnp.dtype(param_dtype) if param_dtype is not None else _POLICY.param_dtype,
        compute_dtype=jnp.dtype(compute_dtype) if compute_dtype is not None else _POLICY.compute_dtype,
        accum_dtype=jnp.dtype(accum_dtype) if accum_dtype is not None else _POLICY.accum_dtype,
    )
    return _POLICY


def compute_dtypes_for(x_dtype):
    """(compute, accum) dtypes for an input dtype. float64 inputs (gradient
    checking) stay in float64; everything else follows the global policy."""
    if jnp.dtype(x_dtype) == jnp.float64:
        return jnp.float64, jnp.float64
    pol = get_policy()
    return pol.compute_dtype, pol.accum_dtype


def bf16_policy() -> DtypePolicy:
    """The TPU training policy: f32 params, bf16 compute, f32 accumulation."""
    return set_policy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16, accum_dtype=jnp.float32)


def f32_policy() -> DtypePolicy:
    return set_policy(param_dtype=jnp.float32, compute_dtype=jnp.float32, accum_dtype=jnp.float32)
