"""Finite-difference gradient checker.

Reference analog: ``GradientCheckUtil``
(/root/reference/deeplearning4j-nn/src/main/java/org/deeplearning4j/
gradientcheck/GradientCheckUtil.java:109) — the correctness backbone of the
reference's entire test suite (14 gradcheck test files, SURVEY.md §4.2).

Central differences per parameter, double precision, relative error
  relError = |analytic - numeric| / max(|analytic|, |numeric|)
with an absolute-error floor below which parameters pass regardless (same
semantics as the reference's minAbsoluteError).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def check_gradients(loss_fn, params, *, epsilon=1e-6, max_rel_error=1e-5,
                    min_abs_error=1e-8, max_params_per_leaf=None, verbose=False):
    """Compare analytic grads of ``loss_fn(params) -> scalar`` to central differences.

    Returns (ok, failures) where failures is a list of dicts. Runs in float64;
    callers must pass float64 params (tests enable jax_enable_x64).
    """
    params = jax.tree_util.tree_map(lambda a: jnp.asarray(a, jnp.float64), params)
    analytic = jax.grad(loss_fn)(params)
    loss_jit = jax.jit(loss_fn)

    leaves, treedef = jax.tree_util.tree_flatten(params)
    a_leaves = jax.tree_util.tree_flatten(analytic)[0]
    paths = [jax.tree_util.keystr(p) for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]]

    failures = []
    total_checked = 0
    for li, (leaf, a_leaf, path) in enumerate(zip(leaves, a_leaves, paths)):
        flat = np.array(leaf, np.float64).ravel().copy()
        a_flat = np.asarray(a_leaf, np.float64).ravel()
        n = flat.size
        idxs = range(n)
        if max_params_per_leaf is not None and n > max_params_per_leaf:
            rng = np.random.RandomState(12345 + li)
            idxs = rng.choice(n, size=max_params_per_leaf, replace=False)
        for i in idxs:
            orig = flat[i]
            flat[i] = orig + epsilon
            leaves_p = list(leaves)
            leaves_p[li] = jnp.asarray(flat.reshape(leaf.shape))
            score_plus = float(loss_jit(jax.tree_util.tree_unflatten(treedef, leaves_p)))
            flat[i] = orig - epsilon
            leaves_p[li] = jnp.asarray(flat.reshape(leaf.shape))
            score_minus = float(loss_jit(jax.tree_util.tree_unflatten(treedef, leaves_p)))
            flat[i] = orig
            numeric = (score_plus - score_minus) / (2.0 * epsilon)
            analytic_i = a_flat[i]
            abs_err = abs(analytic_i - numeric)
            denom = max(abs(analytic_i), abs(numeric))
            rel_err = abs_err / denom if denom > 0 else 0.0
            total_checked += 1
            if rel_err > max_rel_error and abs_err > min_abs_error:
                failures.append({"param": path, "index": int(i), "analytic": float(analytic_i),
                                 "numeric": float(numeric), "rel_error": float(rel_err)})
                if verbose:
                    print(f"FAIL {path}[{i}]: analytic={analytic_i:.3e} numeric={numeric:.3e} rel={rel_err:.3e}")
    if verbose:
        print(f"gradcheck: {total_checked} params checked, {len(failures)} failures")
    return len(failures) == 0, failures
