"""Polymorphic JSON serde for config dataclasses.

Reference analog: Jackson-based serde of the config DSL
(nn/conf/serde/, MultiLayerConfiguration.toJson:120 / fromJson:138 in
/root/reference/deeplearning4j-nn). Every config dataclass registers itself
under its class name; dicts carry a ``"@type"`` discriminator so arbitrary
config trees (layers, updaters, schedules, distributions, graph vertices)
round-trip through JSON.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import typing

_REGISTRY: dict[str, type] = {}


def register_config(cls):
    """Class decorator: make a dataclass JSON round-trippable by name."""
    _REGISTRY[cls.__name__] = cls
    return cls


def _prime_catalog():
    """Import every module that registers config classes, so deserialization
    works as a user's FIRST framework call (checkpoint resume, CLI). Lazy —
    importing here at module load would create an import cycle."""
    import importlib
    for mod in ("deeplearning4j_tpu.nn.layers", "deeplearning4j_tpu.nn.graph",
                "deeplearning4j_tpu.nn.constraints",
                "deeplearning4j_tpu.nn.weightnoise",
                "deeplearning4j_tpu.nn.conf.inputs",
                "deeplearning4j_tpu.nn.updaters"):
        try:
            importlib.import_module(mod)
        except ImportError:
            pass


def lookup(name: str) -> type:
    try:
        return _REGISTRY[name]
    except KeyError:
        pass
    _prime_catalog()  # registry may simply not be populated yet
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"Unknown config type {name!r}. "
                       f"Registered: {sorted(_REGISTRY)}") from None


def config_to_dict(obj):
    """Recursively convert a registered dataclass tree to plain JSON types."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return {"@enum": type(obj).__name__, "value": obj.name}
    if isinstance(obj, (list, tuple)):
        return [config_to_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {k: config_to_dict(v) for k, v in obj.items()}
    if dataclasses.is_dataclass(obj):
        d = {"@type": type(obj).__name__}
        for f in dataclasses.fields(obj):
            d[f.name] = config_to_dict(getattr(obj, f.name))
        return d
    # numpy / jax scalars
    if hasattr(obj, "item") and getattr(obj, "ndim", None) == 0:
        return obj.item()
    if hasattr(obj, "tolist"):
        return obj.tolist()
    raise TypeError(f"Cannot serialize {type(obj)}: {obj!r}")


def config_from_dict(d):
    if isinstance(d, list):
        return [config_from_dict(v) for v in d]
    if isinstance(d, dict):
        if "@enum" in d:
            return lookup(d["@enum"])[d["value"]]
        if "@type" in d:
            cls = lookup(d["@type"])
            fields = {f.name for f in dataclasses.fields(cls)}
            kwargs = {k: config_from_dict(v) for k, v in d.items() if k in fields}
            # tuple-typed fields arrive as lists from JSON
            hints = typing.get_type_hints(cls)
            for f in dataclasses.fields(cls):
                hint = hints.get(f.name)
                if (hint is tuple or typing.get_origin(hint) is tuple) and \
                        isinstance(kwargs.get(f.name), list):
                    kwargs[f.name] = tuple(kwargs[f.name])
            return cls(**kwargs)
        return {k: config_from_dict(v) for k, v in d.items()}
    return d


def register_enum(cls):
    """Enum decorator: register for serde."""
    _REGISTRY[cls.__name__] = cls
    return cls


def to_json(obj, **kwargs) -> str:
    return json.dumps(config_to_dict(obj), **kwargs)


def from_json(s: str):
    return config_from_dict(json.loads(s))
