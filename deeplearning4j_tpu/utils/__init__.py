from deeplearning4j_tpu.utils import dtypes  # noqa: F401
from deeplearning4j_tpu.utils.serde import register_config, config_to_dict, config_from_dict  # noqa: F401
