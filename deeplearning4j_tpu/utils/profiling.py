"""Programmatic profile analysis: per-op device time from jax.profiler traces.

Reference analog: the reference exposes only listener-level timing
(PerformanceListener samples/sec); on TPU the ground truth is the xprof
trace (per-op device time, HBM bandwidth, MXU utilization). This module
turns a captured trace directory into a ranked op table — the method that
found the round-2 LSTM dxz bottleneck (38% of step time in f32
dynamic-update-slices) and verified the ResNet50 HBM-bound ceiling.

Usage:
    jax.profiler.start_trace(logdir); ...timed work...; jax.profiler.stop_trace()
    for op in top_ops(logdir, k=10):
        print(op["total_self_us"], op["category"], op["expression"][:80])

Requires the ``xprof`` package (present in this environment alongside
tensorboard-plugin-profile); raises ImportError otherwise.
"""

from __future__ import annotations

import glob
import json
import os


def find_xplane(trace_dir):
    """Newest .xplane.pb under a jax.profiler log directory."""
    paths = sorted(glob.glob(os.path.join(
        str(trace_dir), "**", "*.xplane.pb"), recursive=True),
        key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(f"no .xplane.pb under {trace_dir}")
    return paths[-1]


def top_ops(trace_dir, k=15):
    """Ranked per-op rows from a trace: list of dicts with keys
    ``total_self_us``, ``occurrences``, ``category``, ``bound_by``,
    ``expression`` (plus every other hlo_stats column, snake-cased as-is).
    """
    from xprof.convert import raw_to_tool_data as rtd

    path = find_xplane(trace_dir)
    data, _ = rtd.xspace_to_tool_data([path], "hlo_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    tbl = json.loads(data)
    cols = [c["id"] for c in tbl["cols"]]
    rows = []
    for r in tbl.get("rows", []):
        d = dict(zip(cols, [c.get("v") for c in r["c"]]))
        rows.append({
            "total_self_us": d.get("total_self_time"),
            "occurrences": d.get("occurrences"),
            "category": d.get("category"),
            "bound_by": d.get("bound_by"),
            "expression": d.get("hlo_op_expression"),
            **d,
        })
    rows.sort(key=lambda r: r["total_self_us"] or 0.0, reverse=True)
    return rows[:k]


def summarize(trace_dir, k=10):
    """Human-readable top-k table (one string), for logs and reports."""
    rows = top_ops(trace_dir, k)
    lines = [f"{'self us':>10}  {'%':>5}  {'x':>5}  {'category':<18} expression"]
    total = sum(r["total_self_us"] or 0.0 for r in rows) or 1.0
    for r in rows:
        us = r["total_self_us"] or 0.0
        occ = r["occurrences"] or 0
        lines.append(
            f"{us:>10.1f}  {100.0 * us / total:>4.1f}  {occ:>5.0f}  "
            f"{(r['category'] or '?'):<18} {(r['expression'] or '')[:90]}")
    return "\n".join(lines)
