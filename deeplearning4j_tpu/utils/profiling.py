"""Programmatic profile analysis: per-op device time from jax.profiler traces.

Reference analog: the reference exposes only listener-level timing
(PerformanceListener samples/sec); on TPU the ground truth is the xprof
trace (per-op device time, HBM bandwidth, MXU utilization). This module
turns a captured trace directory into a ranked op table — the method that
found the round-2 LSTM dxz bottleneck (38% of step time in f32
dynamic-update-slices) and verified the ResNet50 HBM-bound ceiling.

Usage:
    jax.profiler.start_trace(logdir); ...timed work...; jax.profiler.stop_trace()
    for op in top_ops(logdir, k=10):
        print(op["total_self_us"], op["category"], op["expression"][:80])

Requires the ``xprof`` package (present in this environment alongside
tensorboard-plugin-profile); raises ImportError otherwise.
"""

from __future__ import annotations

import glob
import json
import os


def find_xplane(trace_dir):
    """Newest .xplane.pb under a jax.profiler log directory."""
    paths = sorted(glob.glob(os.path.join(
        str(trace_dir), "**", "*.xplane.pb"), recursive=True),
        key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(f"no .xplane.pb under {trace_dir}")
    return paths[-1]


def rows_from_table(tbl):
    """Flatten an hlo_stats gviz table ({cols: [{id}], rows: [{c: [{v}]}]})
    into row dicts with the canonical keys ``total_self_us``,
    ``occurrences``, ``category``, ``bound_by``, ``expression`` (plus every
    other column, snake-cased as-is). Pure — unit-testable on a synthetic
    table with no TPU or xprof capture."""
    cols = [c["id"] for c in tbl["cols"]]
    rows = []
    for r in tbl.get("rows", []):
        d = dict(zip(cols, [c.get("v") for c in r["c"]]))
        rows.append({
            "total_self_us": d.get("total_self_time"),
            "occurrences": d.get("occurrences"),
            "category": d.get("category"),
            "bound_by": d.get("bound_by"),
            "expression": d.get("hlo_op_expression"),
            **d,
        })
    return rows


def merge_rows(rows):
    """Merge rows sharing an expression: self-times and occurrence counts
    add; the first row's other columns win. Needed when one trace window
    yields several tables (multi-host captures produce one xplane per
    process) or when hlo_stats splits an op across program ids."""
    merged = {}
    order = []
    for r in rows:
        key = r.get("expression")
        cur = merged.get(key)
        if cur is None or key is None:
            # None expressions never merge with each other — keep them apart
            key = key if key is not None else object()
            merged[key] = dict(r)
            order.append(key)
            continue
        cur["total_self_us"] = ((cur.get("total_self_us") or 0.0)
                                + (r.get("total_self_us") or 0.0))
        cur["occurrences"] = ((cur.get("occurrences") or 0)
                              + (r.get("occurrences") or 0))
    return [merged[k] for k in order]


def rank_ops(rows, k=None):
    """Rows sorted by descending self-time; ``k`` truncates (None = all)."""
    out = sorted(rows, key=lambda r: r["total_self_us"] or 0.0, reverse=True)
    return out if k is None else out[:k]


def top_ops(trace_dir, k=15):
    """Ranked per-op rows from the newest xplane under a captured trace
    directory (duplicate expressions within the table merge first)."""
    from xprof.convert import raw_to_tool_data as rtd

    path = find_xplane(trace_dir)
    data, _ = rtd.xspace_to_tool_data([path], "hlo_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    return rank_ops(merge_rows(rows_from_table(json.loads(data))), k)


def format_rows(rows):
    """Human-readable ranked-op table (one string), for logs and reports."""
    lines = [f"{'self us':>10}  {'%':>5}  {'x':>5}  {'category':<18} expression"]
    total = sum(r["total_self_us"] or 0.0 for r in rows) or 1.0
    for r in rows:
        us = r["total_self_us"] or 0.0
        occ = r["occurrences"] or 0
        lines.append(
            f"{us:>10.1f}  {100.0 * us / total:>4.1f}  {occ:>5.0f}  "
            f"{(r['category'] or '?'):<18} {(r['expression'] or '')[:90]}")
    return "\n".join(lines)


def summarize(trace_dir, k=10):
    """Human-readable top-k table for a captured trace directory."""
    return format_rows(top_ops(trace_dir, k))
