"""Sharded (multi-device / multi-host) checkpointing via orbax.

Reference analog: ModelSerializer (util/ModelSerializer.java) covers the
single-process zip format — `utils/serialization.py` here. That format
gathers every array to one host, which cannot scale to sharded state
(tensor/pipeline/expert-parallel training holds each shard on its own
device, and on a pod no single host can even fit the model). This module is
the distributed tier's checkpoint path: orbax writes each shard from the
device that owns it and restores arrays WITH their shardings, so a resumed
job continues with the same mesh layout (and multi-host jobs write/read
collectively — orbax coordinates across processes).

Save/restore round-trips the pytree leaves' shapes, dtypes, and
NamedShardings; restore accepts either a template tree of concrete arrays
(e.g. a freshly init'd trainer's params) or ShapeDtypeStruct+sharding.
"""

from __future__ import annotations

import os

import jax


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save_sharded(path, tree):
    """Write a sharded checkpoint of ``tree`` (any pytree of jax.Arrays).

    Each device contributes its own shards; nothing is gathered to one
    host. ``path`` is a directory (created by orbax; must not exist)."""
    path = os.path.abspath(str(path))
    ckptr = _checkpointer()
    ckptr.save(path, tree)
    ckptr.wait_until_finished()
    return path


def restore_sharded(path, like):
    """Restore a checkpoint written by :func:`save_sharded`.

    ``like`` is a template pytree fixing structure, shapes, dtypes AND
    shardings — pass the freshly initialized state (concrete arrays work;
    so do ShapeDtypeStructs with ``.sharding`` set). The restored arrays
    land directly on the devices their shards belong to."""
    path = os.path.abspath(str(path))
    template = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding)
        if isinstance(a, jax.Array) else a, like)
    return _checkpointer().restore(path, template)


def _trainer_tree(trainer):
    """Everything a resume needs: params, optimizer state, MUTABLE layer
    state (BatchNorm running stats), the step RNG (so dropout keys continue
    from step N+1, not replay from step 1), and the iteration counter."""
    tree = {"params": trainer.params, "opt_state": trainer.opt_state,
            "iteration": jax.numpy.asarray(trainer.iteration)}
    state = getattr(trainer, "state", None)
    if state is not None:
        tree["state"] = state
    rng = getattr(trainer, "_rng", None)
    if rng is not None:
        tree["rng"] = rng
    return tree


def save_trainer(path, trainer):
    """Checkpoint a ParallelTrainer / PipelineParallelLM, preserving
    shardings."""
    return save_sharded(path, _trainer_tree(trainer))


def restore_trainer(path, trainer):
    """Restore into an initialized trainer (its current params/opt_state
    provide the sharding template). Returns the trainer."""
    if trainer.params is None:
        trainer.init()
    tree = restore_sharded(path, _trainer_tree(trainer))
    trainer.params = tree["params"]
    trainer.opt_state = tree["opt_state"]
    trainer.iteration = int(tree["iteration"])
    if "state" in tree:
        trainer.state = tree["state"]
    if "rng" in tree:
        trainer._rng = tree["rng"]
    return trainer
