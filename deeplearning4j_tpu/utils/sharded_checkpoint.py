"""Sharded (multi-device / multi-host) checkpointing via orbax.

Reference analog: ModelSerializer (util/ModelSerializer.java) covers the
single-process zip format — `utils/serialization.py` here. That format
gathers every array to one host, which cannot scale to sharded state
(tensor/pipeline/expert-parallel training holds each shard on its own
device, and on a pod no single host can even fit the model). This module is
the distributed tier's checkpoint path: orbax writes each shard from the
device that owns it and restores arrays WITH their shardings, so a resumed
job continues with the same mesh layout (and multi-host jobs write/read
collectively — orbax coordinates across processes).

Save/restore round-trips the pytree leaves' shapes, dtypes, and
NamedShardings; restore accepts either a template tree of concrete arrays
(e.g. a freshly init'd trainer's params) or ShapeDtypeStruct+sharding.
"""

from __future__ import annotations

import os

import jax


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save_sharded(path, tree):
    """Write a sharded checkpoint of ``tree`` (any pytree of jax.Arrays).

    Each device contributes its own shards; nothing is gathered to one
    host. ``path`` is a directory (created by orbax; must not exist)."""
    path = os.path.abspath(str(path))
    ckptr = _checkpointer()
    ckptr.save(path, tree)
    ckptr.wait_until_finished()
    return path


def restore_sharded(path, like):
    """Restore a checkpoint written by :func:`save_sharded`.

    ``like`` is a template pytree fixing structure, shapes, dtypes AND
    shardings — pass the freshly initialized state (concrete arrays work;
    so do ShapeDtypeStructs with ``.sharding`` set). The restored arrays
    land directly on the devices their shards belong to."""
    path = os.path.abspath(str(path))
    template = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding)
        if isinstance(a, jax.Array) else a, like)
    return _checkpointer().restore(path, template)


def _trainer_tree(trainer):
    """Everything a resume needs: params, optimizer state, MUTABLE layer
    state (BatchNorm running stats), the step RNG (so dropout keys continue
    from step N+1, not replay from step 1), and the iteration + epoch
    counters (epoch rode only the single-process zip before — a resumed
    multi-epoch fit restarted its epoch listeners from 0)."""
    tree = {"params": trainer.params, "opt_state": trainer.opt_state,
            "iteration": jax.numpy.asarray(trainer.iteration)}
    state = getattr(trainer, "state", None)
    if state is not None:
        tree["state"] = state
    rng = getattr(trainer, "_rng", None)
    if rng is not None:
        tree["rng"] = rng
    epoch = getattr(trainer, "epoch", None)
    if epoch is not None:
        tree["epoch"] = jax.numpy.asarray(int(epoch))
    return tree


#: the bundle sidecar inside the orbax checkpoint directory (orbax's
#: template-driven restore reads only its own item files, so the extra
#: entry rides along without touching the sharded-array layout)
_EXTRAS_NAME = "dl4j_bundle_extras.zip"


def save_trainer(path, trainer, *, buckets=None, manifest=None):
    """Checkpoint a ParallelTrainer / PipelineParallelLM, preserving
    shardings. ``buckets`` (BucketRegistry / sizes) and ``manifest``
    (utils/compile_cache.WarmManifest; defaults to the trainer net's
    attached one) fold into the same directory, making it the distributed
    tier's instant-restart unit — the single-process analog is
    ``utils.serialization.save_bundle``."""
    import json
    import zipfile

    path = save_sharded(path, _trainer_tree(trainer))
    net = getattr(trainer, "net", trainer)
    if manifest is None:
        manifest = getattr(net, "_warm_manifest", None)
    if buckets is not None or (manifest is not None and len(manifest)):
        from deeplearning4j_tpu.utils.serialization import bucket_sizes
        with zipfile.ZipFile(os.path.join(path, _EXTRAS_NAME), "w",
                             zipfile.ZIP_DEFLATED) as z:
            if buckets is not None:
                z.writestr("buckets.json", json.dumps(bucket_sizes(buckets)))
            if manifest is not None and len(manifest):
                z.writestr("warm_manifest.zip", manifest.to_bytes())
    return path


def restore_trainer(path, trainer):
    """Restore into an initialized trainer (its current params/opt_state
    provide the sharding template). Returns the trainer with params,
    opt_state, mutable state, RNG chain and iteration restored; bundle
    extras (bucket registry, warm manifest) land on ``trainer.buckets`` /
    the net via ``compile_cache.attach_manifest`` when present and
    matching this backend.

    The layout is the DESTINATION trainer's policy, never the file's:
    orbax restores each array into the template's sharding, so a
    checkpoint written by a replicated trainer resumes into a ZeRO-1,
    FSDP or FSDP_STREAM one (and back) with the arrays landing directly
    in the new layout — no gather-to-host hop (tests/test_zero.py pins
    the full cross-layout matrix bit-exact; the streamed tier stores the
    SAME per-leaf zero1 layout as fsdp, so the template is identical and
    only the step differs)."""
    if trainer.params is None:
        trainer.init()
    template = _trainer_tree(trainer)
    if "epoch" in template:
        # pre-ISSUE-14 checkpoints have no epoch entry: probe the
        # checkpoint's OWN key set (orbax metadata — no array reads)
        # rather than retrying a failed restore without the key, which
        # would silently drop the counter on any transient first-attempt
        # error
        try:
            meta = _checkpointer().metadata(os.path.abspath(str(path)))
            has_epoch = meta is None or "epoch" in meta
        except Exception:
            has_epoch = True   # unprobeable: keep the full template
        if not has_epoch:
            template.pop("epoch")
    tree = restore_sharded(path, template)
    trainer.params = tree["params"]
    trainer.opt_state = tree["opt_state"]
    trainer.iteration = int(tree["iteration"])
    if "state" in tree:
        trainer.state = tree["state"]
    if "rng" in tree:
        trainer._rng = tree["rng"]
    if "epoch" in tree:
        trainer.epoch = int(tree["epoch"])
    _restore_extras(path, trainer)
    # refresh the HBM ledger gauges: a resume is a new process whose
    # /health should show the restored layout's realized bytes
    try:
        from deeplearning4j_tpu.telemetry import devices as _devices
        _devices.note_train_tree_bytes(params=trainer.params,
                                       opt_state=trainer.opt_state,
                                       site="parallel_trainer")
    except Exception:
        pass
    return trainer


def _restore_extras(path, trainer):
    import json
    import zipfile

    extras = os.path.join(os.path.abspath(str(path)), _EXTRAS_NAME)
    if not os.path.exists(extras):
        return
    from deeplearning4j_tpu.utils import compile_cache as _cc
    with zipfile.ZipFile(extras) as z:
        names = set(z.namelist())
        if "buckets.json" in names:
            from deeplearning4j_tpu.datasets.iterator import BucketRegistry
            trainer.buckets = BucketRegistry(
                json.loads(z.read("buckets.json")))
        if "warm_manifest.zip" in names:
            manifest = _cc.WarmManifest.load_lenient(
                z.read("warm_manifest.zip"),
                context=f"checkpoint {path}: embedded warm manifest")
            if manifest is None:
                return
            net = getattr(trainer, "net", trainer)
            _cc.attach_if_matches(net, manifest, f"checkpoint {path}")
