"""Compile-artifact cache tier: persistent XLA cache + warm AOT manifests.

Every process start pays the full retrace+compile bill — ``serve`` re-runs
``jit(...).lower().compile()`` per registered bucket, ``train`` recompiles
the fused K-step scan, and a flight-recorder crash→resume restarts from a
stone-cold jit cache. At fleet scale (autoscaling, hot-swap deploys) that
is the dominant time-to-first-request cost. Compiled executables are
artifacts to persist and ship, not side effects to re-derive (the
whole-program AOT stance of the Julia-to-TPU paper, PAPERS.md arxiv
1810.09868; the deployment story of the TensorFlow whitepaper, arxiv
1603.04467). Two complementary tiers:

* **Persistent compilation cache** — :func:`enable_persistent_cache`
  points jax's on-disk compile cache (``jax_compilation_cache_dir``) at a
  directory, with the min-compile-time/min-entry-size thresholds opened up
  so even small executables persist. Every ``jit`` in the process then
  reuses on-disk compilations across restarts. Wired through the
  ``train``/``serve``/``eval`` CLI verbs (``--compile-cache DIR``, env
  ``DL4J_TPU_COMPILE_CACHE``).
* **Warm manifest** — :class:`WarmManifest` serializes *specific* AOT
  executables (``jax.experimental.serialize_executable``) keyed by
  (model fingerprint, backend+jax version, input shape signature) into an
  artifact stored beside the checkpoint. ``ServingEngine`` warmup and the
  fused K-step engine deserialize their executables from it instead of
  compiling — zero compiles on a warm restart — falling back to a live
  compile on any key mismatch (counted separately, never trusted
  silently).

Trust model: manifest entries carry pickled jax pytree defs (the
``serialize_executable`` wire format), so **loading a warm manifest
executes pickle** — treat manifests and bundles like the checkpoints
they ship with: trusted deployment artifacts, never untrusted uploads.
(The plain ``save_model`` zip remains pickle-free; only the
``warm_manifest.zip`` member carries pickled data.)

Observability: ``compile_cache_total{event=hit|miss|serialize|
deserialize_fail}`` counts every manifest interaction, and the
``time_to_first_step_ms`` / ``time_to_first_request_ms`` gauges record the
realized cold-start tax (surfaced on ``/health`` and in the ``coldstart``
bench). All jax interaction goes through :func:`aot_compile` — graftlint
R3 flags raw ``.lower().compile()`` chains elsewhere, so no compile site
can silently bypass the manifest tier.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import threading
import time
import warnings
import zipfile

import jax
import numpy as np

__all__ = ["ENV_CACHE_DIR", "WarmManifest", "aot_compile", "attach_manifest",
           "backend_fingerprint", "enable_persistent_cache",
           "full_signature", "model_fingerprint", "note_first_request",
           "note_first_step", "signature_of", "status"]

#: environment variable naming the persistent compile-cache directory
ENV_CACHE_DIR = "DL4J_TPU_COMPILE_CACHE"

MANIFEST_VERSION = 1

def _process_start_anchor():
    """The perf_counter value at PROCESS start — /proc-derived on Linux
    so the first-step/first-request gauges genuinely include interpreter
    + jax import (the documented claim, and the dominant fixed cost on
    CPU); falls back to module-import time elsewhere."""
    try:
        with open("/proc/self/stat", "rb") as f:
            # fields after the parenthesized comm; starttime is stat
            # field 22 -> index 19 here, in clock ticks since boot
            fields = f.read().rsplit(b")", 1)[1].split()
        start_ticks = int(fields[19])
        with open("/proc/uptime") as f:
            uptime_s = float(f.read().split()[0])
        age_s = uptime_s - start_ticks / os.sysconf("SC_CLK_TCK")
        if age_s > 0:
            return time.perf_counter() - age_s
    except Exception:
        pass
    return time.perf_counter()


#: perf_counter at process start (see _process_start_anchor) — the zero
#: point of the time_to_first_step/request cold-start gauges
PROCESS_T0 = _process_start_anchor()

_lock = threading.Lock()
_first_marks: dict = {}


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def _instruments():
    from deeplearning4j_tpu import telemetry as _tm
    reg = _tm.get_registry()
    return (reg,
            reg.counter(
                "compile_cache_total",
                "warm-manifest interactions by event: hit (executable "
                "deserialized, no compile), miss (no entry — live "
                "compile), serialize (executable written into the "
                "manifest), serialize_fail (backend cannot export), "
                "deserialize_fail (entry present but unloadable — live "
                "compile fallback), mismatch_drop (manifest built for "
                "another model/backend, refused at load)"),
            reg.gauge(
                "time_to_first_step_ms",
                "wall ms from process start to the first completed train "
                "dispatch — the realized training cold-start tax"),
            reg.gauge(
                "time_to_first_request_ms",
                "wall ms from process start to the first served inference "
                "request — the realized serving cold-start tax"))


def count_event(event, n=1):
    """Count one ``compile_cache_total`` interaction (hit/miss/serialize/
    deserialize_fail)."""
    _, c, _, _ = _instruments()
    c.inc(n, event=event)


def event_counts():
    """{event: count} snapshot of ``compile_cache_total`` (for /health and
    the coldstart bench legs)."""
    from deeplearning4j_tpu import telemetry as _tm
    c = _tm.get_registry().get("compile_cache_total")
    if c is None:
        return {}
    return {ls.get("event", ""): c.value(**ls) for ls in c.labelsets()}


def note_first_step():
    """Stamp ``time_to_first_step_ms`` once per process (first completed
    train dispatch). Subsequent calls are two dict reads and a branch."""
    return _note_first("step", "time_to_first_step_ms")


def note_first_request():
    """Stamp ``time_to_first_request_ms`` once per process (first served
    inference request)."""
    return _note_first("request", "time_to_first_request_ms")


def _note_first(mark, gauge_name):
    if mark in _first_marks:                # cheap unlocked fast path
        return None
    with _lock:
        if mark in _first_marks:
            return None
        ms = 1e3 * (time.perf_counter() - PROCESS_T0)
        _first_marks[mark] = ms
    reg, _, g_step, g_req = _instruments()
    (g_step if mark == "step" else g_req).set(ms)
    return ms


def first_marks():
    """{mark: ms} of the stamped first-step/first-request marks."""
    with _lock:
        return dict(_first_marks)


def reset_marks():
    """Forget the once-per-process gauges (test isolation — called from
    ``telemetry.reset()``)."""
    with _lock:
        _first_marks.clear()


def status():
    """The /health ``compile_cache`` payload: persistent-cache dir, event
    counts, and the realized cold-start gauges."""
    marks = first_marks()
    return {
        "persistent_cache_dir": jax.config.jax_compilation_cache_dir,
        "events": event_counts(),
        "time_to_first_step_ms": marks.get("step"),
        "time_to_first_request_ms": marks.get("request"),
    }


# ---------------------------------------------------------------------------
# persistent compilation cache (tier a)
# ---------------------------------------------------------------------------

def enable_persistent_cache(cache_dir=None, *, min_compile_time_s=0.0):
    """Point jax's persistent compilation cache at ``cache_dir``.

    ``cache_dir`` defaults to ``$DL4J_TPU_COMPILE_CACHE``; with neither
    set this is a no-op returning None (callers wire it unconditionally).
    ``min_compile_time_s=0`` persists even sub-second compiles — the CPU
    preflight/bench executables jax's 1s default would silently skip —
    and the min-entry-size threshold is opened to match. jax-0.4.37
    compatible: flags that don't exist on the running jax are skipped,
    and the experimental ``set_cache_dir`` entry point is used as the
    fallback wiring on releases where the config flag alone is inert.
    """
    if cache_dir is None:
        cache_dir = os.environ.get(ENV_CACHE_DIR)
    if not cache_dir:
        return None
    cache_dir = os.path.abspath(str(cache_dir))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for flag, val in (
            ("jax_persistent_cache_min_compile_time_secs",
             float(min_compile_time_s)),
            ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(flag, val)
        except Exception:
            pass  # older jax: threshold flag not present
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.set_cache_dir(cache_dir)
    except Exception:
        pass
    return cache_dir


# ---------------------------------------------------------------------------
# fingerprints + signatures
# ---------------------------------------------------------------------------

def backend_fingerprint():
    """Backend identity an executable is bound to: jax version + platform
    + device kind. A manifest from another backend must never load."""
    try:
        dev = jax.devices()[0]
        plat = dev.platform
        kind = getattr(dev, "device_kind", "?")
    except Exception:
        plat, kind = "?", "?"
    return f"jax-{jax.__version__}/{plat}/{kind}"


def model_fingerprint(net):
    """Architecture fingerprint: config JSON + param/state tree paths,
    shapes and dtypes. Deliberately value-free — XLA executables depend on
    shapes, not weights, so a retrained checkpoint of the same
    architecture reuses its manifest."""
    h = hashlib.sha256()
    conf = getattr(net, "conf", None)
    try:
        h.update(conf.to_json().encode())
    except Exception:
        h.update(repr(type(net)).encode())
    trees = (getattr(net, "params", None), getattr(net, "state", None))
    for path, leaf in jax.tree_util.tree_flatten_with_path(trees)[0]:
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(tuple(np.shape(leaf))).encode())
        h.update(str(getattr(leaf, "dtype", type(leaf).__name__)).encode())
    return h.hexdigest()


def signature_of(args):
    """Canonical input-signature string for a pytree of arrays / structs:
    tree structure + per-leaf (shape, dtype). The manifest key a warm
    process can recompute without compiling anything."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = [(tuple(int(d) for d in np.shape(l)),
            str(getattr(l, "dtype", None) or np.asarray(l).dtype))
           for l in leaves]
    return json.dumps([str(treedef), sig], separators=(",", ":"))


def full_signature(signature):
    """``signature`` with the active TuningDB's content fingerprint
    folded in (no-op without a bound/populated DB — old manifests keep
    hitting). Tuned kernel configs resolve at TRACE time, so an
    executable bakes them in: keying the manifest on the DB content
    means a re-tuned DB cleanly invalidates stale entries (miss → live
    compile with the NEW configs → serialize-back) instead of silently
    serving kernels tuned under the old ones. The one helper every
    manifest key goes through — ``aot_compile`` applies it to lookups
    and write-backs, the serving export walk to its save-time puts."""
    try:
        from deeplearning4j_tpu.tuning.db import active_fingerprint
        fp = active_fingerprint()
    except Exception:
        fp = None
    return str(signature) if not fp else f"{signature}|tuning:{fp}"


# ---------------------------------------------------------------------------
# warm manifest (tier b)
# ---------------------------------------------------------------------------

class WarmManifest:
    """Serialized AOT executables keyed by (kind, input signature), scoped
    to ONE (model fingerprint, backend fingerprint) pair.

    ``put`` serializes a compiled executable
    (``jax.experimental.serialize_executable``) into the manifest;
    ``load_executable`` deserializes one back — every interaction counts
    into ``compile_cache_total``. ``save``/``load`` round-trip the whole
    manifest as a zip (one entry per executable + a JSON header), and
    ``to_bytes``/``from_bytes`` embed it inside a checkpoint bundle
    (utils/serialization.save_bundle)."""

    def __init__(self, model_fp=None, backend_fp=None):
        self.model_fp = model_fp
        self.backend_fp = backend_fp or backend_fingerprint()
        self._entries = {}  # (kind, signature) -> pickled (payload, trees)
        self._mlock = threading.Lock()

    @classmethod
    def for_net(cls, net):
        """A fresh manifest scoped to ``net``'s architecture on this
        backend."""
        return cls(model_fingerprint(net))

    def matches(self, net):
        """True when this manifest's executables were built for ``net``'s
        architecture on the running backend — the load-time gate before
        any executable is trusted."""
        return (self.model_fp == model_fingerprint(net)
                and self.backend_fp == backend_fingerprint())

    def __len__(self):
        with self._mlock:
            return len(self._entries)

    def keys(self):
        with self._mlock:
            return sorted(self._entries)

    def has(self, kind, signature):
        """Uncounted membership probe (export paths — not a cache read)."""
        with self._mlock:
            return (str(kind), str(signature)) in self._entries

    # -- executables ---------------------------------------------------

    def put(self, kind, signature, compiled):
        """Serialize ``compiled`` under (kind, signature). Returns True on
        success; a non-serializable executable (backend quirk) is counted
        and skipped — the manifest never hard-fails a working compile.

        The blob is VERIFIED by deserializing it once before it is kept:
        on some jax releases an executable served from the persistent
        compilation cache serializes cleanly but cannot load back
        ("Symbols not found") — catching that here turns a warm-restart
        surprise into a save-time fallback."""
        from jax.experimental import serialize_executable as _se
        try:
            payload, in_tree, out_tree = _se.serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree),
                                protocol=pickle.HIGHEST_PROTOCOL)
            _se.deserialize_and_load(*pickle.loads(blob))
        except Exception:
            count_event("serialize_fail")
            return False
        with self._mlock:
            self._entries[(str(kind), str(signature))] = blob
        count_event("serialize")
        return True

    def load_executable(self, kind, signature):
        """The deserialized executable for (kind, signature), or None
        (counted as miss / deserialize_fail — the caller live-compiles)."""
        with self._mlock:
            blob = self._entries.get((str(kind), str(signature)))
        if blob is None:
            count_event("miss")
            return None
        from jax.experimental import serialize_executable as _se
        try:
            payload, in_tree, out_tree = pickle.loads(blob)
            loaded = _se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:
            count_event("deserialize_fail")
            return None
        count_event("hit")
        return loaded

    # -- persistence ---------------------------------------------------

    def _write_zip(self, z):
        with self._mlock:
            entries = dict(self._entries)
        names = []
        for i, ((kind, sig), blob) in enumerate(sorted(entries.items())):
            fname = f"exec_{i:04d}.bin"
            names.append({"kind": kind, "signature": sig, "file": fname})
            z.writestr(fname, blob)
        z.writestr("manifest.json", json.dumps({
            "manifest_version": MANIFEST_VERSION,
            "model_fp": self.model_fp,
            "backend_fp": self.backend_fp,
            "jax_version": jax.__version__,
            "entries": names}, indent=1))

    @classmethod
    def _read_zip(cls, z):
        meta = json.loads(z.read("manifest.json"))
        if meta.get("manifest_version", 0) > MANIFEST_VERSION:
            raise ValueError(
                f"warm manifest version {meta['manifest_version']} is "
                f"newer than supported {MANIFEST_VERSION}")
        m = cls(meta.get("model_fp"), meta.get("backend_fp"))
        for e in meta.get("entries", ()):
            m._entries[(e["kind"], e["signature"])] = z.read(e["file"])
        return m

    def save(self, path):
        """Write the manifest zip (atomic: tmp + rename, so a crashed
        writer never leaves a truncated manifest a warm restart would
        choke on)."""
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as z:
                self._write_zip(z)
            os.replace(tmp, path)
        except BaseException:
            # a failed write (disk full, serialization error) must not
            # leave orphan temp blobs accumulating beside the checkpoint
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path

    @classmethod
    def load(cls, path):
        with zipfile.ZipFile(path) as z:
            return cls._read_zip(z)

    @classmethod
    def load_lenient(cls, source, context="warm manifest"):
        """``load`` (path) / ``from_bytes`` (bytes) that degrades instead
        of raising: a truncated or non-zip artifact warns, counts a
        ``deserialize_fail``, and returns None — the cache tier must
        never turn a working restart into a crash. The one shared
        corrupt-manifest path for ServingEngine, load_bundle and the
        sharded-checkpoint extras."""
        try:
            if isinstance(source, bytes):
                return cls.from_bytes(source)
            return cls.load(source)
        except FileNotFoundError:
            # not-yet-created is the normal FIRST cold start of the
            # documented save-after-warmup loop — no warning, no
            # deserialize_fail (that counter means a POISONED artifact)
            return None
        except Exception:
            warnings.warn(
                f"{context} is unreadable (corrupt or not a manifest "
                "zip) — ignoring it; the next warmup/fit pays live "
                "compiles", stacklevel=3)
            count_event("deserialize_fail")
            return None

    def to_bytes(self):
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            self._write_zip(z)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data):
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            return cls._read_zip(z)


# ---------------------------------------------------------------------------
# the one blessed compile site
# ---------------------------------------------------------------------------

def aot_compile(jitted, *args, manifest=None, kind="jit", signature=None,
                serialize_back=True):
    """Manifest-first AOT compile: the ONE ``.lower().compile()`` site.

    Returns ``(executable, source)`` with source ``"manifest"`` (warm —
    deserialized, zero compiles) or ``"compile"`` (live — lowered and
    compiled now, and serialized back into the manifest so the NEXT
    restart is warm). ``serialize_back=False`` skips that write-back —
    for compiles on a latency-sensitive path (a serving lazy compile
    under the forward lock), where the export walk at save time picks
    the executable up instead. graftlint R3 flags raw
    ``.lower().compile()`` chains outside this module, so serving/fused
    compiles cannot silently bypass the cache tier."""
    sig = full_signature(signature if signature is not None
                         else signature_of(args))
    if manifest is not None:
        ex = manifest.load_executable(kind, sig)
        if ex is not None:
            _note_step_peak(kind, ex)
            return ex, "manifest"
    with warnings.catch_warnings():
        # donated buffers rarely match an output shape; the warning is
        # per-compile noise, the donation is still wanted (see nn/fused)
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        ex = jitted.lower(*args).compile()
    if manifest is not None and serialize_back:
        manifest.put(kind, sig, ex)
    _note_step_peak(kind, ex)
    return ex, "compile"


def _note_step_peak(kind, ex):
    """Every executable through the blessed compile site exports its XLA
    memory ledger into the ``step_peak_bytes`` gauges (site ``aot:<kind>``)
    — step-peak observability rides the compile path for free. Best
    effort: deserialized executables without memory_analysis record
    nothing, and telemetry failures never fail a compile."""
    try:
        from deeplearning4j_tpu.telemetry import devices as _devices
        base = str(kind).split(":", 1)[0]
        _devices.note_step_peak_bytes(f"aot:{base}", ex, layout=kind)
    except Exception:
        pass


def attach_if_matches(net, manifest, context):
    """The ONE restore-side refusal policy: attach ``manifest`` when it
    was built for ``net`` on this backend; otherwise warn with
    ``context``, count a ``mismatch_drop``, and return None (the
    checkpoint itself still restores — the next fit pays a live
    compile). Shared by load_bundle and the sharded-checkpoint extras."""
    if manifest is None:
        return None
    if manifest.matches(net):
        attach_manifest(net, manifest)
        return manifest
    warnings.warn(
        f"{context}: warm manifest was built for "
        f"model={manifest.model_fp!r} on backend={manifest.backend_fp!r} "
        "— not this net/backend; dropping it (state restored; the next "
        "fit pays a live compile)", stacklevel=3)
    count_event("mismatch_drop")
    return None


def attach_manifest(net, manifest):
    """Bind ``manifest`` to ``net`` so the fused fit engine
    (nn/fused.make_train_steps) serves its K-step scan executable from it.
    A manifest built for a different architecture/backend is refused —
    an executable that half-matches would fail at call time with an
    opaque XLA error instead of a clean fallback."""
    if manifest is not None and not manifest.matches(net):
        raise ValueError(
            "warm manifest does not match this net/backend "
            f"(manifest model={manifest.model_fp!r} "
            f"backend={manifest.backend_fp!r}, "
            f"net model={model_fingerprint(net)!r} "
            f"backend={backend_fingerprint()!r})")
    net._warm_manifest = manifest
    return net
