"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up re-design of the capabilities of Eclipse Deeplearning4j
(reference: /root/reference, surveyed in SURVEY.md) on jax/XLA/Pallas:

- ``nn``       — layer catalog, config DSL, sequential + DAG networks
                 (reference: deeplearning4j-nn)
- ``ops``      — Pallas kernels + custom lowerings for the hot paths
                 (reference role: libnd4j / deeplearning4j-cuda helpers)
- ``parallel`` — mesh-based data/model parallelism over ICI/DCN
                 (reference role: ParallelWrapper + Spark TrainingMasters)
- ``datasets`` — dataset fetchers/iterators with async prefetch
                 (reference: deeplearning4j-core datasets + AsyncDataSetIterator)
- ``eval``     — evaluation suite (reference: org.deeplearning4j.eval)
- ``models``   — model zoo (reference: deeplearning4j-zoo)
- ``utils``    — dtype policy, serde registry, checkpointing
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.utils import dtypes  # noqa: F401
