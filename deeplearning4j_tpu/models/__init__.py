"""Model zoo (reference: deeplearning4j-zoo, SURVEY.md §2.6)."""

from deeplearning4j_tpu.models.lenet import lenet  # noqa: F401
