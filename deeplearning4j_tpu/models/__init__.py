"""Model zoo (reference: deeplearning4j-zoo, SURVEY.md §2.6)."""

from deeplearning4j_tpu.models.lenet import lenet  # noqa: F401
from deeplearning4j_tpu.models.resnet import (  # noqa: F401
    resnet50, resnet50_mln)
from deeplearning4j_tpu.models.vgg import vgg16, vgg19  # noqa: F401
from deeplearning4j_tpu.models.misc import (  # noqa: F401
    alexnet, darknet19, simple_cnn, text_generation_lstm, tiny_yolo,
    transformer_lm,
)
from deeplearning4j_tpu.models.inception import (  # noqa: F401
    facenet_nn4_small2, googlenet, inception_resnet_v1,
)
from deeplearning4j_tpu.models.zoo import (  # noqa: F401
    PretrainedType, ZooModel, get_model, init_pretrained, model_names,
    register_model,
)
