"""Smaller zoo models.

Reference analogs in /root/reference/deeplearning4j-zoo/src/main/java/org/
deeplearning4j/zoo/model/: SimpleCNN.java, AlexNet.java, Darknet19.java,
TinyYOLO.java, TextGenerationLSTM.java.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn import updaters as U
from deeplearning4j_tpu.nn.conf import inputs as I
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfig


def simple_cnn(height=48, width=48, channels=3, n_classes=10, updater=None, seed=12345):
    """(reference: SimpleCNN.java)"""
    return NeuralNetConfig(seed=seed, updater=updater or U.AdaDelta()).list(
        L.ConvolutionLayer(n_out=16, kernel=(3, 3), padding="same", activation="relu"),
        L.BatchNormalization(),
        L.ConvolutionLayer(n_out=16, kernel=(3, 3), padding="same", activation="relu"),
        L.BatchNormalization(),
        L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)),
        L.DropoutLayer(rate=0.25),
        L.ConvolutionLayer(n_out=32, kernel=(3, 3), padding="same", activation="relu"),
        L.BatchNormalization(),
        L.ConvolutionLayer(n_out=32, kernel=(3, 3), padding="same", activation="relu"),
        L.BatchNormalization(),
        L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)),
        L.DropoutLayer(rate=0.25),
        L.DenseLayer(n_out=256, activation="relu"),
        L.DropoutLayer(rate=0.5),
        L.OutputLayer(n_out=n_classes, loss="mcxent"),
        input_type=I.ConvolutionalType(height, width, channels),
    )


def alexnet(height=224, width=224, channels=3, n_classes=1000, updater=None, seed=12345):
    """(reference: AlexNet.java — conv11/5/3 stack + LRN)"""
    return NeuralNetConfig(seed=seed, updater=updater or U.Nesterovs(learning_rate=0.01)).list(
        L.ConvolutionLayer(n_out=96, kernel=(11, 11), stride=(4, 4), activation="relu"),
        L.LocalResponseNormalization(),
        L.SubsamplingLayer(kernel=(3, 3), stride=(2, 2)),
        L.ConvolutionLayer(n_out=256, kernel=(5, 5), padding="same", activation="relu"),
        L.LocalResponseNormalization(),
        L.SubsamplingLayer(kernel=(3, 3), stride=(2, 2)),
        L.ConvolutionLayer(n_out=384, kernel=(3, 3), padding="same", activation="relu"),
        L.ConvolutionLayer(n_out=384, kernel=(3, 3), padding="same", activation="relu"),
        L.ConvolutionLayer(n_out=256, kernel=(3, 3), padding="same", activation="relu"),
        L.SubsamplingLayer(kernel=(3, 3), stride=(2, 2)),
        L.DenseLayer(n_out=4096, activation="relu", dropout=0.5),
        L.DenseLayer(n_out=4096, activation="relu", dropout=0.5),
        L.OutputLayer(n_out=n_classes, loss="mcxent"),
        input_type=I.ConvolutionalType(height, width, channels),
    )


def _darknet_conv(n_out, kernel):
    return [L.ConvolutionLayer(n_out=n_out, kernel=kernel, padding="same",
                               has_bias=False, weight_init="relu"),
            L.BatchNormalization(activation="leakyrelu")]


def darknet19(height=224, width=224, channels=3, n_classes=1000, updater=None, seed=12345):
    """(reference: Darknet19.java — conv/BN/leaky-relu backbone)"""
    layers = []
    layers += _darknet_conv(32, (3, 3))
    layers += [L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2))]
    layers += _darknet_conv(64, (3, 3))
    layers += [L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2))]
    layers += _darknet_conv(128, (3, 3)) + _darknet_conv(64, (1, 1)) + _darknet_conv(128, (3, 3))
    layers += [L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2))]
    layers += _darknet_conv(256, (3, 3)) + _darknet_conv(128, (1, 1)) + _darknet_conv(256, (3, 3))
    layers += [L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2))]
    layers += (_darknet_conv(512, (3, 3)) + _darknet_conv(256, (1, 1)) +
               _darknet_conv(512, (3, 3)) + _darknet_conv(256, (1, 1)) +
               _darknet_conv(512, (3, 3)))
    layers += [L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2))]
    layers += (_darknet_conv(1024, (3, 3)) + _darknet_conv(512, (1, 1)) +
               _darknet_conv(1024, (3, 3)) + _darknet_conv(512, (1, 1)) +
               _darknet_conv(1024, (3, 3)))
    layers += [L.ConvolutionLayer(n_out=n_classes, kernel=(1, 1), padding="same"),
               L.GlobalPoolingLayer(mode="avg"),
               L.LossLayer(loss="mcxent", activation="softmax")]
    return NeuralNetConfig(seed=seed, updater=updater or U.Adam(learning_rate=1e-3)).list(
        *layers, input_type=I.ConvolutionalType(height, width, channels))


def tiny_yolo(height=416, width=416, channels=3, n_classes=20,
              anchors=((1.08, 1.19), (3.42, 4.41), (6.63, 11.38), (9.42, 5.11),
                       (16.62, 10.52)), updater=None, seed=12345):
    """(reference: TinyYOLO.java — darknet-tiny backbone + Yolo2OutputLayer)"""
    layers = []
    for n_out in (16, 32, 64, 128, 256):
        layers += _darknet_conv(n_out, (3, 3))
        layers += [L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2))]
    layers += _darknet_conv(512, (3, 3))
    layers += _darknet_conv(1024, (3, 3))
    layers += _darknet_conv(1024, (3, 3))
    layers += [L.ConvolutionLayer(n_out=len(anchors) * (5 + n_classes), kernel=(1, 1),
                                  padding="same"),
               L.Yolo2OutputLayer(anchors=tuple(anchors))]
    return NeuralNetConfig(seed=seed, updater=updater or U.Adam(learning_rate=1e-3)).list(
        *layers, input_type=I.ConvolutionalType(height, width, channels))


def text_generation_lstm(vocab_size, hidden=256, seq_len=64, updater=None, seed=12345):
    """Char-RNN (reference: TextGenerationLSTM.java — stacked GravesLSTM +
    RnnOutputLayer; BASELINE.md config #4)."""
    return NeuralNetConfig(seed=seed, updater=updater or U.RmsProp(learning_rate=1e-3)).list(
        L.GravesLSTM(n_out=hidden),
        L.GravesLSTM(n_out=hidden),
        L.RnnOutputLayer(n_out=vocab_size, loss="mcxent"),
        input_type=I.RecurrentType(vocab_size, seq_len),
        backprop_type="tbptt", tbptt_fwd_length=seq_len, tbptt_back_length=seq_len,
    )


def transformer_lm(vocab_size, n_layers=4, d_model=256, n_heads=4,
                   seq_len=128, mlp_ratio=4, updater=None, seed=12345):
    """Decoder-only transformer language model (net-new: the reference has
    no attention — SURVEY.md §5 long-context row; this is the long-context
    tier's flagship config and the fused-attention bench target). Input:
    [B, T] (or [B, T, 1]) integer token ids; output: per-timestep vocab
    softmax trained with cross-entropy."""
    return NeuralNetConfig(seed=seed,
                           updater=updater or U.Adam(learning_rate=3e-4)).list(
        L.EmbeddingSequenceLayer(n_in=vocab_size, n_out=d_model,
                                 add_positional=True),
        *[L.TransformerBlock(n_out=d_model, n_heads=n_heads,
                             mlp_ratio=mlp_ratio, causal=True)
          for _ in range(n_layers)],
        L.RnnOutputLayer(n_out=vocab_size, loss="mcxent"),
        input_type=I.RecurrentType(1, seq_len),
    )
