"""Inception-family zoo models: GoogLeNet, InceptionResNetV1, FaceNetNN4Small2.

Reference analogs (/root/reference/deeplearning4j-zoo/src/main/java/org/
deeplearning4j/zoo/model/):

* ``GoogLeNet.java:123-176`` — inception modules (1x1 / 1x1->3x3 / 1x1->5x5 /
  maxpool->1x1 branches depth-concatenated) with the exact 3a..5b filter
  tables at :154-169, LRN stem, 7x7 avg-pool head.
* ``InceptionResNetV1.java`` + ``helper/InceptionResNetHelper.java`` — stem
  (:112-165), 5x inception-resnet-A, reduction-A (:170-200), 10x B,
  reduction-B, 5x C, then the FaceNet-style head: 128-d bottleneck, L2
  normalize to the embedding hypersphere, center-loss softmax
  (FaceNetNN4Small2.java:82-91 shows the same head).
* ``FaceNetNN4Small2.java:83-300`` — NN4-small2 inception variant, same head.

TPU-first: NHWC bf16-friendly convs; depth-concat via MergeVertex (XLA fuses
the concatenated producers); residual scaling via ScaleVertex +
ElementWiseVertex add. Exact per-branch filter tables are kept where the
reference pins them (GoogLeNet); the residual blocks keep the reference's
block counts and scale factors.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn import updaters as U
from deeplearning4j_tpu.nn.conf import inputs as I
from deeplearning4j_tpu.nn.graph import (ElementWiseVertex, GraphBuilder,
                                         GraphBuilderModule, L2NormalizeVertex,
                                         MergeVertex, ScaleVertex)


def _conv(g, name, inp, n_out, kernel, stride=(1, 1), padding="same",
          activation="relu", bn=False):
    g.add_layer(name, L.ConvolutionLayer(
        n_out=n_out, kernel=kernel, stride=stride, padding=padding,
        activation="identity" if bn else activation, weight_init="relu"), inp)
    if bn:
        g.add_layer(name + "-bn", L.BatchNormalization(activation=activation),
                    name)
        return name + "-bn"
    return name


# ---------------------------------------------------------------------------
# GoogLeNet
# ---------------------------------------------------------------------------

# reference GoogLeNet.java:154-169: {1x1}, {3x3 reduce, 3x3},
# {5x5 reduce, 5x5}, {pool-proj}
_GOOGLENET_TABLE = {
    "3a": ((64,), (96, 128), (16, 32), (32,)),
    "3b": ((128,), (128, 192), (32, 96), (64,)),
    "4a": ((192,), (96, 208), (16, 48), (64,)),
    "4b": ((160,), (112, 224), (24, 64), (64,)),
    "4c": ((128,), (128, 256), (24, 64), (64,)),
    "4d": ((112,), (144, 288), (32, 64), (64,)),
    "4e": ((256,), (160, 320), (32, 128), (128,)),
    "5a": ((256,), (160, 320), (32, 128), (128,)),
    "5b": ((384,), (192, 384), (48, 128), (128,)),
}


def _inception(g, name, inp, cfg):
    """One GoogLeNet inception module (reference GoogLeNet.java:123-138)."""
    (f1,), (f3r, f3), (f5r, f5), (fp,) = cfg
    b1 = _conv(g, f"{name}-1x1", inp, f1, (1, 1))
    r3 = _conv(g, f"{name}-3x3r", inp, f3r, (1, 1))
    b3 = _conv(g, f"{name}-3x3", r3, f3, (3, 3))
    r5 = _conv(g, f"{name}-5x5r", inp, f5r, (1, 1))
    b5 = _conv(g, f"{name}-5x5", r5, f5, (5, 5))
    g.add_layer(f"{name}-pool", L.SubsamplingLayer(
        kernel=(3, 3), stride=(1, 1), padding="same", mode="max"), inp)
    bp = _conv(g, f"{name}-poolproj", f"{name}-pool", fp, (1, 1))
    g.add_vertex(f"{name}-depthconcat", MergeVertex(), b1, b3, b5, bp)
    return f"{name}-depthconcat"


def googlenet(height=224, width=224, channels=3, n_classes=1000, updater=None,
              seed=12345):
    """GoogLeNet / Inception v1 (reference GoogLeNet.java)."""
    g = GraphBuilder(updater=updater or U.Adam(learning_rate=1e-3), seed=seed)
    g.add_inputs("input")
    g.set_input_types(I.ConvolutionalType(height, width, channels))

    x = _conv(g, "cnn1", "input", 64, (7, 7), stride=(2, 2))
    g.add_layer("max1", L.SubsamplingLayer(kernel=(3, 3), stride=(2, 2),
                                           padding="same", mode="max"), x)
    g.add_layer("lrn1", L.LocalResponseNormalization(n=5, alpha=1e-4,
                                                     beta=0.75), "max1")
    x = _conv(g, "cnn2", "lrn1", 64, (1, 1))
    x = _conv(g, "cnn3", x, 192, (3, 3))
    g.add_layer("lrn2", L.LocalResponseNormalization(n=5, alpha=1e-4,
                                                     beta=0.75), x)
    g.add_layer("max2", L.SubsamplingLayer(kernel=(3, 3), stride=(2, 2),
                                           padding="same", mode="max"), "lrn2")
    x = "max2"
    for name in ("3a", "3b"):
        x = _inception(g, name, x, _GOOGLENET_TABLE[name])
    g.add_layer("max3", L.SubsamplingLayer(kernel=(3, 3), stride=(2, 2),
                                           padding="same", mode="max"), x)
    x = "max3"
    for name in ("4a", "4b", "4c", "4d", "4e"):
        x = _inception(g, name, x, _GOOGLENET_TABLE[name])
    g.add_layer("max4", L.SubsamplingLayer(kernel=(3, 3), stride=(2, 2),
                                           padding="same", mode="max"), x)
    x = "max4"
    for name in ("5a", "5b"):
        x = _inception(g, name, x, _GOOGLENET_TABLE[name])
    g.add_layer("avgpool", L.GlobalPoolingLayer(mode="avg"), x)
    g.add_layer("fc1", L.DenseLayer(n_out=1024, activation="relu",
                                    dropout=0.4), "avgpool")
    g.add_layer("output", L.OutputLayer(n_out=n_classes, activation="softmax",
                                        loss="mcxent"), "fc1")
    g.set_outputs("output")
    return g.build()


# ---------------------------------------------------------------------------
# Inception-ResNet v1 (FaceNet backbone)
# ---------------------------------------------------------------------------

def _res_block(g, name, inp, branches, n_channels, scale):
    """Inception-resnet block: branches -> concat -> 1x1 linear projection
    back to n_channels -> scale -> add residual -> relu
    (reference InceptionResNetHelper.inceptionV1ResA/B/C)."""
    outs = []
    for bi, branch in enumerate(branches):
        cur = inp
        for li, (f, k) in enumerate(branch):
            cur = _conv(g, f"{name}-b{bi}-{li}", cur, f, k, bn=True)
        outs.append(cur)
    g.add_vertex(f"{name}-merge", MergeVertex(), *outs)
    proj = _conv(g, f"{name}-proj", f"{name}-merge", n_channels, (1, 1),
                 activation="identity")
    g.add_vertex(f"{name}-scale", ScaleVertex(factor=scale), proj)
    g.add_vertex(f"{name}-add", ElementWiseVertex(op="add"), inp,
                 f"{name}-scale")
    g.add_layer(f"{name}", L.ActivationLayer(activation="relu"),
                f"{name}-add")
    return name


def _irv1_stem(g, channels_label="input"):
    """InceptionResNetV1.java:112-165 stem."""
    x = _conv(g, "stem-cnn1", channels_label, 32, (3, 3), stride=(2, 2), bn=True)
    x = _conv(g, "stem-cnn2", x, 32, (3, 3), bn=True)
    x = _conv(g, "stem-cnn3", x, 64, (3, 3), bn=True)
    g.add_layer("stem-pool4", L.SubsamplingLayer(kernel=(3, 3), stride=(2, 2),
                                                 padding="same", mode="max"), x)
    x = _conv(g, "stem-cnn5", "stem-pool4", 80, (1, 1), bn=True)
    x = _conv(g, "stem-cnn6", x, 128, (3, 3), bn=True)
    x = _conv(g, "stem-cnn7", x, 192, (3, 3), stride=(2, 2), bn=True)
    return x


def _embedding_head(g, x, n_classes, embedding_size, lambda_=2e-4):
    """avgpool -> bottleneck -> L2 normalize -> center-loss softmax
    (reference FaceNetNN4Small2.java:82-91)."""
    g.add_layer("avgpool", L.GlobalPoolingLayer(mode="avg"), x)
    g.add_layer("bottleneck", L.DenseLayer(n_out=embedding_size,
                                           activation="identity"), "avgpool")
    g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
    g.add_layer("lossLayer", L.CenterLossOutputLayer(
        n_out=n_classes, lambda_=lambda_, alpha=0.9), "embeddings")
    g.set_outputs("lossLayer")


def inception_resnet_v1(height=160, width=160, channels=3, n_classes=1001,
                        embedding_size=128, updater=None, seed=12345,
                        blocks_a=5, blocks_b=10, blocks_c=5):
    """Inception-ResNet v1 with FaceNet embedding + center-loss head
    (reference InceptionResNetV1.java; block counts/scales at :167-230:
    5xA @0.17, 10xB @0.10, 5xC @0.20)."""
    g = GraphBuilder(updater=updater or U.RmsProp(learning_rate=0.1),
                     seed=seed)
    g.add_inputs("input")
    g.set_input_types(I.ConvolutionalType(height, width, channels))
    x = _irv1_stem(g)

    for i in range(blocks_a):  # 35x35 blocks
        x = _res_block(g, f"resnetA{i}", x,
                       [[(32, (1, 1))],
                        [(32, (1, 1)), (32, (3, 3))],
                        [(32, (1, 1)), (32, (3, 3)), (32, (3, 3))]],
                       192, 0.17)
    # reduction-A (InceptionResNetV1.java:170-200): stride-2 3x3 conv branch,
    # 1x1->3x3->3x3 stride-2 branch, maxpool branch
    ra1 = _conv(g, "reduceA-cnn1", x, 192, (3, 3), stride=(2, 2), bn=True)
    ra2 = _conv(g, "reduceA-cnn2", x, 128, (1, 1), bn=True)
    ra2 = _conv(g, "reduceA-cnn3", ra2, 128, (3, 3), bn=True)
    ra2 = _conv(g, "reduceA-cnn4", ra2, 192, (3, 3), stride=(2, 2), bn=True)
    g.add_layer("reduceA-pool", L.SubsamplingLayer(
        kernel=(3, 3), stride=(2, 2), padding="same", mode="max"), x)
    g.add_vertex("reduceA", MergeVertex(), ra1, ra2, "reduceA-pool")
    x = "reduceA"
    n_ch = 192 + 192 + 192  # concat of the three branches

    for i in range(blocks_b):  # 17x17 blocks
        x = _res_block(g, f"resnetB{i}", x,
                       [[(128, (1, 1))],
                        [(128, (1, 1)), (128, (1, 7)), (128, (7, 1))]],
                       n_ch, 0.10)
    # reduction-B
    rb1 = _conv(g, "reduceB-cnn1", x, 256, (1, 1), bn=True)
    rb1 = _conv(g, "reduceB-cnn2", rb1, 384, (3, 3), stride=(2, 2), bn=True)
    rb2 = _conv(g, "reduceB-cnn3", x, 256, (1, 1), bn=True)
    rb2 = _conv(g, "reduceB-cnn4", rb2, 256, (3, 3), stride=(2, 2), bn=True)
    rb3 = _conv(g, "reduceB-cnn5", x, 256, (1, 1), bn=True)
    rb3 = _conv(g, "reduceB-cnn6", rb3, 256, (3, 3), bn=True)
    rb3 = _conv(g, "reduceB-cnn7", rb3, 256, (3, 3), stride=(2, 2), bn=True)
    g.add_layer("reduceB-pool", L.SubsamplingLayer(
        kernel=(3, 3), stride=(2, 2), padding="same", mode="max"), x)
    g.add_vertex("reduceB", MergeVertex(), rb1, rb2, rb3, "reduceB-pool")
    x = "reduceB"
    n_ch = 384 + 256 + 256 + n_ch

    for i in range(blocks_c):  # 8x8 blocks
        x = _res_block(g, f"resnetC{i}", x,
                       [[(192, (1, 1))],
                        [(192, (1, 1)), (192, (1, 3)), (192, (3, 1))]],
                       n_ch, 0.20)

    _embedding_head(g, x, n_classes, embedding_size)
    return g.build()


# ---------------------------------------------------------------------------
# FaceNet NN4-small2
# ---------------------------------------------------------------------------

def _nn4_inception(g, name, inp, f3r, f3, f5r, f5, fp, f1=None,
                   stride=(1, 1), pool_mode="max"):
    """NN4 inception module (reference FaceNetNN4Small2.java:146-300 blocks:
    optional 1x1 branch, 1x1->3x3, 1x1->5x5, pool->optional 1x1 proj)."""
    outs = []
    if f1:
        outs.append(_conv(g, f"{name}-1x1", inp, f1, (1, 1), bn=True))
    if f3:
        r = _conv(g, f"{name}-3x3r", inp, f3r, (1, 1), bn=True)
        outs.append(_conv(g, f"{name}-3x3", r, f3, (3, 3), stride=stride,
                          bn=True))
    if f5:
        r = _conv(g, f"{name}-5x5r", inp, f5r, (1, 1), bn=True)
        outs.append(_conv(g, f"{name}-5x5", r, f5, (5, 5), stride=stride,
                          bn=True))
    g.add_layer(f"{name}-pool", L.SubsamplingLayer(
        kernel=(3, 3), stride=stride if fp is None else (1, 1),
        padding="same", mode=pool_mode), inp)
    if fp:
        outs.append(_conv(g, f"{name}-poolproj", f"{name}-pool", fp, (1, 1),
                          bn=True))
    else:
        outs.append(f"{name}-pool")
    g.add_vertex(f"{name}", MergeVertex(), *outs)
    return name


def facenet_nn4_small2(height=96, width=96, channels=3, n_classes=5749,
                       embedding_size=128, updater=None, seed=12345):
    """FaceNet NN4-small2 (reference FaceNetNN4Small2.java — inception
    variant sized for 96x96 faces, embedding + center-loss head)."""
    g = GraphBuilder(updater=updater or U.Adam(learning_rate=1e-3), seed=seed)
    g.add_inputs("input")
    g.set_input_types(I.ConvolutionalType(height, width, channels))

    x = _conv(g, "stem-cnn1", "input", 64, (7, 7), stride=(2, 2), bn=True)
    g.add_layer("stem-pool1", L.SubsamplingLayer(
        kernel=(3, 3), stride=(2, 2), padding="same", mode="max"), x)
    g.add_layer("stem-lrn1", L.LocalResponseNormalization(n=5, alpha=1e-4,
                                                          beta=0.75),
                "stem-pool1")
    x = _conv(g, "inception-2-cnn1", "stem-lrn1", 64, (1, 1), bn=True)
    x = _conv(g, "inception-2-cnn2", x, 192, (3, 3), bn=True)
    g.add_layer("inception-2-lrn1", L.LocalResponseNormalization(
        n=5, alpha=1e-4, beta=0.75), x)
    g.add_layer("inception-2-pool1", L.SubsamplingLayer(
        kernel=(3, 3), stride=(2, 2), padding="same", mode="max"),
        "inception-2-lrn1")

    # NN4-small2 table (FaceNetNN4Small2.java blocks 3a..5b)
    x = _nn4_inception(g, "inception-3a", "inception-2-pool1",
                       96, 128, 16, 32, 32, f1=64)
    x = _nn4_inception(g, "inception-3b", x, 96, 128, 32, 64, 64, f1=64)
    x = _nn4_inception(g, "inception-3c", x, 128, 256, 32, 64, None,
                       stride=(2, 2))
    x = _nn4_inception(g, "inception-4a", x, 96, 192, 32, 64, 128, f1=256)
    x = _nn4_inception(g, "inception-4e", x, 160, 256, 64, 128, None,
                       stride=(2, 2))
    x = _nn4_inception(g, "inception-5a", x, 96, 384, 0, None, 96, f1=256,
                       pool_mode="avg")
    x = _nn4_inception(g, "inception-5b", x, 96, 384, 0, None, 96, f1=256)

    _embedding_head(g, x, n_classes, embedding_size)
    return g.build()


class InceptionModule(GraphBuilderModule):
    """GraphBuilderModule packaging the GoogLeNet inception block (reference:
    the zoo's inception helper consumed through the GraphBuilderModule SPI,
    nn/conf/module/GraphBuilderModule.java). ``config`` is the filter-bank
    table ((f1,), (f3r, f3), (f5r, f5), (fp,)) as in GoogLeNet.java:154-169;
    ``input_size`` is accepted for SPI parity (the conv layers infer their
    input channels from shape inference)."""

    def module_name(self):
        return "inception"

    def update_builder(self, builder, layer_name, input_size, config,
                       input_layer):
        _inception(builder, f"{self.module_name()}-{layer_name}",
                   input_layer, config)
        return builder
