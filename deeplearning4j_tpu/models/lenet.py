"""LeNet (reference: /root/reference/deeplearning4j-zoo/src/main/java/org/
deeplearning4j/zoo/model/LeNet.java — conv5x5x20 -> pool -> conv5x5x50 ->
pool -> dense500 -> softmax10, the classic MNIST config and BASELINE.md
config #1)."""

from __future__ import annotations

from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn import updaters as U
from deeplearning4j_tpu.nn.conf import inputs as I
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfig


def lenet(height=28, width=28, channels=1, n_classes=10, updater=None, seed=12345,
          padding="valid"):
    """Reference parity: LeNet.java specifies no conv padding (DL4J default
    {0,0} = valid), giving the canonical 431,080-parameter Caffe variant at
    28x28. ``padding="same"`` is available for tiny smoke shapes (<14px)
    where valid 5x5 convs would collapse spatial dims to zero."""
    updater = updater or U.Adam(learning_rate=1e-3)
    return NeuralNetConfig(seed=seed, updater=updater).list(
        L.ConvolutionLayer(n_out=20, kernel=(5, 5), stride=(1, 1), padding=padding,
                           activation="relu", weight_init="xavier"),
        L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2), mode="max"),
        L.ConvolutionLayer(n_out=50, kernel=(5, 5), stride=(1, 1), padding=padding,
                           activation="relu", weight_init="xavier"),
        L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2), mode="max"),
        L.DenseLayer(n_out=500, activation="relu", weight_init="xavier"),
        L.OutputLayer(n_out=n_classes, loss="mcxent", weight_init="xavier"),
        input_type=I.ConvolutionalType(height, width, channels),
    )
