"""VGG16 / VGG19 (reference: /root/reference/deeplearning4j-zoo/.../model/
VGG16.java, VGG19.java — sequential conv stacks)."""

from __future__ import annotations

from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn import updaters as U
from deeplearning4j_tpu.nn.conf import inputs as I
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfig

_VGG16_BLOCKS = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
_VGG19_BLOCKS = [(64, 2), (128, 2), (256, 4), (512, 4), (512, 4)]


def _vgg(blocks, height, width, channels, n_classes, updater, seed):
    layers = []
    for n_out, reps in blocks:
        for _ in range(reps):
            layers.append(L.ConvolutionLayer(n_out=n_out, kernel=(3, 3),
                                             padding="same", activation="relu",
                                             weight_init="relu"))
        layers.append(L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2), mode="max"))
    layers += [
        L.DenseLayer(n_out=4096, activation="relu", weight_init="relu", dropout=0.5),
        L.DenseLayer(n_out=4096, activation="relu", weight_init="relu", dropout=0.5),
        L.OutputLayer(n_out=n_classes, loss="mcxent", weight_init="xavier"),
    ]
    return NeuralNetConfig(seed=seed, updater=updater or U.Nesterovs(learning_rate=0.01)).list(
        *layers, input_type=I.ConvolutionalType(height, width, channels))


def vgg16(height=224, width=224, channels=3, n_classes=1000, updater=None, seed=12345):
    return _vgg(_VGG16_BLOCKS, height, width, channels, n_classes, updater, seed)


def vgg19(height=224, width=224, channels=3, n_classes=1000, updater=None, seed=12345):
    return _vgg(_VGG19_BLOCKS, height, width, channels, n_classes, updater, seed)
