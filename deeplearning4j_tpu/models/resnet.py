"""ResNet50 as a ComputationGraph.

Reference analog: /root/reference/deeplearning4j-zoo/src/main/java/org/
deeplearning4j/zoo/model/ResNet50.java (graph of conv/BN/relu bottleneck
blocks with ElementWise-add shortcuts) — BASELINE.md config #2, the MFU-target
model.

TPU-first: NHWC, bf16-friendly convs (stride-2 downsampling inside blocks),
BN with running stats in state; identity vs projection shortcuts exactly as
ResNet v1. Built programmatically on GraphBuilder.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn import updaters as U
from deeplearning4j_tpu.nn.conf import inputs as I
from deeplearning4j_tpu.nn.graph import ElementWiseVertex, GraphBuilder


def _fused_vertex():
    # deferred so the unfused path never imports ops/conv_pallas
    from deeplearning4j_tpu.nn.fusion import FusedConvBNVertex
    return FusedConvBNVertex


def _conv_bn(g, name, inp, n_out, kernel, stride=(1, 1), padding="same",
             activation="relu", fused=False):
    if fused:
        FusedConvBNVertex = _fused_vertex()
        g.add_vertex(f"{name}_bn",
                     FusedConvBNVertex(n_out=n_out, kernel=kernel,
                                       stride=stride, padding=padding,
                                       activation=activation), inp)
        return f"{name}_bn"
    g.add_layer(f"{name}_conv",
                L.ConvolutionLayer(n_out=n_out, kernel=kernel, stride=stride,
                                   padding=padding, has_bias=False,
                                   weight_init="relu"), inp)
    g.add_layer(f"{name}_bn", L.BatchNormalization(activation=activation),
                f"{name}_conv")
    return f"{name}_bn"


def _bottleneck(g, name, inp, filters, stride=(1, 1), project=False,
                fused=False):
    """1x1 reduce -> 3x3 -> 1x1 expand (4x) with shortcut add."""
    f1, f2, f3 = filters, filters, filters * 4
    x = _conv_bn(g, f"{name}_a", inp, f1, (1, 1), stride=stride, fused=fused)
    x = _conv_bn(g, f"{name}_b", x, f2, (3, 3), fused=fused)
    if fused:
        # the bottleneck tail (conv_c -> BN -> add -> relu) collapses into
        # ONE fused vertex with the shortcut as the residual input
        FusedConvBNVertex = _fused_vertex()
        if project:
            shortcut = _conv_bn(g, f"{name}_proj", inp, f3, (1, 1),
                                stride=stride, activation="identity",
                                fused=True)
        else:
            shortcut = inp
        g.add_vertex(f"{name}_relu",
                     FusedConvBNVertex(n_out=f3, kernel=(1, 1),
                                       activation="relu", residual=True),
                     x, shortcut)
        return f"{name}_relu"
    x = _conv_bn(g, f"{name}_c", x, f3, (1, 1), activation="identity")
    if project:
        shortcut = _conv_bn(g, f"{name}_proj", inp, f3, (1, 1), stride=stride,
                            activation="identity")
    else:
        shortcut = inp
    g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, shortcut)
    g.add_layer(f"{name}_relu", L.ActivationLayer(activation="relu"), f"{name}_add")
    return f"{name}_relu"


def resnet50(height=224, width=224, channels=3, n_classes=1000, updater=None,
             seed=12345, checkpoint_scope=None, fused=False):
    """``checkpoint_scope="prefix"`` remats each bottleneck block during
    backward (nn/graph.py scope-level checkpointing): only block-boundary
    activations are stashed, the block interior recomputes. On v5e the
    model is HBM-bandwidth-bound at 27% MXU (PROFILE.md) — trading idle
    FLOPs for the activation-stash traffic is the MFU lever.

    ``fused=True`` builds conv->BN(->add->relu) chains as FusedConvBNVertex
    (nn/fusion.py): the Pallas conv kernel folds the BN statistics
    reduction into the conv epilogue (ops/conv_pallas.py), the stacked
    second lever on the same HBM bound (BENCH_FUSED_CONV A/B)."""
    g = GraphBuilder(updater=updater or U.Adam(learning_rate=1e-3), seed=seed,
                     checkpoint_scope=checkpoint_scope)
    g.add_inputs("input")
    g.set_input_types(I.ConvolutionalType(height, width, channels))

    x = _conv_bn(g, "stem", "input", 64, (7, 7), stride=(2, 2))
    g.add_layer("stem_pool", L.SubsamplingLayer(kernel=(3, 3), stride=(2, 2),
                                                padding="same", mode="max"), x)
    x = "stem_pool"

    stages = [(64, 3, (1, 1)), (128, 4, (2, 2)), (256, 6, (2, 2)), (512, 3, (2, 2))]
    for si, (filters, blocks, stride) in enumerate(stages):
        for bi in range(blocks):
            x = _bottleneck(g, f"s{si}b{bi}", x, filters,
                            stride=stride if bi == 0 else (1, 1),
                            project=bi == 0, fused=fused)

    g.add_layer("avgpool", L.GlobalPoolingLayer(mode="avg"), x)
    g.add_layer("fc", L.OutputLayer(n_out=n_classes, loss="mcxent",
                                    weight_init="xavier"), "avgpool")
    g.set_outputs("fc")
    return g.build()


def resnet50_mln(height=224, width=224, channels=3, n_classes=1000,
                 updater=None, seed=12345, stages=None, stem_filters=64):
    """ResNet50 as a flat MultiLayerNetwork stack of ResidualBottleneck
    composite layers (same geometry as :func:`resnet50`, block-internal
    shortcuts). This is the PIPELINABLE expression of the flagship:
    parallel/pipeline_general.PipelinedNetwork stages MultiLayerNetwork
    configs, and bottleneck blocks are stage-atomic. ``stages`` overrides
    the (filters, blocks, stride) table for reduced-size variants
    (tests / CPU-mesh loss pins)."""
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfig

    stages = stages if stages is not None else [
        (64, 3, (1, 1)), (128, 4, (2, 2)), (256, 6, (2, 2)), (512, 3, (2, 2))]
    layers = [
        L.ConvolutionLayer(n_out=stem_filters, kernel=(7, 7), stride=(2, 2),
                           padding="same", has_bias=False,
                           weight_init="relu"),
        L.BatchNormalization(activation="relu"),
        L.SubsamplingLayer(kernel=(3, 3), stride=(2, 2), padding="same",
                           mode="max"),
    ]
    for filters, blocks, stride in stages:
        for bi in range(blocks):
            layers.append(L.ResidualBottleneck(
                filters=filters, stride=stride if bi == 0 else (1, 1),
                project=bi == 0))
    layers += [
        L.GlobalPoolingLayer(mode="avg"),
        L.OutputLayer(n_out=n_classes, loss="mcxent", weight_init="xavier"),
    ]
    return NeuralNetConfig(seed=seed,
                           updater=updater or U.Adam(learning_rate=1e-3)).list(
        *layers, input_type=I.ConvolutionalType(height, width, channels))


def resnet50_flops_per_example(height=224, width=224, channels=3, n_classes=1000):
    """Approximate forward FLOPs (2*MACs) for MFU accounting.

    2 x the standard ~4.1 GMAC figure at 224x224; round-2 cross-check: XLA
    cost_analysis reports 22.6 GFLOP/example for the full train step, and
    3 x this fwd estimate = 24.6 — the two agree within 9%."""
    base = 2 * 4.1e9  # fwd only, FLOPs = 2*MACs
    scale = (height * width) / (224 * 224)
    return base * scale
