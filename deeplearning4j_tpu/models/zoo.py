"""Zoo model registry + pretrained-weight loading.

Reference analog: /root/reference/deeplearning4j-zoo/src/main/java/org/
deeplearning4j/zoo/ZooModel.java — ``initPretrained`` at :40-52 downloads a
model zip to a local cache, verifies the checksum (delete + fail hard on
mismatch, :77-83), and restores it via ModelSerializer; each model advertises
``pretrainedUrl``/``pretrainedChecksum`` (e.g. ResNet50.java:54).

TPU-native: the checkpoint is this framework's own zip format
(utils/serialization.py — config JSON + param pytree + updater state), cached
through the datasets.cacheable machinery (same offline-first gating). The
registry maps names to config builders so models can also be constructed
fresh (``build``) without weights.
"""

from __future__ import annotations

import os

from deeplearning4j_tpu.datasets import cacheable as _cache
from deeplearning4j_tpu.models import inception as _inc
from deeplearning4j_tpu.models import misc as _misc
from deeplearning4j_tpu.models import resnet as _resnet
from deeplearning4j_tpu.models import vgg as _vgg
from deeplearning4j_tpu.models.lenet import lenet as _lenet_fn


class PretrainedType:
    """Reference: org.deeplearning4j.zoo.PretrainedType enum."""
    IMAGENET = "imagenet"
    MNIST = "mnist"
    CIFAR10 = "cifar10"
    VGGFACE = "vggface"


class ZooModel:
    """One registry entry: a config builder + optional pretrained artifacts
    (url/md5 per PretrainedType)."""

    def __init__(self, name, builder, pretrained=None, graph=True):
        self.name = name
        self.builder = builder
        self.pretrained = pretrained or {}
        self.graph = graph

    def build(self, **kw):
        """Fresh (uninitialized-weights) network."""
        conf = self.builder(**kw)
        if self.graph:
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            net = ComputationGraph(conf)
        else:
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            net = MultiLayerNetwork(conf)
        net.init()
        return net

    def pretrained_available(self, pretrained_type=PretrainedType.IMAGENET):
        return pretrained_type in self.pretrained

    def init_pretrained(self, pretrained_type=PretrainedType.IMAGENET):
        """Download (offline-gated) + checksum + restore
        (ZooModel.java:40-52,77-83 semantics)."""
        if pretrained_type not in self.pretrained:
            raise ValueError(
                f"Model {self.name} has no pretrained weights for "
                f"{pretrained_type!r} (available: {sorted(self.pretrained)})")
        url, md5 = self.pretrained[pretrained_type]
        relpath = os.path.join("zoo", f"{self.name}_{pretrained_type}.zip")
        path = _cache.ensure_file(relpath, url=url, md5=md5)
        # DL4J graph configs carry no input shape (setInputTypes is not
        # serialized in the 0.9 format) — the registry's own builder knows
        # it, so CNN zips restore without the caller supplying dims
        return restore_checkpoint(path, input_type=self._default_input_type())

    def _default_input_type(self):
        try:
            conf = self.builder()
            if self.graph:
                return conf.input_types[0] if conf.input_types else None
            return conf.input_type
        except Exception:
            return None


def restore_checkpoint(path, input_type=None):
    """Restore ANY supported model file by sniffing its format (the
    reference's ModelGuesser role, util/ModelGuesser.java): the
    reference's ModelSerializer zip layout (``configuration.json`` +
    ``coefficients.bin`` — what every zoo ``pretrainedUrl`` serves,
    ZooModel.java:40-52) goes through modelimport.dl4j; this framework's
    own zip layout goes through utils.serialization; a Keras HDF5 file
    (signature ``\\x89HDF``) goes through modelimport.keras
    (Sequential -> MultiLayerNetwork, functional -> ComputationGraph)."""
    import json
    import zipfile
    with open(path, "rb") as f:
        magic = f.read(8)
    if magic.startswith(b"\x89HDF"):
        from deeplearning4j_tpu.modelimport.keras import (
            _layer_list, _model_config, _open,
            import_keras_model_and_weights,
            import_keras_sequential_model_and_weights)
        with _open(path) as archive:
            cls, _ = _layer_list(_model_config(archive))
        # dispatch on the declared model class (the reference's
        # KerasModelImport sniff) — exception-driven fallback would mask
        # the real diagnostic of a failed Sequential import
        if cls == "Sequential":
            return import_keras_sequential_model_and_weights(path)
        return import_keras_model_and_weights(path)
    with zipfile.ZipFile(path) as zf:
        names = set(zf.namelist())
        cfg = (json.loads(zf.read("configuration.json").decode("utf-8"))
               if "configuration.json" in names else None)
    if cfg is not None and "coefficients.bin" in names:
        from deeplearning4j_tpu.modelimport import dl4j
        if "vertices" in cfg:  # graph zips — what the zoo URLs serve
            return dl4j.restore_computation_graph(path,
                                                  input_type=input_type)
        return dl4j.restore_multilayer_network(path, input_type=input_type)
    from deeplearning4j_tpu.utils.serialization import load_model
    return load_model(path)


_REGISTRY = {}


def register_model(name, builder, pretrained=None, graph=True):
    _REGISTRY[name] = ZooModel(name, builder, pretrained=pretrained,
                               graph=graph)
    return _REGISTRY[name]


def model_names():
    return sorted(_REGISTRY)


def get_model(name) -> ZooModel:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"Unknown zoo model {name!r}; "
                       f"known: {model_names()}") from None


def init_pretrained(name, pretrained_type=PretrainedType.IMAGENET):
    return get_model(name).init_pretrained(pretrained_type)


# Registry mirroring the reference zoo/model/ listing. Pretrained artifact
# URLs are deployment-specific (the reference pins blob.deeplearning4j.org
# zips of ITS OWN format, useless here); entries ship without urls until a
# weight-conversion pipeline publishes this framework's zips — the loading
# machinery above is exercised by tests with locally-authored artifacts.
register_model("lenet", _lenet_fn, graph=False)
register_model("simplecnn", _misc.simple_cnn, graph=False)
register_model("alexnet", _misc.alexnet, graph=False)
register_model("darknet19", _misc.darknet19, graph=False)
register_model("tinyyolo", _misc.tiny_yolo, graph=False)
register_model("textgenlstm", _misc.text_generation_lstm, graph=False)
register_model("vgg16", _vgg.vgg16, graph=False)
register_model("vgg19", _vgg.vgg19, graph=False)
register_model("resnet50", _resnet.resnet50)
register_model("googlenet", _inc.googlenet)
register_model("inceptionresnetv1", _inc.inception_resnet_v1)
register_model("facenetnn4small2", _inc.facenet_nn4_small2)
