"""Corpus ingestion SPI: sentence iterators, label-aware document
iterators, and label sources.

Reference analog: the deeplearning4j-nlp ``text/sentenceiterator`` and
``text/documentiterator`` packages —
SentenceIterator.java (next/hasNext/reset/finish + preprocessor slot),
CollectionSentenceIterator, BasicLineIterator/LineSentenceIterator,
FileSentenceIterator, StreamLineIterator, AggregatingSentenceIterator,
MutipleEpochsSentenceIterator (sic), PrefetchingSentenceIterator,
SynchronizedSentenceIterator, labelaware/LabelAware*SentenceIterator,
documentiterator/{LabelledDocument, LabelsSource, BasicLabelAwareIterator,
SimpleLabelAwareIterator, FileLabelAwareIterator,
FilenamesLabelAwareIterator, AsyncLabelAwareIterator}. These are the
front door the reference's Word2Vec/ParagraphVectors builders consume
(SentenceVectors.java's iterate(...) slot); SequenceVectors here accepts
them via ``Word2Vec.fit_iterator`` / ``ParagraphVectors.fit_label_aware``.

Python-idiomatic where it costs nothing: iterators are also plain Python
iterables (``__iter__``), so they drop into any loop; the Java
next/has_next/reset surface is kept verbatim for migration parity.
"""

from __future__ import annotations

import os
import queue
import threading
from dataclasses import dataclass, field


class SentenceIterator:
    """Base contract (reference: SentenceIterator.java): sentences out,
    optional ``pre_processor`` applied in ``next_sentence``."""

    def __init__(self, pre_processor=None):
        self.pre_processor = pre_processor

    # -- Java-parity surface -------------------------------------------
    def next_sentence(self):
        raise NotImplementedError

    def has_next(self):
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def finish(self):
        pass

    def get_pre_processor(self):
        return self.pre_processor

    def set_pre_processor(self, pp):
        self.pre_processor = pp

    # -- pythonic surface ----------------------------------------------
    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_sentence()

    def _apply(self, s):
        return self.pre_processor(s) if self.pre_processor else s


class CollectionSentenceIterator(SentenceIterator):
    """(reference: CollectionSentenceIterator.java) — any sequence."""

    def __init__(self, sentences, pre_processor=None):
        super().__init__(pre_processor)
        self._sentences = list(sentences)
        self._i = 0

    def next_sentence(self):
        s = self._sentences[self._i]
        self._i += 1
        return self._apply(s)

    def has_next(self):
        return self._i < len(self._sentences)

    def reset(self):
        self._i = 0


class LineSentenceIterator(SentenceIterator):
    """One sentence per line of a file (reference: BasicLineIterator.java /
    LineSentenceIterator.java)."""

    def __init__(self, path, pre_processor=None, encoding="utf-8"):
        super().__init__(pre_processor)
        self._path = path
        self._encoding = encoding
        self._fh = None
        self._peek = None
        self.reset()

    def _advance(self):
        line = self._fh.readline() if self._fh else ""
        self._peek = line.rstrip("\n") if line else None
        if self._peek is None:
            self.finish()  # close promptly at EOF, not at GC

    def next_sentence(self):
        s = self._peek
        self._advance()
        return self._apply(s)

    def has_next(self):
        return self._peek is not None

    def reset(self):
        self.finish()
        self._fh = open(self._path, encoding=self._encoding)
        self._advance()

    def finish(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


BasicLineIterator = LineSentenceIterator


class StreamLineIterator(SentenceIterator):
    """Lines from an open file-like object (reference:
    StreamLineIterator.java). Not resettable unless the stream is
    seekable."""

    def __init__(self, stream, pre_processor=None):
        super().__init__(pre_processor)
        self._stream = stream
        self._start = stream.tell() if stream.seekable() else None
        self._advance()

    def _advance(self):
        line = self._stream.readline()
        self._peek = line.rstrip("\n") if line else None

    def next_sentence(self):
        s = self._peek
        self._advance()
        return self._apply(s)

    def has_next(self):
        return self._peek is not None

    def reset(self):
        if self._start is None:
            raise ValueError("stream is not seekable; cannot reset")
        self._stream.seek(self._start)
        self._advance()

    def __iter__(self):
        # non-seekable streams iterate from the CURRENT position (the
        # base __iter__ would reset() and raise)
        if self._start is not None:
            self.reset()
        while self.has_next():
            yield self.next_sentence()


class FileSentenceIterator(SentenceIterator):
    """Every line of every file under a directory (recursive, sorted —
    reference: FileSentenceIterator.java)."""

    def __init__(self, root, pre_processor=None, encoding="utf-8"):
        super().__init__(pre_processor)
        self._root = root
        self._encoding = encoding
        self.reset()

    def _files(self):
        out = []
        for dirpath, _, names in sorted(os.walk(self._root)):
            out.extend(os.path.join(dirpath, n) for n in sorted(names))
        return out

    def _gen(self):
        for f in self._files():
            with open(f, encoding=self._encoding) as fh:
                for line in fh:
                    line = line.rstrip("\n")
                    if line:
                        yield line

    def _advance(self):
        self._peek = next(self._it, None)

    def next_sentence(self):
        s = self._peek
        self._advance()
        return self._apply(s)

    def has_next(self):
        return self._peek is not None

    def reset(self):
        self._it = self._gen()
        self._advance()


class AggregatingSentenceIterator(SentenceIterator):
    """Chains several iterators (reference:
    AggregatingSentenceIterator.java)."""

    def __init__(self, iterators, pre_processor=None):
        super().__init__(pre_processor)
        self._iterators = list(iterators)
        self.reset()

    def next_sentence(self):
        while self._idx < len(self._iterators):
            it = self._iterators[self._idx]
            if it.has_next():
                return self._apply(it.next_sentence())
            self._idx += 1
        raise StopIteration

    def has_next(self):
        return any(it.has_next() for it in self._iterators[self._idx:])

    def reset(self):
        self._idx = 0
        for it in self._iterators:
            it.reset()


class MultipleEpochsSentenceIterator(SentenceIterator):
    """Replays the underlying iterator n_epochs times (reference:
    MutipleEpochsSentenceIterator.java — typo theirs)."""

    def __init__(self, iterator, n_epochs):
        super().__init__(None)
        self._under = iterator
        self._epochs = n_epochs
        self.reset()

    def next_sentence(self):
        if not self.has_next():
            raise StopIteration("all epochs consumed")
        if not self._under.has_next():
            self._epoch += 1
            self._under.reset()
        return self._under.next_sentence()

    def has_next(self):
        if self._empty:
            return False
        return self._under.has_next() or self._epoch + 1 < self._epochs

    def reset(self):
        self._epoch = 0
        self._under.reset()
        self._empty = not self._under.has_next()


class _PrefetchPump:
    """Shared background-prefetch machinery (bounded queue + reader
    thread + stop-flag shutdown) for PrefetchingSentenceIterator and
    AsyncLabelAwareIterator — the FancyBlockingQueue role in Python."""

    _DONE = object()

    def __init__(self, produce_next, has_more, buffer_size):
        self._produce = produce_next
        self._more = has_more
        self._size = buffer_size
        self._thread = None
        self._stop = None
        self._error = None
        self.peek = None

    def _run(self, q, stop):
        try:
            while not stop.is_set() and self._more():
                item = self._produce()
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # rethrown on the consumer side —
            self._error = e         # a dead producer must NOT read as a
        finally:                    # clean end-of-corpus
            if stop.is_set():
                # shutdown path: nothing reads past the stop flag
                try:
                    q.put_nowait(self._DONE)
                except queue.Full:
                    pass
            else:
                # normal completion: the consumer IS reading — a blocking
                # put guarantees _DONE arrives even through a full queue
                q.put(self._DONE)

    def advance(self):
        nxt = self._queue.get()
        if nxt is self._DONE and self._error is not None:
            err, self._error = self._error, None
            self.peek = None
            raise err
        self.peek = None if nxt is self._DONE else nxt

    def start(self):
        self.stop()
        self._queue = queue.Queue(maxsize=self._size)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(self._queue, self._stop), daemon=True)
        self._thread.start()
        self.advance()

    def stop(self):
        """O(buffer) shutdown: signal the pump, unblock it, join."""
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            try:  # unblock a pump stuck on a full queue
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5)
        self._thread = None
        self.peek = None


class PrefetchingSentenceIterator(SentenceIterator):
    """Background-thread prefetch buffer (reference:
    PrefetchingSentenceIterator.java — its dedicated reader thread +
    bounded queue)."""

    def __init__(self, iterator, buffer_size=128):
        super().__init__(None)
        self._under = iterator
        self._pump = _PrefetchPump(iterator.next_sentence,
                                   iterator.has_next, buffer_size)
        self.reset()

    def next_sentence(self):
        s = self._pump.peek
        self._pump.advance()
        return s

    def has_next(self):
        return self._pump.peek is not None

    def reset(self):
        self._pump.stop()
        self._under.reset()
        self._pump.start()

    def finish(self):
        self._pump.stop()


class SynchronizedSentenceIterator(SentenceIterator):
    """Lock-guarded wrapper for shared consumption (reference:
    SynchronizedSentenceIterator.java). The has_next()/next_sentence()
    PAIR is not atomic across consumers (same as the reference's
    per-method synchronization); multi-consumer code should use
    ``next_or_none()``, which checks and consumes under ONE lock."""

    def __init__(self, iterator):
        super().__init__(None)
        self._under = iterator
        self._lock = threading.Lock()

    def next_or_none(self):
        """Atomic check-and-consume: the multi-consumer primitive."""
        with self._lock:
            if not self._under.has_next():
                return None
            return self._under.next_sentence()

    def next_sentence(self):
        s = self.next_or_none()
        if s is None:
            raise StopIteration("iterator exhausted")
        return s

    def has_next(self):
        with self._lock:
            return self._under.has_next()

    def reset(self):
        with self._lock:
            self._under.reset()

    def __iter__(self):
        self.reset()
        while True:
            s = self.next_or_none()
            if s is None:
                return
            yield s


# ---------------------------------------------------------------------------
# Label-aware tier (reference: sentenceiterator/labelaware + documentiterator)
# ---------------------------------------------------------------------------


@dataclass
class LabelledDocument:
    """(reference: documentiterator/LabelledDocument.java)"""

    content: str
    labels: list = field(default_factory=list)

    @property
    def label(self):
        return self.labels[0] if self.labels else None


class LabelsSource:
    """Label generator/registry (reference: LabelsSource.java): either a
    template ("SENT_" -> SENT_0, SENT_1, ... or "DOC_%d_x" with the
    counter spliced at %d) or a predefined list."""

    def __init__(self, template_or_labels="SENT_"):
        if isinstance(template_or_labels, str):
            self._template = template_or_labels
            self._given = None
        else:
            self._template = None
            self._given = list(template_or_labels)
        self._counter = 0
        self._seen = []

    def next_label(self):
        if self._given is not None:
            if self._counter >= len(self._given):
                raise ValueError(
                    f"LabelsSource has {len(self._given)} predefined labels "
                    f"but a {self._counter + 1}th document arrived — the "
                    "label list must match the corpus size")
            label = self._given[self._counter]
        elif "%d" in self._template:
            label = self._template.replace("%d", str(self._counter))
        else:
            label = f"{self._template}{self._counter}"
        self._counter += 1
        if self._given is None:
            self._seen.append(label)
        return label

    def get_labels(self):
        return list(self._given if self._given is not None else self._seen)

    def index_of(self, label):
        return self.get_labels().index(label)

    def size(self):
        return len(self.get_labels())

    def reset(self):
        self._counter = 0
        if self._given is None:
            self._seen = []


class LabelAwareIterator:
    """Base document-iterator contract (reference: LabelAwareIterator.java).
    Yields LabelledDocument; also a plain Python iterable."""

    def next_document(self):
        raise NotImplementedError

    def has_next(self):
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def get_label_source(self):
        return getattr(self, "labels_source", None)

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_document()


class SimpleLabelAwareIterator(LabelAwareIterator):
    """Wraps an iterable of LabelledDocument (reference:
    SimpleLabelAwareIterator.java)."""

    def __init__(self, documents):
        self._docs = list(documents)
        self._i = 0

    def next_document(self):
        d = self._docs[self._i]
        self._i += 1
        return d

    def has_next(self):
        return self._i < len(self._docs)

    def reset(self):
        self._i = 0


class BasicLabelAwareIterator(LabelAwareIterator):
    """SentenceIterator + LabelsSource -> labelled documents (reference:
    BasicLabelAwareIterator.java — the ParagraphVectors default when fed
    plain sentences)."""

    def __init__(self, sentence_iterator, labels_source=None):
        self._under = sentence_iterator
        self.labels_source = labels_source or LabelsSource()

    def next_document(self):
        return LabelledDocument(self._under.next_sentence(),
                                [self.labels_source.next_label()])

    def has_next(self):
        return self._under.has_next()

    def reset(self):
        self._under.reset()
        self.labels_source.reset()


class FileLabelAwareIterator(LabelAwareIterator):
    """Directory-per-label corpus (reference: FileLabelAwareIterator.java):
    root/<label>/<file> — each file is one document labelled by its
    parent directory."""

    def __init__(self, root, encoding="utf-8"):
        self._root = root
        self._encoding = encoding
        self.labels_source = LabelsSource(sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))))
        self.reset()

    def _entries(self):
        for label in sorted(os.listdir(self._root)):
            full = os.path.join(self._root, label)
            if not os.path.isdir(full):
                continue
            for name in sorted(os.listdir(full)):
                yield label, os.path.join(full, name)

    def next_document(self):
        label, path = self._peek
        self._peek = next(self._it, None)
        with open(path, encoding=self._encoding) as fh:
            return LabelledDocument(fh.read().strip(), [label])

    def has_next(self):
        return self._peek is not None

    def reset(self):
        self._it = self._entries()
        self._peek = next(self._it, None)


class FilenamesLabelAwareIterator(LabelAwareIterator):
    """One document per file, labelled by its filename (reference:
    FilenamesLabelAwareIterator.java)."""

    def __init__(self, root, strip_extension=True, encoding="utf-8"):
        self._root = root
        self._strip = strip_extension
        self._encoding = encoding
        self.reset()

    def _files(self):
        return sorted(n for n in os.listdir(self._root)
                      if os.path.isfile(os.path.join(self._root, n)))

    def next_document(self):
        name = self._names[self._i]
        self._i += 1
        label = os.path.splitext(name)[0] if self._strip else name
        with open(os.path.join(self._root, name),
                  encoding=self._encoding) as fh:
            return LabelledDocument(fh.read().strip(), [label])

    def has_next(self):
        return self._i < len(self._names)

    def reset(self):
        self._names = self._files()
        self._i = 0


class AsyncLabelAwareIterator(LabelAwareIterator):
    """Background-thread prefetch over any LabelAwareIterator (reference:
    AsyncLabelAwareIterator.java). Shares the _PrefetchPump machinery."""

    def __init__(self, iterator, buffer_size=64):
        self._under = iterator
        self.labels_source = iterator.get_label_source()
        self._pump = _PrefetchPump(iterator.next_document,
                                   iterator.has_next, buffer_size)
        self.reset()

    def next_document(self):
        d = self._pump.peek
        self._pump.advance()
        return d

    def has_next(self):
        return self._pump.peek is not None

    def reset(self):
        self._pump.stop()
        self._under.reset()
        self._pump.start()
