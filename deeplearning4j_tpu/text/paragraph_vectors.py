"""ParagraphVectors (doc2vec).

Reference analog: models/paragraphvectors/ParagraphVectors.java + sequence
learning algorithms DBOW/DM (models/embeddings/learning/impl/sequence/) in
/root/reference/deeplearning4j-nlp-parent/deeplearning4j-nlp.

PV-DBOW: the document vector predicts each word of the document (skip-gram
with the doc vector as "center"). PV-DM: mean of doc vector + context window
predicts the target. Both reuse the batched SGNS kernels from word2vec.py;
document vectors live in a separate table, updated by the same scatter-add.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import functools

import jax

from deeplearning4j_tpu.text.word2vec import (SequenceVectors, _cbow_step, _sgns_step)


@functools.partial(jax.jit, donate_argnums=(0,))
def _infer_step(vec, syn1neg, targets, negatives, lr):
    """SGNS update of a single doc vector against FROZEN output table."""
    v = vec[0]                                     # [D]
    u_pos = jnp.take(syn1neg, targets, axis=0)     # [T,D]
    u_neg = jnp.take(syn1neg, negatives, axis=0)   # [T,K,D]
    s_pos = jax.nn.sigmoid(u_pos @ v)
    s_neg = jax.nn.sigmoid(jnp.einsum("tkd,d->tk", u_neg, v))
    grad = jnp.mean((s_pos - 1.0)[:, None] * u_pos, axis=0) + \
        jnp.mean(jnp.einsum("tk,tkd->td", s_neg, u_neg), axis=0)
    return vec - lr * grad[None, :]


class ParagraphVectors(SequenceVectors):
    def __init__(self, *, dm=False, tokenizer_factory=None, **kwargs):
        super().__init__(**kwargs)
        self.dm = dm
        from deeplearning4j_tpu.text.tokenization import \
            default_tokenizer_factory
        self.tokenizer_factory = tokenizer_factory or \
            default_tokenizer_factory()
        self.doc_vectors = None
        self.doc_labels = []

    def fit_label_aware(self, iterator):
        """Train from any corpus LabelAwareIterator (reference:
        ParagraphVectors.Builder.iterate(LabelAwareIterator) — see
        text/corpus.py: Basic/Simple/File/Filenames/AsyncLabelAwareIterator
        + LabelsSource). Documents tokenize through the constructor's
        ``tokenizer_factory`` (same contract as Word2Vec)."""
        tf = self.tokenizer_factory
        docs = [(doc.label, tf.create(doc.content).get_tokens())
                for doc in iterator]
        return self.fit_documents(docs)

    def fit_documents(self, documents):
        """documents: list of (label, token list)."""
        if self.mesh is not None:
            raise ValueError(
                "ParagraphVectors doc-vector training is single-device (the "
                "per-document loop does not batch across the mesh); construct "
                "without mesh=. Word co-occurrence tables can still be "
                "pre-trained distributed via SequenceVectors(mesh=...).fit().")
        self.doc_labels = [label for label, _ in documents]
        seqs = [list(tokens) for _, tokens in documents]
        if self.vocab is None:
            self.build_vocab(seqs)
        n_docs, d = len(documents), self.vector_size
        rs = np.random.RandomState(self.seed + 1)
        self.doc_vectors = jnp.asarray(
            (rs.rand(n_docs, d).astype(np.float32) - 0.5) / d)

        for epoch in range(self.epochs):
            lr = max(self.learning_rate * (1 - epoch / max(self.epochs, 1)),
                     self.min_learning_rate)
            for di, seq in enumerate(seqs):
                idx = self._encode(seq)
                if not idx:
                    continue
                targets = np.asarray(idx, np.int32)
                negs = self._draw_negatives((len(targets), self.negative))
                if self.dm:
                    # PV-DM: doc vector is an extra context member. We fold it
                    # in by averaging doc vector with word context -> use the
                    # cbow kernel over a combined table trick: temporarily
                    # treat doc vector as syn0 row via concatenation is
                    # wasteful; instead run a dedicated dm step below.
                    self._dm_step(di, idx, lr)
                else:
                    docs = np.full(len(targets), di, np.int32)
                    self.doc_vectors, self.syn1, _ = _sgns_step(
                        self.doc_vectors, self.syn1, jnp.asarray(docs),
                        jnp.asarray(targets), jnp.asarray(negs), lr)
        return self

    def _dm_step(self, di, idx, lr):
        n = len(idx)
        W = 2 * self.window
        rows, masks, targets = [], [], []
        for pos in range(n):
            b = self._rs.randint(1, self.window + 1)
            window = [idx[pos + off] for off in range(-b, b + 1)
                      if off != 0 and 0 <= pos + off < n]
            row = np.zeros(W, np.int32)
            m = np.zeros(W, np.float32)
            row[:len(window)] = window
            m[:len(window)] = 1.0
            rows.append(row)
            masks.append(m)
            targets.append(idx[pos])
        targets = np.asarray(targets, np.int32)
        negs = self._draw_negatives((len(targets), self.negative))
        # combined table: [doc_vectors; syn0] — doc index = row di
        combined = jnp.concatenate([self.doc_vectors, self.syn0], axis=0)
        n_docs = self.doc_vectors.shape[0]
        ctx = np.stack(rows) + n_docs          # shift word indices
        ctx = np.concatenate([np.full((len(targets), 1), di, np.int32), ctx], axis=1)
        cmask = np.concatenate([np.ones((len(targets), 1), np.float32),
                                np.stack(masks)], axis=1)
        combined, self.syn1, _ = _cbow_step(
            combined, self.syn1, jnp.asarray(ctx), jnp.asarray(cmask),
            jnp.asarray(targets), jnp.asarray(negs), lr)
        self.doc_vectors = combined[:n_docs]
        self.syn0 = combined[n_docs:]

    def get_doc_vector(self, label):
        i = self.doc_labels.index(label)
        return np.asarray(self.doc_vectors[i])

    def doc_similarity(self, l1, l2):
        a, b = self.get_doc_vector(l1), self.get_doc_vector(l2)
        return float(np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))

    def infer_vector(self, tokens, steps=20, lr=0.05):
        """Infer a vector for an unseen document (frozen word tables)."""
        idx = self._encode(tokens)
        rs = np.random.RandomState(0)
        vec = jnp.asarray((rs.rand(1, self.vector_size).astype(np.float32) - 0.5)
                          / self.vector_size)
        if not idx:
            return np.asarray(vec[0])
        targets = np.asarray(idx, np.int32)
        for _ in range(steps):
            negs = self._draw_negatives((len(targets), self.negative))
            vec = _infer_step(vec, self.syn1, jnp.asarray(targets),
                              jnp.asarray(negs), lr)
        return np.asarray(vec[0])
