"""GloVe embeddings.

Reference analog: models/glove/Glove.java (406 LoC) + co-occurrence counting
(models/glove/count/) in /root/reference/deeplearning4j-nlp-parent/
deeplearning4j-nlp. Weighted least squares on log co-occurrence with AdaGrad,
batched over the sparse co-occurrence entries as index arrays — the classic
GloVe objective, executed as jitted gather/scatter steps.
"""

from __future__ import annotations

import collections
import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.utils.hostsync import fetch_losses
from deeplearning4j_tpu.text.vocab import VocabConstructor


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
def _glove_step(w, wc, b, bc, gw, gwc, gb, gbc, rows, cols, logx, weight, lr):
    wi = jnp.take(w, rows, axis=0)
    wj = jnp.take(wc, cols, axis=0)
    bi = jnp.take(b, rows)
    bj = jnp.take(bc, cols)
    diff = jnp.einsum("bd,bd->b", wi, wj) + bi + bj - logx
    wdiff = weight * diff
    loss = 0.5 * jnp.mean(wdiff * diff)

    grad_wi = wdiff[:, None] * wj
    grad_wj = wdiff[:, None] * wi

    # AdaGrad accumulators
    gw = gw.at[rows].add(grad_wi**2)
    gwc = gwc.at[cols].add(grad_wj**2)
    gb = gb.at[rows].add(wdiff**2)
    gbc = gbc.at[cols].add(wdiff**2)

    w = w.at[rows].add(-lr * grad_wi / jnp.sqrt(jnp.take(gw, rows, axis=0) + 1e-8))
    wc = wc.at[cols].add(-lr * grad_wj / jnp.sqrt(jnp.take(gwc, cols, axis=0) + 1e-8))
    b = b.at[rows].add(-lr * wdiff / jnp.sqrt(jnp.take(gb, rows) + 1e-8))
    bc = bc.at[cols].add(-lr * wdiff / jnp.sqrt(jnp.take(gbc, cols) + 1e-8))
    return w, wc, b, bc, gw, gwc, gb, gbc, loss


class GloVe:
    def __init__(self, *, vector_size=50, window=5, min_count=1, x_max=100.0,
                 alpha=0.75, learning_rate=0.05, epochs=25, batch_size=4096,
                 seed=123):
        self.vector_size = vector_size
        self.window = window
        self.min_count = min_count
        self.x_max = x_max
        self.alpha = alpha
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.vocab = None

    def fit(self, sequences):
        seq_list = [list(s) for s in sequences]
        self.vocab = VocabConstructor(self.min_count, build_huffman=False).build(seq_list)
        v, d = len(self.vocab), self.vector_size

        # co-occurrence with 1/distance weighting (standard GloVe counting)
        cooc = collections.defaultdict(float)
        for seq in seq_list:
            idx = [self.vocab.index_of(t) for t in seq]
            idx = [i for i in idx if i >= 0]
            for pos, wi in enumerate(idx):
                for off in range(1, self.window + 1):
                    j = pos + off
                    if j >= len(idx):
                        break
                    cooc[(wi, idx[j])] += 1.0 / off
                    cooc[(idx[j], wi)] += 1.0 / off

        entries = np.array([(r, c, x) for (r, c), x in cooc.items()], np.float64)
        rows = entries[:, 0].astype(np.int32)
        cols = entries[:, 1].astype(np.int32)
        x = entries[:, 2]
        logx = np.log(x).astype(np.float32)
        weight = np.minimum(1.0, (x / self.x_max) ** self.alpha).astype(np.float32)

        rs = np.random.RandomState(self.seed)
        scale = 0.5 / d
        w = jnp.asarray(rs.uniform(-scale, scale, (v, d)).astype(np.float32))
        wc = jnp.asarray(rs.uniform(-scale, scale, (v, d)).astype(np.float32))
        b = jnp.zeros(v, jnp.float32)
        bc = jnp.zeros(v, jnp.float32)
        gw = jnp.zeros((v, d), jnp.float32)
        gwc = jnp.zeros((v, d), jnp.float32)
        gb = jnp.zeros(v, jnp.float32)
        gbc = jnp.zeros(v, jnp.float32)

        self.loss_history = []  # reset up front: a mid-fit failure must not
        losses = []             # leave a previous fit's history behind
        n = len(rows)
        for epoch in range(self.epochs):
            perm = rs.permutation(n)
            for i in range(0, n, self.batch_size):
                sl = perm[i:i + self.batch_size]
                w, wc, b, bc, gw, gwc, gb, gbc, loss = _glove_step(
                    w, wc, b, bc, gw, gwc, gb, gbc,
                    jnp.asarray(rows[sl]), jnp.asarray(cols[sl]),
                    jnp.asarray(logx[sl]), jnp.asarray(weight[sl]),
                    self.learning_rate)
                losses.append(loss)  # stays on device until the end
        self.loss_history = fetch_losses(losses)
        self.syn0 = w + wc  # standard GloVe: sum of word+context vectors
        return self

    def get_word_vector(self, word):
        i = self.vocab.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def similarity(self, w1, w2):
        a, b = self.get_word_vector(w1), self.get_word_vector(w2)
        if a is None or b is None:
            return float("nan")
        return float(np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))
