from deeplearning4j_tpu.text.tokenization import (  # noqa: F401
    DefaultTokenizerFactory, NGramTokenizerFactory, StemmingPreprocessor,
    UimaTokenizerFactory)
from deeplearning4j_tpu.text.languages import (  # noqa: F401
    ChineseTokenizerFactory, JapaneseTokenizerFactory, KoreanTokenizerFactory,
)
from deeplearning4j_tpu.text.corpus import (  # noqa: F401
    AggregatingSentenceIterator, AsyncLabelAwareIterator,
    BasicLabelAwareIterator, BasicLineIterator, CollectionSentenceIterator,
    FileLabelAwareIterator, FileSentenceIterator,
    FilenamesLabelAwareIterator, LabelAwareIterator, LabelledDocument,
    LabelsSource, LineSentenceIterator, MultipleEpochsSentenceIterator,
    PrefetchingSentenceIterator, SentenceIterator,
    SimpleLabelAwareIterator, StreamLineIterator,
    SynchronizedSentenceIterator)
from deeplearning4j_tpu.text.vocab import VocabCache, VocabConstructor, huffman_encode  # noqa: F401
from deeplearning4j_tpu.text.word2vec import SequenceVectors, Word2Vec  # noqa: F401
from deeplearning4j_tpu.text.paragraph_vectors import ParagraphVectors  # noqa: F401
from deeplearning4j_tpu.text.glove import GloVe  # noqa: F401
from deeplearning4j_tpu.text.serializer import (  # noqa: F401
    StaticWordVectors, load_word2vec_binary, load_word_vectors,
    save_word2vec_binary, save_word_vectors)
from deeplearning4j_tpu.text.bow import BagOfWordsVectorizer, TfidfVectorizer  # noqa: F401
