"""Vocabulary construction + Huffman coding.

Reference analog: models/word2vec/wordstore/ (VocabCache,
AbstractCache, VocabConstructor) and the Huffman tree built for hierarchical
softmax (models/word2vec/Huffman.java, graph variant GraphHuffman.java) in
/root/reference/deeplearning4j-nlp-parent/deeplearning4j-nlp.
"""

from __future__ import annotations

import dataclasses as _dc
import heapq

import numpy as np


class VocabWord:
    __slots__ = ("word", "count", "index", "codes", "points")

    def __init__(self, word, count=0, index=-1):
        self.word = word
        self.count = count
        self.index = index
        self.codes = []   # Huffman code bits
        self.points = []  # inner-node indices on the root path

    def __repr__(self):
        return f"VocabWord({self.word!r}, count={self.count}, idx={self.index})"


@_dc.dataclass
class FlatCorpus:
    """One np.unique pass over a whole corpus, shared by vocab construction
    and corpus encoding: tokens[i] == uniq[inverse[i]]."""
    uniq: object      # [U] distinct tokens (sorted)
    inverse: object   # [N] index into uniq per corpus token
    counts: object    # [U]
    lens: object      # [n_sequences] tokens per sequence


def flatten_corpus(sequences):
    """FlatCorpus for the token sequences, or None when the tokens are not
    amenable to np.unique (mixed types that don't order, tuple tokens that
    would form 2-D object arrays, ...) — callers then use dict-loop paths."""
    seqs = sequences if isinstance(sequences, (list, tuple)) else \
        list(sequences)
    lens = np.fromiter((len(s) for s in seqs), np.int64, len(seqs))
    chunks = [np.asarray(s, object) for s in seqs if len(s)]
    if not chunks:
        z = np.zeros(0, object)
        return FlatCorpus(z, np.zeros(0, np.int64), np.zeros(0, np.int64),
                          lens)
    if any(c.ndim != 1 for c in chunks):
        return None  # tuple/sequence tokens became 2-D object arrays
    tokens = np.concatenate(chunks)
    try:
        uniq, inverse, counts = np.unique(tokens, return_inverse=True,
                                          return_counts=True)
    except TypeError:  # unorderable mixed token types
        return None
    return FlatCorpus(uniq, inverse, counts, lens)


class VocabCache:
    """Word <-> index bimap with counts (reference: AbstractCache)."""

    def __init__(self):
        self._words: dict[str, VocabWord] = {}
        self._by_index: list[VocabWord] = []

    def add(self, word, count=1):
        vw = self._words.get(word)
        if vw is None:
            vw = VocabWord(word, 0)
            self._words[word] = vw
        vw.count += count
        return vw

    def finalize(self, min_count=1):
        """Prune rare words, assign indices by descending frequency."""
        kept = [w for w in self._words.values() if w.count >= min_count]
        kept.sort(key=lambda w: (-w.count, w.word))
        self._words = {w.word: w for w in kept}
        self._by_index = kept
        for i, w in enumerate(kept):
            w.index = i
        return self

    def __contains__(self, word):
        return word in self._words

    def __len__(self):
        return len(self._by_index)

    def word_for(self, index):
        return self._by_index[index].word

    def index_of(self, word):
        vw = self._words.get(word)
        return -1 if vw is None else vw.index

    def vocab_word(self, word):
        return self._words.get(word)

    def words(self):
        return [w.word for w in self._by_index]

    def counts(self):
        return np.array([w.count for w in self._by_index], np.int64)

    def total_count(self):
        return int(self.counts().sum())


def huffman_encode(vocab: VocabCache):
    """Assign Huffman codes/points for hierarchical softmax (reference:
    Huffman.java). Inner nodes are numbered 0..V-2."""
    v = len(vocab)
    if v < 2:
        return vocab
    counts = vocab.counts()
    # heap of (count, tiebreak, node_id); leaves 0..v-1, inner v..2v-2
    heap = [(int(counts[i]), i, i) for i in range(v)]
    heapq.heapify(heap)
    parent = {}
    binary = {}
    next_id = v
    while len(heap) > 1:
        c1, _, n1 = heapq.heappop(heap)
        c2, _, n2 = heapq.heappop(heap)
        parent[n1] = next_id
        parent[n2] = next_id
        binary[n1] = 0
        binary[n2] = 1
        heapq.heappush(heap, (c1 + c2, next_id, next_id))
        next_id += 1
    root = heap[0][2]
    for i, vw in enumerate(vocab._by_index):
        codes, points = [], []
        node = i
        while node != root:
            codes.append(binary[node])
            points.append(parent[node] - v)  # inner-node index
            node = parent[node]
        vw.codes = codes[::-1]
        vw.points = points[::-1]
    return vocab


class VocabConstructor:
    """Build a VocabCache from an iterable of token sequences (reference:
    VocabConstructor.buildJointVocabulary). Counting runs through ONE
    np.unique pass over the flattened corpus when token types allow."""

    def __init__(self, min_count=5, build_huffman=True):
        self.min_count = min_count
        self.build_huffman = build_huffman

    def build(self, sequences) -> VocabCache:
        corpus = flatten_corpus(sequences)
        if corpus is not None:
            return self.build_from_counts(corpus.uniq, corpus.counts)
        # fallback: tokens not orderable/scalar (mixed types, tuples, ...)
        vocab = VocabCache()
        for seq in sequences:
            for tok in seq:
                vocab.add(tok)
        vocab.finalize(self.min_count)
        if self.build_huffman:
            huffman_encode(vocab)
        return vocab

    def build_from_counts(self, words, counts) -> VocabCache:
        """Build from precomputed (word, count) pairs — the flatten/unique
        pass is shared with corpus encoding (see flatten_corpus)."""
        vocab = VocabCache()
        for tok, cnt in zip(words, counts):
            vocab.add(tok, int(cnt))
        vocab.finalize(self.min_count)
        if self.build_huffman:
            huffman_encode(vocab)
        return vocab
