"""Language-pack tokenizer factories: Chinese, Japanese, Korean.

Reference analog: the deeplearning4j-nlp-{chinese,japanese,korean} modules
(SURVEY.md §2.6) — ChineseTokenizerFactory (ansj segmenter),
JapaneseTokenizerFactory (kuromoji morphological analyzer),
KoreanTokenizerFactory (twitter-korean-text). Those wrap ~20k LoC of
third-party segmenter code; here the factories implement the same
``create(text) -> Tokenizer`` SPI with self-contained segmentation:

* dictionary-driven maximum-matching when a user lexicon is supplied (the
  standard CJK segmentation baseline the heavyweight libraries refine), and
* script-aware fallback otherwise: CJK-ideograph runs split per character
  (each Han character is a token — the n-gram-friendly default), kana runs
  kept whole per script, Hangul/latin/digit runs kept whole.

The factories plug into everything SequenceVectors-based (Word2Vec,
ParagraphVectors, TF-IDF) exactly like the reference's language packs plug
into SequenceVectors' TokenizerFactory slot.
"""

from __future__ import annotations

import unicodedata

from deeplearning4j_tpu.text.tokenization import Tokenizer


def _char_class(ch):
    o = ord(ch)
    if 0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF or 0xF900 <= o <= 0xFAFF:
        return "han"
    if 0x3040 <= o <= 0x309F:
        return "hiragana"
    if 0x30A0 <= o <= 0x30FF or 0x31F0 <= o <= 0x31FF:
        return "katakana"
    if 0xAC00 <= o <= 0xD7AF or 0x1100 <= o <= 0x11FF or 0x3130 <= o <= 0x318F:
        return "hangul"
    if ch.isspace():
        return "space"
    if ch.isalnum():
        return "word"
    return "punct"


def _script_runs(text):
    runs = []
    cur, cls = "", None
    for ch in text:
        c = _char_class(ch)
        if c == cls:
            cur += ch
        else:
            if cur:
                runs.append((cur, cls))
            cur, cls = ch, c
    if cur:
        runs.append((cur, cls))
    return runs


class _CjkTokenizerFactoryBase:
    """Shared CJK factory: optional lexicon maximum-matching + script runs."""

    #: scripts whose runs are split per-character without a lexicon
    per_char_scripts = ("han",)
    #: scripts dropped from output
    drop = ("space", "punct")

    def __init__(self, lexicon=None, preprocessor=None, max_word_len=8):
        self.lexicon = set(lexicon) if lexicon else None
        self.preprocessor = preprocessor
        self.max_word_len = max_word_len

    def _segment_run(self, run, cls):
        if cls not in self.per_char_scripts:
            return [run]
        if self.lexicon:
            return self._max_match(run)
        return list(run)

    def _max_match(self, run):
        """Greedy forward maximum matching against the lexicon; unmatched
        characters become single-char tokens (the classical CJK baseline)."""
        out, i, n = [], 0, len(run)
        while i < n:
            for ln in range(min(self.max_word_len, n - i), 1, -1):
                if run[i:i + ln] in self.lexicon:
                    out.append(run[i:i + ln])
                    i += ln
                    break
            else:
                out.append(run[i])
                i += 1
        return out

    def create(self, text: str) -> Tokenizer:
        tokens = []
        for run, cls in _script_runs(unicodedata.normalize("NFKC", text)):
            if cls in self.drop:
                continue
            tokens.extend(self._segment_run(run, cls))
        if self.preprocessor is not None:
            tokens = [self.preprocessor.pre_process(t) for t in tokens]
            tokens = [t for t in tokens if t]
        return Tokenizer(tokens)


class ChineseTokenizerFactory(_CjkTokenizerFactoryBase):
    """Reference: deeplearning4j-nlp-chinese ChineseTokenizerFactory (ansj).
    Han runs are lexicon-max-matched (or per-character without a lexicon)."""

    per_char_scripts = ("han",)


class JapaneseTokenizerFactory(_CjkTokenizerFactoryBase):
    """Reference: deeplearning4j-nlp-japanese JapaneseTokenizerFactory
    (kuromoji). Kanji runs segment like Chinese; kana runs are kept whole per
    script (a coarse but useful morpheme proxy), and a lexicon (e.g. a
    user dictionary of surface forms) refines all three scripts."""

    per_char_scripts = ("han", "hiragana", "katakana")

    def _segment_run(self, run, cls):
        if cls not in self.per_char_scripts:
            return [run]  # latin/digit/hangul runs stay whole
        if self.lexicon:
            return self._max_match(run)
        if cls == "han":
            return list(run)
        return [run]  # whole kana run


class KoreanTokenizerFactory(_CjkTokenizerFactoryBase):
    """Reference: deeplearning4j-nlp-korean KoreanTokenizerFactory
    (twitter-korean-text). Hangul runs are whitespace-delimited eojeol;
    a lexicon max-matches morphemes inside each run."""

    per_char_scripts = ("hangul",)

    def _segment_run(self, run, cls):
        if cls == "hangul" and self.lexicon:
            return self._max_match(run)
        return [run]
