"""Language-pack tokenizer factories: Chinese, Japanese, Korean (+ sentence
segmentation, the uima-pack role).

Reference analog: the deeplearning4j-nlp-{chinese,japanese,korean,uima}
modules (SURVEY.md §2.6) — ChineseTokenizerFactory (ansj segmenter),
JapaneseTokenizerFactory (kuromoji morphological analyzer),
KoreanTokenizerFactory (twitter-korean-text), UimaTokenizerFactory
(sentence/token annotators). Those wrap ~20k LoC of third-party segmenter
code; here the factories implement the same ``create(text) -> Tokenizer``
SPI with self-contained segmentation:

* dictionary-driven maximum-matching over an EMBEDDED starter lexicon of
  high-frequency words (extensible/replaceable with a user lexicon) — the
  standard CJK segmentation baseline the heavyweight libraries refine;
* script-aware fallback: unmatched Han characters tokenize per character
  (the n-gram-friendly default), kana/hangul runs follow per-language rules;
* Japanese: okurigana attachment (a short hiragana tail after a kanji run
  joins the kanji token, e.g. 食べ), hiragana runs split on common
  particles (は/が/を/に/で/と/も/の/から/まで/...);
* Korean: josa (particle) stripping from eojeol ends (은/는/이/가/을/를/
  에/의/로/...), emitting the stem — twitter-korean-text's signature
  normalization;
* ``split_sentences``: multi-script rule-based sentence segmentation
  (。！？.!? + closing quotes), the uima SentenceAnnotator role.

The factories plug into everything SequenceVectors-based (Word2Vec,
ParagraphVectors, TF-IDF) exactly like the reference's language packs plug
into SequenceVectors' TokenizerFactory slot.
"""

from __future__ import annotations

import unicodedata

from deeplearning4j_tpu.text.tokenization import Tokenizer

# ---------------------------------------------------------------------------
# embedded starter lexicons: high-frequency words. Deliberately small —
# enough to beat the per-character baseline on common text; production use
# supplies a domain lexicon via the factory argument.
# ---------------------------------------------------------------------------

_ZH_LEXICON = (
    "我们 你们 他们 她们 这个 那个 什么 怎么 为什么 因为 所以 但是 可是 "
    "如果 虽然 然后 现在 时候 今天 明天 昨天 已经 还是 就是 不是 没有 "
    "可以 应该 需要 知道 觉得 喜欢 工作 学习 学校 老师 学生 朋友 时间 "
    "问题 地方 国家 中国 世界 大家 东西 事情 孩子 先生 小姐 谢谢 再见 "
    "电脑 手机 网络 数据 模型 训练 机器 学习 人工 智能").split()

_JA_LEXICON = (
    "これ それ あれ どれ ここ そこ どこ わたし あなた 私たち 日本 東京 "
    "学校 先生 学生 友達 時間 問題 仕事 今日 明日 昨日 食べる 飲む 行く "
    "来る 見る 聞く 話す 読む 書く 思う 言う ありがとう こんにちは "
    "さようなら データ モデル 学習 機械").split()

_KO_LEXICON = (
    "우리 너희 그들 이것 그것 저것 여기 거기 어디 무엇 언제 누구 왜 "
    "어떻게 오늘 내일 어제 시간 문제 일 학교 선생님 학생 친구 한국 "
    "서울 세계 사람 아이 감사합니다 안녕하세요 데이터 모델 학습 기계 "
    # people / family / society
    "나 저 당신 남자 여자 어른 아기 가족 부모 부모님 아버지 어머니 "
    "아빠 엄마 형 누나 오빠 언니 동생 아들 딸 할아버지 할머니 이름 "
    "생일 결혼 사랑 마음 생각 느낌 꿈 희망 약속 이야기 말 말씀 소리 "
    "목소리 웃음 눈물 얼굴 눈 코 입 귀 머리 손 발 팔 다리 몸 건강 "
    # time / calendar
    "지금 아침 점심 저녁 밤 낮 오전 오후 요일 월요일 화요일 수요일 "
    "목요일 금요일 토요일 일요일 주말 평일 휴일 올해 작년 내년 달 "
    "주 날 날짜 계절 봄 여름 가을 겨울 날씨 비 눈 바람 구름 하늘 "
    # places / travel
    "집 방 부엌 화장실 문 창문 마당 길 거리 동네 도시 시골 나라 "
    "고향 회사 사무실 공장 가게 시장 마트 백화점 식당 카페 은행 "
    "병원 약국 우체국 도서관 공원 극장 영화관 박물관 역 정류장 "
    "공항 호텔 바다 강 산 섬 북한 미국 중국 일본 영국 부산 인천 "
    "대구 대전 광주 지하철 버스 기차 택시 자동차 자전거 비행기 배 "
    "표 지도 여행 길거리 "
    # school / work / study
    "공부 수업 교실 숙제 시험 질문 대답 책 공책 연필 볼펜 종이 "
    "사전 신문 잡지 소설 글 글자 한글 영어 한국어 일본어 중국어 "
    "외국어 단어 문장 뜻 의미 번역 발음 문법 역사 과학 수학 음악 "
    "미술 체육 대학 대학교 교수 박사 전공 졸업 입학 취직 직업 "
    "회의 보고 보고서 계획 목표 결과 이유 방법 준비 연습 경험 "
    "실력 능력 성공 실패 노력 기회 책임 "
    # food / daily life
    "밥 물 차 커피 우유 주스 맥주 술 빵 과일 사과 배 포도 수박 "
    "바나나 채소 고기 소고기 돼지고기 닭고기 생선 계란 김치 국 "
    "찌개 라면 국수 떡 과자 사탕 설탕 소금 맛 아침밥 점심밥 저녁밥 "
    "요리 음식 식사 메뉴 그릇 접시 컵 숟가락 젓가락 옷 바지 치마 "
    "셔츠 신발 양말 모자 안경 가방 지갑 우산 시계 선물 돈 값 가격 "
    "전화 전화번호 핸드폰 휴대폰 컴퓨터 노트북 인터넷 이메일 사진 "
    "영화 노래 춤 그림 운동 축구 야구 농구 수영 등산 산책 쇼핑 "
    "청소 빨래 목욕 샤워 잠 침대 의자 책상 텔레비전 냉장고 에어컨 "
    # abstract / misc
    "것 수 때 곳 분 년 월 일월 이월 삼월 앞 뒤 위 아래 안 밖 옆 "
    "사이 가운데 근처 오른쪽 왼쪽 동쪽 서쪽 남쪽 북쪽 처음 마지막 "
    "다음 이번 저번 전 후 중 모두 전부 일부 반 정도 크기 모양 색 "
    "색깔 종류 번호 숫자 나이 키 무게 속도 온도 소식 뉴스 정보 "
    "사실 거짓말 인생 삶 죽음 전쟁 평화 자유 정부 법 경찰 군인 "
    "의사 간호사 요리사 가수 배우 작가 기자 운전사 손님 주인 "
    "이웃 인기 취미 재미 걱정 고민 스트레스 기분 행복 슬픔 화 "
    "용기 힘 도움 인사 축하 칭찬 사과문 질서 규칙 문화 전통 종교 "
    "예술 기술 경제 정치 사회 환경 자연 동물 식물 개 고양이 새 "
    "물고기 소 돼지 닭 꽃 나무 풀 잎 열매 씨 해 달 별 땅 "
    "불 공기 돌 흙 금 은 유리 플라스틱 프로그램 게임 시스템 "
    "네트워크 파일 화면 키보드 마우스 버튼 비밀번호 회원 가입 "
    "웹사이트 블로그 댓글 동영상 방송 광고 기사 "
    # adverbs — listed whole so the josa stripper never unravels them
    # (많이 is NOT 많+이)
    "많이 빨리 천천히 일찍 늦게 같이 함께 혼자 열심히 자주 가끔 "
    "항상 언제나 늘 벌써 아직 이미 곧 방금 바로 먼저 나중에 "
    "정말 진짜 아주 매우 너무 조금 좀 더 덜 가장 제일 잘 못 안 "
    "다시 또 계속 갑자기 천천 아마 물론 특히 역시 그냥 거의 "
    "별로 전혀 서로 모두 다 약간 꽤 상당히 완전히 확실히 "
    "그리고 그러나 하지만 그래서 그러면 그런데 그래도 또는 "
    "즉 만약 비록").split()

#: common Korean particles (josa), longest first for greedy suffix matching
_KO_JOSA = sorted(
    ("은", "는", "이", "가", "을", "를", "에", "의", "와", "과", "도", "만",
     "로", "으로", "에서", "에게", "한테", "께서", "부터", "까지", "보다",
     "처럼", "마다", "조차", "밖에", "이나", "나", "라도", "든지",
     # chain-closers and formal/instrumental/comitative variants
     "께", "이라도", "으로서", "로서", "으로써", "로써", "이며", "이랑",
     "랑", "에게서", "한테서", "에다", "이든지", "이라는",
     "라는", "이란", "란", "야말로", "이야말로"),
    key=len, reverse=True)

#: common Japanese particles used to split long hiragana runs
_JA_PARTICLES = sorted(
    ("は", "が", "を", "に", "で", "と", "も", "の", "へ", "や", "から",
     "まで", "より", "ので", "のに", "けど", "でも", "だけ", "など", "ね",
     "よ", "か"), key=len, reverse=True)


def _char_class(ch):
    o = ord(ch)
    if 0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF or 0xF900 <= o <= 0xFAFF:
        return "han"
    if 0x3040 <= o <= 0x309F:
        return "hiragana"
    if 0x30A0 <= o <= 0x30FF or 0x31F0 <= o <= 0x31FF:
        return "katakana"
    if 0xAC00 <= o <= 0xD7AF or 0x1100 <= o <= 0x11FF or 0x3130 <= o <= 0x318F:
        return "hangul"
    if ch.isspace():
        return "space"
    if ch.isalnum():
        return "word"
    return "punct"


def _script_runs(text):
    runs = []
    cur, cls = "", None
    for ch in text:
        c = _char_class(ch)
        if c == cls:
            cur += ch
        else:
            if cur:
                runs.append((cur, cls))
            cur, cls = ch, c
    if cur:
        runs.append((cur, cls))
    return runs


_SENT_END = set("。！？．.!?")
_SENT_TRAIL = set("」』）)\"'”’")


def split_sentences(text):
    """Rule-based sentence segmentation across scripts (reference: the uima
    pack's SentenceAnnotator role): break after 。！？.!?, keeping trailing
    closing quotes/brackets with the finished sentence."""
    out, cur = [], ""
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        cur += ch
        if ch in _SENT_END:
            # abbreviation guard for latin '.': next char lowercase/digit
            if ch == "." and i + 1 < n and (text[i + 1].isalnum()):
                i += 1
                continue
            while i + 1 < n and text[i + 1] in _SENT_TRAIL:
                cur += text[i + 1]
                i += 1
            s = cur.strip()
            if s:
                out.append(s)
            cur = ""
        i += 1
    s = cur.strip()
    if s:
        out.append(s)
    return out


def max_match(run, lexicon, max_word_len):
    """Greedy forward maximum matching against the lexicon; unmatched
    characters become single-char tokens (the classical CJK baseline)."""
    out, i, n = [], 0, len(run)
    while i < n:
        for ln in range(min(max_word_len, n - i), 1, -1):
            if run[i:i + ln] in lexicon:
                out.append(run[i:i + ln])
                i += ln
                break
        else:
            out.append(run[i])
            i += 1
    return out


class _CjkTokenizerFactoryBase:
    """Shared CJK factory: lexicon maximum-matching + script-run rules."""

    #: scripts whose runs are segmented (vs kept whole)
    per_char_scripts = ("han",)
    #: scripts dropped from output
    drop = ("space", "punct")
    #: built-in starter lexicon (merged under a user-supplied one)
    default_lexicon = ()

    def __init__(self, lexicon=None, preprocessor=None, max_word_len=8,
                 use_default_lexicon=True):
        self.lexicon = set(self.default_lexicon) if use_default_lexicon \
            else set()
        if lexicon:
            self.lexicon |= set(lexicon)
        self.preprocessor = preprocessor
        self.max_word_len = max_word_len

    def _segment_run(self, run, cls):
        if cls not in self.per_char_scripts:
            return [run]
        if self.lexicon:
            return self._max_match(run)
        return list(run)

    def _max_match(self, run):
        return max_match(run, self.lexicon, self.max_word_len)

    def _lattice_create(self, text, tokens):
        """Shared lattice-mode tail: drop-filter + preprocessor + wrap."""
        tokens = [t for t in tokens if _char_class(t[0]) not in self.drop]
        if self.preprocessor is not None:
            tokens = [self.preprocessor.pre_process(t) for t in tokens]
            tokens = [t for t in tokens if t]
        return Tokenizer(tokens)

    def _runs(self, text):
        return _script_runs(unicodedata.normalize("NFKC", text))

    def create(self, text: str) -> Tokenizer:
        tokens = []
        for run, cls in self._runs(text):
            if cls in self.drop:
                continue
            tokens.extend(self._segment_run(run, cls))
        if self.preprocessor is not None:
            tokens = [self.preprocessor.pre_process(t) for t in tokens]
            tokens = [t for t in tokens if t]
        return Tokenizer(tokens)


class ChineseTokenizerFactory(_CjkTokenizerFactoryBase):
    """Reference: deeplearning4j-nlp-chinese ChineseTokenizerFactory (ansj).

    Default mode="lattice" runs the Viterbi lattice segmenter
    (text/zh_lattice.py — dictionary + rule candidates incl. the ansj
    person-name invocation + connection-cost Viterbi, the ansj design
    self-contained). mode="maxmatch" keeps the greedy lexicon
    maximum-matching baseline (per-character fallback without a lexicon).
    """

    per_char_scripts = ("han",)
    default_lexicon = _ZH_LEXICON

    def __init__(self, lexicon=None, preprocessor=None, max_word_len=8,
                 mode="lattice", use_default_lexicon=True,
                 merge_num_quantifier=False):
        super().__init__(lexicon=lexicon, preprocessor=preprocessor,
                         max_word_len=max_word_len,
                         use_default_lexicon=use_default_lexicon)
        if mode not in ("lattice", "maxmatch"):
            raise ValueError(f"unknown mode {mode!r}")
        #: ansj's optional NumRecognition (数量词合并): numeral + measure
        #: word fuse into one token — a lattice-path feature (the merge
        #: uses the Viterbi classes), so a maxmatch factory can't honor it
        if merge_num_quantifier and (mode != "lattice"
                                     or not use_default_lexicon):
            raise ValueError("merge_num_quantifier requires the lattice "
                             "mode (with its bundled dictionary)")
        self.merge_num_quantifier = merge_num_quantifier
        # same contract as the Japanese factory: without its bundled
        # dictionary a lattice cannot run, so that request means maxmatch
        self.mode = mode if use_default_lexicon else "maxmatch"
        from deeplearning4j_tpu.text import zh_lattice
        # merge the user lexicon into the lattice dictionary ONCE (create()
        # runs per document in SequenceVectors loops)
        self._merged = zh_lattice.merge_entries(set(lexicon)
                                                if lexicon else None)

    def create(self, text: str) -> Tokenizer:
        if self.mode == "lattice":
            from deeplearning4j_tpu.text import zh_lattice
            return self._lattice_create(
                text, zh_lattice.tokenize(
                    text, merged=self._merged,
                    merge_num_quantifier=self.merge_num_quantifier))
        return super().create(text)


class JapaneseTokenizerFactory(_CjkTokenizerFactoryBase):
    """Reference: deeplearning4j-nlp-japanese JapaneseTokenizerFactory
    (kuromoji). Default mode="lattice" runs the Viterbi lattice
    morphological analyzer (text/ja_lattice.py — dictionary + unknown-word
    invocation + connection-cost Viterbi, the kuromoji design
    self-contained). mode="maxmatch" keeps the round-2 heuristic:

    * a short hiragana tail (<=2 chars) directly after a kanji run attaches
      to the kanji token (okurigana: 食べ, 思い);
    * longer hiragana runs split on common particles;
    * katakana runs (loanwords) stay whole; the lexicon refines everything.
    """

    per_char_scripts = ("han", "hiragana", "katakana")
    default_lexicon = _JA_LEXICON

    OKURIGANA_MAX = 2

    def __init__(self, lexicon=None, preprocessor=None, max_word_len=8,
                 mode="lattice", use_default_lexicon=True,
                 lattice_mode="normal", user_dict_path=None):
        super().__init__(lexicon=lexicon, preprocessor=preprocessor,
                         max_word_len=max_word_len,
                         use_default_lexicon=use_default_lexicon)
        if mode not in ("lattice", "maxmatch"):
            raise ValueError(f"unknown mode {mode!r}")
        if lattice_mode not in ("normal", "search"):
            raise ValueError(f"unknown lattice_mode {lattice_mode!r}")
        # kuromoji Mode.NORMAL vs Mode.SEARCH (decompounding for indexing)
        self.lattice_mode = lattice_mode
        if lattice_mode == "search" and (mode != "lattice"
                                         or not use_default_lexicon):
            # maxmatch never consults lattice_mode: silently returning
            # undecompounded tokens would betray the caller's request
            raise ValueError(
                "lattice_mode='search' requires mode='lattice' with the "
                "default lexicon (the maxmatch path has no search mode)")
        # lexicon-free segmentation (use_default_lexicon=False) is
        # inherently the heuristic path — a lattice without its bundled
        # dictionary cannot run, so that request selects maxmatch mode
        # (where max_word_len / self.lexicon keep their round-2 contract)
        self.mode = mode if use_default_lexicon else "maxmatch"
        # user-supplied words feed the lattice as mid-cost noun entries,
        # merged into the dictionary ONCE (create() runs per document)
        from deeplearning4j_tpu.text import ja_lattice
        self._merged = ja_lattice.merge_entries(set(lexicon)
                                                if lexicon else None)
        # kuromoji user-dictionary CSV (surface,custom segmentation,...):
        # matching surfaces are force-segmented ahead of the lattice
        if user_dict_path and self.mode != "lattice":
            raise ValueError(
                "user_dict_path requires mode='lattice' (maxmatch never "
                "consults the user dictionary)")
        self._user_dict = (ja_lattice.UserDictionary.load(user_dict_path)
                           if user_dict_path else None)

    def create(self, text: str) -> Tokenizer:
        if self.mode == "lattice":
            from deeplearning4j_tpu.text import ja_lattice
            return self._lattice_create(
                text, ja_lattice.tokenize(text, merged=self._merged,
                                          mode=self.lattice_mode,
                                          user_dict=self._user_dict))
        return self._create_maxmatch(text)

    def _create_maxmatch(self, text: str) -> Tokenizer:
        runs = self._runs(text)
        tokens = []
        i = 0
        while i < len(runs):
            run, cls = runs[i]
            if cls in self.drop:
                i += 1
                continue
            if (cls == "han" and i + 1 < len(runs)
                    and runs[i + 1][1] == "hiragana"
                    and len(runs[i + 1][0]) <= self.OKURIGANA_MAX
                    and runs[i + 1][0] not in _JA_PARTICLES):
                # kanji + short okurigana = one token (e.g. 食べ) — but a
                # bare particle after kanji (肉を) is a boundary, not a tail
                tokens.append(run + runs[i + 1][0])
                i += 2
                continue
            tokens.extend(self._segment_run(run, cls))
            i += 1
        if self.preprocessor is not None:
            tokens = [self.preprocessor.pre_process(t) for t in tokens]
            tokens = [t for t in tokens if t]
        return Tokenizer(tokens)

    def _segment_run(self, run, cls):
        if cls == "katakana":
            return [run]
        if cls == "hiragana":
            return self._split_particles(run)
        if cls == "han":
            if self.lexicon:
                return self._max_match(run)
            return list(run)
        return [run]

    def _split_particles(self, run):
        """Lexicon max-match first; then peel common particles greedily."""
        if self.lexicon:
            pieces = self._max_match(run)
        else:
            pieces = [run]
        out = []
        for piece in pieces:
            if len(piece) == 1 or piece in self.lexicon:
                out.append(piece)
                continue
            i, n = 0, len(piece)
            while i < n:
                for p in _JA_PARTICLES:
                    if piece.startswith(p, i):
                        out.append(p)
                        i += len(p)
                        break
                else:
                    # consume until the next particle boundary
                    j = i + 1
                    while j < n and not any(piece.startswith(p, j)
                                            for p in _JA_PARTICLES):
                        j += 1
                    out.append(piece[i:j])
                    i = j
        return out


#: loanword sub-nouns for morpheme-mode decompounding. twitter-korean-text
#: splits compounds its dictionary lacks into known sub-nouns (딥러닝 ->
#: 딥|러닝 in the reference's own KoreanTokenizerTest) while dictionary
#: compounds stay whole (오픈소스). This table plays its sub-noun
#: dictionary's role; grow it as coverage needs grow.
_KO_LOANWORD_SUBS = frozenset(
    "딥 러닝 소스 코드 베이스 프레임 워크 소프트 웨어 하드 "
    "라이브러리 오픈소스 클라우드 컴퓨팅 모바일 서비스 플랫폼 "
    "인터페이스 알고리즘 서버 클라이언트 데이터".split())


class KoreanTokenizerFactory(_CjkTokenizerFactoryBase):
    """Reference: deeplearning4j-nlp-korean KoreanTokenizerFactory
    (twitter-korean-text). Hangul runs are eojeol (space-delimited); each
    eojeol max-matches the lexicon, then common trailing particles (josa)
    are stripped so '학교에' and '학교는' normalize to '학교' — the
    behavior that makes Korean embeddings usable without full morphology.

    ``morpheme=True`` matches twitter-korean-text's morpheme granularity
    — the exact token stream the reference pack's own KoreanTokenizerTest
    asserts (tests/test_cjk_heldout.py consumes it in place): josa emitted
    as tokens, unknown loanword compounds decompounded by the sub-noun
    table (딥러닝 -> 딥|러닝), and the formal copula's final 다 split off
    (입니다 -> 입니|다)."""

    per_char_scripts = ("hangul",)
    default_lexicon = _KO_LEXICON

    def __init__(self, lexicon=None, preprocessor=None, max_word_len=8,
                 use_default_lexicon=True, strip_josa=True,
                 emit_josa=False, morpheme=False):
        super().__init__(lexicon, preprocessor, max_word_len,
                         use_default_lexicon)
        self.morpheme = morpheme
        self.strip_josa = strip_josa  # with emit on, strip SPLITS the josa
        self.emit_josa = emit_josa or morpheme

    def _segment_run(self, run, cls):
        if cls != "hangul":
            return [run]
        from deeplearning4j_tpu.text import ko_stemmer
        toks = ko_stemmer.analyze_eojeol(
            run, self.lexicon, _KO_JOSA, max_word_len=self.max_word_len,
            strip=self.strip_josa, emit_suffixes=self.emit_josa)
        if not self.morpheme:
            return toks
        out = []
        for t in toks:
            out.extend(self._morpheme_split(t))
        return out

    def _morpheme_split(self, tok):
        # formal copula / polite endings: the final 다 is its own morpheme
        # (reference KoreanTokenizerTest: 라이브러리입니다 -> ... 입니|다)
        if tok.endswith("니다") and len(tok) >= 3:
            for stem_end in ("입니", "습니"):
                if tok.endswith(stem_end + "다"):
                    head = tok[:-3]
                    return ([*self._morpheme_split(head)] if head else []) \
                        + [stem_end, "다"]
            # contracted ㅂ니다 endings (갑니다): the ㅂ fuses into the
            # preceding syllable's jongseong, so the closest surface
            # split keeps the fused stem and frees the final 다
            return [tok[:-1], "다"]
        if tok in self.lexicon or tok in _KO_LOANWORD_SUBS:
            return [tok]
        parts = self._decompound(tok)
        return parts if parts is not None else [tok]

    def _decompound(self, tok):
        """Greedy longest-match split over lexicon + sub-noun table;
        None unless the whole token is covered by >= 2 known parts."""
        vocab = _KO_LOANWORD_SUBS
        parts, i, n = [], 0, len(tok)
        while i < n:
            for ln in range(min(self.max_word_len, n - i), 0, -1):
                piece = tok[i:i + ln]
                if piece in vocab or piece in self.lexicon:
                    parts.append(piece)
                    i += ln
                    break
            else:
                return None
        return parts if len(parts) >= 2 else None
