"""Bag-of-words / TF-IDF vectorizers.

Reference analog: bagofwords/vectorizer/ (BagOfWordsVectorizer,
TfidfVectorizer) in /root/reference/deeplearning4j-nlp-parent/
deeplearning4j-nlp.
"""

from __future__ import annotations


import numpy as np

from deeplearning4j_tpu.text.tokenization import CommonPreprocessor, DefaultTokenizerFactory
from deeplearning4j_tpu.text.vocab import VocabConstructor


class BagOfWordsVectorizer:
    def __init__(self, *, min_count=1, tokenizer_factory=None):
        self.min_count = min_count
        self.tokenizer_factory = tokenizer_factory or \
            DefaultTokenizerFactory(CommonPreprocessor())
        self.vocab = None

    def _tokenize(self, text):
        return self.tokenizer_factory.create(text).get_tokens()

    def fit(self, documents):
        seqs = [self._tokenize(d) for d in documents]
        self.vocab = VocabConstructor(self.min_count, build_huffman=False).build(seqs)
        return self

    def transform(self, documents):
        out = np.zeros((len(documents), len(self.vocab)), np.float32)
        for r, d in enumerate(documents):
            for t in self._tokenize(d):
                i = self.vocab.index_of(t)
                if i >= 0:
                    out[r, i] += 1.0
        return out

    def fit_transform(self, documents):
        return self.fit(documents).transform(documents)


class TfidfVectorizer(BagOfWordsVectorizer):
    def fit(self, documents):
        super().fit(documents)
        n = len(documents)
        df = np.zeros(len(self.vocab), np.float64)
        for d in documents:
            seen = {self.vocab.index_of(t) for t in self._tokenize(d)}
            for i in seen:
                if i >= 0:
                    df[i] += 1
        self.idf = np.log((n + 1.0) / (df + 1.0)) + 1.0
        return self

    def transform(self, documents):
        tf = super().transform(documents)
        return (tf * self.idf).astype(np.float32)
