"""Word-vector serialization: text and word2vec C binary formats.

Reference analog: models/embeddings/loader/WordVectorSerializer.java in
/root/reference/deeplearning4j-nlp-parent/deeplearning4j-nlp
(writeWordVectors / loadTxtVectors / readBinaryModel — the loader behind
loadGoogleModel for GoogleNews-vectors-negative300.bin et al.). Loaded
vectors come back either as raw (words, matrix) or as a queryable
StaticWordVectors exposing the WordVectors interface surface
(get_word_vector / similarity / words_nearest).
"""

from __future__ import annotations

import gzip

import numpy as np


def save_word_vectors(model, path):
    """Write `<word> <v0> <v1> ...` lines with a `<count> <dim>` header."""
    words = model.vocab.words()
    vecs = np.asarray(model.syn0)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wt", encoding="utf-8") as f:
        f.write(f"{len(words)} {vecs.shape[1]}\n")
        for i, w in enumerate(words):
            f.write(w + " " + " ".join(f"{v:.6f}" for v in vecs[i]) + "\n")
    return path


def load_word_vectors(path):
    """Returns (words list, matrix [V,D])."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:
        header = f.readline().split()
        count, dim = int(header[0]), int(header[1])
        words, rows = [], []
        for line in f:
            parts = line.rstrip("\n").split(" ")
            words.append(parts[0])
            rows.append([float(v) for v in parts[1:dim + 1]])
    return words, np.asarray(rows, np.float32)


def save_word2vec_binary(model, path):
    """word2vec C binary format (the GoogleNews interchange format the
    reference reads via readBinaryModel): ASCII `<count> <dim>\\n` header,
    then per word `<word> ` + dim little-endian float32s + `\\n`."""
    words = model.vocab.words()
    vecs = np.asarray(model.syn0, np.float32)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(f"{len(words)} {vecs.shape[1]}\n".encode("utf-8"))
        for i, w in enumerate(words):
            f.write(w.encode("utf-8") + b" ")
            f.write(vecs[i].astype("<f4").tobytes())
            f.write(b"\n")
    return path


class _BufReader:
    """Chunked reader: delimiter-scanned word reads + exact-size vector
    reads, so multi-GB models (GoogleNews et al.) load without a Python
    call per byte."""

    def __init__(self, f, chunk=1 << 20):
        self.f = f
        self.chunk = chunk
        self.buf = b""
        self.pos = 0

    def _fill(self):
        data = self.f.read(self.chunk)
        self.buf = self.buf[self.pos:] + data
        self.pos = 0
        return bool(data)

    def read_until(self, delim):
        """Bytes up to (not including) delim; consumes the delimiter."""
        while True:
            idx = self.buf.find(delim, self.pos)
            if idx >= 0:
                out = self.buf[self.pos:idx]
                self.pos = idx + 1
                return out
            if not self._fill():
                raise ValueError("truncated word2vec binary data")

    def read_exact(self, n):
        while len(self.buf) - self.pos < n:
            if not self._fill():
                raise ValueError("truncated vector data")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out


def load_word2vec_binary(path):
    """Read the word2vec C binary format. Returns (words, matrix [V,D]).
    Tolerates both `vec\\n` and bare `vec` record terminators (tools differ,
    the reference's reader skips the byte when present)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        r = _BufReader(f)
        count, dim = (int(x) for x in r.read_until(b"\n").split())
        vec_bytes = dim * 4
        words, rows = [], []
        for _ in range(count):
            w = r.read_until(b" ").lstrip(b"\n")
            buf = r.read_exact(vec_bytes)
            words.append(w.decode("utf-8"))
            rows.append(np.frombuffer(buf, dtype="<f4"))
    return words, np.asarray(rows, np.float32)


class StaticWordVectors:
    """Queryable lookup over loaded vectors (reference: the WordVectors
    interface surface returned by WordVectorSerializer loaders)."""

    def __init__(self, words, matrix):
        self.words = list(words)
        self.matrix = np.asarray(matrix, np.float32)
        self._index = {w: i for i, w in enumerate(self.words)}
        norms = np.linalg.norm(self.matrix, axis=1, keepdims=True)
        self._unit = self.matrix / np.maximum(norms, 1e-12)

    @classmethod
    def load(cls, path, binary=None):
        """Auto-detects text vs binary unless ``binary`` is given: tries the
        text parser first and falls back to binary when the body is not
        parseable text (byte-sniffing heuristics misclassify non-ASCII
        words, which CJK vocabularies make routine)."""
        if binary is True:
            return cls(*load_word2vec_binary(path))
        if binary is False:
            return cls(*load_word_vectors(path))
        try:
            return cls(*load_word_vectors(path))
        except (UnicodeDecodeError, ValueError, IndexError):
            return cls(*load_word2vec_binary(path))

    def has_word(self, word):
        return word in self._index

    def get_word_vector(self, word):
        i = self._index.get(word)
        return None if i is None else self.matrix[i]

    def similarity(self, w1, w2):
        a, b = self._index.get(w1), self._index.get(w2)
        if a is None or b is None:
            return float("nan")
        return float(self._unit[a] @ self._unit[b])

    def words_nearest(self, word, top_n=10):
        i = self._index.get(word)
        if i is None:
            return []
        sims = self._unit @ self._unit[i]
        order = np.argsort(-sims)
        return [(self.words[j], float(sims[j]))
                for j in order if j != i][:top_n]
