"""Word-vector serialization (text format, word2vec-compatible).

Reference analog: models/embeddings/loader/WordVectorSerializer.java in
/root/reference/deeplearning4j-nlp-parent/deeplearning4j-nlp (writeWordVectors
/ loadTxtVectors).
"""

from __future__ import annotations

import gzip

import numpy as np


def save_word_vectors(model, path):
    """Write `<word> <v0> <v1> ...` lines with a `<count> <dim>` header."""
    words = model.vocab.words()
    vecs = np.asarray(model.syn0)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wt", encoding="utf-8") as f:
        f.write(f"{len(words)} {vecs.shape[1]}\n")
        for i, w in enumerate(words):
            f.write(w + " " + " ".join(f"{v:.6f}" for v in vecs[i]) + "\n")
    return path


def load_word_vectors(path):
    """Returns (words list, matrix [V,D])."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:
        header = f.readline().split()
        count, dim = int(header[0]), int(header[1])
        words, rows = [], []
        for line in f:
            parts = line.rstrip("\n").split(" ")
            words.append(parts[0])
            rows.append([float(v) for v in parts[1:dim + 1]])
    return words, np.asarray(rows, np.float32)
